"""Benchmark: device elimination-forest build throughput (edges/sec).

Prints ONE JSON line.  The metric is end-to-end edges/sec of the fused
single-chip build step (degree histogram + (degree,vid) sort + edge links +
forest fixpoint + pst) on an R-MAT power-law graph — the analog of the
reference's load-free sort+map phases.  ``vs_baseline`` compares against the
reference's best aggregate MPI throughput on twitter-2010: 1,468,364,884
edges / 18.7 s map = 78.5M edges/s across 18 ranks (BASELINE.md,
data/slurm-twitter/slurm-25.avg:15); the north-star target is 10x that.

Sizes are env-tunable: SHEEP_BENCH_LOG_N (default 23), SHEEP_BENCH_EDGE_FACTOR
(default 8 edges per vertex), SHEEP_BENCH_REPS (default 3).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

_BASELINE_EDGES_PER_SEC = 1_468_364_884 / 18.7  # twitter map, 18 MPI ranks


def _probe_hardware(timeout_s: int = 180) -> bool:
    """True when the default JAX backend initializes within the timeout.

    A tunneled TPU plugin can hang backend init indefinitely when the
    tunnel is down; probing in a subprocess lets the benchmark fall back
    to CPU (clearly labeled) instead of hanging the driver.
    """
    import subprocess

    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            capture_output=True, text=True, timeout=timeout_s)
        return proc.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def main() -> None:
    from sheep_tpu.cli.common import ensure_jax_platform
    ensure_jax_platform()  # honor JAX_PLATFORMS even under a forced plugin
    fell_back = False
    if os.environ.get("JAX_PLATFORMS", "") != "cpu" \
            and not os.environ.get("SHEEP_BENCH_NO_PROBE") \
            and not _probe_hardware():
        print("bench: hardware backend unreachable; falling back to CPU",
              file=sys.stderr)
        os.environ["JAX_PLATFORMS"] = "cpu"
        ensure_jax_platform()
        fell_back = True
    import jax
    import jax.numpy as jnp
    from sheep_tpu.ops import build_step
    from sheep_tpu.utils import rmat_edges

    platform = jax.devices()[0].platform
    on_accel = platform not in ("cpu",)
    log_n = int(os.environ.get("SHEEP_BENCH_LOG_N", "23" if on_accel else "18"))
    factor = int(os.environ.get("SHEEP_BENCH_EDGE_FACTOR", "8"))
    reps = int(os.environ.get("SHEEP_BENCH_REPS", "3"))
    n = 1 << log_n
    e = factor * n

    print(f"bench: platform={platform} n=2^{log_n} edges={e}", file=sys.stderr)
    tail, head = rmat_edges(log_n, e, seed=1)
    t = jax.device_put(jnp.asarray(tail, jnp.int32))
    h = jax.device_put(jnp.asarray(head, jnp.int32))

    # warmup / compile
    out = build_step(t, h, n)
    jax.block_until_ready(out)
    rounds = int(out[5])
    print(f"bench: fixpoint rounds={rounds}", file=sys.stderr)

    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = build_step(t, h, n)
        jax.block_until_ready(out)
        times.append(time.perf_counter() - t0)
    best = min(times)
    eps = e / best
    print(f"bench: times={['%.3f' % x for x in times]} best={best:.3f}s",
          file=sys.stderr)

    tag = "_cpu_fallback" if fell_back else ""
    print(json.dumps({
        "metric": f"device_build_edges_per_sec_rmat_n2^{log_n}_e{factor}x{tag}",
        "value": round(eps, 1),
        "unit": "edges/sec",
        "vs_baseline": round(eps / _BASELINE_EDGES_PER_SEC, 4),
    }))


if __name__ == "__main__":
    main()
