"""Benchmark: device elimination-forest build throughput (edges/sec).

Prints ONE JSON line on stdout (the driver contract).  The metric is
end-to-end edges/sec of the fused single-chip build step (degree histogram +
(degree,vid) sort + edge links + forest fixpoint + pst) on an R-MAT
power-law graph — the analog of the reference's load-free sort+map phases.
``vs_baseline`` compares against the reference's best aggregate MPI
throughput on twitter-2010: 1,468,364,884 edges / 18.7 s map = 78.5M edges/s
across 18 ranks (BASELINE.md, data/slurm-twitter/slurm-25.avg:15); the
north-star target is 10x that.

Robustness (round-2 lesson: one device fault at the default size produced an
empty BENCH file): each size runs in its OWN subprocess (``--one``), per-size
records stream to stderr as they complete, and the final stdout line is the
largest passing size — annotated with the whole sweep and the first faulting
size when one faults.  A crash can reduce coverage but can no longer erase
the result.  If the accelerator sweep yields NO records at all (round-3
lesson: the probe can pass and the tunnel still degrade minutes later,
hanging the first compile), the whole sweep reruns on CPU with the
``_cpu_fallback`` tag and the accelerator fault recorded as ``accel_fault``
— value 0 is never published while any backend can produce a number.

Env: SHEEP_BENCH_SIZES (csv of log2 sizes; default "16,18,20,22,23" on
accelerators, "16,18,20,22" on cpu), SHEEP_BENCH_LOG_N (single size override),
SHEEP_BENCH_PATHS (csv subset of "hybrid,device,host"; default is all
three on cpu but hybrid+host on accelerators — the pure-device path's
one-compile-per-slice-shape cost can eat a tunneled per-size budget, so
it is measured by its own watcher step instead),
SHEEP_BENCH_EDGE_FACTOR (default 8), SHEEP_BENCH_REPS (default 3),
SHEEP_BENCH_TIMEOUT (seconds per size, default 1500 — tunneled-backend
compiles run 30-130s per program and each size is a fresh process, so a
persistent jax compilation cache is also enabled under /tmp),
SHEEP_BENCH_STARTUP_TIMEOUT (seconds for a child to get past backend
init, default 300; a child that hasn't printed its platform marker by
then is recorded as ``backend_hang`` instead of eating the size timeout),
SHEEP_BENCH_NO_FALLBACK (suppress the labeled CPU rerun after an empty
accelerator sweep — for callers whose record is accelerator-or-nothing).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

_BASELINE_EDGES_PER_SEC = 1_468_364_884 / 18.7  # twitter map, 18 MPI ranks


def _last_onchip_pointer(search_dir: str | None = None) -> dict | None:
    """Headline of the newest committed on-chip sweep (TPU_BENCH_*.json),
    for embedding in a CPU-fallback record — VERDICT r04 item 5: a
    scoreboard reading only BENCH_r0N must still see that a real chip
    number exists.  Clearly labeled; never substituted into ``value``.
    """
    import glob
    best: tuple[str, dict] | None = None
    repo = search_dir or os.path.dirname(os.path.abspath(__file__))
    for path in glob.glob(os.path.join(repo, "TPU_BENCH*.json")):
        try:
            with open(path) as f:
                lines = f.read().strip().splitlines()
        except OSError:
            continue
        for line in reversed(lines):
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if not isinstance(rec, dict) or "value" not in rec:
                continue
            if "_cpu_fallback" in (rec.get("metric") or "") \
                    or rec.get("_partial"):
                continue
            utc = rec.get("_utc", "")
            if best is None or utc > best[1].get("_utc", ""):
                best = (os.path.basename(path), rec)
            break
    if best is None:
        return None
    src, rec = best
    return {"metric": rec.get("metric"), "value": rec.get("value"),
            "unit": rec.get("unit"), "vs_baseline": rec.get("vs_baseline"),
            "utc": rec.get("_utc"), "source": src,
            "note": "prior committed on-chip sweep, NOT this run's "
                    "measurement (this run fell back to CPU)"}


def _probe_hardware(timeout_s: int = 180) -> str | None:
    """The default backend's platform name, or None when it won't come up.

    A tunneled TPU plugin can hang backend init indefinitely when the
    tunnel is down; probing in a subprocess lets the benchmark fall back
    to CPU (clearly labeled) instead of hanging the driver.
    """
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            capture_output=True, text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return None
    if proc.returncode != 0:
        return None
    lines = proc.stdout.strip().splitlines()
    return lines[-1] if lines else None


def last_record(stdout) -> dict | None:
    """Newest parseable JSON line — children stream partial records
    after each measured path, so a timeout/crash mid-size still
    yields whatever completed."""
    if isinstance(stdout, bytes):
        stdout = stdout.decode(errors="replace")
    for line in reversed((stdout or "").strip().splitlines()):
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict) and "edges_per_sec" in rec:
            return rec
    return None


def run_sweep(sizes, run_child, timeout_s: int, startup_s: int,
              checkpoint=lambda sweep: None):
    """Walk the sizes through ``run_child`` and collect per-size records.

    ``run_child(log_n) -> (stdout, stderr, returncode, fault_kind|None)``
    is injected (subprocess in production, fakes in tests — this loop
    runs unattended inside the watcher's one hardware window per round,
    so its fault semantics are unit-tested).  Returns (sweep,
    first_fault); the sweep ends at the first fault EXCEPT a timeout
    whose child already streamed a headline record — that size is kept
    (marked partial) and the sweep continues (round-4 lesson: the first
    TPU window's whole sweep died at 2^16 because the pure-device path's
    per-slice compiles outlived the budget after the hybrid number was
    already in).  ``checkpoint(sweep)`` is called after every appended
    record so a killed parent still leaves the sizes that finished.
    """
    sweep: list[dict] = []
    first_fault: dict | None = None
    for log_n in sizes:
        rec = None
        stdout, stderr, rc_child, fault_kind = run_child(log_n)
        if fault_kind is not None:
            if stderr:
                sys.stderr.write(stderr)
            budget = startup_s if fault_kind == "backend_hang" \
                else timeout_s
            print(f"bench: n=2^{log_n} {fault_kind.upper()} "
                  f"after {budget}s", file=sys.stderr)
            rec = last_record(stdout)
            if fault_kind == "timeout" and rec is not None:
                rec["partial"] = True
                sweep.append(rec)
                checkpoint(sweep)
                print(f"bench: n=2^{log_n} -> "
                      f"{rec['edges_per_sec']:.0f} edges/s "
                      f"(headline path done; secondary cut)",
                      file=sys.stderr)
                continue
            first_fault = {"log_n": log_n, "error": fault_kind}
        else:
            sys.stderr.write(stderr)
            rec = last_record(stdout)
            if rc_child != 0:
                err = (stderr or "").strip().splitlines()
                first_fault = {"log_n": log_n,
                               "error": err[-1][:300] if err else "crash"}
                print(f"bench: n=2^{log_n} FAULT rc={rc_child}",
                      file=sys.stderr)
            elif rec is None:
                first_fault = {"log_n": log_n,
                               "error": "unparseable child output"}
                print(f"bench: n=2^{log_n} produced no record",
                      file=sys.stderr)
        if rec is not None:
            if first_fault is not None:
                rec["partial"] = True  # some paths of this size were lost
            sweep.append(rec)
            print(f"bench: n=2^{log_n} -> "
                  f"{rec['edges_per_sec']:.0f} edges/s "
                  f"({rec['rounds']} rounds, best {rec['best_s']}s)",
                  file=sys.stderr)
            checkpoint(sweep)
        if first_fault is not None:
            break
    return sweep, first_fault


def _wanted_paths(platform: str | None = None) -> list[str] | None:
    """Validated SHEEP_BENCH_PATHS (csv subset of hybrid,device,host).

    Unset defaults by platform: everything on cpu (where the secondary
    paths are cheap), hybrid+host on accelerators — the pure-device path
    compiles one program per power-of-two slice shape, which on a
    tunneled backend (30-130s per compile) can eat a whole per-size
    budget for a secondary number (it gets its own watcher step
    instead).  Called in main() with platform=None BEFORE any backend
    work so an explicit-value typo fails in under a second, not after a
    full sweep of per-size children each paying backend init + data gen
    + upload; returns None there when the choice is platform-deferred.
    """
    raw = os.environ.get("SHEEP_BENCH_PATHS", "")
    if not raw.strip():
        if platform is None:
            return None  # resolved per child once the platform is known
        return ["hybrid", "device", "host"] if platform == "cpu" \
            else ["hybrid", "host"]
    wanted = [p.strip() for p in raw.split(",") if p.strip()]
    known = {"hybrid", "device", "host"}
    if set(wanted) - known or not set(wanted) & {"hybrid", "device"}:
        print(f"bench: SHEEP_BENCH_PATHS={','.join(wanted)!r} must be a "
              f"subset of {sorted(known)} and include hybrid or device",
              file=sys.stderr)
        sys.exit(2)
    return wanted


def _run_one(log_n: int) -> dict:
    """Measure one size in this process; returns the result record.

    Two paths are timed end-to-end:
      device — prepare_links + the chunked hosted fixpoint, everything on
               the accelerator (parent materializes on device);
      hybrid — the flagship graph2tree pipeline: device reduction rounds
               kill ~90% of links, the host C++ union-find finishes
               (ops/build.py build_graph_hybrid).  Includes the transfer.
    The headline number is the faster of the two (both are full builds of
    the same bit-identical forest).
    """
    from sheep_tpu.cli.common import ensure_jax_platform
    ensure_jax_platform()
    import jax
    import jax.numpy as jnp
    from sheep_tpu.ops import (build_graph_hybrid, forest_fixpoint_hosted,
                               prepare_links)
    from sheep_tpu.utils import rmat_edges

    platform = jax.devices()[0].platform
    factor = int(os.environ.get("SHEEP_BENCH_EDGE_FACTOR", "8"))
    reps = int(os.environ.get("SHEEP_BENCH_REPS", "3"))
    n = 1 << log_n
    e = factor * n

    print(f"bench: platform={platform} n=2^{log_n} edges={e}", file=sys.stderr)
    # cache the synthetic graph across child processes (generation on the
    # 1-core host costs ~a minute at 2^23 — real per-size-timeout budget)
    # rmat16: namespace bumped with the uint16-entropy generator — a
    # stale cache from the float64 generator is a different graph and
    # would pass the length/range validation below
    cache = f"/tmp/rmat16_{log_n}_{factor}.npz"
    tail = head = None
    try:
        d = np.load(cache)
        tail, head = d["tail"], d["head"]
        # trust nothing from /tmp: wrong length or out-of-range vids mean
        # a stale/foreign file and would silently skew the published number
        if len(tail) != e or len(head) != e or \
                (e and max(int(tail.max()), int(head.max())) >= n):
            tail = head = None
    except Exception:  # missing, truncated, or foreign file
        pass
    if tail is None:
        try:
            os.unlink(cache)
        except OSError:
            pass
        tail, head = rmat_edges(log_n, e, seed=1)
        try:
            np.savez(f"{cache}.{os.getpid()}", tail=tail, head=head)
            os.replace(f"{cache}.{os.getpid()}.npz", cache)
        except OSError:
            pass
    t0 = time.perf_counter()
    t = jax.device_put(jnp.asarray(tail, jnp.int32))
    h = jax.device_put(jnp.asarray(head, jnp.int32))
    t.block_until_ready(), h.block_until_ready()
    h2d_s = time.perf_counter() - t0  # one-time edge upload (load phase)

    def device_build(perf=None):
        seq, pos, m, lo, hi, pst = prepare_links(t, h, n)
        parent, rounds = forest_fixpoint_hosted(lo, hi, n)
        # async dispatch on the tunneled backend: force completion with a
        # scalar fetch that depends on the whole parent array
        return int(jnp.max(parent)), rounds

    def hybrid_build(perf=None):
        # edges are device-resident (t, h) before the clock starts, same
        # as device_build: the reference's 78.5M edges/s baseline is the
        # MAP phase with the graph already in each rank's RAM (load and
        # sort are separate lines in data/slurm-twitter/slurm-25.avg) —
        # while the timed region here still includes the degree sort AND
        # the device->host fetch of the finished tree.  The one-time edge
        # upload runs ~15-25MB/s through the tunnel (scripts/
        # tunnel_probe.py) and is reported separately as ``h2d_s``.
        # after any real load phase the edges are resident in host RAM as
        # well as HBM; on accelerators the host copy lets the hybrid
        # recompute seq/pst host-side (bit-identical) instead of fetching
        # 2n*4B through the ~10MB/s tunnel, and on cpu it enables the
        # streaming handoff's host-seq prep (native counting-sort
        # sequence + device link mapping — ops/build.host_seq_mode)
        return build_graph_hybrid(t, h, n, host_edges=(tail, head),
                                  perf=perf)

    from sheep_tpu.utils.envinfo import env_capture
    rec = {"log_n": log_n, "edges": e, "platform": platform,
           "h2d_s": round(h2d_s, 4), "env": env_capture(platform)}

    wanted = _wanted_paths(platform)

    # hybrid first: it is the faster path, so if the per-size timeout cuts
    # the slower pure-device measurement short, the partial record printed
    # below still carries the headline-capable number (the parent parses
    # the LAST stdout line).
    for name, fn in (("hybrid", hybrid_build), ("device", device_build)):
        if name not in wanted:
            continue
        out = fn()  # warmup / compile (all chunk shapes)
        times = []
        perfs = []
        for _ in range(reps):
            p: dict = {}
            t0 = time.perf_counter()
            fn(p)
            times.append(time.perf_counter() - t0)
            perfs.append(p)
        best = min(times)
        rec[name] = {"best_s": round(best, 4),
                     "times": [round(x, 4) for x in times],
                     "edges_per_sec": round(e / best, 1)}
        # overlap/pipeline observability for on-chip interpretation: the
        # best rep's reduce+tail breakdown — the streaming windowed
        # handoff's per-window fetch/fold timers and overlap fraction
        # (reduce_and_finish_native), plus the legacy speculation
        # counters when the serial path ran
        best_perf = perfs[times.index(best)]
        if best_perf:
            rec[name]["perf"] = {k: v for k, v in best_perf.items()
                                 if k in ("loop_s", "fetch_tail_s",
                                          "overlap", "stream_mode",
                                          "fetch_windows", "fold_s",
                                          "window_fetch_s",
                                          "window_fold_s", "overlap_s",
                                          "overlap_frac",
                                          "handoff_links",
                                          "packed_handoff")
                                 or k.startswith("spec_")}
        if name == "device":
            rec[name]["rounds"] = int(out[1])
        if name == "hybrid":
            # flight-recorder A/B (ISSUE 10): one extra traced rep of
            # the same build — the record carries the measured tracing
            # overhead vs the untraced best, the per-phase rollup (the
            # ONE code path the overlap/fetch/fold splits come from),
            # and the wall reconciliation (top-level span coverage)
            rec[name]["trace_ab"] = _trace_ab(fn, best, log_n)
        print(f"bench: n=2^{log_n} {name}: {e / best:.0f} edges/s "
              f"(best {best:.3f}s)", file=sys.stderr)
        partial = dict(rec)
        _headline(partial)
        print(json.dumps(partial), flush=True)

    # transparency: the pure host-native path (graph2tree's serial build),
    # recorded but never the headline — the headline must exercise the
    # accelerator.  Measured AFTER the accelerator paths so a slow host
    # build can never consume the per-size budget before the headline
    # number has streamed (the round-4 window-1 failure shape).
    from sheep_tpu.core.forest import build_forest, native_or_none
    from sheep_tpu.core.sequence import degree_sequence
    if "host" in wanted and native_or_none("auto") is not None:
        def host_build():  # same scope as device/hybrid: sort + links + UF
            seq_host = degree_sequence(tail, head)
            build_forest(tail, head, seq_host, max_vid=n - 1)

        host_build()  # warmup (page in edge arrays, build the .so)
        host_times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            host_build()
            host_times.append(time.perf_counter() - t0)
        host_s = min(host_times)
        rec["host_native"] = {"best_s": round(host_s, 4),
                              "edges_per_sec": round(e / host_s, 1)}
        # threads_ab (round 14, SHEEP_BENCH_THREADS_AB=1): the same host
        # build under forced SHEEP_NATIVE_THREADS ∈ {1,2,4}, best-of-reps,
        # CRC-asserted bit-identical across T.  The dedicated acceptance
        # record is scripts/threadbench.py (own subprocess per arm); this
        # in-sweep arm rides the existing sizes so a committed BENCH
        # record carries the per-size thread scaling too.
        if os.environ.get("SHEEP_BENCH_THREADS_AB", "") == "1":
            import zlib
            prev_t = os.environ.get("SHEEP_NATIVE_THREADS")
            ab: dict = {}
            crcs = set()
            try:
                for t in (1, 2, 4):
                    os.environ["SHEEP_NATIVE_THREADS"] = str(t)
                    seq_host = degree_sequence(tail, head)
                    f = build_forest(tail, head, seq_host, max_vid=n - 1)
                    crcs.add((zlib.crc32(f.parent.tobytes()),
                              zlib.crc32(f.pst_weight.tobytes())))
                    times = []
                    for _ in range(reps):
                        t0 = time.perf_counter()
                        host_build()
                        times.append(time.perf_counter() - t0)
                    ab[f"t{t}_best_s"] = round(min(times), 4)
            finally:
                if prev_t is None:
                    os.environ.pop("SHEEP_NATIVE_THREADS", None)
                else:
                    os.environ["SHEEP_NATIVE_THREADS"] = prev_t
            ab["crc_identical"] = len(crcs) == 1
            assert ab["crc_identical"], "threads_ab arms diverged"
            rec["host_native"]["threads_ab"] = ab

    _headline(rec)
    # final stream line: the record including host_native (the parent and
    # the watcher salvage parse the LAST stdout line)
    print(json.dumps(rec), flush=True)
    return rec


def _trace_ab(fn, untraced_best_s: float, log_n: int) -> dict:
    """Run ``fn`` SHEEP_BENCH_REPS times with SHEEP_TRACE on; return the
    A/B record: best traced vs best untraced (best-vs-best — a single
    traced rep against an untraced best would charge run-to-run variance
    to the recorder), the in-memory phase rollup of the best rep, and
    the trace-file reconciliation (sum of top-level span durations vs
    the traced wall — the <=5% acceptance check of ISSUE 10)."""
    import tempfile
    from sheep_tpu.obs import trace as obs_trace

    reps = int(os.environ.get("SHEEP_BENCH_REPS", "3"))
    tdir = tempfile.mkdtemp(prefix="sheep-bench-trace-")
    prev = os.environ.get(obs_trace.ENV)
    times, paths, summaries = [], [], []
    try:
        for i in range(reps):
            tpath = os.path.join(tdir, f"hybrid_{log_n}_{i}.trace")
            os.environ[obs_trace.ENV] = tpath
            t0 = time.perf_counter()
            fn({})
            times.append(time.perf_counter() - t0)
            paths.append(tpath)
            summaries.append(obs_trace.trace_summary())
            obs_trace.close_recorder()
    finally:
        if prev is None:
            os.environ.pop(obs_trace.ENV, None)
        else:
            os.environ[obs_trace.ENV] = prev
    best_i = times.index(min(times))
    traced_s = times[best_i]
    out = {
        "traced_best_s": round(traced_s, 4),
        "traced_times": [round(x, 4) for x in times],
        "untraced_best_s": round(untraced_best_s, 4),
        "overhead_frac": round(traced_s / untraced_best_s - 1.0, 4)
        if untraced_best_s > 0 else 0.0,
        "summary": summaries[best_i],
    }
    try:
        records, _, _ = obs_trace.read_trace(paths[best_i], "repair")
        top = sum(float(r.get("dur", 0.0)) for r in records
                  if r.get("k") == "span" and r.get("par") is None)
        out["top_level_span_s"] = round(top, 4)
        out["wall_recon_frac"] = round(top / traced_s, 4) \
            if traced_s > 0 else 0.0
    except Exception as exc:  # a failed read must not sink the bench
        out["trace_read_error"] = f"{type(exc).__name__}: {exc}"
    return out


def _headline(rec: dict) -> None:
    """Fill the headline fields from whichever accelerator paths exist."""
    paths = [k for k in ("device", "hybrid") if k in rec]
    top = max(paths, key=lambda k: rec[k]["edges_per_sec"])
    rec["path"] = top
    rec["rounds"] = rec.get("device", {}).get("rounds", 0)
    rec["best_s"] = rec[top]["best_s"]
    rec["edges_per_sec"] = rec[top]["edges_per_sec"]
    rec["vs_baseline"] = round(
        rec[top]["edges_per_sec"] / _BASELINE_EDGES_PER_SEC, 4)


def main() -> None:
    _wanted_paths()  # fail fast on a config typo, before any backend work
    if len(sys.argv) > 2 and sys.argv[1] == "--one":
        # the per-path stream inside _run_one already printed the final
        # record; printing it again would just duplicate the line
        _run_one(int(sys.argv[2]))
        return

    from sheep_tpu.cli.common import ensure_jax_platform
    ensure_jax_platform()  # honor JAX_PLATFORMS even under a forced plugin
    fell_back = False

    def _force_cpu():
        """Point all future children at the CPU backend.  Popping the
        plugin gate is load-bearing: a sick-but-listening tunnel can block
        interpreter STARTUP in the plugin-registering sitecustomize even
        under JAX_PLATFORMS=cpu (observed: ~7min hangs), so fallback
        children must skip tunnel registration entirely."""
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)

    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        platform = "cpu"
        _force_cpu()  # children never need the tunnel on cpu
    elif os.environ.get("SHEEP_BENCH_NO_PROBE"):
        # probe skipped on operator's say-so: assume the accelerator is up
        platform = "accel"
    else:
        platform = _probe_hardware()
        if platform is None:
            print("bench: hardware backend unreachable; falling back to CPU",
                  file=sys.stderr)
            _force_cpu()
            fell_back = True
            platform = "cpu"
    on_accel = platform != "cpu"

    factor = int(os.environ.get("SHEEP_BENCH_EDGE_FACTOR", "8"))
    if os.environ.get("SHEEP_BENCH_LOG_N"):
        sizes = [int(os.environ["SHEEP_BENCH_LOG_N"])]
    else:
        default = "16,18,20,22,23" if on_accel else "16,18,20,22"
        sizes = [int(s) for s in
                 os.environ.get("SHEEP_BENCH_SIZES", default).split(",")]
    timeout_s = int(os.environ.get("SHEEP_BENCH_TIMEOUT", "1500"))
    # amortize the slow per-process compiles across children (harmless
    # where the backend ignores the persistent cache); under $HOME, not a
    # guessable /tmp path a foreign user could pre-own or poison
    cache_dir = os.path.join(os.path.expanduser("~"), ".cache", "sheep_jax")
    try:
        os.makedirs(cache_dir, exist_ok=True)
        os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", cache_dir)
    except OSError:
        pass

    progress_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "bench_progress.json")
    try:
        os.unlink(progress_path)  # never leave a stale sidecar looking live
    except OSError:
        pass
    # A sick tunnel blocks child interpreters before they print anything
    # (backend init retry loop).  Give each child a short budget to produce
    # its FIRST stderr line — printed right after backend init, before any
    # compile — so a backend hang costs minutes, not the full per-size
    # timeout.
    startup_s = int(os.environ.get("SHEEP_BENCH_STARTUP_TIMEOUT", "300"))

    def run_child(log_n: int):
        """Returns (stdout, stderr, returncode, fault_kind|None)."""
        import tempfile
        with tempfile.TemporaryFile() as out_f, \
                tempfile.TemporaryFile() as err_f:
            proc = subprocess.Popen(
                [sys.executable, os.path.abspath(__file__),
                 "--one", str(log_n)],
                stdout=out_f, stderr=err_f)
            t0 = time.monotonic()
            fault = None
            saw_marker = False
            scanned = 0  # stderr bytes checked for the marker so far

            def marker_seen() -> bool:
                # the marker prints right after jax.devices() returns;
                # plugin warnings appear BEFORE the blocking init, so
                # any-bytes is not a liveness signal.  Scan incrementally
                # (only newly appended bytes, with overlap for a marker
                # split across polls) and latch — pread keeps the child's
                # shared write offset untouched.
                nonlocal scanned
                fd = err_f.fileno()
                size = os.fstat(fd).st_size
                while scanned < size:
                    start = max(0, scanned - 32)  # overlap a split marker
                    chunk = os.pread(fd, min(1 << 20, size - start), start)
                    scanned = start + len(chunk)
                    if b"bench: platform" in chunk:
                        return True
                    if not chunk:
                        break
                return False

            while True:
                rc = proc.poll()
                if rc is not None:
                    break
                elapsed = time.monotonic() - t0
                saw_marker = saw_marker or marker_seen()
                if elapsed > timeout_s:
                    fault = "timeout"
                elif elapsed > startup_s and not saw_marker:
                    fault = "backend_hang"
                if fault:
                    proc.kill()
                    proc.wait()
                    break
                time.sleep(1)
            out_f.seek(0)
            err_f.seek(0)
            return (out_f.read().decode(errors="replace"),
                    err_f.read().decode(errors="replace"),
                    proc.returncode, fault)

    def _checkpoint(sweep: list[dict]) -> None:
        # Sidecar survives the benchmark being killed mid-sweep; it must
        # carry the fallback marker so a mid-fallback kill can't pass CPU
        # numbers off as accelerator results.
        try:
            with open(progress_path, "w") as f:
                json.dump({"sweep": sweep,
                           "cpu_fallback": fell_back,
                           "accel_fault": accel_fault}, f)
        except OSError:
            pass

    accel_fault: dict | None = None
    sweep, first_fault = run_sweep(sizes, run_child, timeout_s, startup_s,
                                   _checkpoint)
    if not sweep and on_accel \
            and not os.environ.get("SHEEP_BENCH_NO_FALLBACK"):
        # The probe can pass and the tunnel still degrade minutes later
        # (observed: backend init OK, first compile hangs).  An empty
        # accelerator sweep must not publish value 0 — rerun on CPU,
        # clearly labeled, and carry the accelerator fault alongside.
        # SHEEP_BENCH_NO_FALLBACK suppresses the rerun for callers whose
        # record is accelerator-or-nothing (the watcher's 2^24 stretch
        # step: a 134M-edge CPU build would burn the step budget for an
        # unusable record).
        accel_fault = first_fault
        print("bench: accelerator sweep produced no records; "
              "falling back to CPU", file=sys.stderr)
        _force_cpu()
        fell_back = True
        if not os.environ.get("SHEEP_BENCH_LOG_N") \
                and not os.environ.get("SHEEP_BENCH_SIZES"):
            sizes = [s for s in sizes if s <= 22]
        sweep, first_fault = run_sweep(sizes, run_child, timeout_s,
                                       startup_s, _checkpoint)

    tag = "_cpu_fallback" if fell_back else ""
    last_onchip = _last_onchip_pointer() if fell_back else None
    if not sweep:
        # Even a total failure must yield a parseable record.
        rec = {"metric": f"device_build_edges_per_sec{tag}",
               "value": 0.0, "unit": "edges/sec", "vs_baseline": 0.0,
               "fault": first_fault, "accel_fault": accel_fault}
        if last_onchip is not None:
            rec["last_onchip"] = last_onchip
        print(json.dumps(rec))
        sys.exit(1)
    from sheep_tpu.utils.envinfo import env_capture
    top = max(sweep, key=lambda r: r["log_n"])
    out = {
        "metric": (f"device_build_edges_per_sec_rmat_n2^{top['log_n']}"
                   f"_e{factor}x{tag}"),
        "value": top["edges_per_sec"],
        "unit": "edges/sec",
        "vs_baseline": top["vs_baseline"],
        # parent-side capture: per-size records carry their own child
        # capture; this one attributes the sweep-level conditions (the
        # VERDICT r05 item-5 driver-vs-clean attribution)
        "env": env_capture("cpu" if not on_accel else None),
        "sweep": [{k: r[k] for k in
                   ("log_n", "edges_per_sec", "rounds", "best_s", "path",
                    "h2d_s", "partial", "hybrid", "device", "host_native",
                    "env")
                   if k in r}
                  for r in sweep],
    }
    if first_fault is not None:
        out["first_fault"] = first_fault
    if accel_fault is not None:
        out["accel_fault"] = accel_fault
    if last_onchip is not None:
        out["last_onchip"] = last_onchip
    print(json.dumps(out))


if __name__ == "__main__":
    main()
