// sheep_native: C++ host runtime for the hot sequential loops.
//
// The reference implements these in C++ (lib/jtree.cpp insert loop,
// lib/unionfind.h find/unify, lib/jnode.cpp merge, lib/partition.cpp
// forwardPartition).  The TPU framework keeps the same split: batched
// fixed-shape work runs on device (sheep_tpu.ops), while the inherently
// sequential pointer-chasing passes run here, vectorized over dense arrays
// instead of the reference's per-object structures.
//
// API style: plain C functions over caller-allocated numpy buffers
// (ctypes-friendly; no pybind11 in this toolchain).  All functions return 0
// on success, negative on error.

#include <chrono>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <algorithm>
#include <memory>
#include <vector>

#ifdef _OPENMP
#include <omp.h>
#endif

namespace {
constexpr uint32_t kInvalid = 0xFFFFFFFFu;

// ---------------------------------------------------------------------------
// Threading (round-14).  SHEEP_NATIVE_THREADS (resolved by the governor
// from SHEEP_LEG_CORES/affinity, resources/governor.py) arms an OpenMP
// path in the hot kernels; unset/1, a build without OpenMP, or an input
// below the engagement floor all take the unchanged serial code.  The
// parallel decomposition is the SAME associative primitive the whole
// repo leans on: every thread folds a contiguous slice of the input
// into a PRIVATE partial forest / histogram, and the partials merge
// deterministically — histogram adds commute, and partial forests over
// one sequence merge through the existing grouping+adoption to the
// unique forest of the union (lib/jnode.cpp:174-201; the tournament
// bracket's exactness argument).  Outputs are therefore BIT-IDENTICAL
// to the single-thread build for every thread count — the merge is not
// a heuristic, it is the same fold.
// ---------------------------------------------------------------------------

constexpr int kMaxThreads = 64;
//: links/records below which the per-thread O(n) partial tables cost
//: more than the slice saves (SHEEP_NATIVE_THREAD_FLOOR overrides —
//: tests force 0 to engage the threaded path on small inputs)
constexpr int64_t kThreadFloor = int64_t{1} << 15;

static inline int affinity_cores() {
#ifdef _OPENMP
  // affinity-aware available-processor count: forcing more compute
  // threads than granted cores buys nothing and costs real work (the
  // partial merges are not free), so the resolver clamps to it unless
  // SHEEP_NATIVE_OVERSUB=1 explicitly opts into oversubscription (the
  // determinism tests and the informational bench arm use that to
  // exercise the parallel code path on a 1-core host)
  return omp_get_num_procs();
#else
  return 1;
#endif
}

static inline int resolve_threads() {
#ifdef _OPENMP
  const char* v = std::getenv("SHEEP_NATIVE_THREADS");
  if (!v || !v[0]) return 1;
  int t = std::atoi(v);
  if (t <= 1) return 1;
  const char* over = std::getenv("SHEEP_NATIVE_OVERSUB");
  if (!(over && over[0] == '1')) {
    const int cores = affinity_cores();
    if (t > cores) t = cores;
  }
  return t > kMaxThreads ? kMaxThreads : (t < 1 ? 1 : t);
#else
  return 1;  // compiled without OpenMP: always serial, report so
#endif
}

static inline int64_t thread_floor() {
  const char* v = std::getenv("SHEEP_NATIVE_THREAD_FLOOR");
  if (v && v[0]) {
    long long f = std::atoll(v);
    if (f >= 0) return (int64_t)f;
  }
  return kThreadFloor;
}

// Threads the NEXT kernel call over m records will actually use: the
// resolved count, gated by the engagement floor and capped so every
// slice still carries real work.
static inline int threads_for_work(int64_t m) {
  int t = resolve_threads();
  if (t <= 1 || m < thread_floor()) return 1;
  while (t > 1 && m / t < 256) --t;
  return t;
}

// Per-call thread telemetry, read by the Python bindings right after a
// kernel returns (sheep_last_thread_stats) and annotated onto the
// native.* flight-recorder spans.  thread_local on the CALLING thread,
// so concurrent Python-level callers never smear each other; OpenMP
// workers write distinct slots through the captured pointer.
struct ThreadStats {
  int used = 1;
  double busy[kMaxThreads] = {};
};
static thread_local ThreadStats g_tstats;

// Per-caller-thread slab arena for the per-thread partial tables (8n+8
// bytes per OpenMP thread: a union-find + parent pair, or an int64
// histogram partial — exactly the 8n-per-extra-thread the governor
// prices as RESIDENT).  Persistent across kernel calls on purpose: the
// streaming folds call the kernel once per block, and re-faulting ~8n
// of freshly mmap'd pages per thread per block was measured to be most
// of the forced-thread overhead on the 1-core host.  Grows, never
// shrinks; freed when the calling thread dies.
struct ThreadArena {
  int64_t units = 0;  // uint32 units per slab
  int slots = 0;
  std::unique_ptr<uint32_t[]> buf;
  uint32_t* ensure(int64_t n, int T) {
    const int64_t need = 2 * n + 2;
    if (units < need || slots < T) {
      buf.reset(new uint32_t[(size_t)(need * T)]);
      units = need;
      slots = T;
    }
    return buf.get();
  }
  uint32_t* slab(int t) { return buf.get() + (size_t)t * (size_t)units; }
};
static thread_local ThreadArena g_arena;

// Per-thread (h, kid) capture lists of the bucket-run fold — same
// persistence story as the arena: capacity survives across calls so
// the per-block folds stop re-faulting fresh pages every block.
static thread_local std::vector<std::vector<uint64_t>> g_caps;

static inline void tstats_reset() {
  g_tstats.used = 1;
  std::memset(g_tstats.busy, 0, sizeof(g_tstats.busy));
}

static inline double mono_s() {
  return std::chrono::duration<double>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

// SHEEP_NATIVE_TIME=1: per-phase stderr timings for the hot kernels
// (dev observability; costs two getenv + clock reads per call when off).
static inline bool time_enabled() {
  const char* v = std::getenv("SHEEP_NATIVE_TIME");
  return v && v[0] == '1';
}

struct PhaseTimer {
  bool on;
  std::chrono::steady_clock::time_point t;
  const char* tag;
  explicit PhaseTimer(const char* tag) : on(time_enabled()), tag(tag) {
    if (on) t = std::chrono::steady_clock::now();
  }
  void mark(const char* phase) {
    if (!on) return;
    auto now = std::chrono::steady_clock::now();
    std::fprintf(stderr, "native %s.%s %.3fs\n", tag, phase,
                 std::chrono::duration<double>(now - t).count());
    t = now;
  }
};

// Find over a flat uint32 union-find array whose representative is the
// *max-position* element of each component (the later-in-sequence vertex
// survives, mirroring lib/unionfind.h:82-102 unify(lesser, greater)).
// Two-phase: a read-only walk to the root, then full path compression —
// the write-free <=1-hop fast path matters on the latency-bound bench
// host, where path-halving's unconditional store dirtied a cache line
// (RFO traffic) even for chains it could not shorten.  Returns the same
// root as any compression flavor (roots are never rewritten), so
// outputs are unchanged.
static inline uint32_t uf_find(uint32_t* uf, uint32_t x) {
  uint32_t r = uf[x];
  if (r == x) return x;
  uint32_t rr = uf[r];
  if (rr == r) return r;  // 2 reads, 0 writes — the overwhelming cases
  do {
    r = rr;
    rr = uf[r];
  } while (rr != r);
  while (uf[x] != r) {  // full compression of the (rare) long chain
    uint32_t nx = uf[x];
    uf[x] = r;
    x = nx;
  }
  return r;
}

// ---------------------------------------------------------------------------
// Cache-blocked kernels (round-6).
//
// Measured on the 1-core bench host, the forest build decays 43M -> 13.3M
// edges/s from 2^16 to 2^23 (CPUBENCH23_r05).  Phase timers
// (SHEEP_NATIVE_TIME=1) put the loss in two places at 2^23: the
// counting-sort group fill (1.07s -- a random cursor RMW over a 67MB
// offs table plus a random 4-byte store into the 268MB lo_by_hi array,
// which outlives the LLC) and the adoption loop (1.0s -- the union-find
// chase plus a random parent_out read per link).  Plain independent
// random loads on this host cost a flat ~7.8ns (260MB L3; prefetch and
// hugepages were measured to change nothing), so the wins come from
// REMOVING random touches and passes, not from streaming:
//
//   * the group fill becomes a two-phase split: links partition into
//     K = 128 EQUAL-COUNT buckets (quantiles of the per-h prefix
//     table) as packed (h << 32 | lo) records — each bucket's stream
//     write is sequential and the cursor table lives in L1 — and each
//     bucket then scatters against its own small slice of the prefix
//     table into a reused ~linked/K-sized group buffer, with the
//     adoption scan fused right behind while the bucket's lo values
//     are still warm;
//   * every loop stays a TIGHT single-purpose pass: fusing several
//     random-access streams into one loop body was measured up to 2x
//     slower (it starves the out-of-order window's memory-level
//     parallelism), and more than ~128 concurrent write streams
//     measured up to 3x slower per record — both shaped this design;
//   * the adoption loop drops its random parent_out read: a root
//     returned by find has parent set iff it was adopted in the
//     CURRENT group (uf chains are strictly increasing, so a root
//     adopted in an earlier group can never be found again), and the
//     current group's few adoptions sit in a hot vector a linear scan
//     checks faster than one L3 miss (large hub groups fall back to
//     the parent check to stay O(len)).
//
// Everything is order-stable, so outputs are bit-identical to the
// unblocked path (kept for small inputs and the SHEEP_NATIVE_BLOCKED=0
// A/B escape hatch).
// ---------------------------------------------------------------------------

constexpr int kMaxBuckets = 128;  // write-stream cap (measured knee)

static inline bool blocked_enabled() {
  const char* v = std::getenv("SHEEP_NATIVE_BLOCKED");
  return !(v && v[0] == '0' && v[1] == '\0');
}

static inline bool use_blocked(int64_t m, int64_t n) {
  // below one bucket of vertices (or trivially few records) the plain
  // counting sort is already cache-resident and the extra pass is waste
  // m < 2^31: the blocked kernel's int32 prefix table must fit the
  // link count (larger inputs take the int64 unblocked path)
  return blocked_enabled() && n > (int64_t{1} << 16) &&
         m > (int64_t{1} << 16) && m < (int64_t{1} << 31) - 2;
}

// One hi-group's adoption scan (the reference's per-vertex edge scan,
// lib/jtree.cpp:34-55): shared verbatim by the blocked and unblocked
// paths so their semantics cannot drift.  Unions are deferred to the end
// of the group (adoptKids, lib/jnode.h:184-188).  The already-adopted
// check scans the group's own adoption list while it is small: a found
// root r has uf[r] == r, uf chains are strictly increasing and adoption
// at an earlier group set uf[r] to that group's (larger) vertex forever,
// so parent_out[r] != kInvalid can ONLY mean "adopted earlier in this
// group" -- which the hot list answers without a ~7.8ns random read.
template <bool kPre>
static inline void adopt_group_impl(const uint32_t* grp, int64_t len,
                                    uint32_t hh, uint32_t* uf,
                                    uint32_t* parent_out, uint32_t* pre_out,
                                    std::vector<uint32_t>& adopted,
                                    std::vector<uint64_t>* cap) {
  adopted.clear();
  for (int64_t i = 0; i < len; ++i) {
    if (i + 8 < len) __builtin_prefetch(&uf[grp[i + 8]]);
    uint32_t r = uf_find(uf, grp[i]);
    if (kPre) ++pre_out[r];
    if (r == hh) continue;
    bool seen;
    if (adopted.size() <= 48) {
      seen = false;
      for (uint32_t a : adopted)
        if (a == r) { seen = true; break; }
    } else {  // hub group: the list outgrew one cache miss's worth
      seen = parent_out[r] != kInvalid;
    }
    if (!seen) {
      parent_out[r] = hh;  // adopt: lib/jnode.h:158-162
      adopted.push_back(r);
    }
  }
  for (uint32_t r : adopted) uf[r] = hh;
  if (cap)  // the threaded arm's (h, kid) capture — already h-ascending
    for (uint32_t r : adopted)
      cap->push_back(((uint64_t)hh << 32) | r);
}

static inline void adopt_group(const uint32_t* grp, int64_t len, uint32_t hh,
                               uint32_t* uf, uint32_t* parent_out,
                               uint32_t* pre_out,
                               std::vector<uint32_t>& adopted,
                               std::vector<uint64_t>* cap = nullptr) {
  if (pre_out)
    adopt_group_impl<true>(grp, len, hh, uf, parent_out, pre_out, adopted,
                           cap);
  else
    adopt_group_impl<false>(grp, len, hh, uf, parent_out, pre_out, adopted,
                            cap);
}

static inline uint32_t rec_lo(uint64_t r) { return (uint32_t)r; }
static inline int64_t rec_h(uint64_t r) { return (int64_t)(r >> 32); }

static void blocked_group_adopt(const uint32_t* lo, const uint32_t* hi,
                                int64_t m, int64_t n, uint32_t* pst_out,
                                uint32_t* uf, uint32_t* parent_out,
                                uint32_t* pre_out, PhaseTimer& pt);

// Serial grouping+adoption dispatch (the one place the blocked/plain
// choice lives for a given slice of links).
static inline void group_adopt_dispatch(const uint32_t* lo, const uint32_t* hi,
                                        int64_t m, int64_t n,
                                        uint32_t* pst_out, uint32_t* uf,
                                        uint32_t* parent_out,
                                        uint32_t* pre_out, PhaseTimer& pt);

static void threaded_group_adopt(const uint32_t* lo, const uint32_t* hi,
                                 int64_t m, int64_t n, uint32_t* pst_out,
                                 uint32_t* uf, uint32_t* parent_out, int T,
                                 PhaseTimer& pt);

// Unblocked grouping + adoption (counting sort by hi, then the shared
// adopt_group): the small-input path of sheep_build_forest, factored so
// the resumable block fold below reuses it verbatim.
static void plain_group_adopt(const uint32_t* lo, const uint32_t* hi,
                              int64_t m, int64_t n, uint32_t* pst_out,
                              uint32_t* uf, uint32_t* parent_out,
                              uint32_t* pre_out, PhaseTimer& pt) {
  if (pst_out)
    for (int64_t i = 0; i < m; ++i) ++pst_out[lo[i]];
  pt.mark("pst");
  std::vector<int64_t> offs((size_t)n + 1, 0);
  for (int64_t i = 0; i < m; ++i)
    if (hi[i] < (uint64_t)n) ++offs[hi[i] + 1];
  pt.mark("count");
  for (int64_t h = 0; h < n; ++h) offs[h + 1] += offs[h];
  int64_t linked = offs[n];
  std::vector<uint32_t> lo_by_hi((size_t)linked);
  {
    std::vector<int64_t> cur(offs.begin(), offs.end() - 1);
    for (int64_t i = 0; i < m; ++i)
      if (hi[i] < (uint64_t)n) lo_by_hi[(size_t)cur[hi[i]]++] = lo[i];
  }
  pt.mark("scatter");
  std::vector<uint32_t> adopted;
  for (int64_t h = 0; h < n; ++h)
    adopt_group(lo_by_hi.data() + offs[h], offs[h + 1] - offs[h],
                (uint32_t)h, uf, parent_out, pre_out, adopted);
  pt.mark("adopt");
}

// One block of the resumable link fold — sheep_build_forest's loop split
// at the block boundary (the streaming windowed handoff, round-7).
// Blocks must arrive in ascending-hi order: every linked record (hi < n)
// must satisfy hi >= lo_bound, where lo_bound is the previous block's
// return value (0 for the first).  An equal-hi group MAY split across
// adjacent blocks: within one hi-group the adoption order cannot change
// parent (distinct component roots each adopt exactly once, repeats are
// no-ops, and a root adopted by the first half is found AS h by the
// second half's uf chase — the same no-op), so a boundary landing inside
// a group is exact.  ``accumulate_pst`` adds 1 to pst_out[lo] per record
// (pst-only links hi >= n included) — exact only when the blocks
// together carry the ORIGINAL link multiset; chunk-rewritten callers
// pass their prep-time pst at begin instead.  Returns the new bound
// (max linked hi seen), -3 on a malformed lo, -7 on an out-of-order
// block (which would silently build a different forest).
static int64_t fold_links_block(const uint32_t* lo, const uint32_t* hi,
                                int64_t m, int64_t n, int64_t lo_bound,
                                bool accumulate_pst, uint32_t* uf,
                                uint32_t* parent_out, uint32_t* pst_out,
                                uint32_t* pre_out, PhaseTimer& pt) {
  // pre accounting is inherently order-within-the-whole-build (the root
  // BEFORE adoption), so the threaded partial decomposition keeps off it
  const int T = pre_out ? 1 : threads_for_work(m);
  int64_t mx = lo_bound;
  bool bad_lo = false, bad_order = false;
#ifdef _OPENMP
  if (T > 1) {
#pragma omp parallel for num_threads(T) schedule(static) \
    reduction(max : mx) reduction(|| : bad_lo, bad_order)
    for (int64_t i = 0; i < m; ++i) {
      if (lo[i] >= (uint64_t)n) bad_lo = true;
      if (hi[i] < (uint64_t)n) {
        if ((int64_t)hi[i] < lo_bound) bad_order = true;
        if ((int64_t)hi[i] > mx) mx = (int64_t)hi[i];
      }
    }
  } else
#endif
  {
    for (int64_t i = 0; i < m; ++i) {
      if (lo[i] >= (uint64_t)n) return -3;  // malformed link
      if (hi[i] < (uint64_t)n) {
        if ((int64_t)hi[i] < lo_bound) return -7;  // out-of-order block
        if ((int64_t)hi[i] > mx) mx = (int64_t)hi[i];
      }
    }
  }
  if (bad_lo) return -3;
  if (bad_order) return -7;
  pt.mark("validate");
  if (T > 1) {
    threaded_group_adopt(lo, hi, m, n,
                         accumulate_pst ? pst_out : nullptr, uf,
                         parent_out, T, pt);
  } else {
    group_adopt_dispatch(lo, hi, m, n,
                         accumulate_pst ? pst_out : nullptr, uf,
                         parent_out, pre_out, pt);
  }
  return mx;
}

// Grouping + adoption of (lo, hi<n) links, shared by sheep_build_forest
// and the fused sheep_build_forest_edges.  One global per-h count
// builds the prefix table; EQUAL-COUNT bucket boundaries come from its
// quantiles -- equal-SPAN buckets were measured useless on power-law
// inputs, where ONE 2^16-position window held 79% of all links at 2^23
// and its scatter degenerated back to the cache-hostile global fill.
// With ~linked/128 links per bucket, a hub bucket's position span is
// tiny (its slice of the prefix table and its group buffer are L2-
// resident) while a sparse bucket's wide span carries few links.  The
// per-link bucket lookup is O(1): a 32KB block table (h >> 10) gives
// the starting bucket and a short forward walk crosses any remaining
// boundaries.  ``pst_out`` non-null also accumulates the tree-link pst
// histogram inside the partition pass's read loop (its own tight pass
// upstream would reread the full link arrays).
static void blocked_group_adopt(const uint32_t* lo, const uint32_t* hi,
                                int64_t m, int64_t n, uint32_t* pst_out,
                                uint32_t* uf, uint32_t* parent_out,
                                uint32_t* pre_out, PhaseTimer& pt) {
  // int32 prefix table: the count pass's random increments measured
  // ~27% cheaper on 4-byte counters than 8-byte (narrower line
  // footprint); use_blocked guarantees m < 2^31 so the prefix fits
  std::vector<int32_t> offs((size_t)n + 1, 0);
  for (int64_t i = 0; i < m; ++i)
    if (hi[i] < (uint64_t)n) ++offs[hi[i] + 1];
  if (pst_out)
    for (int64_t i = 0; i < m; ++i) ++pst_out[lo[i]];
  pt.mark("count+pst");
  for (int64_t h = 0; h < n; ++h) offs[h + 1] += offs[h];
  const int64_t linked = offs[n];
  // equal-count boundaries (a single h never splits: a bucket is just
  // allowed to run over when one group alone exceeds the target)
  const int64_t K = kMaxBuckets;
  std::vector<int64_t> bound((size_t)K + 1);
  bound[0] = 0;
  bound[(size_t)K] = n;
  for (int64_t b = 1; b < K; ++b)
    bound[(size_t)b] = std::lower_bound(offs.begin(), offs.begin() + n + 1,
                                        (int32_t)(b * linked / K)) -
                       offs.begin();
  // per-h bucket-id table (uint8; K <= 128): one sequential O(n) build,
  // then the per-link lookup is a single gather that hub-heavy inputs
  // keep L1/L2-hot (a boundary-walk lookup was measured 4x slower —
  // boundaries CLUSTER inside the hub windows, exactly where most
  // links land, so walks there crossed dozens of boundaries per link)
  std::vector<uint8_t> bucket_of((size_t)n);
  for (int64_t b = 0; b < K; ++b)
    std::memset(bucket_of.data() + bound[(size_t)b], (int)b,
                (size_t)(bound[(size_t)b + 1] - bound[(size_t)b]));
  std::vector<int64_t> bstart((size_t)K + 1);
  for (int64_t b = 0; b <= K; ++b) bstart[(size_t)b] = offs[bound[(size_t)b]];
  std::unique_ptr<uint64_t[]> recs(new uint64_t[(size_t)linked]);
  {
    std::vector<int64_t> bcur(bstart.begin(), bstart.end() - 1);
    for (int64_t i = 0; i < m; ++i) {
      const uint32_t h = hi[i];
      if (h >= (uint64_t)n) continue;
      recs[(size_t)bcur[bucket_of[h]]++] = ((uint64_t)h << 32) | lo[i];
    }
  }
  pt.mark("partition");
  std::vector<uint32_t> grouped, adopted;
  double scat_s = 0, adopt_s = 0;
  const bool timed = time_enabled();
  for (int64_t b = 0; b < K; ++b) {
    const int64_t s = bstart[(size_t)b], t = bstart[(size_t)b + 1];
    if (s == t) continue;
    auto t0 = timed ? std::chrono::steady_clock::now()
                    : std::chrono::steady_clock::time_point();
    if ((int64_t)grouped.size() < t - s) grouped.resize((size_t)(t - s));
    // offs[h] is the global start of group h; mutate it as the scatter
    // cursor, leaving offs[h] == end of group h for the boundary walk
    for (int64_t i = s; i < t; ++i)
      grouped[(size_t)(offs[rec_h(recs[(size_t)i])]++ - s)] =
          rec_lo(recs[(size_t)i]);
    auto t1 = timed ? std::chrono::steady_clock::now()
                    : std::chrono::steady_clock::time_point();
    int64_t prev = s;
    for (int64_t h = bound[(size_t)b]; h < bound[(size_t)b + 1]; ++h) {
      const int64_t end = offs[h];
      if (end > prev)
        adopt_group(grouped.data() + (prev - s), end - prev, (uint32_t)h,
                    uf, parent_out, pre_out, adopted);
      prev = end;
    }
    if (timed) {
      auto t2 = std::chrono::steady_clock::now();
      scat_s += std::chrono::duration<double>(t1 - t0).count();
      adopt_s += std::chrono::duration<double>(t2 - t1).count();
    }
  }
  if (timed)
    std::fprintf(stderr, "native buckets.scatter %.3fs .adopt %.3fs\n",
                 scat_s, adopt_s);
  pt.mark("buckets");
}

static inline void group_adopt_dispatch(const uint32_t* lo, const uint32_t* hi,
                                        int64_t m, int64_t n,
                                        uint32_t* pst_out, uint32_t* uf,
                                        uint32_t* parent_out,
                                        uint32_t* pre_out, PhaseTimer& pt) {
  if (use_blocked(m, n))
    blocked_group_adopt(lo, hi, m, n, pst_out, uf, parent_out, pre_out, pt);
  else
    plain_group_adopt(lo, hi, m, n, pst_out, uf, parent_out, pre_out, pt);
}

// The threaded fold (round-14): T contiguous record slices, each folded
// by one thread into a PRIVATE partial forest (its own identity
// union-find + kInvalid parent over the full [n] position space — the
// 8n-per-extra-thread the governor prices), then ONE deterministic
// merge replaying every partial's (kid -> parent) links into the real
// carried state through the same grouping+adoption.
//
// Exactness is the associative-merge theorem the tournament and every
// streaming fold already stand on: forest(A ∪ B) == forest(links(
// forest(A)) ∪ links(forest(B))), so the merged result is the unique
// forest of the whole multiset — independent of T, of where the slices
// cut (an equal-hi group MAY span slices: the same group-split argument
// as resumable block boundaries), and of the merge bracket (k-way
// concat here == any pairwise tree, proven by the bracket-independence
// test).  pst partials are int-add commutative, summed in fixed thread
// order.  Slices are cut on raw record positions, not on hi-group
// boundaries: the input is not hi-sorted at this layer (each slice's
// own counting sort / quantile bucketing does that privately), so
// aligning cuts would cost a full partition pass before any thread
// could start — and exactness needs no alignment.
#ifdef _OPENMP
// One thread's half of the decomposition: fold already-mapped slice
// links into a PRIVATE partial forest (identity union-find + kInvalid
// parent over the full [n] space) and emit the partial's (kid ->
// parent) links for the merge.  pst_l non-null accumulates this slice's
// pst contribution (all links, pst-only included).
static void slice_partial_fold(const uint32_t* lo, const uint32_t* hi,
                               int64_t m, int64_t n, uint32_t* pst_l,
                               std::vector<uint32_t>& out_lo,
                               std::vector<uint32_t>& out_hi) {
  std::vector<uint32_t> uf_l((size_t)n), parent_l((size_t)n);
  for (int64_t v = 0; v < n; ++v) {
    uf_l[(size_t)v] = (uint32_t)v;
    parent_l[(size_t)v] = kInvalid;
  }
  PhaseTimer ptl("thread_slice");
  ptl.on = false;  // per-thread phase prints would interleave
  group_adopt_dispatch(lo, hi, m, n, pst_l, uf_l.data(), parent_l.data(),
                       nullptr, ptl);
  for (int64_t v = 0; v < n; ++v)
    if (parent_l[(size_t)v] != kInvalid) {
      out_lo.push_back((uint32_t)v);
      out_hi.push_back(parent_l[(size_t)v]);
    }
}

// The deterministic merge: per-thread pst partials sum in fixed
// ascending thread order (uint32 adds commute — the sum is the serial
// count bit for bit), and every partial forest's links replay into the
// real carried state through the same grouping+adoption.  The k-way
// concat here equals ANY pairwise merge bracket by associativity
// (proven by the bracket-independence test in test_native_threads.py).
static void merge_partials(std::vector<std::vector<uint32_t>>& mlo,
                           std::vector<std::vector<uint32_t>>& mhi,
                           std::vector<std::vector<uint32_t>>& psts,
                           uint32_t* pst_out, int64_t n, int T, uint32_t* uf,
                           uint32_t* parent_out, PhaseTimer& pt) {
  if (pst_out) {
#pragma omp parallel for num_threads(T) schedule(static)
    for (int64_t v = 0; v < n; ++v) {
      uint32_t s = 0;
      for (int tt = 0; tt < T; ++tt) s += psts[(size_t)tt][(size_t)v];
      pst_out[v] += s;
    }
  }
  size_t total = 0;
  for (auto& v2 : mlo) total += v2.size();
  std::vector<uint32_t> alo, ahi;
  alo.reserve(total);
  ahi.reserve(total);
  for (int tt = 0; tt < T; ++tt) {
    alo.insert(alo.end(), mlo[(size_t)tt].begin(), mlo[(size_t)tt].end());
    ahi.insert(ahi.end(), mhi[(size_t)tt].begin(), mhi[(size_t)tt].end());
  }
  // partial links never count pst (they are tree edges, not records)
  group_adopt_dispatch(alo.data(), ahi.data(), (int64_t)alo.size(), n,
                       nullptr, uf, parent_out, nullptr, pt);
  pt.mark("merge");
}
#endif

#ifdef _OPENMP
// The threaded BLOCKED kernel (round-14, the tentpole): the cache-
// blocked kernel's quantile buckets are the parallel decomposition.
// One shared count + bucket partition (threaded over contiguous record
// slices with per-thread partial counts / cursor matrices — adds
// commute, cursor segments are disjoint), then the K equal-count
// buckets split into T contiguous RUNS cut on bucket boundaries, so no
// bucket — and therefore no hi-group — ever spans two threads.  Each
// thread adopts its run into a PRIVATE partial forest (identity uf +
// kInvalid parent over the full [n] space: the 8n-per-extra-thread the
// governor prices), capturing its (h, kid) adoptions; runs own
// DISJOINT ASCENDING h-ranges, so the captures concatenated in thread
// order are one globally h-ascending stream, and the merge is a single
// scaffold-free linear fold of that stream into the real carried state
// through the same adopt_group (no counting sort, no bucket tables —
// the merge reuses the exact serial group semantics).
//
// Exactness is the associative-merge theorem the tournament and every
// streaming fold already stand on: the merged result is the unique
// forest of the whole multiset, independent of T, of where the runs
// cut, and of the merge bracket (proven by the bracket-independence
// test in test_native_threads.py) — parent and pst are bit-identical
// to the single-thread build for every thread count.
static void blocked_group_adopt_mt(const uint32_t* lo, const uint32_t* hi,
                                   int64_t m, int64_t n, uint32_t* pst_out,
                                   uint32_t* uf, uint32_t* parent_out,
                                   int T, PhaseTimer& pt) {
  ThreadStats* ts = &g_tstats;
  ts->used = T;
  // the arena's T slabs back BOTH per-thread table phases (count+pst
  // partials here, union-find+parent partials in phase 4) — 8n+8 bytes
  // per thread, warm across calls (struct comment)
  ThreadArena* arena = &g_arena;
  arena->ensure(n, T);
  // phase 1: per-h counts (+ pst) — per-thread partials over contiguous
  // record slices, summed in fixed thread order (int adds commute)
  std::vector<int32_t> offs((size_t)n + 1, 0);
#pragma omp parallel num_threads(T)
  {
    const int t = omp_get_thread_num();
    const double t0 = mono_s();
    const int64_t a = m * t / T, b = m * (t + 1) / T;
    int32_t* c = (int32_t*)arena->slab(t);  // [n+1]
    uint32_t* p = arena->slab(t) + n + 1;   // [n]
    std::memset(c, 0, sizeof(int32_t) * (size_t)(n + 1));
    for (int64_t i = a; i < b; ++i)
      if (hi[i] < (uint64_t)n) ++c[hi[i] + 1];
    if (pst_out) {
      std::memset(p, 0, sizeof(uint32_t) * (size_t)n);
      for (int64_t i = a; i < b; ++i) ++p[lo[i]];
    }
    if (t < kMaxThreads) ts->busy[t] += mono_s() - t0;
  }
#pragma omp parallel for num_threads(T) schedule(static)
  for (int64_t h = 0; h <= n; ++h) {
    int32_t s = 0;
    for (int tt = 0; tt < T; ++tt) s += ((int32_t*)arena->slab(tt))[h];
    offs[(size_t)h] = s;
  }
  if (pst_out) {
    // one region, fixed ascending tt order per v — adds commute
#pragma omp parallel for num_threads(T) schedule(static)
    for (int64_t v = 0; v < n; ++v) {
      uint32_t s = 0;
      for (int tt = 0; tt < T; ++tt) s += (arena->slab(tt) + n + 1)[v];
      pst_out[v] += s;
    }
  }
  pt.mark("count+pst");
  // phase 2: prefix + the SAME quantile bucket rule as the serial
  // kernel (equal-count boundaries over the per-h prefix)
  for (int64_t h = 0; h < n; ++h) offs[h + 1] += offs[h];
  const int64_t linked = offs[n];
  const int64_t K = kMaxBuckets;
  std::vector<int64_t> bound((size_t)K + 1);
  bound[0] = 0;
  bound[(size_t)K] = n;
  for (int64_t b = 1; b < K; ++b)
    bound[(size_t)b] = std::lower_bound(offs.begin(), offs.begin() + n + 1,
                                        (int32_t)(b * linked / K)) -
                       offs.begin();
  std::vector<uint8_t> bucket_of((size_t)n);
  for (int64_t b = 0; b < K; ++b)
    std::memset(bucket_of.data() + bound[(size_t)b], (int)b,
                (size_t)(bound[(size_t)b + 1] - bound[(size_t)b]));
  std::vector<int64_t> bstart((size_t)K + 1);
  for (int64_t b = 0; b <= K; ++b) bstart[(size_t)b] = offs[bound[(size_t)b]];
  // phase 3: threaded partition — per-(thread, bucket) counts give each
  // thread disjoint write segments (thread-major inside a bucket; group
  // adoption is order-free within a group, so outputs are unchanged).
  // The counts come FREE from phase 1's per-h slabs (a sequential O(n)
  // sum per thread) instead of a second O(m) pass over the records.
  std::unique_ptr<uint64_t[]> recs(new uint64_t[(size_t)linked]);
  {
    std::vector<std::vector<int64_t>> bcnt((size_t)T);
#pragma omp parallel num_threads(T)
    {
      const int t = omp_get_thread_num();
      const double t0 = mono_s();
      const int64_t a = m * t / T, b = m * (t + 1) / T;
      const int32_t* c = (const int32_t*)arena->slab(t);  // phase-1 counts
      std::vector<int64_t>& bc = bcnt[(size_t)t];
      bc.assign((size_t)K, 0);
      for (int64_t b2 = 0; b2 < K; ++b2) {
        int64_t s = 0;
        for (int64_t h = bound[(size_t)b2]; h < bound[(size_t)b2 + 1]; ++h)
          s += c[h + 1];
        bc[(size_t)b2] = s;
      }
#pragma omp barrier
      std::vector<int64_t> curl((size_t)K);
      for (int64_t b2 = 0; b2 < K; ++b2) {
        int64_t at = bstart[(size_t)b2];
        for (int tt = 0; tt < t; ++tt) at += bcnt[(size_t)tt][(size_t)b2];
        curl[(size_t)b2] = at;
      }
      for (int64_t i = a; i < b; ++i) {
        const uint32_t h = hi[i];
        if (h >= (uint64_t)n) continue;
        recs[(size_t)curl[bucket_of[h]]++] = ((uint64_t)h << 32) | lo[i];
      }
      if (t < kMaxThreads) ts->busy[t] += mono_s() - t0;
    }
  }
  pt.mark("partition");
  // phase 4: bucket RUNS — T contiguous runs cut on bucket boundaries
  // balanced by link count; each thread adopts its run into a private
  // partial forest, capturing (h, kid) pairs in ascending-h order
  std::vector<int64_t> rb((size_t)T + 1);
  rb[0] = 0;
  rb[(size_t)T] = K;
  for (int64_t t = 1; t < T; ++t) {
    int64_t cut = std::lower_bound(bstart.begin(), bstart.begin() + K + 1,
                                   t * linked / T) -
                  bstart.begin();
    rb[(size_t)t] = std::max(rb[(size_t)t - 1], std::min(cut, (int64_t)K));
  }
  std::vector<std::vector<uint64_t>>& caps = g_caps;
  if ((int)caps.size() < T) caps.resize((size_t)T);
  for (int t = 0; t < T; ++t) caps[(size_t)t].clear();  // capacity kept
#pragma omp parallel num_threads(T)
  {
    const int t = omp_get_thread_num();
    const double t0 = mono_s();
    uint32_t* uf_l = arena->slab(t);          // [n] — phase 1 is done
    uint32_t* parent_l = arena->slab(t) + n;  // [n] with these slabs
    for (int64_t v = 0; v < n; ++v) {
      uf_l[(size_t)v] = (uint32_t)v;
      parent_l[(size_t)v] = kInvalid;
    }
    std::vector<uint32_t> grouped, adopted;
    std::vector<uint64_t>* cap = &caps[(size_t)t];
    for (int64_t b2 = rb[(size_t)t]; b2 < rb[(size_t)t + 1]; ++b2) {
      const int64_t s = bstart[(size_t)b2], e = bstart[(size_t)b2 + 1];
      if (s == e) continue;
      if ((int64_t)grouped.size() < e - s) grouped.resize((size_t)(e - s));
      // offs[h] mutates as the scatter cursor exactly like the serial
      // bucket loop — every h lives in exactly one bucket, every bucket
      // in exactly one run, so the mutation is thread-exclusive
      for (int64_t i = s; i < e; ++i)
        grouped[(size_t)(offs[rec_h(recs[(size_t)i])]++ - s)] =
            rec_lo(recs[(size_t)i]);
      int64_t prev = s;
      for (int64_t h = bound[(size_t)b2]; h < bound[(size_t)b2 + 1]; ++h) {
        const int64_t end = offs[h];
        if (end > prev)
          adopt_group(grouped.data() + (prev - s), end - prev, (uint32_t)h,
                      uf_l, parent_l, nullptr, adopted, cap);
        prev = end;
      }
    }
    if (t < kMaxThreads) ts->busy[t] += mono_s() - t0;
  }
  pt.mark("slices");
  // phase 5: the linear merge — thread captures concatenate into one
  // globally h-ascending stream (runs own disjoint ascending h-ranges;
  // an equal-h group can never span runs because runs cut on bucket
  // boundaries), replayed into the real carried state group by group
  // through the exact serial adoption
  std::vector<uint32_t> run, adopted;
  for (int64_t t = 0; t < T; ++t) {
    const std::vector<uint64_t>& cp = caps[(size_t)t];
    size_t i = 0;
    while (i < cp.size()) {
      const uint32_t h = (uint32_t)(cp[i] >> 32);
      run.clear();
      while (i < cp.size() && (uint32_t)(cp[i] >> 32) == h)
        run.push_back((uint32_t)cp[i++]);
      adopt_group(run.data(), (int64_t)run.size(), h, uf, parent_out,
                  nullptr, adopted);
    }
  }
  pt.mark("merge");
}
#endif

static void threaded_group_adopt(const uint32_t* lo, const uint32_t* hi,
                                 int64_t m, int64_t n, uint32_t* pst_out,
                                 uint32_t* uf, uint32_t* parent_out, int T,
                                 PhaseTimer& pt) {
#ifdef _OPENMP
  if (use_blocked(m, n)) {
    // the tentpole path: shared count/partition, bucket-run slices
    blocked_group_adopt_mt(lo, hi, m, n, pst_out, uf, parent_out, T, pt);
    return;
  }
  // plain-path inputs (below the blocked floor, or past the int32
  // prefix limit): per-thread partial forests over contiguous record
  // slices, merged through the same grouping+adoption
  std::vector<std::vector<uint32_t>> mlo((size_t)T), mhi((size_t)T);
  std::vector<std::vector<uint32_t>> psts(pst_out ? (size_t)T : 0);
  ThreadStats* ts = &g_tstats;
  ts->used = T;
#pragma omp parallel num_threads(T)
  {
    const int t = omp_get_thread_num();
    const double t0 = mono_s();
    const int64_t a = m * t / T, b = m * (t + 1) / T;
    uint32_t* pst_l = nullptr;
    if (pst_out) {
      psts[(size_t)t].assign((size_t)n, 0);
      pst_l = psts[(size_t)t].data();
    }
    slice_partial_fold(lo + a, hi + a, b - a, n, pst_l, mlo[(size_t)t],
                       mhi[(size_t)t]);
    if (t < kMaxThreads) ts->busy[t] += mono_s() - t0;
  }
  pt.mark("slices");
  merge_partials(mlo, mhi, psts, pst_out, n, T, uf, parent_out, pt);
#else
  (void)T;
  group_adopt_dispatch(lo, hi, m, n, pst_out, uf, parent_out, nullptr, pt);
#endif
}
}  // namespace

extern "C" {

// Build the elimination forest from links (lo -> hi), lo < hi elementwise,
// in ascending-hi order — the exact sequential semantics of the reference's
// streaming insert (lib/jtree.cpp:34-55: each earlier root is adopted by the
// later endpoint).  Links are grouped by hi with a counting sort, so the
// cost is O(m + n) plus near-O(1) amortized finds.
//
//   lo, hi     [m]  uint32 sequence positions; lo must be < n; hi >= n marks
//              a "pst-only" link (edge to a vertex absent from the sequence,
//              which counts toward pst but never forms a tree edge — the
//              reference's forever-uninserted neighbor, jtree.cpp:47-49)
//   pst_in     [n]  uint32 or NULL; when NULL each link adds 1 to pst[lo]
//   parent_out [n]  uint32, kInvalid for roots
//   pst_out    [n]  uint32
//   pre_out    [n]  uint32 or NULL; when given, filled with the reference's
//              USE_PRE_WEIGHT accounting (lib/jnode.h:174-176 meetKid): each
//              tree link (lo -> h) adds 1 to pre[r] where r is lo's
//              component root *before* h's adoptions — the number of graph
//              edges between parent(r) and r's subtree.  Unions are deferred
//              to the end of each h-group to match the reference, which
//              unifies only in adoptKids after the whole edge scan
//              (lib/jnode.h:184-188, jtree.cpp:102).
//   scratch: internally allocates ~ (m + 2n) * 4 bytes.
int sheep_build_forest(const uint32_t* lo, const uint32_t* hi, int64_t m,
                       int64_t n, const uint32_t* pst_in,
                       uint32_t* parent_out, uint32_t* pst_out,
                       uint32_t* pre_out) {
  if (n < 0 || m < 0) return -1;
  tstats_reset();
  PhaseTimer pt("build_forest");
  const bool blocked = use_blocked(m, n);
  if (pst_in) {
    std::memcpy(pst_out, pst_in, sizeof(uint32_t) * (size_t)n);
  } else {
    std::memset(pst_out, 0, sizeof(uint32_t) * (size_t)n);
  }
  for (int64_t v = 0; v < n; ++v) parent_out[v] = kInvalid;
  if (pre_out) std::memset(pre_out, 0, sizeof(uint32_t) * (size_t)n);
  std::vector<uint32_t> uf((size_t)n);
  for (int64_t v = 0; v < n; ++v) uf[(size_t)v] = (uint32_t)v;
  (void)blocked;  // dispatch lives in fold_links_block (use_blocked)

  // The whole input as ONE block of the resumable fold — the monolithic
  // build and the streaming windowed handoff share every semantic by
  // construction.  Outputs are undefined on error, so a partially
  // filled pst at the -3 return is fine.
  int64_t rc = fold_links_block(lo, hi, m, n, 0, !pst_in, uf.data(),
                                parent_out, pst_out, pre_out, pt);
  return rc < 0 ? (int)rc : 0;
}

// ---------------------------------------------------------------------------
// Resumable link fold (streaming windowed handoff, round-7): the exact
// sheep_build_forest split at block boundaries so a host fold can consume
// device link windows AS THEY ARRIVE (fetch of window k+1 overlapping the
// fold of window k) without ever materializing the full link table.  All
// state is caller-owned ([n] buffers: parent/pst plus the union-find
// array), so the Python side can keep it across an arbitrary number of
// blocks and recover it after a failed stream.
// ---------------------------------------------------------------------------

// Initialize the fold state.  pst_in NULL => blocks accumulate pst from
// their own records (see fold_links_block's exactness note); non-NULL =>
// the precomputed prep-time pst is copied and blocks leave it alone.
int sheep_build_forest_links_begin(int64_t n, const uint32_t* pst_in,
                                   uint32_t* parent_out, uint32_t* pst_out,
                                   uint32_t* uf) {
  if (n < 0) return -1;
  if (pst_in) {
    std::memcpy(pst_out, pst_in, sizeof(uint32_t) * (size_t)n);
  } else {
    std::memset(pst_out, 0, sizeof(uint32_t) * (size_t)n);
  }
  for (int64_t v = 0; v < n; ++v) {
    parent_out[v] = kInvalid;
    uf[(size_t)v] = (uint32_t)v;
  }
  return 0;
}

// Fold one ascending-hi window; see fold_links_block for the ordering
// contract and return values (new bound >= 0, or -3/-7).
int64_t sheep_build_forest_links_block(const uint32_t* lo, const uint32_t* hi,
                                       int64_t m, int64_t n, int64_t lo_bound,
                                       int32_t accumulate_pst,
                                       uint32_t* parent_out,
                                       uint32_t* pst_out, uint32_t* uf) {
  if (n < 0 || m < 0 || lo_bound < 0) return -1;
  tstats_reset();
  PhaseTimer pt("links_block");
  return fold_links_block(lo, hi, m, n, lo_bound, accumulate_pst != 0, uf,
                          parent_out, pst_out, nullptr, pt);
}

// Seal the fold.  The ascending-hi discipline leaves no deferred work —
// parent/pst are already final after the last block; this exists so the
// ABI brackets the stream (begin/block/finish) and a future deferred
// pass has a home.  Returns 0.
int sheep_build_forest_links_finish(int64_t n, uint32_t* parent_out,
                                    uint32_t* uf) {
  (void)parent_out;
  (void)uf;
  return n < 0 ? -1 : 0;
}

// Map raw edge records to links through a vid->position table.  A vid
// beyond the table or mapped to kInvalid is absent from the sequence:
// self-loops and both-absent edges are dropped; a one-absent edge becomes a
// pst-only link (lo = present position, hi = kInvalid) so its pst count
// survives, matching the reference's forever-uninserted neighbors.
// Returns the number of links written (<= m).
int64_t sheep_edges_to_links(const uint32_t* tail, const uint32_t* head,
                             int64_t m, const uint32_t* pos, int64_t pos_len,
                             uint32_t* lo_out, uint32_t* hi_out) {
  int64_t k = 0;
  for (int64_t i = 0; i < m; ++i) {
    uint32_t pt = tail[i] < (uint64_t)pos_len ? pos[tail[i]] : kInvalid;
    uint32_t ph = head[i] < (uint64_t)pos_len ? pos[head[i]] : kInvalid;
    if (pt == ph) continue;  // self-loop or both absent
    lo_out[k] = pt < ph ? pt : ph;
    hi_out[k] = pt < ph ? ph : pt;
    ++k;
  }
  return k;
}

// forwardPartition (lib/partition.cpp:86-157): ascending pass accumulating
// component_below with first-fit-decreasing bin packing of overweight
// subtrees, then a descending pass inheriting parts from parents and packing
// leftover roots from the most-recent bin backwards.  Kid sorts use a stable
// descending-weight order with ascending-jnid tie break (the reference's
// std::sort is unstable there; see SURVEY.md §7 determinism note).
//
//   parent   [n] uint32 (kInvalid roots)
//   weights  [n] int64 node weights
//   parts_out[n] int32, filled 0..num_parts-1
// Returns number of bins opened, or negative on error (-2: a single node
// outweighs max_component, which would loop forever in the reference; -3: a
// parent entry is neither kInvalid nor < n, e.g. a corrupt .tre file — the
// reference dies on such input via live asserts / .at(), lib/jdata.h:36-40).
int64_t sheep_forward_partition(const uint32_t* parent, const int64_t* weights,
                                int64_t n, int64_t max_component,
                                int32_t* parts_out) {
  constexpr int32_t kNoPart = -1;
  for (int64_t i = 0; i < n; ++i)
    if (parent[i] != kInvalid && parent[i] >= (uint64_t)n) return -3;
  std::vector<int64_t> component_below(weights, weights + n);
  for (int64_t i = 0; i < n; ++i) {
    if (weights[i] > max_component) return -2;
    parts_out[i] = kNoPart;
  }

  // kids grouped by parent in ascending-jnid order (counting sort).
  std::vector<int64_t> koffs((size_t)n + 1, 0);
  for (int64_t i = 0; i < n; ++i)
    if (parent[i] != kInvalid) ++koffs[parent[i] + 1];
  for (int64_t v = 0; v < n; ++v) koffs[v + 1] += koffs[v];
  std::vector<uint32_t> kids((size_t)koffs[n]);
  {
    std::vector<int64_t> cur(koffs.begin(), koffs.end() - 1);
    for (int64_t i = 0; i < n; ++i)
      if (parent[i] != kInvalid) kids[(size_t)cur[parent[i]]++] = (uint32_t)i;
  }

  std::vector<int64_t> part_size;
  std::vector<uint32_t> ks;
  for (int64_t i = 0; i < n; ++i) {
    if (component_below[i] > max_component) {
      ks.assign(kids.begin() + koffs[i], kids.begin() + koffs[i + 1]);
      // descending weight, ascending-jnid ties (stable) — deterministic
      // and identical to the python twin.  The reference uses an UNSTABLE
      // std::sort here (partition.cpp:104-108), so its tie permutation is
      // toolchain-defined; this rule matches 30/31 published rows of
      // data/quality/hep.cost col 2 exactly (24 parts: 2723 vs 2720,
      // +0.1% — no consistent tie direction reproduces that row without
      // breaking others; see scripts/quality_sweep.py)
      std::stable_sort(ks.begin(), ks.end(),
                       [&](uint32_t a, uint32_t b) {
                         return component_below[a] > component_below[b];
                       });
      while (component_below[i] > max_component) {
        for (uint32_t kid : ks) {
          if (component_below[i] <= max_component) break;
          if (parts_out[kid] != kNoPart) continue;
          int64_t cb = component_below[kid];
          for (size_t cur = 0; cur < part_size.size(); ++cur) {
            if (part_size[cur] + cb <= max_component) {
              component_below[i] -= cb;
              part_size[cur] += cb;
              parts_out[kid] = (int32_t)cur;
              break;
            }
          }
        }
        if (component_below[i] > max_component) part_size.push_back(0);
      }
    }
    if (parent[i] != kInvalid) component_below[parent[i]] += component_below[i];
  }

  for (int64_t i = n - 1; i >= 0; --i) {
    if (parts_out[i] == kNoPart && parent[i] != kInvalid)
      parts_out[i] = parts_out[parent[i]];
    while (parts_out[i] == kNoPart) {
      for (int64_t cur = (int64_t)part_size.size() - 1; cur >= 0; --cur) {
        if (part_size[(size_t)cur] + component_below[i] <= max_component) {
          part_size[(size_t)cur] += component_below[i];
          parts_out[i] = (int32_t)cur;
          break;
        }
      }
      if (parts_out[i] == kNoPart) part_size.push_back(0);
    }
  }
  return (int64_t)part_size.size();
}

// Per-vertex degree accumulation for the sequence sort: each record adds 1
// to both endpoints (undirected-doubled semantics, graph_wrapper.h:87-89).
// Returns 0, or -3 when a record names a vid >= n (corrupt input; the
// reference's LLAMA path sizes the table from the real max vid, so an
// out-of-range vid can only come from a malformed file).
int sheep_degree_histogram_acc(const uint32_t* tail, const uint32_t* head,
                               int64_t m, int64_t n, int64_t* deg_io);

int sheep_degree_histogram(const uint32_t* tail, const uint32_t* head,
                           int64_t m, int64_t n, int64_t* deg_out) {
  std::memset(deg_out, 0, sizeof(int64_t) * (size_t)n);
  return sheep_degree_histogram_acc(tail, head, m, n, deg_out);
}

// Accumulating variant for the out-of-core streaming pass (round-8): adds
// this block's degree contributions INTO deg_io without zeroing it, so the
// per-block histogram of an edge stream folds into one int64 accumulator
// with no per-block allocation.  Summing blocks is exact (integer adds
// commute), so the accumulated histogram equals sheep_degree_histogram over
// the concatenated records — which is what keeps the streaming degree
// sequence bit-identical to the in-RAM one.  Same -3 contract on a vid
// >= n; a failed block leaves deg_io with a PARTIAL block added (callers
// abort the pass — the accumulator is not salvageable mid-block).
int sheep_degree_histogram_acc(const uint32_t* tail, const uint32_t* head,
                               int64_t m, int64_t n, int64_t* deg_io) {
  tstats_reset();
#ifdef _OPENMP
  // Threaded arm (round-14): per-thread int64 partial histograms over
  // contiguous record slices, summed in fixed thread order — integer
  // adds commute, so the sum equals the serial accumulation bit for
  // bit.  Costs 8n per extra thread (the governor's veto term).  On a
  // bad vid NO partial is merged (a stricter contract than the serial
  // loop's partial adds; callers abort the pass either way).
  const int T = threads_for_work(m);
  if (T > 1) {
    bool bad = false;
    ThreadStats* ts = &g_tstats;
    ts->used = T;
    ThreadArena* arena = &g_arena;  // int64[n] partial per slab
    arena->ensure(n, T);
#pragma omp parallel num_threads(T) reduction(|| : bad)
    {
      const int t = omp_get_thread_num();
      const double t0 = mono_s();
      const int64_t a = m * t / T, b = m * (t + 1) / T;
      int64_t* part = (int64_t*)arena->slab(t);
      std::memset(part, 0, sizeof(int64_t) * (size_t)n);
      for (int64_t i = a; i < b; ++i) {
        if (tail[i] >= (uint64_t)n || head[i] >= (uint64_t)n) {
          bad = true;
          break;
        }
        ++part[tail[i]];
        ++part[head[i]];
      }
      if (t < kMaxThreads) ts->busy[t] += mono_s() - t0;
    }
    if (bad) return -3;
#pragma omp parallel for num_threads(T) schedule(static)
    for (int64_t v = 0; v < n; ++v) {
      int64_t s = 0;
      for (int tt = 0; tt < T; ++tt)
        s += ((const int64_t*)arena->slab(tt))[v];
      deg_io[v] += s;
    }
    return 0;
  }
#endif
  for (int64_t i = 0; i < m; ++i) {
    if (tail[i] >= (uint64_t)n || head[i] >= (uint64_t)n) return -3;
    ++deg_io[tail[i]];
    ++deg_io[head[i]];
  }
  return 0;
}

// Fused degree sequence straight from edge records (round-6): histogram
// + ascending-degree counting sort in one call, with the histogram in
// uint32 — int64 counters measured ~27% slower per random increment on
// the bench host purely from the wider line footprint, and per-vertex
// degrees fit uint32 up to 2^31 records (validated; falls back -5 past
// it, callers use the two-call path).  Semantics identical to
// sheep_degree_histogram + sheep_degree_sequence: nonzero degrees only,
// ascending degree, ascending-vid tie break.  Returns the sequence
// length, -3 on an out-of-range vid, -5 when m is too large for the
// uint32 histogram.
int64_t sheep_degree_sequence_edges(const uint32_t* tail,
                                    const uint32_t* head, int64_t m,
                                    int64_t n, uint32_t* seq_out) {
  if (n < 0 || m < 0 || 2 * m > (int64_t)kInvalid) return -5;
  tstats_reset();
  std::vector<uint32_t> deg((size_t)n, 0);
#ifdef _OPENMP
  // Threaded histogram (round-14): uint32 per-thread partials (the same
  // narrow-counter win as the serial kernel) summed in thread order —
  // commutative adds, bit-identical to the serial count.
  const int T = threads_for_work(m);
  if (T > 1) {
    bool bad = false;
    ThreadStats* ts = &g_tstats;
    ts->used = T;
    ThreadArena* arena = &g_arena;  // uint32[n] partial per slab
    arena->ensure(n, T);
#pragma omp parallel num_threads(T) reduction(|| : bad)
    {
      const int t = omp_get_thread_num();
      const double t0 = mono_s();
      const int64_t a = m * t / T, b = m * (t + 1) / T;
      uint32_t* part = arena->slab(t);
      std::memset(part, 0, sizeof(uint32_t) * (size_t)n);
      for (int64_t i = a; i < b; ++i) {
        if (tail[i] >= (uint64_t)n || head[i] >= (uint64_t)n) {
          bad = true;
          break;
        }
        ++part[tail[i]];
        ++part[head[i]];
      }
      if (t < kMaxThreads) ts->busy[t] += mono_s() - t0;
    }
    if (bad) return -3;
#pragma omp parallel for num_threads(T) schedule(static)
    for (int64_t v = 0; v < n; ++v) {
      uint32_t s = 0;
      for (int tt = 0; tt < T; ++tt) s += arena->slab(tt)[v];
      deg[(size_t)v] += s;
    }
  } else
#endif
  {
    for (int64_t i = 0; i < m; ++i) {
      if (tail[i] >= (uint64_t)n || head[i] >= (uint64_t)n) return -3;
      ++deg[tail[i]];
      ++deg[head[i]];
    }
  }
  uint32_t max_deg = 0;
  for (int64_t v = 0; v < n; ++v)
    if (deg[v] > max_deg) max_deg = deg[v];
  // same bucket-width guard as the two-call path: a multigraph hub can
  // push max_degree far past n, where counting buckets explode; callers
  // fall back to the comparison sort on -6
  if ((int64_t)max_deg > std::max<int64_t>(4 * n, int64_t{1} << 20))
    return -6;
  std::vector<int64_t> offs((size_t)max_deg + 2, 0);
  for (int64_t v = 0; v < n; ++v)
    if (deg[v] > 0) ++offs[deg[v] + 1];
  for (uint32_t d = 0; d <= max_deg; ++d) offs[d + 1] += offs[d];
  const int64_t total = offs[max_deg + 1];
  for (int64_t v = 0; v < n; ++v)
    if (deg[v] > 0) seq_out[offs[deg[v]]++] = (uint32_t)v;
  return total;
}

// Fused edge->forest build: maps raw records through the vid->position
// table and feeds the blocked grouping DIRECTLY — the lo/hi link arrays
// of the two-call path (sheep_edges_to_links + sheep_build_forest) are
// never materialized, which at 2^23 removes ~0.5GB of stream traffic
// plus the second full-m validation scan.  Exact same semantics: a vid
// beyond the table or mapped to kInvalid is absent; self-loops and
// both-absent records drop; one-absent records count toward pst at the
// present endpoint but never group (the reference's forever-uninserted
// neighbors, jtree.cpp:47-49).  pst_out/parent_out as sheep_build_forest
// (pst always recomputed here — callers with precomputed pst use the
// two-call path).  Returns 0, or -3 when a mapped position lands at or
// beyond n (corrupt pos table).
int sheep_build_forest_edges(const uint32_t* tail, const uint32_t* head,
                             int64_t m, const uint32_t* pos, int64_t pos_len,
                             int64_t n, uint32_t* parent_out,
                             uint32_t* pst_out, uint32_t* pre_out) {
  if (n < 0 || m < 0) return -1;
  tstats_reset();
  PhaseTimer pt("build_forest_edges");
  std::memset(pst_out, 0, sizeof(uint32_t) * (size_t)n);
  for (int64_t v = 0; v < n; ++v) parent_out[v] = kInvalid;
  if (pre_out) std::memset(pre_out, 0, sizeof(uint32_t) * (size_t)n);
  std::vector<uint32_t> uf((size_t)n);
  for (int64_t v = 0; v < n; ++v) uf[(size_t)v] = (uint32_t)v;

#ifdef _OPENMP
  // Threaded arm (round-14): the mapping pass parallelizes over record
  // slices with a count-then-write split — pass A counts each slice's
  // kept records (and validates), pass B writes them DIRECTLY into the
  // final arrays at prefix offsets, so the mapped table is byte-
  // identical to the serial pass's with no per-thread staging buffers
  // (staging was measured to double the phase in page faults alone).
  // The shared bucket-run kernel does the rest — pst accumulates inside
  // its threaded count pass exactly like the serial fused path.
  const int T = pre_out ? 1 : threads_for_work(m);
  if (T > 1) {
    bool bad = false;
    std::vector<int64_t> kept((size_t)T, 0);
    ThreadStats* ts = &g_tstats;  // the CALLER's telemetry slot —
    // g_tstats inside the parallel region is each worker's own
#pragma omp parallel num_threads(T) reduction(|| : bad)
    {
      const int t = omp_get_thread_num();
      const double t0 = mono_s();
      const int64_t a = m * t / T, b = m * (t + 1) / T;
      int64_t cnt = 0;
      for (int64_t i = a; i < b; ++i) {
        const uint32_t pt_ =
            tail[i] < (uint64_t)pos_len ? pos[tail[i]] : kInvalid;
        const uint32_t ph_ =
            head[i] < (uint64_t)pos_len ? pos[head[i]] : kInvalid;
        if (pt_ == ph_) continue;  // self-loop or both absent
        if ((pt_ < ph_ ? pt_ : ph_) >= (uint64_t)n) {  // corrupt pos
          bad = true;
          break;
        }
        ++cnt;
      }
      kept[(size_t)t] = cnt;
      if (t < kMaxThreads) ts->busy[t] += mono_s() - t0;
    }
    if (bad) return -3;
    int64_t k = 0;
    std::vector<int64_t> starts((size_t)T);
    for (int t = 0; t < T; ++t) {
      starts[(size_t)t] = k;
      k += kept[(size_t)t];
    }
    std::vector<uint32_t> mlo((size_t)k), mhi((size_t)k);
#pragma omp parallel num_threads(T)
    {
      const int t = omp_get_thread_num();
      const double t0 = mono_s();
      const int64_t a = m * t / T, b = m * (t + 1) / T;
      int64_t at = starts[(size_t)t];
      for (int64_t i = a; i < b; ++i) {
        const uint32_t pt_ =
            tail[i] < (uint64_t)pos_len ? pos[tail[i]] : kInvalid;
        const uint32_t ph_ =
            head[i] < (uint64_t)pos_len ? pos[head[i]] : kInvalid;
        if (pt_ == ph_) continue;
        mlo[(size_t)at] = pt_ < ph_ ? pt_ : ph_;
        mhi[(size_t)at] = pt_ < ph_ ? ph_ : pt_;
        ++at;
      }
      if (t < kMaxThreads) ts->busy[t] += mono_s() - t0;
    }
    pt.mark("map");
    threaded_group_adopt(mlo.data(), mhi.data(), k, n, pst_out, uf.data(),
                         parent_out, T, pt);
    return 0;
  }
#endif

  // Tight mapping pass (the only pos-gather pass; pst and the group
  // count live in blocked_group_adopt's own read passes — a fused loop
  // mixing extra random-access streams here was measured to starve the
  // out-of-order window's memory-level parallelism), then the shared
  // quantile-bucketed grouping+adoption.  pst-only links (absent
  // neighbor, hi = kInvalid >= n) stay in the mapped arrays: the pst
  // pass counts every link's lo, the grouping skips hi >= n.
  std::vector<uint32_t> mlo((size_t)m), mhi((size_t)m);
  int64_t k = 0;
  for (int64_t i = 0; i < m; ++i) {
    const uint32_t pt_ = tail[i] < (uint64_t)pos_len ? pos[tail[i]] : kInvalid;
    const uint32_t ph_ = head[i] < (uint64_t)pos_len ? pos[head[i]] : kInvalid;
    if (pt_ == ph_) continue;  // self-loop or both absent
    const uint32_t l = pt_ < ph_ ? pt_ : ph_;
    if (l >= (uint64_t)n) return -3;  // corrupt pos table
    mlo[(size_t)k] = l;
    mhi[(size_t)k] = pt_ < ph_ ? ph_ : pt_;
    ++k;
  }
  pt.mark("map");
  blocked_group_adopt(mlo.data(), mhi.data(), k, n, pst_out, uf.data(),
                      parent_out, pre_out, pt);
  return 0;
}

// Ascending-degree sequence with ascending-vid tie break, nonzero degrees
// only (lib/sequence.h:52-63).  Degrees are small integers, so this is a
// counting sort over degree buckets — iterating vids in ascending order
// within a bucket gives the vid tie break for free; O(n + max_degree)
// versus the reference's comparison sort.  Returns the sequence length.
int64_t sheep_degree_sequence(const int64_t* deg, int64_t n,
                              uint32_t* seq_out) {
  tstats_reset();
  int64_t max_deg = 0;
#ifdef _OPENMP
  int T = threads_for_work(n);
#pragma omp parallel for num_threads(T) schedule(static) \
    reduction(max : max_deg) if (T > 1)
#endif
  for (int64_t v = 0; v < n; ++v)
    if (deg[v] > max_deg) max_deg = deg[v];
#ifdef _OPENMP
  // Threaded counting sort (round-14): per-thread degree-bucket counts
  // over contiguous vid slices, exclusive-prefixed into per-thread
  // write cursors — thread t's vids land after threads < t's within
  // every bucket, so the scatter preserves the ascending-vid tie break
  // and the output is bit-identical to the serial sort.  Gated off when
  // the T bucket tables would dwarf the O(n) work they parallelize.
  if (T > 1 && (max_deg + 2) * (int64_t)T * 8 > 16 * n) T = 1;
  if (T > 1) {
    std::vector<std::vector<int64_t>> cnt((size_t)T);
    ThreadStats* ts = &g_tstats;
    ts->used = T;
#pragma omp parallel num_threads(T)
    {
      const int t = omp_get_thread_num();
      const double t0 = mono_s();
      const int64_t a = n * t / T, b = n * (t + 1) / T;
      std::vector<int64_t>& c = cnt[(size_t)t];
      c.assign((size_t)max_deg + 2, 0);
      for (int64_t v = a; v < b; ++v)
        if (deg[v] > 0) ++c[(size_t)deg[v]];
      if (t < kMaxThreads) ts->busy[t] = mono_s() - t0;
    }
    // serial exclusive prefix over (degree, thread): cursor[t][d] =
    // (elements of degree < d anywhere) + (degree-d elements of earlier
    // threads)
    std::vector<int64_t> base((size_t)max_deg + 2, 0);
    int64_t run = 0;
    for (int64_t d = 1; d <= max_deg; ++d) {
      base[(size_t)d] = run;
      for (int tt = 0; tt < T; ++tt) run += cnt[(size_t)tt][(size_t)d];
    }
    const int64_t total = run;
    std::vector<std::vector<int64_t>> cur((size_t)T);
    for (int tt = 0; tt < T; ++tt)
      cur[(size_t)tt].assign((size_t)max_deg + 2, 0);
    for (int64_t d = 1; d <= max_deg; ++d) {
      int64_t at = base[(size_t)d];
      for (int tt = 0; tt < T; ++tt) {
        cur[(size_t)tt][(size_t)d] = at;
        at += cnt[(size_t)tt][(size_t)d];
      }
    }
#pragma omp parallel num_threads(T)
    {
      const int t = omp_get_thread_num();
      const int64_t a = n * t / T, b = n * (t + 1) / T;
      std::vector<int64_t>& c = cur[(size_t)t];
      for (int64_t v = a; v < b; ++v)
        if (deg[v] > 0) seq_out[c[(size_t)deg[v]]++] = (uint32_t)v;
    }
    return total;
  }
#endif
  std::vector<int64_t> offs((size_t)max_deg + 2, 0);
  for (int64_t v = 0; v < n; ++v)
    if (deg[v] > 0) ++offs[deg[v] + 1];
  for (int64_t d = 0; d <= max_deg; ++d) offs[d + 1] += offs[d];
  int64_t total = offs[max_deg + 1];
  for (int64_t v = 0; v < n; ++v)
    if (deg[v] > 0) seq_out[offs[deg[v]]++] = (uint32_t)v;
  return total;
}

// Parameterized jxn/treewidth insert (lib/jtree.cpp:65-231) — the C++ twin
// of core/jxn.py build_jxn_tree, returning the dense outputs the CLI needs
// (parent, pst, effective seq, widths); the python oracle keeps the full
// kids/pst/jxn tables.  Semantics replicated exactly:
//   - per-edge postorder counting with width_limit fail-fast,
//   - jxn = k-way union of kid jxns + unique postorder vids, minus X,
//     failing when it exceeds width_limit (merge.h heuristic merges; here
//     a heap-free repeated two-way merge with early abort),
//   - failed vertices defer to wide_seq; find_max_width bound checks run
//     on failed inserts too (jtree.cpp:130-136),
//   - do_rooting stops when width == remaining; deferred + remaining
//     vertices become the trivial tail chain (jtree.cpp:152-222),
//   - pst/jxn item counts charge 4 bytes each against memory_limit.
// flags bitmask: 1=make_pad 2=make_kids 4=make_pst 8=make_jxn
//                16=find_max_width 32=do_rooting
// Returns n_out (>=0), or -4 when memory_limit is exceeded.
int64_t sheep_jxn_build(const uint32_t* tail, const uint32_t* head, int64_t m,
                        const uint32_t* seq, int64_t seq_len, int64_t n_vid,
                        int64_t width_limit, int64_t memory_limit,
                        int64_t flags, uint32_t* parent_out,
                        uint32_t* pst_out, uint32_t* seq_out,
                        int64_t* widths_out) {
  const bool make_pad = flags & 1;
  const bool make_pst = flags & 4;
  const bool make_jxn = flags & 8;
  const bool find_max_width = flags & 16;
  const bool do_rooting = flags & 32;
  const uint64_t wlimit = width_limit > 0 ? (uint64_t)width_limit
                                          : ~0ull >> 2;

  // CSR (undirected doubled) via counting sort.
  std::vector<int64_t> offs((size_t)n_vid + 1, 0);
  for (int64_t i = 0; i < m; ++i) {
    if (tail[i] >= (uint64_t)n_vid || head[i] >= (uint64_t)n_vid) return -3;
    ++offs[tail[i] + 1];
    ++offs[head[i] + 1];
  }
  for (int64_t v = 0; v < n_vid; ++v) offs[v + 1] += offs[v];
  std::vector<uint32_t> dst((size_t)offs[n_vid]);
  {
    std::vector<int64_t> cur(offs.begin(), offs.end() - 1);
    for (int64_t i = 0; i < m; ++i) {
      dst[(size_t)cur[tail[i]]++] = head[i];
      dst[(size_t)cur[head[i]]++] = tail[i];
    }
  }

  std::vector<uint32_t> index((size_t)n_vid, kInvalid);
  std::vector<uint32_t> uf;
  std::vector<std::vector<uint32_t>> jxn_tbl;  // sorted; empty when !make_jxn
  // stamp keys on a per-ATTEMPT counter, not the jnid: a failed insert
  // leaves n_out unchanged, so jnid-keyed stamps would leak into the next
  // vertex's root dedup.
  std::vector<uint32_t> stamp((size_t)seq_len + 1, 0);
  uint32_t attempt = 0;
  std::vector<uint32_t> ks, pvids, jx, merged;
  std::vector<uint32_t> wide_seq;
  int64_t n_out = 0;
  int64_t mem_used = 0;
  uint64_t current_width = 0;
  int64_t stopped_at = -1;

  auto uf_find_local = [&](uint32_t x) {
    while (uf[x] != x) {
      uf[x] = uf[uf[x]];
      x = uf[x];
    }
    return x;
  };

  for (int64_t si = 0; si < seq_len; ++si) {
    const uint32_t X = seq[si];
    if (X >= (uint64_t)n_vid) return -3;
    if (!make_pad && offs[X + 1] == offs[X]) continue;
    const uint32_t current = (uint32_t)n_out;
    ++attempt;
    uint64_t pw = 0;
    bool fail = false;
    ks.clear();
    pvids.clear();
    for (int64_t j = offs[X]; j < offs[X + 1]; ++j) {
      const uint32_t nbr = dst[(size_t)j];
      const uint32_t nid = index[nbr];
      if (nid != kInvalid) {
        uint32_t r = uf_find_local(nid);
        if (stamp[r] != attempt) {  // met-root dedup (meetKid's check)
          stamp[r] = attempt;
          ks.push_back(r);
        }
      } else if (nbr != X) {
        if (++pw > wlimit) { fail = true; break; }
        pvids.push_back(nbr);
      }
    }
    if (!fail) {
      std::sort(pvids.begin(), pvids.end());
      pvids.erase(std::unique(pvids.begin(), pvids.end()), pvids.end());
      if (make_jxn) {
        // union of kid jxns + pvids, minus X, early abort past wlimit
        jx.assign(pvids.begin(), pvids.end());  // never contains X
        for (uint32_t k : ks) {
          if (jxn_tbl[k].empty()) continue;
          merged.clear();
          merged.reserve(jx.size() + jxn_tbl[k].size());
          size_t a = 0, b = 0;
          const auto& kb = jxn_tbl[k];
          while (a < jx.size() || b < kb.size()) {
            uint32_t v;
            if (b >= kb.size() || (a < jx.size() && jx[a] <= kb[b])) {
              v = jx[a++];
              if (b < kb.size() && kb[b] == v) ++b;
            } else {
              v = kb[b++];
            }
            if (v == X) continue;
            merged.push_back(v);
            if (merged.size() > wlimit) { fail = true; break; }
          }
          if (fail) break;
          jx.swap(merged);
        }
      }
    }
    if (fail) {
      // find_max_width bound check runs on failed inserts too
      if (find_max_width &&
          current_width >= wide_seq.size() + (uint64_t)(seq_len - si))
        return n_out;
      wide_seq.push_back(X);
      continue;
    }

    // Commit
    parent_out[n_out] = kInvalid;
    pst_out[n_out] = (uint32_t)pw;
    seq_out[n_out] = X;
    uf.push_back(current);
    for (uint32_t r : ks) {
      parent_out[r] = current;
      uf[r] = current;
    }
    if (make_pst) {
      mem_used += 4 * (int64_t)pvids.size();
      if (mem_used > memory_limit) return -4;
    }
    if (make_jxn) {
      mem_used += 4 * (int64_t)jx.size();
      if (mem_used > memory_limit) return -4;
      jxn_tbl.emplace_back(jx);
    } else {
      jxn_tbl.emplace_back();
    }
    const uint64_t cur_w = 1 + (make_jxn ? jx.size() : pw);
    widths_out[n_out] = (int64_t)cur_w;
    index[X] = current;
    ++n_out;

    const uint64_t remaining = wide_seq.size() + (uint64_t)(seq_len - si);
    if (find_max_width) {
      if (cur_w > current_width) current_width = cur_w;
      if (current_width >= remaining) return n_out;
    }
    if (do_rooting && cur_w == remaining) {
      stopped_at = si + 1;
      break;
    }
  }

  // Tail phase: deferred + unvisited vertices become a root chain.
  std::vector<uint32_t> rest(wide_seq);
  if (stopped_at >= 0)
    for (int64_t si = stopped_at; si < seq_len; ++si)
      rest.push_back(seq[si]);
  for (size_t ti = 0; ti < rest.size(); ++ti) {
    const uint32_t X = rest[ti];
    const uint32_t current = (uint32_t)n_out;
    parent_out[n_out] = kInvalid;
    seq_out[n_out] = X;
    uf.push_back(current);
    if (ti == 0) {
      for (uint32_t kid = 0; kid < current; ++kid)
        if (parent_out[kid] == kInvalid) {
          parent_out[kid] = current;
          uf[kid] = current;
        }
    } else {
      parent_out[current - 1] = current;
      uf[current - 1] = current;
    }
    uint64_t pw = 0;
    uint64_t upw = 0;  // unique postorder vids (pst table accounting)
    pvids.clear();
    for (int64_t j = offs[X]; j < offs[X + 1]; ++j) {
      const uint32_t nbr = dst[(size_t)j];
      if (index[nbr] == kInvalid && nbr != X) {
        ++pw;
        pvids.push_back(nbr);
      }
    }
    std::sort(pvids.begin(), pvids.end());
    pvids.erase(std::unique(pvids.begin(), pvids.end()), pvids.end());
    upw = pvids.size();
    pst_out[n_out] = (uint32_t)pw;
    if (make_pst) {
      mem_used += 4 * (int64_t)upw;
      if (mem_used > memory_limit) return -4;
    }
    const uint64_t jx_len = rest.size() - ti - 1;
    if (make_jxn) {
      mem_used += 4 * (int64_t)jx_len;
      if (mem_used > memory_limit) return -4;
      widths_out[n_out] = (int64_t)(1 + jx_len);
    } else {
      widths_out[n_out] = (int64_t)(1 + pw);
    }
    index[X] = current;
    ++n_out;
    if (ti == 0 && find_max_width) return n_out;
  }
  return n_out;
}

// Fennel greedy streaming vertex partitioner (lib/partition.cpp:282-329).
// Exact semantics of the python oracle (partition/fennel.py): vertices
// stream in ascending-vid order; score = (neighbors already in part)
// - a*((s+w)^1.5 - s^1.5); parts considered up to the first empty one;
// capacity-violating parts are skipped; fallback part 0.
//
//   tail/head [m] uint32; parts_out [n_vid] int64 (-1 = INVALID_PART)
// Returns 0, or -3 on a vid >= n_vid.
int sheep_fennel_vertex(const uint32_t* tail, const uint32_t* head, int64_t m,
                        int64_t n_vid, int64_t num_parts,
                        double balance_factor, int edge_balanced,
                        int64_t* parts_out) {
  for (int64_t i = 0; i < m; ++i)
    if (tail[i] >= (uint64_t)n_vid || head[i] >= (uint64_t)n_vid) return -3;

  // CSR of the undirected-doubled graph via counting sort.
  std::vector<int64_t> offs((size_t)n_vid + 1, 0);
  for (int64_t i = 0; i < m; ++i) {
    ++offs[tail[i] + 1];
    ++offs[head[i] + 1];
  }
  for (int64_t v = 0; v < n_vid; ++v) offs[v + 1] += offs[v];
  std::vector<uint32_t> dst((size_t)offs[n_vid]);
  {
    std::vector<int64_t> cur(offs.begin(), offs.end() - 1);
    for (int64_t i = 0; i < m; ++i) {
      dst[(size_t)cur[tail[i]]++] = head[i];
      dst[(size_t)cur[head[i]]++] = tail[i];
    }
  }

  int64_t n_active = 0;
  for (int64_t v = 0; v < n_vid; ++v)
    if (offs[v + 1] > offs[v]) ++n_active;
  for (int64_t v = 0; v < n_vid; ++v) parts_out[v] = -1;
  if (m == 0 || n_active == 0) return 0;

  const double y = 1.5;
  const double n = (double)n_active;
  const double md = (double)(2 * m);
  const double k = (double)num_parts;
  const double a = edge_balanced ? n * std::pow(k / md, y)
                                 : md * (std::pow(k, y - 1.0) / std::pow(n, y));
  const int64_t total_weight = edge_balanced ? 2 * m : n_active;
  const double max_component =
      (double)(total_weight / num_parts) * balance_factor;

  std::vector<double> part_size((size_t)num_parts, 0.0);
  std::vector<int64_t> nbr_cnt((size_t)num_parts, 0);
  for (int64_t X = 0; X < n_vid; ++X) {
    if (offs[X + 1] == offs[X]) continue;
    const double w = edge_balanced ? (double)(offs[X + 1] - offs[X]) : 1.0;
    for (int64_t j = offs[X]; j < offs[X + 1]; ++j) {
      int64_t p = parts_out[dst[(size_t)j]];
      if (p >= 0) ++nbr_cnt[(size_t)p];
    }
    int64_t last = num_parts - 1;
    for (int64_t p = 0; p < num_parts; ++p)
      if (part_size[(size_t)p] == 0.0) { last = p; break; }
    int64_t best = -1;
    double best_score = 0.0;
    for (int64_t p = 0; p <= last; ++p) {
      if (part_size[(size_t)p] + w > max_component) continue;
      double s = part_size[(size_t)p];
      double score = (double)nbr_cnt[(size_t)p]
          - a * (std::pow(s + w, y) - std::pow(s, y));
      if (best < 0 || score > best_score) { best = p; best_score = score; }
    }
    if (best < 0) best = 0;  // reference fallback: max_part = 0
    parts_out[X] = best;
    part_size[(size_t)best] += w;
    for (int64_t j = offs[X]; j < offs[X + 1]; ++j) {
      int64_t p = parts_out[dst[(size_t)j]];
      if (p >= 0) --nbr_cnt[(size_t)p];  // cheap reset (only touched slots)
    }
    nbr_cnt[(size_t)best] = 0;  // X itself may appear via self-loops
  }
  return 0;
}

// Fennel streaming edge partitioner (lib/partition.cpp:331-407 prototype,
// slips corrected as in partition/fennel.py).  touches is a per-vertex
// bitset of ceil(k/64) words.  eparts_out [m] int64.
int sheep_fennel_edges(const uint32_t* tail, const uint32_t* head, int64_t m,
                       int64_t n_vid, int64_t num_parts,
                       double balance_factor, int64_t* eparts_out) {
  for (int64_t i = 0; i < m; ++i)
    if (tail[i] >= (uint64_t)n_vid || head[i] >= (uint64_t)n_vid) return -3;
  if (m == 0) return 0;

  std::vector<uint8_t> seen((size_t)n_vid, 0);
  int64_t n_active = 0;
  for (int64_t i = 0; i < m; ++i) {
    if (!seen[tail[i]]) { seen[tail[i]] = 1; ++n_active; }
    if (!seen[head[i]]) { seen[head[i]] = 1; ++n_active; }
  }
  if (n_active == 0) n_active = 1;

  const double y = 1.5;
  const double n = (double)n_active;
  const double md = (double)(2 * m);
  const double k = (double)num_parts;
  const double a = md * (std::pow(k, y - 1.0) / std::pow(n, y));
  const double max_component = (double)(m / num_parts) * balance_factor;

  const int64_t words = (num_parts + 63) / 64;
  std::vector<uint64_t> touch((size_t)(n_vid * words), 0);
  std::vector<double> part_size((size_t)num_parts, 0.0);

  for (int64_t i = 0; i < m; ++i) {
    const uint64_t* tX = &touch[(size_t)(tail[i] * words)];
    const uint64_t* tY = &touch[(size_t)(head[i] * words)];
    int64_t last = num_parts - 1;
    for (int64_t p = 0; p < num_parts; ++p)
      if (part_size[(size_t)p] == 0.0) { last = p; break; }
    int64_t best = -1;
    double best_score = 0.0;
    for (int64_t p = 0; p <= last; ++p) {
      if (part_size[(size_t)p] + 1.0 > max_component) continue;
      double s = part_size[(size_t)p];
      double value = (double)((tX[p / 64] >> (p % 64)) & 1)
                   + (double)((tY[p / 64] >> (p % 64)) & 1);
      double score = value - a * (std::pow(s + 1.0, y) - std::pow(s, y));
      if (best < 0 || score > best_score) { best = p; best_score = score; }
    }
    if (best < 0) best = 0;
    eparts_out[i] = best;
    part_size[(size_t)best] += 1.0;
    touch[(size_t)(tail[i] * words) + best / 64] |= 1ull << (best % 64);
    touch[(size_t)(head[i] * words) + best / 64] |= 1ull << (best % 64);
  }
  return 0;
}

// One edge block of the streamed O(n)-memory partition evaluator
// (partition/evaluate.py evaluate_partition_streamed; reference metric
// definitions at lib/partition.cpp:428-521).  Updates the caller's
// window bitmaps / load counters in place; bit-identical to the python
// block body.  ``pos`` may be null (sequence-free overload: m_down/m_up
// untouched).  Returns the edges_cut increment (first window only,
// else 0), or -1 on an out-of-range vid — the wrapper raises.
int64_t sheep_eval_block(const uint32_t* tail, const uint32_t* head,
                         int64_t e, const int64_t* parts, int64_t n,
                         const uint32_t* pos, int64_t pos_len,
                         int64_t w0, int32_t first_window,
                         uint64_t* m_vcom, uint64_t* m_hash,
                         uint64_t* m_down, uint64_t* m_up,
                         uint8_t* deg_mask, int64_t* hash_loads,
                         int64_t* down_loads, int64_t* up_loads,
                         int64_t num_parts) {
  constexpr uint32_t kMult = 2654435769u;  // floor(0.5*(sqrt(5)-1)*2^32)
  const int64_t w_hi = w0 + 64;
  int64_t edges_cut = 0;
  for (int64_t i = 0; i < e; ++i) {
    const uint32_t t = tail[i], h = head[i];
    if (t >= (uint64_t)n || h >= (uint64_t)n) return -1;
    if (pos && (t >= (uint64_t)pos_len || h >= (uint64_t)pos_len)) return -1;
    const int64_t pt = parts[t], ph = parts[h];
    if (first_window) {
      deg_mask[t] = 1;
      deg_mask[h] = 1;
      edges_cut += pt != ph;
    }
    const uint32_t ht = t * kMult, hh = h * kMult;
    const uint32_t post = pos ? pos[t] : 0, posh = pos ? pos[h] : 0;
    for (int dir = 0; dir < 2; ++dir) {
      const uint32_t X = dir ? h : t;
      const int64_t pX = dir ? ph : pt, pY = dir ? pt : ph;
      const uint32_t hX = dir ? hh : ht, hY = dir ? ht : hh;
      const uint32_t sX = dir ? posh : post, sY = dir ? post : posh;
      if (pY >= w0 && pY < w_hi) m_vcom[X] |= 1ull << (pY - w0);
      const int64_t p_hash = hX < hY ? pX : pY;
      if (p_hash >= w0 && p_hash < w_hi) m_hash[X] |= 1ull << (p_hash - w0);
      if (pos) {
        const int64_t p_down = sX < sY ? pX : pY;
        if (p_down >= w0 && p_down < w_hi) m_down[X] |= 1ull << (p_down - w0);
        const int64_t p_up = sX > sY ? pX : pY;
        if (p_up >= w0 && p_up < w_hi) m_up[X] |= 1ull << (p_up - w0);
      }
    }
    if (first_window) {
      // the caller contract requires parts to cover every streamed vid;
      // an INVALID_PART (-1) here would be heap corruption, and the
      // python body's np.bincount raises on it — error out the same way
      if (t != h) {
        const uint32_t a = t < h ? t : h, b = t < h ? h : t;
        const uint32_t ha = a * kMult, hb = b * kMult;
        const int64_t p = ha < hb ? parts[a] : parts[b];
        if (p < 0 || p >= num_parts) return -1;
        ++hash_loads[p];
      }
      if (pos) {
        if (pt < 0 || pt >= num_parts || ph < 0 || ph >= num_parts)
          return -1;
        if (post < posh) ++down_loads[pt]; else if (post > posh) ++up_loads[pt];
        if (posh < post) ++down_loads[ph]; else if (posh > post) ++up_loads[ph];
      }
    }
  }
  return edges_cut;
}

// ---------------------------------------------------------------------------
// Threading introspection (round-14): the Python bindings and the
// governor ask the library — not the environment — what the kernels
// will actually do, so a build compiled without OpenMP reports
// threads=1 honestly no matter what SHEEP_NATIVE_THREADS says.
// ---------------------------------------------------------------------------

// 1 when the library was compiled with OpenMP (the Makefile probes the
// toolchain and drops -fopenmp when absent — kernels then run serial).
int sheep_native_omp(void) {
#ifdef _OPENMP
  return 1;
#else
  return 0;
#endif
}

// The resolved SHEEP_NATIVE_THREADS (1 without OpenMP; clamped to
// [1, 64]) — what an UNGATED kernel call would use.
int sheep_native_threads(void) { return resolve_threads(); }

// Threads a kernel call over m records/links will actually use (the
// resolved count after the engagement floor and per-slice-work gates).
int sheep_threads_for(int64_t m) { return threads_for_work(m); }

// omp_get_max_threads() of the loaded runtime (1 without OpenMP) — the
// env_capture field bench records embed.
int sheep_omp_max_threads(void) {
#ifdef _OPENMP
  return omp_get_max_threads();
#else
  return 1;
#endif
}

// Per-thread busy seconds of this caller thread's LAST kernel call
// (filled by the threaded arms, reset to {1, 0.0} at every kernel
// entry).  Returns the thread count used; writes min(used, cap) busy
// values.  The bindings annotate native.* spans with these.
int sheep_last_thread_stats(double* busy_out, int cap) {
  const int u = g_tstats.used;
  for (int i = 0; i < u && i < cap; ++i) busy_out[i] = g_tstats.busy[i];
  return u;
}

}  // extern "C"
