"""ctypes bindings to the C++ host runtime (``libsheep_native.so``).

The library is built on demand with ``make`` (g++ is part of the toolchain;
pybind11 is not, so the ABI is plain C over caller-allocated numpy buffers).
If the toolchain is unavailable the callers fall back to the numpy oracle —
``available()`` reports which path is live.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

from ..obs import trace as _obs

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libsheep_native.so")
_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False

_u32p = np.ctypeslib.ndpointer(np.uint32, flags="C_CONTIGUOUS")
_i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
_i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
_u64p = np.ctypeslib.ndpointer(np.uint64, flags="C_CONTIGUOUS")
_u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")


def _load() -> ctypes.CDLL | None:
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        src = os.path.join(_DIR, "src", "sheep_native.cpp")
        stale = (os.path.exists(src) and os.path.exists(_SO)
                 and os.path.getmtime(_SO) < os.path.getmtime(src))
        if not os.path.exists(_SO) or stale:
            # Concurrent CLI processes may race to build: compile to a
            # process-unique name and publish with an atomic rename.
            tmp = f"{_SO}.{os.getpid()}.tmp"
            try:
                subprocess.run(
                    ["make", "-C", _DIR, f"OUT={os.path.basename(tmp)}"],
                    check=True,
                    stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
                os.replace(tmp, _SO)
            except (OSError, subprocess.CalledProcessError):
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                return None
        try:
            lib = ctypes.CDLL(_SO)
            _bind(lib)
        except (OSError, AttributeError):
            # missing file, wrong arch, or a stale .so lacking a newer
            # symbol — fall back to the numpy oracle
            return None
        _lib = lib
        return _lib


def _bind(lib: ctypes.CDLL) -> None:
    """Declare the C ABI; raises AttributeError on a stale library."""
    lib.sheep_build_forest.restype = ctypes.c_int
    lib.sheep_build_forest.argtypes = [
        _u32p, _u32p, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_void_p, _u32p, _u32p, ctypes.c_void_p]
    lib.sheep_edges_to_links.restype = ctypes.c_int64
    lib.sheep_edges_to_links.argtypes = [
        _u32p, _u32p, ctypes.c_int64, _u32p, ctypes.c_int64, _u32p, _u32p]
    lib.sheep_build_forest_edges.restype = ctypes.c_int
    lib.sheep_build_forest_edges.argtypes = [
        _u32p, _u32p, ctypes.c_int64, _u32p, ctypes.c_int64,
        ctypes.c_int64, _u32p, _u32p, ctypes.c_void_p]
    lib.sheep_build_forest_links_begin.restype = ctypes.c_int
    lib.sheep_build_forest_links_begin.argtypes = [
        ctypes.c_int64, ctypes.c_void_p, _u32p, _u32p, _u32p]
    lib.sheep_build_forest_links_block.restype = ctypes.c_int64
    lib.sheep_build_forest_links_block.argtypes = [
        _u32p, _u32p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int32, _u32p, _u32p, _u32p]
    lib.sheep_build_forest_links_finish.restype = ctypes.c_int
    lib.sheep_build_forest_links_finish.argtypes = [
        ctypes.c_int64, _u32p, _u32p]
    lib.sheep_forward_partition.restype = ctypes.c_int64
    lib.sheep_forward_partition.argtypes = [
        _u32p, _i64p, ctypes.c_int64, ctypes.c_int64, _i32p]
    lib.sheep_degree_histogram.restype = ctypes.c_int
    lib.sheep_degree_histogram.argtypes = [
        _u32p, _u32p, ctypes.c_int64, ctypes.c_int64, _i64p]
    lib.sheep_degree_histogram_acc.restype = ctypes.c_int
    lib.sheep_degree_histogram_acc.argtypes = [
        _u32p, _u32p, ctypes.c_int64, ctypes.c_int64, _i64p]
    lib.sheep_degree_sequence.restype = ctypes.c_int64
    lib.sheep_degree_sequence.argtypes = [
        _i64p, ctypes.c_int64, _u32p]
    lib.sheep_degree_sequence_edges.restype = ctypes.c_int64
    lib.sheep_degree_sequence_edges.argtypes = [
        _u32p, _u32p, ctypes.c_int64, ctypes.c_int64, _u32p]
    lib.sheep_jxn_build.restype = ctypes.c_int64
    lib.sheep_jxn_build.argtypes = [
        _u32p, _u32p, ctypes.c_int64, _u32p, ctypes.c_int64,
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        _u32p, _u32p, _u32p, _i64p]
    lib.sheep_fennel_vertex.restype = ctypes.c_int
    lib.sheep_fennel_vertex.argtypes = [
        _u32p, _u32p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_double, ctypes.c_int, _i64p]
    lib.sheep_fennel_edges.restype = ctypes.c_int
    lib.sheep_fennel_edges.argtypes = [
        _u32p, _u32p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_double, _i64p]
    lib.sheep_eval_block.restype = ctypes.c_int64
    lib.sheep_eval_block.argtypes = [
        _u32p, _u32p, ctypes.c_int64, _i64p, ctypes.c_int64,
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int32,
        _u64p, _u64p, ctypes.c_void_p, ctypes.c_void_p,
        _u8p, _i64p, _i64p, _i64p, ctypes.c_int64]
    lib.sheep_native_omp.restype = ctypes.c_int
    lib.sheep_native_omp.argtypes = []
    lib.sheep_native_threads.restype = ctypes.c_int
    lib.sheep_native_threads.argtypes = []
    lib.sheep_threads_for.restype = ctypes.c_int
    lib.sheep_threads_for.argtypes = [ctypes.c_int64]
    lib.sheep_omp_max_threads.restype = ctypes.c_int
    lib.sheep_omp_max_threads.argtypes = []
    lib.sheep_last_thread_stats.restype = ctypes.c_int
    lib.sheep_last_thread_stats.argtypes = [
        ctypes.POINTER(ctypes.c_double), ctypes.c_int]


def available() -> bool:
    return _load() is not None


# -- threading (round-14) ---------------------------------------------------
# SHEEP_NATIVE_THREADS (resolved by the governor from SHEEP_LEG_CORES /
# affinity / cgroup quota, resources/governor.py) arms the kernels'
# OpenMP decomposition: per-thread partial forests/histograms over
# contiguous slices, merged deterministically (bit-identical to T=1 for
# every thread count).  The LIBRARY is the authority on what actually
# runs — a build compiled without OpenMP reports threads=1 no matter
# what the environment says.


def omp_compiled() -> bool:
    """True when the loaded library was compiled with OpenMP (the
    Makefile probes the toolchain and degrades to a serial build)."""
    lib = _load()
    return bool(lib is not None and lib.sheep_native_omp())


def resolve_threads() -> int:
    """The resolved ``SHEEP_NATIVE_THREADS`` of the loaded library —
    what an ungated kernel call would use (1 without OpenMP)."""
    lib = _load()
    return int(lib.sheep_native_threads()) if lib is not None else 1


def threads_for(m: int) -> int:
    """Threads a kernel call over ``m`` records/links will ACTUALLY use
    (after the engagement floor and per-slice-work gates)."""
    lib = _load()
    return int(lib.sheep_threads_for(m)) if lib is not None else 1


def omp_max_threads() -> int:
    """``omp_get_max_threads()`` of the loaded runtime (1 without
    OpenMP) — the env_capture field bench records embed."""
    lib = _load()
    return int(lib.sheep_omp_max_threads()) if lib is not None else 1


def _threads_live() -> bool:
    """Cheap pre-gate: skip the per-call stats read entirely when no
    thread count is configured (the overwhelming default path)."""
    return os.environ.get("SHEEP_NATIVE_THREADS", "") not in ("", "0", "1")


def _annotate_threads(sp) -> None:
    """Merge the last kernel call's thread telemetry into its span:
    ``threads`` (what the kernel really used — the gates may have picked
    1) and per-thread busy seconds, the flight recorder's arbiter for
    whether a forced T did parallel work or just time-shared a core."""
    lib = _lib
    if lib is None:
        return
    buf = (ctypes.c_double * 64)()
    used = int(lib.sheep_last_thread_stats(buf, 64))
    if used > 1:
        sp.annotate(threads=used,
                    thread_busy_s=[round(buf[i], 6) for i in range(used)])
    else:
        sp.annotate(threads=1)


def build_forest_links(lo: np.ndarray, hi: np.ndarray, n: int,
                       pst: np.ndarray | None = None,
                       compute_pre: bool = False):
    """Native elimination-forest build; returns (parent, pst) uint32 [n],
    plus a pre_weight array (lib/jnode.h:174-176) when ``compute_pre``."""
    lib = _load()
    assert lib is not None
    lo = np.ascontiguousarray(lo, dtype=np.uint32)
    hi = np.ascontiguousarray(hi, dtype=np.uint32)
    parent = np.empty(n, dtype=np.uint32)
    pst_out = np.empty(n, dtype=np.uint32)
    pst_ptr = None
    if pst is not None:
        pst = np.ascontiguousarray(pst, dtype=np.uint32)
        pst_ptr = pst.ctypes.data_as(ctypes.c_void_p)
    pre_out = np.empty(n, dtype=np.uint32) if compute_pre else None
    pre_ptr = pre_out.ctypes.data_as(ctypes.c_void_p) if compute_pre else None
    with _obs.span("native.build_forest", links=len(lo), n=n) as sp:
        rc = lib.sheep_build_forest(lo, hi, len(lo), n, pst_ptr, parent,
                                    pst_out, pre_ptr)
        if _threads_live():
            _annotate_threads(sp)
    if rc != 0:
        raise RuntimeError(f"sheep_build_forest failed rc={rc}")
    if compute_pre:
        return parent, pst_out, pre_out
    return parent, pst_out


class LinksFold:
    """Resumable native link fold (sheep_build_forest_links_begin/_block/
    _finish): the exact forest build consumed one ascending-hi window at a
    time, so the streaming handoff can fold window k while window k+1 is
    still in flight and the full link table never materializes host-side.

    Blocks must arrive in ascending-hi order (an equal-hi group may split
    across adjacent blocks — exact, see the kernel comment); ``block``
    raises ValueError on an out-of-order window so a mis-sliced stream
    fails loudly instead of building a different forest.  ``pst`` None
    means the fold accumulates pst from the streamed records themselves —
    exact only when the windows together carry the ORIGINAL link multiset
    (the immediate-handoff stream); reduced/rewritten links need the
    prep-time pst passed here.
    """

    def __init__(self, n: int, pst: np.ndarray | None = None):
        lib = _load()
        assert lib is not None
        self._lib = lib
        self.n = n
        self.accumulate_pst = pst is None
        self.parent = np.empty(n, dtype=np.uint32)
        self.pst = np.empty(n, dtype=np.uint32)
        self._uf = np.empty(n, dtype=np.uint32)
        self._bound = 0
        self._done = False
        pst_ptr = None
        if pst is not None:
            pst = np.ascontiguousarray(pst, dtype=np.uint32)
            pst_ptr = pst.ctypes.data_as(ctypes.c_void_p)
        rc = lib.sheep_build_forest_links_begin(n, pst_ptr, self.parent,
                                                self.pst, self._uf)
        if rc != 0:
            raise RuntimeError(f"sheep_build_forest_links_begin rc={rc}")

    def block(self, lo: np.ndarray, hi: np.ndarray) -> None:
        """Fold one window of links (uint32-safe arrays, every lo < n,
        linked hi >= every previous window's linked hi)."""
        assert not self._done, "fold already finished"
        lo = np.ascontiguousarray(lo, dtype=np.uint32)
        hi = np.ascontiguousarray(hi, dtype=np.uint32)
        with _obs.span("native.links_fold.block", links=len(lo)) as sp:
            r = self._lib.sheep_build_forest_links_block(
                lo, hi, len(lo), self.n, self._bound,
                1 if self.accumulate_pst else 0, self.parent, self.pst,
                self._uf)
            if _threads_live():
                _annotate_threads(sp)
        if r == -7:
            raise ValueError(
                "out-of-order fold window: a linked hi precedes the "
                "previous window's range — windows must ascend by hi")
        if r == -3:
            raise ValueError(f"malformed link: lo >= n ({self.n})")
        if r < 0:
            raise RuntimeError(f"sheep_build_forest_links_block rc={r}")
        self._bound = int(r)

    def finish(self) -> tuple[np.ndarray, np.ndarray]:
        """Seal the fold; returns (parent, pst) uint32 [n]."""
        with _obs.span("native.links_fold.finish", n=self.n):
            rc = self._lib.sheep_build_forest_links_finish(
                self.n, self.parent, self._uf)
        if rc != 0:
            raise RuntimeError(f"sheep_build_forest_links_finish rc={rc}")
        self._done = True
        return self.parent, self.pst


def blocked_enabled() -> bool:
    """The cache-blocked kernel gate (SHEEP_NATIVE_BLOCKED, default on).
    Read per call so A/B arms can flip it without reloading the library;
    the C++ side reads the same variable for its internal dispatch."""
    return os.environ.get("SHEEP_NATIVE_BLOCKED", "1") != "0"


def build_forest_edges(tail: np.ndarray, head: np.ndarray, pos: np.ndarray,
                       n: int, compute_pre: bool = False):
    """Fused edge->forest build (round-6): maps records through the
    position table and groups into the cache-blocked union-find without
    materializing the intermediate link arrays.  Returns (parent, pst)
    uint32 [n] (+ pre when ``compute_pre``), bit-identical to
    edges_to_links + build_forest_links."""
    lib = _load()
    assert lib is not None
    tail = np.ascontiguousarray(tail, dtype=np.uint32)
    head = np.ascontiguousarray(head, dtype=np.uint32)
    pos = np.ascontiguousarray(pos, dtype=np.uint32)
    parent = np.empty(n, dtype=np.uint32)
    pst_out = np.empty(n, dtype=np.uint32)
    pre_out = np.empty(n, dtype=np.uint32) if compute_pre else None
    pre_ptr = pre_out.ctypes.data_as(ctypes.c_void_p) if compute_pre else None
    with _obs.span("native.build_forest_edges", records=len(tail),
                   n=n) as sp:
        rc = lib.sheep_build_forest_edges(tail, head, len(tail), pos,
                                          len(pos), n, parent, pst_out,
                                          pre_ptr)
        if _threads_live():
            _annotate_threads(sp)
    if rc != 0:
        raise RuntimeError(f"sheep_build_forest_edges failed rc={rc}")
    if compute_pre:
        return parent, pst_out, pre_out
    return parent, pst_out


def edges_to_links(tail: np.ndarray, head: np.ndarray, pos: np.ndarray):
    """Map edge records through a position table; drops self-loops and
    absent vids.  Returns (lo, hi) uint32 arrays."""
    lib = _load()
    assert lib is not None
    tail = np.ascontiguousarray(tail, dtype=np.uint32)
    head = np.ascontiguousarray(head, dtype=np.uint32)
    pos = np.ascontiguousarray(pos, dtype=np.uint32)
    lo = np.empty(len(tail), dtype=np.uint32)
    hi = np.empty(len(tail), dtype=np.uint32)
    k = lib.sheep_edges_to_links(tail, head, len(tail), pos, len(pos), lo, hi)
    return lo[:k], hi[:k]


def forward_partition(parent: np.ndarray, weights: np.ndarray,
                      max_component: int) -> np.ndarray:
    """Native FFD tree partition; returns int32 part array."""
    lib = _load()
    assert lib is not None
    parent = np.ascontiguousarray(parent, dtype=np.uint32)
    weights = np.ascontiguousarray(weights, dtype=np.int64)
    parts = np.empty(len(parent), dtype=np.int32)
    with _obs.span("native.forward_partition", n=len(parent)):
        rc = lib.sheep_forward_partition(parent, weights, len(parent),
                                         max_component, parts)
    if rc == -2:
        raise ValueError(
            f"max_component {max_component} smaller than the heaviest node; "
            f"request fewer partitions or a larger balance factor")
    if rc == -3:
        raise ValueError(
            "corrupt tree: a parent entry is neither INVALID nor a valid "
            "node id (malformed .tre input?)")
    if rc < 0:
        raise RuntimeError(f"sheep_forward_partition failed rc={rc}")
    return parts


def degree_histogram(tail: np.ndarray, head: np.ndarray, n: int) -> np.ndarray:
    lib = _load()
    assert lib is not None
    tail = np.ascontiguousarray(tail, dtype=np.uint32)
    head = np.ascontiguousarray(head, dtype=np.uint32)
    deg = np.empty(n, dtype=np.int64)
    rc = lib.sheep_degree_histogram(tail, head, len(tail), n, deg)
    if rc == -3:
        raise ValueError(
            f"corrupt edge records: a vid is out of range for n={n}")
    if rc != 0:
        raise RuntimeError(f"sheep_degree_histogram failed rc={rc}")
    return deg


def degree_histogram_acc(tail: np.ndarray, head: np.ndarray,
                         deg: np.ndarray) -> None:
    """Add one edge block's degree contributions INTO ``deg`` (int64
    [n], caller-owned, NOT zeroed here) — the streaming accumulator of
    the out-of-core degree pass (ops/extmem.py): per-block adds fold into
    one histogram with no per-block allocation, exactly equal to the
    one-shot histogram over the concatenated records."""
    lib = _load()
    assert lib is not None
    tail = np.ascontiguousarray(tail, dtype=np.uint32)
    head = np.ascontiguousarray(head, dtype=np.uint32)
    assert deg.dtype == np.int64 and deg.flags["C_CONTIGUOUS"]
    with _obs.span("native.degree_histogram_acc", records=len(tail)) as sp:
        rc = lib.sheep_degree_histogram_acc(tail, head, len(tail),
                                            len(deg), deg)
        if _threads_live():
            _annotate_threads(sp)
    if rc == -3:
        raise ValueError(
            f"corrupt edge records: a vid is out of range for n={len(deg)}")
    if rc != 0:
        raise RuntimeError(f"sheep_degree_histogram_acc failed rc={rc}")


def jxn_build(tail: np.ndarray, head: np.ndarray, seq: np.ndarray,
              n_vid: int, width_limit: int, memory_limit: int,
              make_pad: bool, make_pst: bool, make_jxn: bool,
              find_max_width: bool, do_rooting: bool):
    """Native parameterized jxn insert (sheep_jxn_build).

    Returns (parent, pst, out_seq, widths) trimmed to the effective node
    count.  Raises MemoryError past memory_limit (rc -4) like the oracle.
    """
    lib = _load()
    assert lib is not None
    tail = np.ascontiguousarray(tail, dtype=np.uint32)
    head = np.ascontiguousarray(head, dtype=np.uint32)
    seq = np.ascontiguousarray(seq, dtype=np.uint32)
    cap = len(seq)
    parent = np.empty(cap, dtype=np.uint32)
    pst = np.empty(cap, dtype=np.uint32)
    out_seq = np.empty(cap, dtype=np.uint32)
    widths = np.empty(cap, dtype=np.int64)
    flags = (1 * make_pad) | (4 * make_pst) | (8 * make_jxn) | \
        (16 * find_max_width) | (32 * do_rooting)
    n_out = lib.sheep_jxn_build(tail, head, len(tail), seq, cap, n_vid,
                                width_limit, memory_limit, flags,
                                parent, pst, out_seq, widths)
    if n_out == -4:
        raise MemoryError(
            f"pst/jxn tables exceed memory_limit={memory_limit}")
    if n_out < 0:
        raise ValueError(f"sheep_jxn_build failed rc={n_out}")
    k = int(n_out)
    return (parent[:k].copy(), pst[:k].copy(), out_seq[:k].copy(),
            widths[:k].copy())


def fennel_vertex(tail: np.ndarray, head: np.ndarray, n_vid: int,
                  num_parts: int, balance_factor: float,
                  edge_balanced: bool) -> np.ndarray:
    """Native greedy Fennel vertex partition; int64 [n_vid], -1 invalid."""
    lib = _load()
    assert lib is not None
    tail = np.ascontiguousarray(tail, dtype=np.uint32)
    head = np.ascontiguousarray(head, dtype=np.uint32)
    parts = np.empty(n_vid, dtype=np.int64)
    rc = lib.sheep_fennel_vertex(tail, head, len(tail), n_vid, num_parts,
                                 balance_factor, int(edge_balanced), parts)
    if rc != 0:
        raise ValueError(f"sheep_fennel_vertex failed rc={rc}")
    return parts


def fennel_edges(tail: np.ndarray, head: np.ndarray, n_vid: int,
                 num_parts: int, balance_factor: float) -> np.ndarray:
    """Native streaming Fennel edge partition; int64 [num_records]."""
    lib = _load()
    assert lib is not None
    tail = np.ascontiguousarray(tail, dtype=np.uint32)
    head = np.ascontiguousarray(head, dtype=np.uint32)
    eparts = np.empty(len(tail), dtype=np.int64)
    rc = lib.sheep_fennel_edges(tail, head, len(tail), n_vid, num_parts,
                                balance_factor, eparts)
    if rc != 0:
        raise ValueError(f"sheep_fennel_edges failed rc={rc}")
    return eparts


def degree_sequence_from_edges(tail: np.ndarray, head: np.ndarray,
                               n: int) -> np.ndarray | None:
    """Fused histogram + counting-sort degree sequence (round-6): one
    call, uint32 histogram internally.  Returns None when the record or
    degree range outgrows the fused kernel's buckets (callers fall back
    to the two-call path), raises on out-of-range vids."""
    lib = _load()
    assert lib is not None
    tail = np.ascontiguousarray(tail, dtype=np.uint32)
    head = np.ascontiguousarray(head, dtype=np.uint32)
    seq = np.empty(n, dtype=np.uint32)
    with _obs.span("native.degree_sequence_edges", records=len(tail)) as sp:
        k = lib.sheep_degree_sequence_edges(tail, head, len(tail), n, seq)
        if _threads_live():
            _annotate_threads(sp)
    if k == -3:
        raise ValueError(
            f"corrupt edge records: a vid is out of range for n={n}")
    if k < 0:
        return None
    return seq[:k].copy()


def degree_sequence_from_degrees(deg: np.ndarray) -> np.ndarray | None:
    """Counting-sort degree sequence (ascending degree, vid tie break).

    Returns None when the degree range is too wide for counting buckets
    (a multigraph hub can push max_degree far past n); callers fall back
    to the comparison sort.
    """
    deg = np.ascontiguousarray(deg, dtype=np.int64)
    if len(deg) and int(deg.max()) > max(4 * len(deg), 1 << 20):
        return None
    lib = _load()
    assert lib is not None
    seq = np.empty(len(deg), dtype=np.uint32)
    with _obs.span("native.degree_sequence", n=len(deg)) as sp:
        k = lib.sheep_degree_sequence(deg, len(deg), seq)
        if _threads_live():
            _annotate_threads(sp)
    return seq[:k].copy()


def eval_block(tail: np.ndarray, head: np.ndarray, parts: np.ndarray,
               pos: np.ndarray | None, w0: int, first_window: bool,
               m_vcom: np.ndarray, m_hash: np.ndarray,
               m_down: np.ndarray | None, m_up: np.ndarray | None,
               deg_mask: np.ndarray, hash_loads: np.ndarray,
               down_loads: np.ndarray, up_loads: np.ndarray,
               num_parts: int) -> int:
    """One block of the streamed partition evaluator (updates the window
    bitmaps / load counters in place); returns the edges_cut increment.
    All array arguments must be the caller-owned state buffers — they are
    mutated, not copied.
    """
    lib = _load()
    assert lib is not None
    tail = np.ascontiguousarray(tail, dtype=np.uint32)
    head = np.ascontiguousarray(head, dtype=np.uint32)
    pos_ptr, pos_len = 0, 0
    if pos is not None:
        # the C kernel writes m_down/m_up whenever pos is given — a
        # missing mask would be a null-pointer store
        assert m_down is not None and m_up is not None, \
            "pos requires both m_down and m_up buffers"
        assert pos.dtype == np.uint32 and pos.flags["C_CONTIGUOUS"]
        pos_ptr, pos_len = pos.ctypes.data, len(pos)
        assert m_down.dtype == np.uint64 and m_down.flags["C_CONTIGUOUS"]
        assert m_up.dtype == np.uint64 and m_up.flags["C_CONTIGUOUS"]
    # parts / masks / counters go through ndpointer argtypes, which
    # already enforce dtype + contiguity with clear TypeErrors; only the
    # raw-pointer (c_void_p) arguments need manual validation above.
    down_ptr = m_down.ctypes.data if pos is not None else 0
    up_ptr = m_up.ctypes.data if pos is not None else 0
    rc = lib.sheep_eval_block(
        tail, head, len(tail), parts, len(parts), pos_ptr, pos_len,
        w0, 1 if first_window else 0, m_vcom, m_hash, down_ptr, up_ptr,
        deg_mask, hash_loads, down_loads, up_loads, num_parts)
    if rc < 0:
        raise ValueError(
            "sheep_eval_block: a vid is out of range of parts/pos, or a "
            "streamed vertex has an invalid part id (e.g. INVALID_PART "
            "-1) — parts must cover every vid in the edge stream")
    return int(rc)
