"""Defense-in-depth data integrity for every artifact the pipeline
exchanges (ISSUE 2).

Layers, cheapest first:

  1. sidecar checksums (integrity.sidecar) — every writer seals a
     ``.sum`` next to its artifact; every reader verifies on read under a
     strict/repair/trust policy (env ``SHEEP_INTEGRITY``).
  2. hardened parsers (io/) — malformed input raises a typed
     :class:`IntegrityError` naming the byte-level lie instead of
     producing a silently wrong partition.
  3. fast oracles (core.validate.check_forest_fast) — vectorized O(E)
     invariants (pst conservation, parent monotonicity) run at chunk /
     merge / partition boundaries; the exact root-path oracle
     (core.validate.is_valid_forest) is the slow tier.
  4. ``sheep fsck`` (integrity.fsck, cli.fsck, bin/fsck) — verify any
     artifact or trial directory; the shell pipeline runs it before every
     merge tournament.
"""

from .errors import (ChecksumMismatch, IncompatibleMerge, IntegrityError,
                     MalformedArtifact)
from .fsck import collect_artifacts, fsck_file, fsck_paths
from .sidecar import (DEFAULT_ALGO, POLICIES, checksummed_write, read_sidecar,
                      resolve_policy, sidecar_path, verify_bytes, verify_file,
                      write_sidecar)

__all__ = [
    "ChecksumMismatch",
    "IncompatibleMerge",
    "IntegrityError",
    "MalformedArtifact",
    "collect_artifacts",
    "fsck_file",
    "fsck_paths",
    "DEFAULT_ALGO",
    "POLICIES",
    "checksummed_write",
    "read_sidecar",
    "resolve_policy",
    "sidecar_path",
    "verify_bytes",
    "verify_file",
    "write_sidecar",
]
