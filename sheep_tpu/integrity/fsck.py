"""``sheep fsck`` core: verify any artifact, or a whole trial directory.

One function per artifact class; each returns a human-readable summary on
success and raises IntegrityError (or OSError) on any corruption.  The
checks are layered — sidecar checksum first (when one exists), then the
format's structural invariants, then the cheap semantic invariants the
merge-associativity property gives us for free (parents strictly later
than kids, pst totals plausible).  ``sheep fsck`` exits nonzero iff any
checked artifact fails; the shell pipeline runs it on the worker trees
before every merge tournament (scripts/horizontal-dist.sh).
"""

from __future__ import annotations

import os

import numpy as np

from .errors import IntegrityError, MalformedArtifact
from .sidecar import read_sidecar, resolve_policy, verify_file

#: suffixes fsck knows how to verify (``.npz`` = runtime snapshots,
#: ``.wal``/``.snap`` = the serve daemon's log + serving snapshots,
#: ``.trace`` = flight-recorder span logs, ISSUE 10; ``.hist`` = the
#: distext legs' per-range degree histograms, ISSUE 13)
ARTIFACT_SUFFIXES = (".tre", ".seq", ".dat", ".net", ".npz",
                     ".wal", ".snap", ".trace", ".hist")


def _fsck_tre(path: str, mode: str) -> str:
    from .. import INVALID_JNID
    from ..io.trefile import read_tree

    parent, pst = read_tree(path, integrity=mode)
    linked = parent != INVALID_JNID
    return (f"n={len(parent)} links={int(linked.sum())} "
            f"pst_total={int(pst.sum())}")


def _fsck_seq(path: str, mode: str) -> str:
    from ..io.seqfile import read_sequence

    seq = read_sequence(path, binary="auto", integrity=mode)
    if len(seq) and len(np.unique(seq)) != len(seq):
        raise MalformedArtifact(
            f"{path}: corrupt sequence — duplicate vids (an elimination "
            f"order visits each vertex once)")
    return f"m={len(seq)}"


def _fsck_dat(path: str, mode: str) -> str:
    from ..io.edges import read_dat

    el = read_dat(path, integrity=mode)
    return f"records={el.num_edges}"


def _fsck_net(path: str, mode: str) -> str:
    from ..io.edges import read_net

    el = read_net(path, integrity=mode)
    return f"records={el.num_edges}"


def _fsck_npz(path: str, mode: str) -> str:
    from ..runtime.snapshot import load_snapshot

    snap = load_snapshot(path, integrity=mode)
    return (f"n={snap.n} links={len(snap.lo)} rounds={snap.rounds} "
            f"rung={snap.rung}")


def _fsck_wal(path: str, mode: str) -> str:
    """Verify the serve WAL chain: header magic/version/epoch,
    per-record crc32, strictly monotone sequence numbers, and the
    cross-artifact chain (ISSUES 6+7) — the log and its sibling
    snapshot must name the same build input, a log whose epoch differs
    from the snapshot's must respect the promotion boundary (an
    earlier-epoch log may never reach PAST the later epoch's sealed
    seqno: that is a fenced ex-leader's divergent tail), and two sibling
    logs of different epochs must cover disjoint seqno ranges.  Strict
    refuses a torn tail; repair reports the salvageable prefix."""
    from ..serve.wal import read_wal

    sig, epoch, records, _, torn = read_wal(path, mode)
    last = records[-1][0] if records else 0
    first = records[0][0] if records else 0
    detail = f"records={len(records)} last_seqno={last} epoch={epoch}"
    if torn:
        detail += " torn_tail=truncatable"
    here = os.path.dirname(path) or "."
    # chain check against the newest loadable sibling snapshot — by
    # epoch first: a promotion crash window can leave the later term
    # under a lower applied-seqno filename (serve/state.py open)
    from ..serve.state import load_serve_snapshot, snap_paths
    best = None
    for snap_path in snap_paths(here):
        try:
            snap = load_serve_snapshot(snap_path, integrity="trust")
        except (IntegrityError, OSError):
            continue
        if best is None or ((snap.epoch, snap.applied_seqno)
                            > (best[1].epoch, best[1].applied_seqno)):
            best = (snap_path, snap)
    if best is not None:
        snap_path, snap = best
        if snap.sig != sig:
            # a sig mismatch is corruption UNLESS the reseq manifest
            # sanctions it (ISSUE 18): the crash window between the
            # re-sequence seal and the WAL swap leaves the old-sig log
            # beside the new-generation snapshot, and ServeCore.open
            # heals exactly that — provided no log record lies past the
            # snapshot boundary.  Records past it are the torn mid-swap
            # state: refused strict, reported truncatable in repair.
            from ..serve.reseq import sanctions_sig_change
            if not sanctions_sig_change(here, sig, snap.sig):
                raise MalformedArtifact(
                    f"{path}: WAL signature {sig[:12]}... does not match "
                    f"snapshot {os.path.basename(snap_path)} "
                    f"({snap.sig[:12]}...) — log and snapshot are not one "
                    f"recovery chain")
            beyond = sum(1 for s, _ in records
                         if s > snap.applied_seqno)
            if beyond:
                if mode != "repair":
                    raise MalformedArtifact(
                        f"{path}: torn mid-re-sequence swap — the "
                        f"old-generation log holds {beyond} record(s) "
                        f"past the re-sequenced snapshot boundary "
                        f"{snap.applied_seqno} "
                        f"({os.path.basename(snap_path)}); they were "
                        f"applied to a tree that no longer exists and "
                        f"can only be truncated (repair mode)")
                detail += (f" reseq_heal=pending "
                           f"torn_records={beyond} truncatable")
            else:
                detail += " reseq_heal=pending"
        if epoch < snap.epoch and records and last > snap.applied_seqno:
            raise MalformedArtifact(
                f"{path}: cross-epoch seqno overlap — the epoch-{epoch} "
                f"log reaches seqno {last}, past the epoch-{snap.epoch} "
                f"snapshot boundary {snap.applied_seqno} "
                f"({os.path.basename(snap_path)}); a fenced log may "
                f"never extend a later epoch's history")
        if epoch > snap.epoch:
            raise MalformedArtifact(
                f"{path}: WAL epoch {epoch} is ahead of every readable "
                f"snapshot (newest is epoch {snap.epoch}, "
                f"{os.path.basename(snap_path)}) — the promotion that "
                f"sealed epoch {epoch} left no loadable snapshot; the "
                f"chain cannot replay across that boundary")
        detail += f" chain={os.path.basename(snap_path)}"
    # sibling logs: different epochs must cover DISJOINT seqno ranges
    # (the archived pre-promotion log vs the live one)
    from ..serve.wal import archived_wal_paths, wal_path
    siblings = set(archived_wal_paths(here))
    live = wal_path(here)
    if os.path.exists(live):
        siblings.add(live)
    siblings.discard(os.path.abspath(path))
    siblings.discard(path)
    for other in sorted(siblings):
        try:
            o_sig, o_epoch, o_records, _, _ = read_wal(other, "repair")
        except (IntegrityError, OSError):
            continue  # the sibling fails on its own fsck line
        if o_sig != sig:
            # sibling logs across a sanctioned re-sequence (an archived
            # pre-reseq log beside the new-generation live one) are one
            # history in two generations, not two histories
            from ..serve.reseq import sanctions_sig_change
            if not (sanctions_sig_change(here, o_sig, sig)
                    or sanctions_sig_change(here, sig, o_sig)):
                raise MalformedArtifact(
                    f"{path}: sibling log {os.path.basename(other)} "
                    f"names a different build input ({o_sig[:12]}... vs "
                    f"{sig[:12]}...) — one state dir, two histories")
        if o_epoch == epoch or not records or not o_records:
            continue
        o_first, o_last = o_records[0][0], o_records[-1][0]
        lo_last, hi_first = ((last, o_first) if epoch < o_epoch
                             else (o_last, first))
        if hi_first <= lo_last:
            raise MalformedArtifact(
                f"{path}: cross-epoch seqno overlap with "
                f"{os.path.basename(other)} — epoch {min(epoch, o_epoch)}"
                f" ends at seqno {lo_last} but epoch "
                f"{max(epoch, o_epoch)} starts at {hi_first}; epochs "
                f"must hand off disjoint seqno ranges")
    return detail


def _fsck_snap(path: str, mode: str) -> str:
    from ..serve.state import load_serve_snapshot

    snap = load_serve_snapshot(path, integrity=mode)
    from .. import INVALID_JNID
    links = int((snap.parent != INVALID_JNID).sum())
    detail = (f"n={len(snap.seq)} links={links} "
              f"applied={snap.applied_seqno} epoch={snap.epoch} "
              f"inserted={len(snap.ins_tail)} parts={snap.num_parts}")
    if snap.seq_gen:
        detail += f" seq_gen={snap.seq_gen}"
    return detail


def _fsck_trace(path: str, mode: str) -> str:
    """Verify a flight-recorder trace (obs/trace.py): every line parses
    as a JSON trace record; a torn trailing line — the kill -9 shape —
    is refused strict / reported truncatable in repair (same contract as
    the WAL); an unparseable line with intact records after it is
    mid-file rot, refused in every mode.

    Rotation chains (ISSUE 12): a ROTATED segment (``x.0001.trace``) had
    its tail sealed at rotation, so a torn tail there is mid-chain
    damage, not a kill — rotated segments are read strictly even under
    repair (trust still trusts).  Only the newest (active) file of a
    chain may legally be torn."""
    from ..obs.trace import is_rotated_segment, read_trace

    rotated = is_rotated_segment(path)
    if rotated and mode != "trust":
        mode = "strict"
    import warnings
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")  # the tear shows in the detail
        records, _, torn = read_trace(path, mode)
    spans = sum(1 for r in records if r.get("k") == "span")
    events = sum(1 for r in records if r.get("k") == "ev")
    segments = sum(1 for r in records if r.get("k") == "meta")
    detail = (f"records={len(records)} spans={spans} events={events} "
              f"segments={segments}")
    if rotated:
        detail += " segment=rotated"
    if torn:
        detail += " torn_tail=truncatable"
    return detail


def _fsck_hist(path: str, mode: str) -> str:
    """Verify a distext per-range histogram (ISSUE 13): sidecar
    checksum, magic/length/int64 dtype, nonnegativity, and the range
    invariants (records == slice length, degree total == 2 x records,
    the max vid really appears).  The cross-artifact half — a histogram
    whose range disagrees with the distext manifest's shard map — is
    checked by :func:`fsck_distext_manifest` when fsck walks a state
    dir."""
    from ..ops.distext import read_histogram

    h = read_histogram(path, integrity=mode)
    return (f"n={len(h['deg'])} records={h['records']} "
            f"range=[{h['start']}:{h['end']}) max_vid={h['max_vid']}")


def fsck_distext_manifest(state_dir: str,
                          mode: str | None = None) -> str | None:
    """Verify a distext state dir's shard-map chain (ISSUE 13): the
    manifest loads + verifies, its shards are a contiguous edge-disjoint
    cover of the graph's record count, and every published ``.hist``
    artifact's recorded range matches its leg's shard — a histogram
    whose coverage disagrees with the manifest is REFUSED here, because
    summing it would produce a plausible-looking but wrong sequence.

    Returns the summary line, or None when the directory's manifest is
    a plain (non-distext) tournament; raises on any corruption."""
    from ..ops.distext import read_histogram
    from ..supervisor.manifest import load_manifest

    mode = resolve_policy(mode)
    manifest = load_manifest(state_dir, mode)
    if manifest.shards is None:
        return None
    shards = [(int(a), int(b)) for a, b in manifest.shards]
    at = 0
    for i, (a, b) in enumerate(shards):
        if a != at or b < a:
            raise MalformedArtifact(
                f"{state_dir}: shard map is not a contiguous cover — "
                f"shard {i} is [{a}:{b}) but the previous one ends at "
                f"{at}")
        at = b
    if manifest.graph.endswith(".dat") and manifest.graph_bytes >= 0 \
            and at != manifest.graph_bytes // 12:
        raise MalformedArtifact(
            f"{state_dir}: shard map covers {at} records but the "
            f"manifest's graph has {manifest.graph_bytes // 12}")
    checked = 0
    for leg in manifest.legs:
        if leg.kind != "hist" or not os.path.exists(leg.output):
            continue
        h = read_histogram(leg.output, integrity=mode)
        a, b = shards[leg.index]
        if (h["start"], h["end"]) != (a, b):
            raise MalformedArtifact(
                f"{leg.output}: histogram covers "
                f"[{h['start']}:{h['end']}) but the manifest's shard "
                f"map assigns leg {leg.index} [{a}:{b}) — refusing a "
                f"histogram that disagrees with the manifest")
        checked += 1
    return (f"distext legs={len(shards)} records={at} "
            f"hists={checked}/{len(shards)} shard-map-ok")


_CHECKERS = {
    ".tre": _fsck_tre,
    ".seq": _fsck_seq,
    ".dat": _fsck_dat,
    ".net": _fsck_net,
    ".npz": _fsck_npz,
    ".wal": _fsck_wal,
    ".snap": _fsck_snap,
    ".trace": _fsck_trace,
    ".hist": _fsck_hist,
}


def fsck_file(path: str, mode: str | None = None) -> str:
    """Verify one artifact; returns a summary string or raises."""
    mode = resolve_policy(mode)
    for suffix, checker in _CHECKERS.items():
        if path.endswith(suffix):
            detail = checker(path, mode)
            status = "sum=" + _sidecar_state(path, mode)
            return f"{detail} {status}"
    # unknown suffix: the sidecar (if any) is still checkable
    state = verify_file(path, mode)
    if state == "no-sidecar":
        raise MalformedArtifact(
            f"{path}: not a sheep artifact (want one of "
            f"{'/'.join(_CHECKERS)}) and no sidecar to verify")
    return f"opaque bytes sum={state}"


def _sidecar_state(path: str, mode: str) -> str:
    if mode == "trust":
        return "trusted"
    try:
        return "absent" if read_sidecar(path) is None else "verified"
    except MalformedArtifact:
        return "unreadable"


def collect_artifacts(root: str) -> list[str]:
    """Every checkable artifact under ``root`` (a file is itself)."""
    if os.path.isfile(root):
        return [root]
    out = []
    for dirpath, _, names in os.walk(root):
        for name in sorted(names):
            if name.endswith(ARTIFACT_SUFFIXES):
                out.append(os.path.join(dirpath, name))
    return out


def repair_sidecar(path: str) -> str:
    """Reseal ``path``'s ``.sum`` sidecar after verifying the artifact
    STRUCTURALLY (``sheep fsck --repair-sidecar``).

    The operation covers exactly two legitimate states: a sidecar that was
    LOST (a foreign copy, an interrupted ``cp`` that moved the artifact
    but not its sidecar) and a sidecar that is WRONG for bytes that still
    parse (the crash window between the artifact rename and the sidecar
    rename, sidecar.py module docstring).  Every format/semantic check the
    artifact class has still runs — only the checksum layer is skipped —
    so garbage is refused, never vouched for; but note the honest limit:
    a corruption that keeps the artifact structurally valid is
    indistinguishable from a legitimate reseal, which is why this is an
    explicit operator command and never an automatic fsck response.

    The old sidecar's ``sig`` is deliberately DROPPED: the signature ties
    an artifact to the build input that produced it, and bytes that no
    longer match their sidecar can no longer prove that tie.  A resealed
    tree therefore re-enters merges as a foreign (sig-less) input.

    Returns the check summary; raises IntegrityError when the artifact
    does not verify.
    """
    for suffix, checker in _CHECKERS.items():
        if path.endswith(suffix):
            detail = checker(path, "trust")
            break
    else:
        raise MalformedArtifact(
            f"{path}: not a sheep artifact (want one of "
            f"{'/'.join(_CHECKERS)}) — nothing to reseal")
    from .sidecar import write_sidecar
    write_sidecar(path)
    return detail


def fsck_paths(paths, mode: str | None = None):
    """Verify every artifact reachable from ``paths``.

    Returns (results, failures): ``results`` is a list of
    (path, ok, detail) in check order; ``failures`` the failing subset.
    """
    mode = resolve_policy(mode)
    results = []
    for root in paths:
        targets = collect_artifacts(root)
        chain = _manifest_chain_result(root, mode)
        reseq_chain = _reseq_chain_result(root, mode)
        scrub_chain = _scrub_chain_result(root, mode)
        quarantined = _quarantined_results(root, mode)
        if not targets and chain is None and reseq_chain is None \
                and scrub_chain is None and not quarantined:
            results.append((root, False, "no artifacts found"))
            continue
        for path in targets:
            try:
                detail = fsck_file(path, mode)
                results.append((path, True, detail))
            except (IntegrityError, OSError) as exc:
                results.append((path, False, str(exc)))
        if chain is not None:
            results.append(chain)
        if reseq_chain is not None:
            results.append(reseq_chain)
        if scrub_chain is not None:
            results.append(scrub_chain)
        results.extend(quarantined)
    failures = [r for r in results if not r[1]]
    return results, failures


def _scrub_chain_result(root: str, mode: str):
    """The anti-entropy scrub-history line for a state dir (ISSUE 20),
    or None when the root is a file / was never scrubbed.  The scrub
    manifest is hash-chained (serve/scrub.py), so an edited or dropped
    run record is a failure, not a shrug."""
    from ..serve import scrub as scrub_mod
    if not os.path.isdir(root):
        return None
    mpath = scrub_mod.scrub_manifest_path(root)
    if not os.path.exists(mpath):
        return None
    try:
        return (mpath, True, scrub_mod.verify_scrub_chain(root))
    except (IntegrityError, OSError) as exc:
        return (mpath, False, str(exc))


def _quarantined_results(root: str, mode: str):
    """The quarantine convention (ISSUE 20): ``*.quarantined`` artifacts
    are REPORTED, never loaded, and never counted as failures — the
    scrubber already did the failing; the rename IS the containment.
    Repair mode re-verifies each one and reclaims (renames back) those
    whose bytes now check out — the transient-rot case; anything still
    corrupt stays quarantined.  A dir-level quarantine marker
    (divergence, not rot) is reported the same way."""
    from ..serve import scrub as scrub_mod
    out = []
    if not os.path.isdir(root):
        return out
    marker = scrub_mod.read_quarantine(root) \
        if os.path.exists(scrub_mod.quarantine_path(root)) else None
    if marker is not None:
        out.append((scrub_mod.quarantine_path(root), True,
                    f"dir quarantined: phase={marker.get('phase', '?')} "
                    f"reason={marker.get('reason', '?')} — reads "
                    f"refused until the re-sync clears it"))
    for qpath in scrub_mod.quarantined_paths(root):
        if mode == "repair":
            try:
                detail = scrub_mod.reclaim_quarantined(qpath)
                out.append((qpath, True, f"reclaimed: {detail}"))
                continue
            except (IntegrityError, OSError) as exc:
                out.append((qpath, True,
                            f"quarantined, still corrupt — kept "
                            f"({exc})"))
                continue
        out.append((qpath, True,
                    "quarantined by the scrubber; never loaded "
                    "(repair mode re-verifies and reclaims)"))
    return out


def _reseq_chain_result(root: str, mode: str):
    """The re-sequence generation-chain line for a serve state dir
    (ISSUE 18), or None when the root is a file / never re-sequenced
    AND its snapshots are all generation 0.

    What it refuses: a snapshot claiming a sequence generation its
    reseq manifest never sanctioned (silent tampering or a foreign
    snapshot dropped into the dir), an unparseable manifest, and — in
    strict mode — an in-flight manifest whose durable inputs are gone
    (phase ``swap`` with neither a pending artifact nor fold
    checkpoints, on a dir whose snapshot is still the OLD generation:
    resumable only by a full refold, which repair-mode reports and
    strict refuses to vouch for)."""
    from ..serve import reseq as reseq_mod
    from ..serve.state import load_serve_snapshot, snap_paths
    if not os.path.isdir(root):
        return None
    mpath = reseq_mod.manifest_path(root)
    has_manifest = os.path.exists(mpath)
    # newest loadable snapshot's (gen, sig) is what the chain must vouch
    best = None
    for snap_path in snap_paths(root):
        try:
            snap = load_serve_snapshot(snap_path, integrity="trust")
        except (IntegrityError, OSError):
            continue
        if best is None or ((snap.epoch, snap.applied_seqno)
                            > (best[1].epoch, best[1].applied_seqno)):
            best = (snap_path, snap)
    if not has_manifest:
        if best is not None and best[1].seq_gen:
            return (mpath, False,
                    f"{os.path.basename(best[0])} claims sequence "
                    f"generation {best[1].seq_gen} but no reseq manifest "
                    f"exists to sanction it — not one recovery chain")
        return None
    try:
        man = reseq_mod.load_manifest(root)
    except (IntegrityError, OSError) as exc:
        return (mpath, False, str(exc))
    phase = man.get("phase", "?")
    chain = [(int(c.get("gen", -1)), c.get("sig", ""))
             for c in man.get("chain", []) if isinstance(c, dict)]
    detail = f"phase={phase} generations={len(chain)}"
    if best is not None:
        snap_path, snap = best
        sanctioned = dict(chain)
        if phase in ("swap", "adopt", "done"):
            sanctioned.setdefault(int(man.get("new_gen", -1)),
                                  man.get("new_sig", ""))
        if sanctioned.get(snap.seq_gen) != snap.sig:
            return (mpath, False,
                    f"{os.path.basename(snap_path)} serves sequence "
                    f"generation {snap.seq_gen} (sig "
                    f"{snap.sig[:12]}...) which the reseq manifest "
                    f"chain never sanctioned — torn or foreign swap")
        detail += f" snap_gen={snap.seq_gen} chain-ok"
    if phase not in reseq_mod.DONE_PHASES:
        resumable = (os.path.exists(reseq_mod.pending_path(root))
                     or os.path.isdir(reseq_mod.ckpt_dir(root)))
        if phase == "swap" and not resumable \
                and best is not None \
                and best[1].seq_gen < int(man.get("new_gen", 0)):
            if mode != "repair":
                return (mpath, False,
                        f"in-flight re-sequence at phase=swap lost its "
                        f"pending artifact and checkpoints — resumable "
                        f"only by a full refold (repair mode reports, "
                        f"strict refuses)")
            detail += " in_flight=refold-required"
        else:
            detail += " in_flight=resumable"
    return (mpath, True, detail)


def _manifest_chain_result(root: str, mode: str):
    """The distext shard-map chain line for a state-dir root (ISSUE 13),
    or None when the root is a file / has no manifest / holds a plain
    tournament."""
    if not os.path.isdir(root) \
            or not os.path.exists(os.path.join(root, "manifest.json")):
        return None
    mpath = os.path.join(root, "manifest.json")
    try:
        detail = fsck_distext_manifest(root, mode)
    except (IntegrityError, OSError) as exc:
        return (mpath, False, str(exc))
    if detail is None:
        return None
    return (mpath, True, detail)
