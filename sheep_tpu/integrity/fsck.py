"""``sheep fsck`` core: verify any artifact, or a whole trial directory.

One function per artifact class; each returns a human-readable summary on
success and raises IntegrityError (or OSError) on any corruption.  The
checks are layered — sidecar checksum first (when one exists), then the
format's structural invariants, then the cheap semantic invariants the
merge-associativity property gives us for free (parents strictly later
than kids, pst totals plausible).  ``sheep fsck`` exits nonzero iff any
checked artifact fails; the shell pipeline runs it on the worker trees
before every merge tournament (scripts/horizontal-dist.sh).
"""

from __future__ import annotations

import os

import numpy as np

from .errors import IntegrityError, MalformedArtifact
from .sidecar import read_sidecar, resolve_policy, verify_file

#: suffixes fsck knows how to verify (``.npz`` = runtime snapshots,
#: ``.wal``/``.snap`` = the serve daemon's log + serving snapshots)
ARTIFACT_SUFFIXES = (".tre", ".seq", ".dat", ".net", ".npz",
                     ".wal", ".snap")


def _fsck_tre(path: str, mode: str) -> str:
    from .. import INVALID_JNID
    from ..io.trefile import read_tree

    parent, pst = read_tree(path, integrity=mode)
    linked = parent != INVALID_JNID
    return (f"n={len(parent)} links={int(linked.sum())} "
            f"pst_total={int(pst.sum())}")


def _fsck_seq(path: str, mode: str) -> str:
    from ..io.seqfile import read_sequence

    seq = read_sequence(path, binary="auto", integrity=mode)
    if len(seq) and len(np.unique(seq)) != len(seq):
        raise MalformedArtifact(
            f"{path}: corrupt sequence — duplicate vids (an elimination "
            f"order visits each vertex once)")
    return f"m={len(seq)}"


def _fsck_dat(path: str, mode: str) -> str:
    from ..io.edges import read_dat

    el = read_dat(path, integrity=mode)
    return f"records={el.num_edges}"


def _fsck_net(path: str, mode: str) -> str:
    from ..io.edges import read_net

    el = read_net(path, integrity=mode)
    return f"records={el.num_edges}"


def _fsck_npz(path: str, mode: str) -> str:
    from ..runtime.snapshot import load_snapshot

    snap = load_snapshot(path, integrity=mode)
    return (f"n={snap.n} links={len(snap.lo)} rounds={snap.rounds} "
            f"rung={snap.rung}")


def _fsck_wal(path: str, mode: str) -> str:
    """Verify the serve WAL chain: header magic/version, per-record
    crc32, strictly monotone sequence numbers, and — when a sibling
    snapshot generation is readable — that the log and snapshot belong to
    the same build input (the snapshot+WAL recovery chain, ISSUE 6).
    Strict refuses a torn tail; repair reports the salvageable prefix."""
    from ..serve.wal import read_wal

    sig, records, _, torn = read_wal(path, mode)
    last = records[-1][0] if records else 0
    detail = f"records={len(records)} last_seqno={last}"
    if torn:
        detail += " torn_tail=truncatable"
    # chain check against the newest loadable sibling snapshot
    from ..serve.state import load_serve_snapshot, snap_paths
    for snap_path in reversed(snap_paths(os.path.dirname(path) or ".")):
        try:
            snap = load_serve_snapshot(snap_path, integrity="trust")
        except (IntegrityError, OSError):
            continue
        if snap.sig != sig:
            raise MalformedArtifact(
                f"{path}: WAL signature {sig[:12]}... does not match "
                f"snapshot {os.path.basename(snap_path)} "
                f"({snap.sig[:12]}...) — log and snapshot are not one "
                f"recovery chain")
        detail += f" chain={os.path.basename(snap_path)}"
        break
    return detail


def _fsck_snap(path: str, mode: str) -> str:
    from ..serve.state import load_serve_snapshot

    snap = load_serve_snapshot(path, integrity=mode)
    from .. import INVALID_JNID
    links = int((snap.parent != INVALID_JNID).sum())
    return (f"n={len(snap.seq)} links={links} "
            f"applied={snap.applied_seqno} "
            f"inserted={len(snap.ins_tail)} parts={snap.num_parts}")


_CHECKERS = {
    ".tre": _fsck_tre,
    ".seq": _fsck_seq,
    ".dat": _fsck_dat,
    ".net": _fsck_net,
    ".npz": _fsck_npz,
    ".wal": _fsck_wal,
    ".snap": _fsck_snap,
}


def fsck_file(path: str, mode: str | None = None) -> str:
    """Verify one artifact; returns a summary string or raises."""
    mode = resolve_policy(mode)
    for suffix, checker in _CHECKERS.items():
        if path.endswith(suffix):
            detail = checker(path, mode)
            status = "sum=" + _sidecar_state(path, mode)
            return f"{detail} {status}"
    # unknown suffix: the sidecar (if any) is still checkable
    state = verify_file(path, mode)
    if state == "no-sidecar":
        raise MalformedArtifact(
            f"{path}: not a sheep artifact (want one of "
            f"{'/'.join(_CHECKERS)}) and no sidecar to verify")
    return f"opaque bytes sum={state}"


def _sidecar_state(path: str, mode: str) -> str:
    if mode == "trust":
        return "trusted"
    try:
        return "absent" if read_sidecar(path) is None else "verified"
    except MalformedArtifact:
        return "unreadable"


def collect_artifacts(root: str) -> list[str]:
    """Every checkable artifact under ``root`` (a file is itself)."""
    if os.path.isfile(root):
        return [root]
    out = []
    for dirpath, _, names in os.walk(root):
        for name in sorted(names):
            if name.endswith(ARTIFACT_SUFFIXES):
                out.append(os.path.join(dirpath, name))
    return out


def repair_sidecar(path: str) -> str:
    """Reseal ``path``'s ``.sum`` sidecar after verifying the artifact
    STRUCTURALLY (``sheep fsck --repair-sidecar``).

    The operation covers exactly two legitimate states: a sidecar that was
    LOST (a foreign copy, an interrupted ``cp`` that moved the artifact
    but not its sidecar) and a sidecar that is WRONG for bytes that still
    parse (the crash window between the artifact rename and the sidecar
    rename, sidecar.py module docstring).  Every format/semantic check the
    artifact class has still runs — only the checksum layer is skipped —
    so garbage is refused, never vouched for; but note the honest limit:
    a corruption that keeps the artifact structurally valid is
    indistinguishable from a legitimate reseal, which is why this is an
    explicit operator command and never an automatic fsck response.

    The old sidecar's ``sig`` is deliberately DROPPED: the signature ties
    an artifact to the build input that produced it, and bytes that no
    longer match their sidecar can no longer prove that tie.  A resealed
    tree therefore re-enters merges as a foreign (sig-less) input.

    Returns the check summary; raises IntegrityError when the artifact
    does not verify.
    """
    for suffix, checker in _CHECKERS.items():
        if path.endswith(suffix):
            detail = checker(path, "trust")
            break
    else:
        raise MalformedArtifact(
            f"{path}: not a sheep artifact (want one of "
            f"{'/'.join(_CHECKERS)}) — nothing to reseal")
    from .sidecar import write_sidecar
    write_sidecar(path)
    return detail


def fsck_paths(paths, mode: str | None = None):
    """Verify every artifact reachable from ``paths``.

    Returns (results, failures): ``results`` is a list of
    (path, ok, detail) in check order; ``failures`` the failing subset.
    """
    mode = resolve_policy(mode)
    results = []
    for root in paths:
        targets = collect_artifacts(root)
        if not targets:
            results.append((root, False, "no artifacts found"))
            continue
        for path in targets:
            try:
                detail = fsck_file(path, mode)
                results.append((path, True, detail))
            except (IntegrityError, OSError) as exc:
                results.append((path, False, str(exc)))
    failures = [r for r in results if not r[1]]
    return results, failures
