"""Versioned sidecar checksums for every durable artifact.

Each artifact ``foo.tre`` gets a tiny text sidecar ``foo.tre.sum`` written
through the same crash-safe path as the artifact itself (io/atomic.py):

    sheep-sum 1
    algo crc32c
    size 1432
    sum 9a3c1f08
    sig 5f1d...        (optional: the producing build's input signature)

``algo`` is CRC32C (Castagnoli) when a native implementation is importable
(``google_crc32c`` or ``crc32c``), else zlib's CRC-32 — both are recorded,
so a reader verifies with whatever the writer used; a pure-python CRC32C
over multi-GB edge files would be slower than the disks it guards, so the
dependency is gated, not required.  ``sig`` ties an artifact to the build
input that produced it (runtime.snapshot.input_signature); merge_trees
refuses to zip trees whose signatures disagree.

Writer contract (ISSUE 5 hardened): the sidecar lands FIRST, then the
artifact — the same ordering as every other publish in the system
(scripts/lib.sh ``sheep_mv_artifact``, the supervisor's publish).  Both
renames are atomic and the sidecar rename happens via
``atomic_write``'s ``pre_publish`` seam, so ANY write failure — crash,
ENOSPC, injected fault (io/faultfs.py) — aborts with the previous
(artifact, sidecar) pair intact: a new artifact can never appear under
its final name without the checksum that vouches for it.  The remaining
crash window (new sidecar + old artifact) reads as a mismatch; "repair"
treats a mismatched pair as corrupt and a missing sidecar as unverified
— never as silently fine when a sidecar says otherwise.

Policy modes (env ``SHEEP_INTEGRITY``, default "strict"):

    strict   sidecar present + mismatch  -> ChecksumMismatch
             sidecar absent              -> accepted (foreign files have
                                            none); structural checks apply
    repair   mismatch -> warn, let the reader salvage what it can
    trust    skip checksum verification entirely (structural parse errors
             still raise)
"""

from __future__ import annotations

import contextlib
import os
import warnings
import zlib

from .errors import ChecksumMismatch, MalformedArtifact

# NOTE: io.atomic is imported lazily inside the writers.  A module-level
# import would cycle: integrity.sidecar -> io (package init) -> io.edges ->
# integrity.sidecar (mid-import).

SIDECAR_SUFFIX = ".sum"
SIDECAR_VERSION = 1

POLICIES = ("strict", "repair", "trust")

try:  # gated native CRC32C (the container may or may not ship one)
    import google_crc32c as _crc32c_mod

    def _crc32c(data: bytes, crc: int = 0) -> int:
        return _crc32c_mod.extend(crc, data)
except ImportError:
    try:
        import crc32c as _crc32c_mod2

        def _crc32c(data: bytes, crc: int = 0) -> int:
            return _crc32c_mod2.crc32c(data, crc)
    except ImportError:
        _crc32c = None

DEFAULT_ALGO = "crc32c" if _crc32c is not None else "crc32"


def resolve_policy(mode: str | None = None) -> str:
    """``mode`` if given, else SHEEP_INTEGRITY, else "strict"."""
    mode = mode or os.environ.get("SHEEP_INTEGRITY") or "strict"
    if mode not in POLICIES:
        raise ValueError(
            f"integrity mode {mode!r} must be one of {'/'.join(POLICIES)}")
    return mode


def crc_update(data: bytes, crc: int = 0, algo: str = DEFAULT_ALGO) -> int:
    if algo == "crc32":
        return zlib.crc32(data, crc)
    if algo == "crc32c":
        if _crc32c is None:
            raise MalformedArtifact(
                "sidecar uses crc32c but no crc32c implementation is "
                "available in this environment")
        return _crc32c(data, crc)
    raise MalformedArtifact(f"unknown sidecar checksum algo {algo!r}")


def sidecar_path(path: str) -> str:
    return path + SIDECAR_SUFFIX


def write_sidecar(path: str, crc: int | None = None, size: int | None = None,
                  algo: str = DEFAULT_ALGO,
                  extra: dict | None = None,
                  data_path: str | None = None) -> str:
    """Write ``path``'s sidecar.  With crc/size None the artifact is read
    back and summed (the npz writer seeks, so its bytes cannot be teed);
    ``data_path`` reads the bytes from a different file — the sealed
    temp, for writers that sum before publishing (:func:`sealed_write`)."""
    if crc is None or size is None:
        crc, size = 0, 0
        with open(data_path or path, "rb") as f:
            while True:
                block = f.read(1 << 24)
                if not block:
                    break
                crc = crc_update(block, crc, algo)
                size += len(block)
    from ..io.atomic import atomic_write
    sc = sidecar_path(path)
    with atomic_write(sc, "w") as f:
        f.write(f"sheep-sum {SIDECAR_VERSION}\n")
        f.write(f"algo {algo}\n")
        f.write(f"size {size}\n")
        f.write(f"sum {crc & 0xFFFFFFFF:08x}\n")
        for k, v in (extra or {}).items():
            f.write(f"{k} {v}\n")
    return sc


def read_sidecar(path: str) -> dict | None:
    """Parse ``path``'s sidecar; None when there is none.  An unparseable
    sidecar raises MalformedArtifact — a sidecar that cannot vouch for its
    artifact must never read as 'no sidecar, accept'."""
    sc = sidecar_path(path)
    try:
        with open(sc, "rb") as f:
            raw = f.read()
    except FileNotFoundError:
        return None
    try:
        text = raw.decode("ascii")
        lines = [ln for ln in text.splitlines() if ln.strip()]
        head = lines[0].split()
        if head[0] != "sheep-sum":
            raise ValueError("bad magic")
        out: dict = {"version": int(head[1])}
        for ln in lines[1:]:
            k, v = ln.split(None, 1)
            out[k] = v.strip()
        out["size"] = int(out["size"])
        out["sum"] = int(out["sum"], 16)
        if out["version"] > SIDECAR_VERSION:
            raise ValueError(f"sidecar version {out['version']} "
                             f"> supported {SIDECAR_VERSION}")
        if out["algo"] not in ("crc32", "crc32c"):
            raise ValueError(f"unknown algo {out['algo']!r}")
        return out
    except (ValueError, IndexError, KeyError, UnicodeDecodeError) as exc:
        raise MalformedArtifact(
            f"{sc}: corrupt sidecar ({exc}) — cannot vouch for {path}")


def verify_bytes(path: str, data: bytes, mode: str | None = None) -> str:
    """Check ``data`` (the artifact's full bytes) against ``path``'s
    sidecar under the policy.  Returns "ok" / "no-sidecar" / "trusted" /
    "repair-mismatch"; raises ChecksumMismatch in strict mode."""
    mode = resolve_policy(mode)
    if mode == "trust":
        return "trusted"
    try:
        sc = read_sidecar(path)
    except MalformedArtifact:
        if mode == "repair":
            warnings.warn(f"{path}: unreadable sidecar; proceeding on "
                          f"structural checks only")
            return "repair-mismatch"
        raise
    if sc is None:
        return "no-sidecar"
    problems = []
    if sc["size"] != len(data):
        problems.append(f"size {len(data)} != recorded {sc['size']}")
    else:
        got = crc_update(data, 0, sc["algo"]) & 0xFFFFFFFF
        if got != sc["sum"]:
            problems.append(f"{sc['algo']} {got:08x} != recorded "
                            f"{sc['sum']:08x}")
    if not problems:
        return "ok"
    msg = f"{path}: checksum mismatch ({'; '.join(problems)}) — " \
          f"the artifact was corrupted after it was written"
    if mode == "repair":
        warnings.warn(msg + "; repair mode salvaging what parses")
        return "repair-mismatch"
    raise ChecksumMismatch(msg)


def verify_file(path: str, mode: str | None = None) -> str:
    """:func:`verify_bytes` reading the artifact from disk (streamed)."""
    mode = resolve_policy(mode)
    if mode == "trust":
        return "trusted"
    try:
        sc = read_sidecar(path)
    except MalformedArtifact:
        if mode == "repair":
            warnings.warn(f"{path}: unreadable sidecar; proceeding on "
                          f"structural checks only")
            return "repair-mismatch"
        raise
    if sc is None:
        return "no-sidecar"
    crc, size = 0, 0
    with open(path, "rb") as f:
        while True:
            block = f.read(1 << 24)
            if not block:
                break
            crc = crc_update(block, crc, sc["algo"])
            size += len(block)
    if size == sc["size"] and (crc & 0xFFFFFFFF) == sc["sum"]:
        return "ok"
    msg = f"{path}: checksum mismatch (size {size} vs {sc['size']}, " \
          f"{sc['algo']} {crc & 0xFFFFFFFF:08x} vs {sc['sum']:08x})"
    if mode == "repair":
        warnings.warn(msg + "; repair mode salvaging what parses")
        return "repair-mismatch"
    raise ChecksumMismatch(msg)


class _CrcTee:
    """File-object proxy that checksums every byte written through it.
    Sequential writers only (the npz writer seeks; it uses read-back)."""

    def __init__(self, f, text: bool):
        self._f = f
        self._text = text
        self.crc = 0
        self.size = 0

    def write(self, data):
        b = data.encode("ascii") if self._text else data
        self.crc = crc_update(b, self.crc)
        self.size += len(b)
        return self._f.write(data)

    def flush(self):
        return self._f.flush()


@contextlib.contextmanager
def checksummed_write(path: str, mode: str = "wb",
                      extra: dict | None = None,
                      expect_bytes: int | None = None):
    """:func:`io.atomic.atomic_write` + a sidecar sealed sidecar-first.

    The sidecar lands first (via the ``pre_publish`` seam, after the
    artifact's bytes are durable at the temp name), the artifact second
    (module docstring).  On any exception — including mid-write
    ENOSPC/EIO, real or injected — neither appears and the previous
    (artifact, sidecar) pair is untouched.  ``expect_bytes`` enables the
    disk preflight (io/atomic.py).
    """
    from ..io.atomic import atomic_write
    tee_box: list = []

    def seal(tmp: str) -> None:
        tee = tee_box[0]
        write_sidecar(path, tee.crc, tee.size, extra=extra)

    with atomic_write(path, mode, expect_bytes=expect_bytes,
                      pre_publish=seal) as f:
        tee = _CrcTee(f, text=(mode == "w"))
        tee_box.append(tee)
        yield tee


@contextlib.contextmanager
def sealed_write(path: str, mode: str = "wb", extra: dict | None = None,
                 expect_bytes: int | None = None):
    """:func:`checksummed_write` for SEEKING writers (the npz snapshot):
    the bytes cannot be teed, so the fsync'd temp file is read back for
    the checksum — then the sidecar lands first and the artifact renames
    second, same invariant as every other writer."""
    from ..io.atomic import atomic_write

    def seal(tmp: str) -> None:
        write_sidecar(path, extra=extra, data_path=tmp)

    with atomic_write(path, mode, expect_bytes=expect_bytes,
                      pre_publish=seal) as f:
        yield f
