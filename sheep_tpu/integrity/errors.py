"""The integrity-error taxonomy: every way an artifact can lie to us.

All types subclass :class:`IntegrityError`, which itself subclasses
``ValueError`` so pre-existing callers (and tests) that catch ValueError on
a corrupt read keep working.  The split matters operationally:

  ChecksumMismatch    the bytes changed after the writer sealed them — a
                      bit-flip, torn copy, or tampering.  The artifact may
                      still PARSE; only the sidecar knows it is wrong.
  MalformedArtifact   the bytes do not parse as the format claims —
                      truncated records, non-integer tokens, a header that
                      lies about the payload.  Detectable without a sidecar.
  IncompatibleMerge   two individually-valid artifacts that must not be
                      combined (different n, different input signature).

Policy modes (see integrity.sidecar): "strict" raises on any of these,
"repair" salvages what provably survives and warns, "trust" skips the
checksum work entirely (structural parse errors still raise — garbage that
cannot be parsed is never silently accepted in any mode).
"""

from __future__ import annotations


class IntegrityError(ValueError):
    """Base of every data-integrity failure in sheep_tpu."""


class ChecksumMismatch(IntegrityError):
    """Artifact bytes disagree with their sidecar checksum."""


class MalformedArtifact(IntegrityError):
    """Artifact bytes do not parse as the format they claim to be."""


class IncompatibleMerge(IntegrityError):
    """Two valid artifacts that cannot be combined (n / signature clash)."""
