"""make_graph: synthesize benchmark graphs (R-MAT / Graph500-style).

The reference benchmarks on downloaded SNAP graphs; in an offline
environment the scale sweep needs synthetic power-law graphs instead.
Writes the ``.dat`` XS1 format the whole toolchain consumes.

USAGE: make_graph log_n edge_factor output.dat [seed]
"""

from __future__ import annotations

import sys

from ..io.edges import write_edges
from ..utils import rmat_edges


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) < 3:
        print("USAGE: make_graph log_n edge_factor output.dat [seed]")
        return 1
    log_n = int(argv[0])
    factor = int(argv[1])
    out = argv[2]
    seed = int(argv[3]) if len(argv) > 3 else 1
    tail, head = rmat_edges(log_n, factor << log_n, seed=seed)
    write_edges(out, tail, head)
    print(f"wrote {out}: n=2^{log_n} records={factor << log_n}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
