"""merge_trees: associative pairwise merge of two .tre files
(merge_trees.cpp:37-101).  ``Loaded in: Nms`` / ``Built in: Nms`` grammar.
"""

from __future__ import annotations

import getopt
import sys

from ..core.facts import compute_facts
from ..core.forest import Forest, merge_forests
from ..io.trefile import read_tree, write_tree
from .common import PhaseClock, print_phase_ms


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    try:
        opts, args = getopt.gnu_getopt(argv, "o:vkf")
    except getopt.GetoptError as exc:
        o = (exc.opt or "?")[:1]
        if o == "o":
            print(f"Option -{o} requires a string.")
        else:
            print(f"Unknown option character '{o}'.")
        return 1

    output_filename = ""
    verbose = False
    do_faqs = False
    for o, a in opts:
        if o == "-o":
            output_filename = a
        elif o == "-v":
            verbose = not verbose
        elif o == "-k":
            pass  # make_kids: kids are always derivable from parents here
        elif o == "-f":
            do_faqs = not do_faqs

    if len(args) < 2:
        print("USAGE: merge_trees [options ...] first.tree second.tree")
        return 1

    clock = PhaseClock()
    # All positional trees merge in one associative pass (the reference
    # takes exactly two, which silently pins the scripts' REDUCTION to 2;
    # accepting k inputs makes any tournament fan-in correct).
    inputs = [Forest(*read_tree(a)) for a in args]
    if verbose:
        print_phase_ms("Loaded", clock.phase_seconds())

    merged = merge_forests(*inputs)
    if output_filename:
        write_tree(output_filename, merged.parent, merged.pst_weight)
    if verbose:
        print_phase_ms("Built", clock.phase_seconds())

    if do_faqs:
        compute_facts(merged).print()
    return 0


if __name__ == "__main__":
    sys.exit(main())
