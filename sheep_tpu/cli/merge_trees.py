"""merge_trees: associative pairwise merge of two .tre files
(merge_trees.cpp:37-101).  ``Loaded in: Nms`` / ``Built in: Nms`` grammar.

Integrity (ISSUE 2): inputs are verified on read (sidecar checksums +
structural hardening, io/trefile.py) and checked for merge COMPATIBILITY
before any zipping — trees of differing length, or carrying differing
input signatures in their sidecars (written by graph2tree's map phase),
come from different builds and merging them would produce a plausible-
looking but wrong tree.  Both refusals exit nonzero with a typed message.
"""

from __future__ import annotations

import getopt
import os
import sys

from ..core.facts import compute_facts
from ..core.forest import Forest, merge_forests
from ..integrity.errors import IncompatibleMerge, IntegrityError
from ..integrity.sidecar import read_sidecar
from ..io.trefile import read_tree, write_tree
from .common import PhaseClock, print_phase_ms


def check_merge_compatible(paths: list[str],
                           forests: list[Forest]) -> str | None:
    """Refuse incompatible merge inputs; returns the shared input
    signature (to stamp onto the merged output's sidecar), if any."""
    sizes = {len(f.parent) for f in forests}
    if len(sizes) > 1:
        detail = ", ".join(
            f"{os.path.basename(p)}:{len(f.parent)}"
            for p, f in zip(paths, forests))
        raise IncompatibleMerge(
            f"trees disagree on node count ({detail}) — partial trees "
            f"must share one sequence; refusing to merge")
    sigs = {}
    for p in paths:
        try:
            sc = read_sidecar(p)
        except IntegrityError:
            continue  # unreadable sidecar already warned at read time
        if sc and sc.get("sig"):
            sigs[p] = sc["sig"]
    distinct = set(sigs.values())
    if len(distinct) > 1:
        detail = ", ".join(f"{os.path.basename(p)}:{s[:12]}..."
                           for p, s in sigs.items())
        raise IncompatibleMerge(
            f"trees carry different input signatures ({detail}) — they "
            f"were built from different graphs/sequences; refusing to "
            f"merge")
    return next(iter(distinct), None)


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    from .common import maybe_start_heartbeat
    _hb = maybe_start_heartbeat()  # noqa: F841 — beats while we merge
    try:
        # --expect-sig: the tournament supervisor pins every merge to the
        # manifest's input signature, so a stale artifact from a different
        # build (or a speculative loser that raced a resume) can never be
        # zipped in even if its own sidecars agree with each other.
        opts, args = getopt.gnu_getopt(argv, "o:vkf", ["expect-sig="])
    except getopt.GetoptError as exc:
        o = (exc.opt or "?")[:1]
        if (exc.opt or "").startswith("expect-sig"):
            print(f"Option --{exc.opt}: {exc.msg}.")
            return 1
        if o == "o":
            print(f"Option -{o} requires a string.")
        else:
            print(f"Unknown option character '{o}'.")
        return 1

    output_filename = ""
    verbose = False
    do_faqs = False
    expect_sig = None
    for o, a in opts:
        if o == "-o":
            output_filename = a
        elif o == "-v":
            verbose = not verbose
        elif o == "-k":
            pass  # make_kids: kids are always derivable from parents here
        elif o == "-f":
            do_faqs = not do_faqs
        elif o == "--expect-sig":
            expect_sig = a

    if len(args) < 2:
        print("USAGE: merge_trees [options ...] first.tree second.tree")
        return 1

    clock = PhaseClock()
    # All positional trees merge in one associative pass (the reference
    # takes exactly two, which silently pins the scripts' REDUCTION to 2;
    # accepting k inputs makes any tournament fan-in correct).
    try:
        inputs = [Forest(*read_tree(a)) for a in args]
        sig = check_merge_compatible(args, inputs)
        if expect_sig is not None and sig is not None and sig != expect_sig:
            raise IncompatibleMerge(
                f"inputs carry signature {sig[:12]}... but the caller "
                f"expects {expect_sig[:12]}... — these trees belong to a "
                f"different build; refusing to merge")
    except IntegrityError as exc:
        print(f"merge_trees: {exc}", file=sys.stderr)
        return 1
    if verbose:
        print_phase_ms("Loaded", clock.phase_seconds())

    merged = merge_forests(*inputs)
    if output_filename:
        write_tree(output_filename, merged.parent, merged.pst_weight,
                   sig=sig)
    if verbose:
        print_phase_ms("Built", clock.phase_seconds())

    if do_faqs:
        compute_facts(merged).print()
    return 0


if __name__ == "__main__":
    sys.exit(main())
