"""sheep worker: run one remote build worker daemon (ISSUE 16).

No reference counterpart — the reference is a single-process build.
This daemon is the multi-host arm of the distributed out-of-core build:
it accepts ``LEG`` jobs from a distext supervisor over the fleet wire
(serve/worker.py documents the frame shapes), runs the existing
``hist``/``distmap`` leg code over the shipped slice under THIS
process's ``SHEEP_MEM_BUDGET``, and streams the sealed artifact back
crc-checked.  It shares no filesystem with the supervisor — everything
it touches lives in its own state dir.

    bin/worker -d wstate/                  # ephemeral port; address is
                                           # printed and written to
                                           # <state-dir>/worker.addr
    bin/worker -d wstate/ -p 7070 -H 0.0.0.0

Options:
  -d DIR     state dir (required): slices, artifacts, checkpoints,
             worker.addr
  -p PORT    listen port (default 0 = ephemeral)
  -H HOST    bind host (default 127.0.0.1)
  -m MODE    integrity policy for leg checkpoints: strict (default) /
             repair

Env: SHEEP_MEM_BUDGET (each leg folds under the WORKER's budget — the
point of shipping the leg), SHEEP_WORKER_BEAT_S (wire heartbeat
interval), SHEEP_SERVE_NETFAULT_PLAN (worker-wire sites wbeat/wart).

Exit codes: 0 clean shutdown (QUIT verb or SIGTERM/SIGINT), 1 startup
failure, 2 usage error.
"""

from __future__ import annotations

import getopt
import signal
import sys

USAGE = "USAGE: worker -d state_dir [-p port] [-H host] [-m strict|repair]"


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    try:
        opts, args = getopt.gnu_getopt(argv, "d:p:H:m:", [])
    except getopt.GetoptError as exc:
        print(f"Unknown option character '{(exc.opt or '?')[:1]}'.")
        return 2

    state_dir = None
    port = 0
    host = "127.0.0.1"
    mode = None
    from ..integrity.sidecar import POLICIES
    for o, a in opts:
        if o == "-d":
            state_dir = a
        elif o == "-p":
            port = int(a)
        elif o == "-H":
            host = a
        elif o == "-m":
            if a not in POLICIES:
                print(f"worker: -m {a!r} must be one of "
                      f"{'/'.join(POLICIES)}")
                return 2
            mode = a

    if state_dir is None or args:
        print(USAGE)
        return 2

    from ..serve.worker import WorkerDaemon
    try:
        daemon = WorkerDaemon(state_dir, host=host, port=port,
                              integrity=mode).start()
    except OSError as exc:
        print(f"worker: {exc}", file=sys.stderr)
        return 1
    h, p = daemon.address
    print(f"worker: listening on {h}:{p}", flush=True)
    print(f"worker: state dir {state_dir} beat={daemon.beat_s}s",
          flush=True)

    def _term(signum, frame):
        daemon.shutdown()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    try:
        daemon.run_forever()
    finally:
        daemon.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
