"""CLI binaries mirroring the reference's flag surfaces (SURVEY §2.2).

Each module is runnable via ``python -m sheep_tpu.cli.<name>`` and via the
``bin/`` shims; flags, positional arguments, and the stdout phase grammar
("Loaded graph in: %f seconds" etc., which the plot scripts grep) match
graph2tree.cpp / partition_tree.cpp / degree_sequence.cpp / merge_trees.cpp.
"""
