"""sheep plan: resolve and explain a build's execution plan.

The operational face of the planner (ISSUE 15) — the promotion of
``sheep trace``'s after-the-fact rung explanation into a BEFORE-the-run
answer: which rung would run, at what priced (and history-corrected)
cost, and which ``SHEEP_*`` knob decided each part of the plan::

    bin/plan --explain g.dat                  # plan the build of g.dat
    bin/plan --explain -n 1048576 -e 4194304  # plan a hypothetical size
    bin/plan --explain --json g.dat           # machine-readable
    bin/plan --harvest prior.store run.trace EXTBENCH_r01.json
                                              # learn priors from history

Inputs: a ``.dat`` file (one streaming histogram pass derives n and the
record count — the same pass-1 arithmetic the ext build runs), or
``-n``/``-e`` for a hypothetical build.  The plan reads the same env
the build would (budgets, knobs, ``SHEEP_PLAN_PRIORS``), so running it
in a build's environment answers for THAT build.

``--assume-rss BYTES`` pins the measured-RSS input of the headroom
arithmetic, making the plan a pure function of its inputs — the
verify_tier1 smoke runs the same plan twice and asserts byte-equal
output.  ``--harvest`` folds trace files (rotated segment chains
included; a torn newest segment is legal evidence) and bench records
into a prior store for ``SHEEP_PLAN_PRIORS``.

Exit codes: 0 planned/harvested, 1 unreadable input, 2 usage error.
"""

from __future__ import annotations

import getopt
import json
import os
import sys

USAGE = ("USAGE: plan [--explain] [--json] [-n N] [-e EDGES] [-w WORKERS]\n"
         "            [--assume-rss BYTES] [--priors STORE] [graph.dat]\n"
         "       plan --harvest STORE <trace|bench.json>...")


def _harvest(store_path: str, inputs: list[str]) -> int:
    from ..plan import PriorStore
    store = PriorStore(store_path)
    total = 0
    for p in inputs:
        if not os.path.exists(p):
            print(f"plan: {p}: no such file", file=sys.stderr)
            return 1
        if p.endswith(".json"):
            got = store.harvest_bench(p)
        else:
            got = store.harvest_trace(p)
        print(f"harvested {got:>4} sample(s) from {p}")
        total += got
    store.save(store_path)
    print(f"{store_path}: {len(store)} prior(s) ({total} new sample(s))")
    return 0


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    try:
        opts, args = getopt.gnu_getopt(
            argv, "n:e:w:",
            ["explain", "json", "harvest=", "priors=", "assume-rss="])
    except getopt.GetoptError as exc:
        print(f"Unknown option character '{(exc.opt or '?')[:1]}'.")
        return 2
    as_json = False
    harvest_store = None
    priors_path = None
    assume_rss = None
    n = edges = None
    workers = None
    for o, a in opts:
        if o == "--json":
            as_json = True
        elif o == "--harvest":
            harvest_store = a
        elif o == "--priors":
            priors_path = a
        elif o == "--assume-rss":
            assume_rss = int(a)
        elif o == "-n":
            n = int(a)
        elif o == "-e":
            edges = int(a)
        elif o == "-w":
            workers = int(a)
        # --explain is the default (and only) render mode; accepted for
        # the ROADMAP's spelling of the command

    if harvest_store is not None:
        if not args:
            print(USAGE)
            return 2
        return _harvest(harvest_store, args)

    edges_path = None
    if args:
        if len(args) != 1:
            print(USAGE)
            return 2
        edges_path = args[0]
        if not os.path.exists(edges_path):
            print(f"plan: {edges_path}: no such file", file=sys.stderr)
            return 1
        if not edges_path.endswith(".dat"):
            print(f"plan: {edges_path}: only .dat record streams can be "
                  f"planned from the file alone (use -n/-e)",
                  file=sys.stderr)
            return 1
        if n is None or edges is None:
            # pass-1 arithmetic: one streaming histogram derives the
            # position-space size and record count without loading the
            # edge list (the exact pass the ext build would run)
            from ..ops.extmem import dat_num_records, range_degree_histogram
            records = dat_num_records(edges_path)
            if edges is None:
                edges = records
            if n is None:
                deg, _, _ = range_degree_histogram(edges_path)
                n = int((deg > 0).sum())
    if n is None:
        print(USAGE)
        return 2
    if edges is None:
        edges = 4 * n

    from ..plan import PriorStore, plan_build
    priors = PriorStore(priors_path) if priors_path else None
    plan = plan_build(int(n), int(edges),
                      num_workers=workers, devices=1,
                      edges_path=edges_path, priors=priors,
                      assume_rss=assume_rss,
                      with_distext=edges_path is not None)
    if as_json:
        json.dump(plan.to_dict(), sys.stdout, indent=2, sort_keys=True)
        sys.stdout.write("\n")
    else:
        sys.stdout.write("\n".join(plan.explain()) + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
