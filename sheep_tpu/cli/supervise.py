"""sheep supervise: the chaos-hardened distributed tournament driver.

No reference counterpart — the reference's file path is fire-and-forget
bash; this tool is the operational face of sheep_tpu.supervisor.  It owns
the sort -> map -> merge-tournament lifecycle of the distributed file
path and survives any single-point failure (dead/hung/straggling workers,
corrupted artifacts, its own death — rerun with the same -d to resume):

    bin/supervise graph.dat -w 8 -d state/ -o graph.tre
    bin/supervise graph.dat -w 8 -d state/ -o graph.tre   # resumes

scripts/horizontal-dist.sh delegates to this under dist-partition.sh -S.

Options:
  -d DIR     state dir: manifest + intermediate artifacts + worker logs
             (default: <graph>.supervisor).  Rerunning with the same dir
             fscks the surviving artifacts and re-dispatches only the
             dirty/missing legs.
  -w N       tournament width (map workers; default SHEEP_WORKERS or 2)
  -r N       tournament fan-in (default REDUCTION or 2)
  -s SEQ     existing sequence file to build over (skip the sort phase)
  -o OUT     final tree path (default <state-dir>/<base>.tre)
  -t SEC     heartbeat deadline (default SHEEP_DEADLINE_S or 30)
  -v         echo the event trace as it happens
  --status   read-only operator report of the state dir (leg states,
             dispatch counts, heartbeat ages, disk/mem budget headroom,
             and — for distext jobs (ISSUE 13) — per-leg ext progress
             as blocks done/total from each leg's own checkpoint —
             supervisor/status.py) instead of running anything
  --json     with --status: emit the report as one JSON object so the
             serve daemon's liveness probe and outside monitors consume
             it without scraping the table

Exit codes: 0 tournament complete, 1 failure (budget spent / bad state
dir), 2 usage error.  SHEEP_FAULT_PLAN (see supervisor/chaos.py) injects
deterministic faults, SHEEP_IO_FAULT_PLAN (io/faultfs.py) injects
ENOSPC/EIO/short/slow at any write site, and SHEEP_MEM_BUDGET /
SHEEP_DISK_BUDGET / SHEEP_LEG_CORES bound what a run may consume —
operators can rehearse a recovery before trusting a multi-hour run to it.
"""

from __future__ import annotations

import getopt
import sys

from ..integrity.errors import IntegrityError
from ..supervisor import (SupervisionFailed, SupervisorConfig,
                          SupervisorKilled, run_supervised)

USAGE = ("USAGE: supervise graph [-d state_dir] [-w workers] [-r reduction]"
         " [-s seq_file] [-o out_tree] [-t deadline_s] [-v] "
         "[--status [--json]]")


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    try:
        opts, args = getopt.gnu_getopt(argv, "d:w:r:s:o:t:v",
                                       ["status", "json"])
    except getopt.GetoptError as exc:
        print(f"Unknown option character '{(exc.opt or '?')[:1]}'.")
        return 2

    state_dir = None
    seq_file = None
    out_file = None
    verbose = False
    status = False
    as_json = False
    overrides: dict = {}
    for o, a in opts:
        if o == "-d":
            state_dir = a
        elif o == "-w":
            overrides["workers"] = int(a)
        elif o == "-r":
            overrides["reduction"] = int(a)
        elif o == "-s":
            seq_file = a
        elif o == "-o":
            out_file = a
        elif o == "-t":
            overrides["deadline_s"] = float(a)
        elif o == "-v":
            verbose = True
        elif o == "--status":
            status = True
        elif o == "--json":
            as_json = True

    if status:
        # --status needs a state dir: given directly, or derived from the
        # graph argument the way a run would derive it
        if state_dir is None and len(args) == 1:
            state_dir = args[0] + ".supervisor"
        if state_dir is None:
            print(USAGE)
            return 2
        from ..supervisor.status import main_status
        return main_status(state_dir, as_json=as_json)

    if as_json:
        print("supervise: --json only applies to --status")
        return 2

    if len(args) != 1:
        print(USAGE)
        return 2
    graph = args[0]
    state_dir = state_dir or graph + ".supervisor"

    try:
        config = SupervisorConfig.from_env(**overrides)
    except ValueError as exc:
        print(f"supervise: {exc}", file=sys.stderr)
        return 2

    if verbose:
        class _Echo(list):
            def append(self, item):
                print(f"supervise: {' '.join(str(x) for x in item)}",
                      flush=True)
                super().append(item)
        config.events = _Echo()

    try:
        manifest = run_supervised(graph, state_dir, config,
                                  seq_file=seq_file, out_file=out_file)
    except (SupervisionFailed, SupervisorKilled, IntegrityError,
            OSError) as exc:
        print(f"supervise: {exc}", file=sys.stderr)
        return 1
    dispatches = sum(leg.dispatches for leg in manifest.legs)
    print(f"supervise: {len(manifest.legs)} leg(s) complete in "
          f"{dispatches} dispatch(es); tree at "
          f"{out_file or manifest.final_tree}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
