"""sheep top: the fleet's live operator console (ISSUE 12).

No reference counterpart — this is the read side of the router's fleet
scrape (serve/router.py ``fleet_metrics``): one ``METRICS`` request to
the router fans in every reachable cluster member, and this tool renders
the result as a refreshing per-tenant table::

    bin/top -d route-dir/              # router state dir (router.addr)
    bin/top -r 127.0.0.1:7700          # explicit router (or daemon) addr
    bin/top --json -i 0.5              # one machine-readable snapshot

Columns (per tenant): the hosting cluster, current qps (counter delta
between two scrapes), windowed p99 (the sliding-window gauge — CURRENT
latency, not since-boot), max replication lag and epoch across the
instances hosting the tenant, and how many instances hold it resident.
While a live migration is visible (ISSUE 17) two extra columns appear:
``MIG`` (snap/delta/cutover on the adopting target, ``moved`` on the
fenced source) and ``DLAG`` (delta-stream records the target still
trails by); the footer adds the router's migration tallies and, when
the rebalancer is on, its go/hold verdict counts.  Likewise once a
tenant reports sequence drift or a completed re-sequence (ISSUE 18)
the ``SDRIFT`` (out-of-sequence inserts since the last cut) and
``RESEQ`` (completed re-sequence generations) columns appear.  Once a
tenant has paid a group-commit fsync or a lock-free read has retried
(ISSUE 19) the write-path columns appear: ``FSYN/s`` (shared WAL
fsyncs per second — the amortization the group commit buys), ``GC50``
/ ``GC99`` (records per shared fsync, p50/p99) and ``SLRT`` (seqlock
read retries).  Anti-entropy (ISSUE 20): a ``DVRG`` column appears
once any tenant is quarantined (state diverged from the leader; reads
refused until re-sync), and the instance footer grows
``SCRUB``/``QUAR``/``REP`` (scrub passes, artifacts quarantined,
artifacts repaired) once any member has completed a scrub pass.  An
``instances`` footer shows per-instance epoch/lag/RSS from the same
scrape.

``--json`` takes two scrapes ``-i`` seconds apart (default 1.0; 0 =
single scrape, qps null) and prints one JSON object — what the tier-1
smoke and scripts consume.  Interactive mode refreshes every ``-i``
seconds (default 2) until ``-n`` iterations or Ctrl-C.

Exit codes: 0 rendered, 1 unreachable/unparseable, 2 usage error.
"""

from __future__ import annotations

import getopt
import json
import os
import sys
import time

from ..obs.metrics import parse_prometheus
from ..serve.protocol import ServeClient

USAGE = ("USAGE: top [-r host:port | -d state-dir] [-i interval_s] "
         "[-n iterations] [--json]")


def resolve_addr(host_port: str | None,
                 state_dir: str | None) -> tuple[str, int] | None:
    if host_port:
        host, _, port = host_port.rpartition(":")
        try:
            return (host or "127.0.0.1"), int(port)
        except ValueError:
            return None
    if state_dir:
        for name in ("router.addr", "serve.addr", "worker.addr"):
            try:
                host, port = open(os.path.join(state_dir, name)) \
                    .read().split()
                return host, int(port)
            except (OSError, ValueError):
                continue
    return None


def fleet_view(samples) -> dict:
    """Shape one scrape's samples into the per-tenant / per-instance
    view the table renders.  Tenant residency series name the hosting
    instances; lag/epoch roll up as max over those instances."""
    tenants: dict[str, dict] = {}
    instances: dict[str, dict] = {}
    workers: dict[str, dict] = {}

    def wk(labels):
        # a build worker's scrape (serve/worker.py) has no tenant
        # series; its identity is its instance label, or "local" when
        # the scrape came straight off one worker daemon
        return workers.setdefault(labels.get("instance", "local"), {})

    def tn(labels):
        t = labels.get("tenant")
        if t is None:
            return None
        return tenants.setdefault(
            t, {"instances": [], "resident_on": [], "requests": 0.0,
                "window_p99_ms": None, "applied_seqno": 0,
                "cluster": None, "mig": None, "mig_lag": None,
                "seq_drift": None, "reseqs": None, "diverged": None,
                "gc_fsyncs": 0.0, "gc_p50": None, "gc_p99": None,
                "seqlock_retries": None})

    for name, labels, val in samples:
        inst = labels.get("instance")
        # fleet-DERIVED gauges keep their own cluster= label (that is
        # the point of them), so they must not mint the instance row's
        # cluster — the per-member/router series do
        if inst and inst not in instances \
                and not name.startswith("sheep_fleet_"):
            instances[inst] = {"cluster": labels.get("cluster")}
        if inst and inst not in instances:
            continue
        if name == "sheep_serve_epoch" and inst:
            instances[inst]["epoch"] = int(val)
        elif name == "sheep_serve_repl_lag_records" and inst \
                and "node" not in labels:
            instances[inst]["repl_lag"] = int(val)
        elif name == "sheep_process_vmrss_bytes" and inst:
            instances[inst]["vmrss_mb"] = round(val / (1 << 20), 1)
        elif name == "sheep_serve_tenant_resident":
            rec = tn(labels)
            if rec is None:
                continue
            if inst and inst not in rec["instances"]:
                rec["instances"].append(inst)
                if rec["cluster"] is None:
                    rec["cluster"] = labels.get("cluster")
            if val >= 1 and inst:
                rec["resident_on"].append(inst)
        elif name == "sheep_serve_tenant_requests_total":
            rec = tn(labels)
            if rec is not None:
                rec["requests"] += val
        elif name == "sheep_serve_tenant_window_p99_seconds":
            rec = tn(labels)
            if rec is not None:
                ms = round(val * 1000, 3)
                if rec["window_p99_ms"] is None \
                        or ms > rec["window_p99_ms"]:
                    rec["window_p99_ms"] = ms
        elif name == "sheep_serve_tenant_applied_seqno":
            rec = tn(labels)
            if rec is not None:
                rec["applied_seqno"] = max(rec["applied_seqno"],
                                           int(val))
        elif name == "sheep_serve_mig_phase" and val >= 1:
            # migration visibility (ISSUE 17): a member reporting
            # snap/delta (target adopting in) wins over the source's
            # "moved" when both show up in one scrape
            rec = tn(labels)
            if rec is not None:
                phase = labels.get("phase", "?")
                if rec["mig"] is None or phase != "moved":
                    rec["mig"] = phase
        elif name in ("sheep_serve_mig_delta_lag_records",
                      "sheep_migrate_delta_lag_records"):
            rec = tn(labels)
            if rec is not None:
                rec["mig_lag"] = max(rec["mig_lag"] or 0, int(val))
        elif name == "sheep_diverged":
            # anti-entropy (ISSUE 20): any instance reporting the
            # tenant quarantined marks the whole tenant row
            rec = tn(labels)
            if rec is not None:
                rec["diverged"] = max(rec["diverged"] or 0, int(val))
        elif name == "sheep_scrub_runs_total" and inst:
            instances[inst]["scrub_runs"] = int(val)
        elif name == "sheep_scrub_quarantined_total" and inst:
            instances[inst]["scrub_quar"] = int(val)
        elif name == "sheep_scrub_repaired_total" and inst:
            instances[inst]["scrub_rep"] = int(val)
        elif name == "sheep_serve_seq_drift":
            rec = tn(labels)
            if rec is not None:
                rec["seq_drift"] = max(rec["seq_drift"] or 0, int(val))
        elif name == "sheep_serve_reseqs_total":
            rec = tn(labels)
            if rec is not None:
                rec["reseqs"] = max(rec["reseqs"] or 0, int(val))
        elif name == "sheep_serve_group_commit_fsyncs_total":
            rec = tn(labels)
            if rec is not None:
                rec["gc_fsyncs"] += val
        elif name == "sheep_serve_group_commit_size_p50":
            rec = tn(labels)
            if rec is not None:
                rec["gc_p50"] = max(rec["gc_p50"] or 0, int(val))
        elif name == "sheep_serve_group_commit_size_p99":
            rec = tn(labels)
            if rec is not None:
                rec["gc_p99"] = max(rec["gc_p99"] or 0, int(val))
        elif name == "sheep_serve_read_seqlock_retries_total":
            rec = tn(labels)
            if rec is not None:
                rec["seqlock_retries"] = (rec["seqlock_retries"] or 0) \
                    + int(val)
        elif name == "sheep_worker_legs_inflight":
            wk(labels)["legs_inflight"] = int(val)
        elif name == "sheep_worker_legs_done":
            wk(labels)["legs_done"] = int(val)
        elif name == "sheep_worker_bytes_shipped":
            wk(labels)["bytes_shipped"] = int(val)
    # a build worker's process gauges ride the same scrape; attach them
    # only to scrapes that identified themselves as workers above
    if workers:
        for name, labels, val in samples:
            key = labels.get("instance", "local")
            if key not in workers:
                continue
            if name == "sheep_process_vmrss_bytes":
                workers[key]["vmrss_mb"] = round(val / (1 << 20), 1)
            elif name == "sheep_process_uptime_seconds":
                workers[key]["uptime_s"] = round(val, 1)
    for rec in tenants.values():
        hosting = [instances.get(i, {}) for i in rec["instances"]]
        rec["repl_lag"] = max((h.get("repl_lag", 0) for h in hosting),
                              default=0)
        rec["epoch"] = max((h.get("epoch", 0) for h in hosting),
                           default=0)
        rec["resident"] = len(rec["resident_on"])
    fleet = {}
    for name, labels, val in samples:
        if name == "sheep_fleet_epoch_skew":
            fleet.setdefault("epoch_skew", {})[
                labels.get("cluster", "?")] = int(val)
        elif name == "sheep_fleet_repl_lag_max_records":
            fleet.setdefault("repl_lag_max", {})[
                labels.get("cluster", "?")] = int(val)
        elif name == "sheep_fleet_members_reachable":
            fleet.setdefault("members_reachable", {})[
                labels.get("cluster", "?")] = int(val)
        elif name == "sheep_fleet_scrape_seconds":
            fleet["scrape_s"] = val
        elif name == "sheep_migrate_inflight":
            fleet["migrate_inflight"] = int(val)
        elif name == "sheep_migrate_completed":
            fleet["migrate_completed"] = int(val)
        elif name == "sheep_migrate_aborted":
            fleet["migrate_aborted"] = int(val)
        elif name == "sheep_rebalance_verdicts_total":
            fleet.setdefault("rebalance_verdicts", {})[
                labels.get("action", "?")] = int(val)
    return {"tenants": tenants, "instances": instances, "fleet": fleet,
            "workers": workers}


def qps_between(prev: dict, cur: dict, dt: float) -> None:
    """Stamp per-tenant qps (and group-commit fsyncs/s) from two views'
    counter deltas."""
    for t, rec in cur["tenants"].items():
        before = prev["tenants"].get(t, {}).get("requests", 0.0)
        rec["qps"] = round(max(0.0, rec["requests"] - before)
                           / max(dt, 1e-9), 1)
        gc0 = prev["tenants"].get(t, {}).get("gc_fsyncs", 0.0)
        rec["fsyncs_per_s"] = round(max(0.0, rec["gc_fsyncs"] - gc0)
                                    / max(dt, 1e-9), 1)


def render_table(view: dict, scrape_bytes: int) -> str:
    # the MIG/DLAG columns only appear while a migration is visible in
    # the scrape (the remote-worker-columns discipline: byte-stable
    # output for fleets that never migrate)
    migrating = any(rec.get("mig") for rec in view["tenants"].values())
    # same discipline for the re-sequence columns (ISSUE 18): they only
    # appear once a tenant reports sequence drift or a completed reseq
    reseqing = any(rec.get("reseqs") or rec.get("seq_drift")
                   for rec in view["tenants"].values())
    # ...and again for the group-commit write path (ISSUE 19): the
    # columns appear once a tenant has paid any shared fsync or a
    # lock-free read has retried — an idle fleet's table is unchanged
    committing = any(rec.get("gc_fsyncs") or rec.get("seqlock_retries")
                     for rec in view["tenants"].values())
    # anti-entropy columns (ISSUE 20): DVRG appears once any tenant is
    # quarantined; the instance table's SCRUB/QUAR/REP appear once any
    # member has completed a scrub pass
    diverging = any(rec.get("diverged")
                    for rec in view["tenants"].values())
    scrubbing = any(rec.get("scrub_runs")
                    for rec in view["instances"].values())
    head = (f"{'TENANT':<12} {'CLUSTER':<8} {'QPS':>8} {'P99w':>9} "
            f"{'LAG':>5} {'EPOCH':>5} {'RES':>4} {'APPLIED':>9}")
    if migrating:
        head += f" {'MIG':>8} {'DLAG':>6}"
    if reseqing:
        head += f" {'SDRIFT':>6} {'RESEQ':>5}"
    if committing:
        head += f" {'FSYN/s':>7} {'GC50':>5} {'GC99':>5} {'SLRT':>6}"
    if diverging:
        head += f" {'DVRG':>4}"
    lines = [head, "-" * len(head)]
    for t, rec in sorted(view["tenants"].items()):
        p99 = rec.get("window_p99_ms")
        row = (
            f"{t:<12} {rec.get('cluster') or '?':<8} "
            f"{rec.get('qps', '-'):>8} "
            f"{(f'{p99:.2f}ms' if p99 is not None else '-'):>9} "
            f"{rec.get('repl_lag', 0):>5} {rec.get('epoch', 0):>5} "
            f"{rec.get('resident', 0):>4} "
            f"{rec.get('applied_seqno', 0):>9}")
        if migrating:
            mlag = rec.get("mig_lag")
            row += (f" {rec.get('mig') or '-':>8} "
                    f"{(mlag if mlag is not None else '-'):>6}")
        if reseqing:
            sd = rec.get("seq_drift")
            rq = rec.get("reseqs")
            row += (f" {(sd if sd is not None else '-'):>6} "
                    f"{(rq if rq is not None else '-'):>5}")
        if committing:
            fps = rec.get("fsyncs_per_s")
            slr = rec.get("seqlock_retries")
            row += (f" {(fps if fps is not None else '-'):>7} "
                    f"{(rec.get('gc_p50') if rec.get('gc_p50') is not None else '-'):>5} "
                    f"{(rec.get('gc_p99') if rec.get('gc_p99') is not None else '-'):>5} "
                    f"{(slr if slr is not None else '-'):>6}")
        if diverging:
            row += f" {('YES' if rec.get('diverged') else '-'):>4}"
        lines.append(row)
    lines.append("")
    ihead = (f"{'INSTANCE':<22} {'CLUSTER':<8} {'EPOCH':>5} "
             f"{'LAG':>5} {'RSS':>9}")
    if scrubbing:
        ihead += f" {'SCRUB':>5} {'QUAR':>4} {'REP':>4}"
    lines += [ihead, "-" * len(ihead)]
    for inst, rec in sorted(view["instances"].items()):
        rss = rec.get("vmrss_mb")
        irow = (
            f"{inst:<22} {rec.get('cluster') or '?':<8} "
            f"{rec.get('epoch', '-'):>5} {rec.get('repl_lag', '-'):>5} "
            f"{(f'{rss}M' if rss is not None else '-'):>9}")
        if scrubbing:
            irow += (f" {rec.get('scrub_runs', '-'):>5} "
                     f"{rec.get('scrub_quar', '-'):>4} "
                     f"{rec.get('scrub_rep', '-'):>4}")
        lines.append(irow)
    if view.get("workers"):
        whead = (f"{'WORKER':<22} {'INFLT':>5} {'DONE':>6} "
                 f"{'SHIPPED':>10} {'RSS':>9}")
        lines += ["", whead, "-" * len(whead)]
        for w, rec in sorted(view["workers"].items()):
            rss = rec.get("vmrss_mb")
            shipped = rec.get("bytes_shipped")
            lines.append(
                f"{w:<22} {rec.get('legs_inflight', '-'):>5} "
                f"{rec.get('legs_done', '-'):>6} "
                f"{(f'{shipped / (1 << 20):.1f}M' if shipped is not None else '-'):>10} "
                f"{(f'{rss}M' if rss is not None else '-'):>9}")
    fleet = view["fleet"]
    foot = [f"scrape: {scrape_bytes} bytes"]
    if "scrape_s" in fleet:
        foot.append(f"fan-in {fleet['scrape_s'] * 1000:.1f}ms")
    if fleet.get("epoch_skew"):
        skews = ", ".join(f"{c}={v}" for c, v in
                          sorted(fleet["epoch_skew"].items()))
        foot.append(f"epoch skew {skews}")
    if fleet.get("migrate_inflight") or fleet.get("migrate_completed") \
            or fleet.get("migrate_aborted"):
        foot.append(f"migrations {fleet.get('migrate_inflight', 0)} "
                    f"live / {fleet.get('migrate_completed', 0)} done "
                    f"/ {fleet.get('migrate_aborted', 0)} aborted")
    if fleet.get("rebalance_verdicts"):
        rv = fleet["rebalance_verdicts"]
        foot.append(f"rebalancer {rv.get('migrate', 0)} go / "
                    f"{rv.get('hold', 0)} hold")
    lines += ["", "  ".join(foot)]
    return "\n".join(lines) + "\n"


def snapshot(addr) -> tuple[dict, int]:
    with ServeClient(addr[0], addr[1], timeout_s=30.0) as c:
        body = c.metrics()
    return fleet_view(parse_prometheus(body)), len(body)


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    try:
        opts, args = getopt.gnu_getopt(argv, "r:d:i:n:", ["json"])
    except getopt.GetoptError as exc:
        print(f"Unknown option character '{(exc.opt or '?')[:1]}'.")
        return 2
    host_port = state_dir = None
    interval = None
    iters = 0  # 0 = forever (interactive); --json always one shot
    as_json = False
    for o, a in opts:
        if o == "-r":
            host_port = a
        elif o == "-d":
            state_dir = a
        elif o == "-i":
            interval = float(a)
        elif o == "-n":
            iters = int(a)
        elif o == "--json":
            as_json = True
    if args and host_port is None and state_dir is None:
        host_port = args[0]
        args = args[1:]
    if args:
        print(USAGE)
        return 2
    addr = resolve_addr(host_port, state_dir)
    if addr is None:
        print("top: no router address (-r host:port or -d state-dir "
              "with a router.addr/serve.addr)", file=sys.stderr)
        return 1

    if as_json:
        dt = 1.0 if interval is None else interval
        try:
            view, nbytes = snapshot(addr)
            if dt > 0:
                time.sleep(dt)
                view2, nbytes = snapshot(addr)
                qps_between(view, view2, dt)
                view = view2
        except (OSError, ConnectionError) as exc:
            print(f"top: {addr[0]}:{addr[1]} unreachable ({exc})",
                  file=sys.stderr)
            return 1
        view["scrape_bytes"] = nbytes
        json.dump(view, sys.stdout, indent=2, sort_keys=True)
        sys.stdout.write("\n")
        return 0

    dt = 2.0 if interval is None else max(0.1, interval)
    prev = None
    n = 0
    try:
        while True:
            try:
                view, nbytes = snapshot(addr)
            except (OSError, ConnectionError) as exc:
                print(f"top: {addr[0]}:{addr[1]} unreachable ({exc})",
                      file=sys.stderr)
                return 1
            if prev is not None:
                qps_between(prev, view, dt)
            prev = view
            sys.stdout.write("\x1b[2J\x1b[H" if n else "")
            sys.stdout.write(
                f"sheep top — {addr[0]}:{addr[1]}  "
                f"{time.strftime('%H:%M:%S')}  (refresh {dt:g}s)\n\n")
            sys.stdout.write(render_table(view, nbytes))
            sys.stdout.flush()
            n += 1
            if iters and n >= iters:
                return 0
            time.sleep(dt)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
