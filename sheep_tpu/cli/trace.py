"""sheep trace: render a flight-recorder file for a human.

No reference counterpart — this is the operational face of the ISSUE-10
observability layer (sheep_tpu/obs), and the precursor of the planner's
``plan --explain``: it answers "where did this build spend its time,
which ladder rung ran, and why" from the one trace file a run leaves
behind::

    bin/trace run.trace                # rollup + rung explanation + timeline
    bin/trace --json run.trace         # the same, as one JSON object
    bin/trace -m strict run.trace      # refuse a torn (killed-run) trace

Sections:

  rollup     per-phase span totals (count / total / max / % of wall)
  ladder     the rung-decision explanation: governor-priced peak vs the
             measured headroom per rung, which rung actually ran, every
             degrade hop, and the measured wall/RSS of the winner
  timeline   top spans in time order, indented by nesting, with a text
             duration bar (the poor terminal's flame graph)

``--merge`` (ISSUE 12) switches to the FLEET view: the arguments become
dirs / globs / files naming many processes' traces, and the output is
one timeline per rid stitched across them — router retry, the dead
leader's final spans, the promoted leader's first fsync, one rid.
Clock alignment is per-file (obs/merge.py): rid-paired containment when
files share rids (offset reported with an honest ±bound), wall-clock
meta otherwise (bound reported unknown).  ``--rid <hex>`` picks one
request; ``-n`` caps how many rids render.

Default read policy is ``repair``: a kill -9 mid-run leaves a torn
trailing line by design (obs/trace.py), and the whole point of a flight
recorder is reading the wreckage; ``-m strict`` refuses the tear for
pipelines that must only consume sealed traces.  Exit codes: 0 rendered,
1 unreadable/corrupt, 2 usage error.
"""

from __future__ import annotations

import getopt
import json
import sys

from ..integrity.errors import IntegrityError
from ..integrity.sidecar import POLICIES
from ..obs.trace import read_trace, rollup

USAGE = ("USAGE: trace [-m strict|repair|trust] [--json] [-n N] "
         "file.trace\n"
         "       trace --merge [--rid RID] [--json] [-n N] "
         "<dir|glob|file>...")

#: timeline rows beyond this are elided (traces can carry one span per
#: chunk round; the timeline is for orientation, the rollup for totals)
DEFAULT_ROWS = 60


def _fmt_s(s: float) -> str:
    if s >= 100:
        return f"{s:.0f}s"
    if s >= 1:
        return f"{s:.2f}s"
    return f"{s * 1000:.1f}ms"


def _fmt_bytes(n) -> str:
    if n is None:
        return "-"
    n = float(n)
    for unit, shift in (("G", 30), ("M", 20), ("K", 10)):
        if abs(n) >= (1 << shift):
            return f"{n / (1 << shift):.1f}{unit}"
    return f"{int(n)}B"


def wall_seconds(records: list[dict]) -> float:
    """The trace's wall: last span/event end minus first start."""
    t_min, t_max = None, 0.0
    for r in records:
        if r.get("k") not in ("span", "ev"):
            continue
        t = float(r.get("t", 0.0))
        end = t + float(r.get("dur", 0.0))
        t_min = t if t_min is None else min(t_min, t)
        t_max = max(t_max, end)
    return max(0.0, t_max - (t_min or 0.0))


def ladder_explanation(records: list[dict]) -> list[str]:
    """The rung-decision story: plan (price vs headroom per rung),
    degrades, and the rung that finished with its measured cost."""
    lines: list[str] = []
    rung_spans = {}
    for r in records:
        if r.get("k") == "span" and r.get("name") == "rung":
            rung_spans[r.get("a", {}).get("rung", "?")] = r
    for r in records:
        if r.get("k") != "ev":
            continue
        a = r.get("a", {})
        name = r.get("name")
        if name == "ladder.plan":
            planned = a.get("rungs", [])
            lines.append(f"ladder plan: {' -> '.join(planned) or '-'}"
                         + (f"  (headroom {_fmt_bytes(a.get('headroom_bytes'))},"
                            f" rss {_fmt_bytes(a.get('rss_bytes'))},"
                            f" budget {_fmt_bytes(a.get('budget_bytes'))})"
                            if a.get("budget_bytes") is not None else
                            "  (unbudgeted: no rung priced out)"))
            for p in a.get("priced", []):
                verdict = p.get("verdict", "?")
                lines.append(
                    f"  {p.get('rung', '?'):<7} governor price "
                    f"{_fmt_bytes(p.get('est_bytes')):>8}"
                    + (f" (history x-> {_fmt_bytes(p['corrected_bytes'])}"
                       f" via {p.get('prior', '?')})"
                       if p.get("corrected_bytes") is not None else "")
                    + f" -> {verdict}")
            # planner decisions (ISSUE 15): surface the overridden /
            # history-corrected knobs — the full story is `sheep plan`
            for d in a.get("decisions", []):
                if d.get("provenance") in ("forced", "learned"):
                    lines.append(
                        f"  knob {d.get('name')} = {d.get('value')} "
                        f"[{d.get('provenance')}]"
                        + (f" (analytic said {d['analytic']})"
                           if d.get("analytic") is not None else ""))
        elif name == "rung.degrade":
            lines.append(f"degrade: {a.get('rung')} -> {a.get('next')} "
                         f"({a.get('why', '?')})")
        elif name == "rung.resume":
            lines.append(f"resume: rung {a.get('rung')} at boundary "
                         f"{a.get('boundary')} ({a.get('rounds')} rounds in)")
        elif name == "rung.ok":
            rung = a.get("rung", "?")
            sp = rung_spans.get(rung, {})
            lines.append(
                f"ran: rung '{rung}' in "
                f"{_fmt_s(float(sp.get('dur', a.get('wall_s', 0.0) or 0.0)))}"
                f" (measured rss {_fmt_bytes(a.get('rss_bytes'))}"
                + (f", priced {_fmt_bytes(a.get('est_bytes'))}"
                   if a.get("est_bytes") is not None else "") + ")")
    if not lines and rung_spans:
        for rung, sp in rung_spans.items():
            lines.append(f"ran: rung '{rung}' in "
                         f"{_fmt_s(float(sp.get('dur', 0.0)))}")
    return lines


def timeline_rows(records: list[dict], max_rows: int = DEFAULT_ROWS):
    """(depth, name, t, dur, attrs) per span in start order, nesting from
    the id/par links (spans land at exit, so file order is exit order)."""
    spans = [r for r in records if r.get("k") == "span"]
    spans.sort(key=lambda r: float(r.get("t", 0.0)))
    depth_of: dict = {}
    rows = []
    for r in spans:
        par = r.get("par")
        depth = depth_of.get(par, -1) + 1 if par is not None else 0
        depth_of[r.get("id")] = depth
        rows.append((depth, r.get("name", "?"), float(r.get("t", 0.0)),
                     float(r.get("dur", 0.0)), r.get("a", {})))
    elided = max(0, len(rows) - max_rows)
    if elided:
        # keep the longest spans plus every top-level one, in time order
        keep = sorted(rows, key=lambda x: (-(x[0] == 0), -x[3]))[:max_rows]
        rows = sorted(keep, key=lambda x: x[2])
    return rows, elided


def render(records: list[dict], torn: bool, path: str,
           max_rows: int = DEFAULT_ROWS) -> str:
    wall = wall_seconds(records)
    roll = rollup(records)
    events = roll.pop("_events", {})
    lines = [f"trace: {path}"
             + ("  [TORN TAIL: partial trace from a killed run]"
                if torn else ""),
             f"wall: {_fmt_s(wall)}   spans: "
             f"{sum(p['count'] for p in roll.values())}   events: "
             f"{sum(events.values())}", ""]

    head = f"{'PHASE':<28} {'COUNT':>6} {'TOTAL':>9} {'MAX':>9} {'%WALL':>6}"
    lines += ["phase rollup", head, "-" * len(head)]
    for name, p in sorted(roll.items(), key=lambda kv: -kv[1]["total_s"]):
        pct = 100.0 * p["total_s"] / wall if wall > 0 else 0.0
        lines.append(f"{name:<28} {p['count']:>6} "
                     f"{_fmt_s(p['total_s']):>9} {_fmt_s(p['max_s']):>9} "
                     f"{pct:>5.1f}%")
    # reconciliation: top-level span coverage of the wall (the acceptance
    # check — phase sums must explain the clock, not hand-wave at it)
    top = [r for r in records
           if r.get("k") == "span" and r.get("par") is None]
    top_sum = sum(float(r.get("dur", 0.0)) for r in top)
    if wall > 0:
        lines.append(f"{'':<28} top-level spans cover "
                     f"{100.0 * min(top_sum, wall) / wall:.1f}% of wall")

    expl = ladder_explanation(records)
    if expl:
        lines += ["", "ladder decisions"] + ["  " + e for e in expl]
    if events:
        lines += ["", "events: " + "  ".join(
            f"{k}={v}" for k, v in sorted(events.items()))]

    rows, elided = timeline_rows(records, max_rows)
    if rows:
        lines += ["", "timeline"]
        for depth, name, t, dur, attrs in rows:
            bar = "#" * max(1, min(30, int(30 * dur / wall))) \
                if wall > 0 else "#"
            extra = " ".join(f"{k}={v}" for k, v in list(attrs.items())[:3])
            lines.append(f"  {t:>9.4f}s {'  ' * depth}{name:<24} "
                         f"{_fmt_s(dur):>9}  {bar}"
                         + (f"  [{extra}]" if extra else ""))
        if elided:
            lines.append(f"  ... {elided} shorter span(s) elided "
                         f"(rollup above counts them)")
    return "\n".join(lines) + "\n"


def summary_json(records: list[dict], torn: bool, path: str) -> dict:
    roll = rollup(records)
    events = roll.pop("_events", {})
    wall = wall_seconds(records)
    top = [r for r in records
           if r.get("k") == "span" and r.get("par") is None]
    return {
        "path": path,
        "torn": torn,
        "wall_s": round(wall, 6),
        "phases": roll,
        "events": events,
        "top_level_span_s": round(
            sum(float(r.get("dur", 0.0)) for r in top), 6),
        "ladder": ladder_explanation(records),
    }


def merge_main(args: list[str], mode: str, as_json: bool,
               only_rid: str | None, max_rids: int) -> int:
    """The ``--merge`` mode: stitch many processes' traces by rid."""
    from ..obs.merge import (collect_trace_paths, estimate_offsets,
                             load_sources, merge_by_rid, merged_json,
                             render_merged)
    paths = collect_trace_paths(args)
    if not paths:
        print(f"trace: no .trace files under {' '.join(args)!r}",
              file=sys.stderr)
        return 1
    import warnings
    try:
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")  # tears show in the render
            sources = load_sources(paths, mode)
    except (IntegrityError, OSError) as exc:
        print(f"trace: {exc}", file=sys.stderr)
        return 1
    estimate_offsets(sources)
    rids = merge_by_rid(sources)
    if only_rid is not None and only_rid not in rids:
        print(f"trace: rid {only_rid!r} appears in none of "
              f"{len(paths)} file(s)", file=sys.stderr)
        return 1
    if as_json:
        json.dump(merged_json(sources, rids, only_rid), sys.stdout,
                  indent=2, sort_keys=True)
        sys.stdout.write("\n")
    else:
        sys.stdout.write(render_merged(sources, rids, only_rid,
                                       max_rids))
    return 0


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    try:
        opts, args = getopt.gnu_getopt(argv, "m:n:",
                                       ["json", "merge", "rid="])
    except getopt.GetoptError as exc:
        print(f"Unknown option character '{(exc.opt or '?')[:1]}'.")
        return 2
    mode = "repair"  # a killed run's torn tail is the expected customer
    as_json = False
    max_rows = DEFAULT_ROWS
    merge = False
    only_rid = None
    for o, a in opts:
        if o == "-m":
            if a not in POLICIES:
                print(f"trace: -m {a!r} must be one of "
                      f"{'/'.join(POLICIES)}")
                return 2
            mode = a
        elif o == "--json":
            as_json = True
        elif o == "--merge":
            merge = True
        elif o == "--rid":
            only_rid = a
        elif o == "-n":
            max_rows = int(a)
    if merge:
        if not args:
            print(USAGE)
            return 2
        return merge_main(args, mode, as_json, only_rid,
                          max_rows if max_rows != DEFAULT_ROWS else 20)
    if len(args) != 1:
        print(USAGE)
        return 2
    path = args[0]
    import warnings
    try:
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")  # the tear shows in the render
            records, _, torn = read_trace(path, mode)
    except (IntegrityError, OSError) as exc:
        print(f"trace: {exc}", file=sys.stderr)
        return 1
    if as_json:
        json.dump(summary_json(records, torn, path), sys.stdout,
                  indent=2, sort_keys=True)
        sys.stdout.write("\n")
    else:
        sys.stdout.write(render(records, torn, path, max_rows))
    return 0


if __name__ == "__main__":
    sys.exit(main())
