"""sheep serve: run the crash-safe, replicated partition service.

No reference counterpart — the reference answers nothing without a cold
build; this daemon keeps the tree + partition resident and serves
part/ECV/subtree queries plus WAL-backed incremental inserts over the
line protocol (sheep_tpu.serve.protocol).

    bin/serve -d state/ -g graph.dat -k 8          # bootstrap + serve
    bin/serve -d state/ -T g.tre -s g.seq -g g.dat # serve existing build
    bin/serve -d state/                            # restart: snapshot+WAL
    bin/serve -d lead/ -g g.dat --role leader --peers f1/,f2/
    bin/serve -d f1/ --role follower --peers lead/,f2/   # joins + streams
    bin/serve -d state/ -g g.dat --tenant web=web/:web.dat:8 \
              --tenant social=soc/                 # multi-tenant (ISSUE 11)

First start (artifact flags given) bootstraps the state dir: artifacts
load through the strict integrity readers, generation-0 snapshot seals
sidecar-first, an empty WAL is created.  Restart (no artifact flags)
recovers: newest loadable snapshot + WAL replay — bit-identical to the
pre-crash tree; a torn trailing WAL record is refused in strict mode and
truncated under ``-m repair``.  A FOLLOWER with an empty state dir and
no artifacts bootstraps over the wire instead: it fetches the leader's
sealed snapshot (crc-verified, resealed locally) and then tails the WAL
stream (serve/replicate.py).

Options:
  -d DIR     state dir (required): snapshots + WAL + serve.addr/serve.hb
  -g GRAPH   edge file; with no -T/-s the sequence+tree are built from it
  -T TRE     tree artifact (pairs with -s)
  -s SEQ     sequence artifact
  -P FILE    jnid-indexed partition file (default: partition in-process)
  -k N       number of partitions (default 2; ignored with -P)
  -p PORT    listen port (default 0 = ephemeral; the bound address is
             printed and written to <state-dir>/serve.addr)
  -H HOST    bind host (default 127.0.0.1)
  -m MODE    integrity policy for recovery: strict (default) / repair
  -b F       partition balance factor (default 1.03)
  --role R   leader | follower (default SHEEP_SERVE_ROLE or leader)
  --peers L  comma list of peers: host:port, a peer's state dir, or an
             addr file (default SHEEP_SERVE_PEERS)
  --node-id N  this node's id for election tie-breaks and lag reports
             (default SHEEP_SERVE_NODE_ID or host:port)
  --tenant name=dir[:graph[:k]]   host another serve state dir behind
             this daemon (repeatable; also SHEEP_SERVE_TENANTS as a
             comma list of the same entries).  Connections select it
             with the ``TENANT name`` verb; an empty dir bootstraps
             from its :graph (or, on a clustered follower, over the
             wire from the leader's same-named tenant).  Cold tenants
             evict to their sealed snapshot under memory pressure
             (SHEEP_MEM_BUDGET / SHEEP_SERVE_MAX_RESIDENT) and restore
             lazily on the next touch.

Env: SHEEP_SERVE_DEADLINE_S, SHEEP_SERVE_MAX_INFLIGHT,
SHEEP_SERVE_SNAP_EVERY, SHEEP_SERVE_DRIFT, SHEEP_SERVE_DRIFT_MIN,
SHEEP_SERVE_GROUP_COMMIT_MAX / _DELAY_S (leader group-commit window),
SHEEP_SERVE_TENANTS (comma list of name=dir[:graph[:k]]),
SHEEP_SERVE_MAX_RESIDENT (resident-tenant cap; cold ones evict),
SHEEP_TRACE_SAMPLE (1/N per-request serve.req span sampling),
SHEEP_SERVE_ROLE, SHEEP_SERVE_PEERS, SHEEP_SERVE_NODE_ID,
SHEEP_SERVE_REPL_ACKS (follower acks per insert OK, default 1),
SHEEP_SERVE_REPL_HB_S, SHEEP_SERVE_FAILOVER_S, SHEEP_SERVE_MAX_LAG
(bounded staleness for follower reads), SHEEP_SERVE_FAULT_PLAN
(serve/faults.py), SHEEP_SERVE_NETFAULT_PLAN (serve/netfaults.py),
SHEEP_IO_FAULT_PLAN sites ``wal``/``snap``, SHEEP_MEM_BUDGET (read-only
degradation).

Exit codes: 0 clean shutdown, 1 startup/recovery failure, 2 usage error.
"""

from __future__ import annotations

import getopt
import os
import signal
import sys

from ..integrity.errors import IntegrityError
from ..integrity.sidecar import POLICIES

USAGE = ("USAGE: serve -d state_dir [-g graph] [-T tree -s seq] [-P parts]"
         " [-k num_parts] [-p port] [-H host] [-m strict|repair]"
         " [-b balance] [--role leader|follower] [--peers p1,p2]"
         " [--node-id id] [--tenant name=dir[:graph[:k]] ...]")


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    try:
        opts, args = getopt.gnu_getopt(argv, "d:g:T:s:P:k:p:H:m:b:",
                                       ["role=", "peers=", "node-id=",
                                        "tenant="])
    except getopt.GetoptError as exc:
        print(f"Unknown option character '{(exc.opt or '?')[:1]}'.")
        return 2

    state_dir = None
    graph = tre = seq = parts_file = None
    num_parts = 2
    port = 0
    host = "127.0.0.1"
    mode = None
    balance = 1.03
    cluster_kw: dict = {}
    tenant_args: list[str] = []
    for o, a in opts:
        if o == "-d":
            state_dir = a
        elif o == "-g":
            graph = a
        elif o == "-T":
            tre = a
        elif o == "-s":
            seq = a
        elif o == "-P":
            parts_file = a
        elif o == "-k":
            num_parts = int(a)
        elif o == "-p":
            port = int(a)
        elif o == "-H":
            host = a
        elif o == "-m":
            if a not in POLICIES:
                print(f"serve: -m {a!r} must be one of "
                      f"{'/'.join(POLICIES)}")
                return 2
            mode = a
        elif o == "-b":
            balance = float(a)
        elif o == "--role":
            cluster_kw["role"] = a.strip().lower()
        elif o == "--peers":
            cluster_kw["peers"] = [p.strip() for p in a.split(",")
                                   if p.strip()]
        elif o == "--node-id":
            cluster_kw["node_id"] = a.strip()
        elif o == "--tenant":
            tenant_args.append(a.strip())

    if state_dir is None or args:
        print(USAGE)
        return 2

    from ..serve import (ClusterConfig, ServeConfig, ServeCore,
                         ServeDaemon, TenantManager, parse_tenant_specs)
    from ..serve.state import snap_paths

    config = ServeConfig.from_env(host=host, port=port)
    try:
        cluster = ClusterConfig.from_env(**cluster_kw)
        tenant_specs = parse_tenant_specs(",".join(tenant_args))
    except ValueError as exc:
        print(f"serve: {exc}", file=sys.stderr)
        return 2
    core_kw = dict(snap_every=config.snap_every,
                   drift_frac=config.drift_frac,
                   drift_min_cut=config.drift_min_cut,
                   reseq_frac=config.reseq_frac,
                   reseq_min=config.reseq_min,
                   reseq_rank=config.reseq_rank,
                   group_commit_max=config.group_commit_max,
                   group_commit_delay_s=config.group_commit_delay_s)
    try:
        bootstrap = not snap_paths(state_dir) if os.path.isdir(state_dir) \
            else True
        if bootstrap and graph is None and tre is None \
                and cluster.clustered and cluster.role == "follower":
            # over-the-wire bootstrap: fetch the leader's snapshot, then
            # enter through the exact restart path
            from ..serve.cluster import find_leader
            from ..serve.replicate import bootstrap_state_dir
            found = None
            deadline = 60.0
            import time as _time
            t0 = _time.monotonic()
            while found is None and _time.monotonic() - t0 < deadline:
                found = find_leader(cluster.peers,
                                    cluster.poll_timeout_s)
                if found is None:
                    _time.sleep(0.2)
            if found is None:
                print(f"serve: follower bootstrap found no reachable "
                      f"leader among {cluster.peers}", file=sys.stderr)
                return 1
            lhost, _, lport = found[0].rpartition(":")
            bootstrap_state_dir(state_dir, lhost, int(lport))
            bootstrap = False
        if bootstrap:
            if graph is None and tre is None:
                print(f"serve: {state_dir} holds no snapshots and no "
                      f"artifacts were given to bootstrap from", flush=True,
                      file=sys.stderr)
                return 1
            core = ServeCore.bootstrap(
                state_dir, tre_path=tre, seq_path=seq, graph_path=graph,
                parts_path=parts_file, num_parts=num_parts,
                balance=balance, integrity=mode, **core_kw)
        else:
            core = ServeCore.open(state_dir, integrity=mode, **core_kw)
    except (IntegrityError, OSError, ValueError) as exc:
        print(f"serve: {exc}", file=sys.stderr)
        return 1

    try:
        tenants = TenantManager.from_env(core, extra_specs=tenant_specs,
                                         open_kw=core_kw)
    except ValueError as exc:
        print(f"serve: {exc}", file=sys.stderr)
        return 2
    if cluster.clustered and cluster.role == "follower":
        # named tenants with empty dirs bootstrap over the wire from the
        # leader's same-named tenant, exactly like the default did above
        from ..serve.cluster import find_leader
        from ..serve.replicate import bootstrap_state_dir
        for name in tenants.names():
            t = tenants.get(name)
            if t.core is not None or (os.path.isdir(t.state_dir)
                                      and snap_paths(t.state_dir)):
                continue
            found = find_leader(cluster.peers, cluster.poll_timeout_s)
            if found is None:
                print(f"serve: tenant {name!r} bootstrap found no "
                      f"reachable leader", file=sys.stderr)
                return 1
            lhost, _, lport = found[0].rpartition(":")
            bootstrap_state_dir(t.state_dir, lhost, int(lport),
                                tenant=name)

    daemon = ServeDaemon(core, config, cluster=cluster,
                         tenants=tenants).start()
    h, p = daemon.address
    st = core.stats()
    print(f"serve: listening on {h}:{p}", flush=True)
    print(f"serve: ready role={daemon.role} epoch={st['epoch']} "
          f"n={st['n']} links={st['links']} "
          f"applied={st['applied_seqno']} inserted={st['inserted']}",
          flush=True)

    def _term(signum, frame):
        daemon.shutdown()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    try:
        daemon.run_forever()
    finally:
        daemon.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
