"""Shared CLI plumbing: phase timers (exact stdout grammar) + graph stats.

The reference prints phase lines like ``Loaded graph in: 1.234000 seconds``
(graph2tree.cpp:167,183,193,200,225,240, %f formatting) and the shell /
plotting layer parses them (data/make-parallel.sh), so the grammar is API.
Millisecond truncation matches std::chrono::duration_cast<milliseconds>.
"""

from __future__ import annotations

import time

import numpy as np


def maybe_start_heartbeat():
    """Start the worker heartbeat when a supervisor launched this process
    with SHEEP_HEARTBEAT_FILE in the environment (supervisor/heartbeat.py).
    Returns the writer (kept alive for the process lifetime) or None —
    unsupervised invocations are unaffected."""
    from ..supervisor.heartbeat import maybe_start_from_env
    return maybe_start_from_env()


def ensure_jax_platform() -> None:
    """Honor JAX_PLATFORMS even when a sitecustomize force-registered a
    hardware plugin and initialized the backend programmatically (in which
    case the env var alone is ignored).  Call before any mesh work."""
    import os

    want = os.environ.get("JAX_PLATFORMS", "")
    if not want:
        return
    import jax

    try:
        from jax._src import distributed as _dist
        if _dist.global_state.client is not None:
            # A multi-host session is active (maybe_init_distributed ran):
            # the platform was pinned before joining, and clearing backends
            # now would re-register the topology with the coordination
            # service (ALREADY_EXISTS crash).  Nothing to do.
            return
    except Exception:
        pass

    # Never query the current backend here — that would *initialize* it,
    # which on a tunneled hardware platform can block for a long time.
    # Drop any already-initialized backends and pin the requested platform;
    # the next jax use initializes it fresh.
    try:
        from jax.extend.backend import clear_backends
        clear_backends()
    except Exception:
        pass
    try:
        jax.config.update("jax_platforms", want)
    except Exception:
        pass


def maybe_init_distributed() -> int:
    """Join a multi-host coordination service when the launcher asks for it.

    The reference distributes `graph2tree -i -r` with `mpiexec` across
    nodes (README:88-89, data/slurm-uk2007); the launcher analog here is
    env vars: SHEEP_COORDINATOR=host:port plus SHEEP_NUM_PROCESSES /
    SHEEP_PROCESS_ID per process.  After joining, jax.devices() spans all
    hosts and the same SPMD build runs over the DCN mesh.  Returns this
    process's index (0 when not distributed) for leader gating — the
    reference's rank-0 logic (graph2tree.cpp:158-159).
    """
    import os

    coord = os.environ.get("SHEEP_COORDINATOR")
    if not coord:
        return 0
    from ..parallel import init_distributed
    num = os.environ.get("SHEEP_NUM_PROCESSES")
    pid = os.environ.get("SHEEP_PROCESS_ID")
    init_distributed(coordinator_address=coord,
                     num_processes=int(num) if num else None,
                     process_id=int(pid) if pid else None)
    import jax
    return jax.process_index()


def runtime_config_from_opts(opts):
    """Fold the fault-tolerance long options (--checkpoint-dir DIR,
    --resume, --max-retries N) plus their env fallbacks
    (SHEEP_CHECKPOINT_DIR / SHEEP_RESUME / SHEEP_MAX_RETRIES, the
    dist-partition.sh -C contract) into a runtime.RuntimeConfig.

    Returns None when no checkpoint dir is configured anywhere — the
    caller then keeps the plain fast path.  --resume / --max-retries
    without a checkpoint dir are a configuration error (there is nothing
    to resume from and nothing durable to retry toward): reported, not
    ignored.
    """
    import os

    ckpt_dir = os.environ.get("SHEEP_CHECKPOINT_DIR") or None
    resume = os.environ.get("SHEEP_RESUME", "") == "1"
    max_retries = None
    for o, a in opts:
        if o == "--checkpoint-dir":
            ckpt_dir = a
        elif o == "--resume":
            resume = True
        elif o == "--max-retries":
            max_retries = int(a)
    if ckpt_dir is None:
        if resume or max_retries is not None:
            raise SystemExit(
                "--resume/--max-retries need --checkpoint-dir (or "
                "SHEEP_CHECKPOINT_DIR) to name the checkpoint location")
        return None
    from ..runtime.driver import RuntimeConfig
    overrides = {"checkpoint_dir": ckpt_dir, "resume": resume}
    if max_retries is not None:
        overrides["max_retries"] = max_retries
    return RuntimeConfig.from_env(**overrides)


class PhaseClock:
    """Elapsed-time phases with duration_cast<milliseconds> truncation."""

    def __init__(self):
        self.start = time.perf_counter()
        self.last = 0.0  # total at the previous phase boundary, in ms

    def _total_ms(self) -> int:
        return int((time.perf_counter() - self.start) * 1000)

    def phase_seconds(self) -> float:
        """Seconds since the previous phase boundary."""
        total = self._total_ms()
        out = (total - self.last) / 1000.0
        self.last = total
        return out

    def total_seconds(self) -> float:
        return self._total_ms() / 1000.0


def print_phase(label: str, seconds: float) -> None:
    print(f"{label} in: {seconds:f} seconds", flush=True)


def print_phase_ms(label: str, seconds: float) -> None:
    """merge_trees/degree_sequence style: ``Loaded in: 12ms``."""
    print(f"{label} in: {int(seconds * 1000)}ms", flush=True)


def graph_stats(edges) -> tuple[int, int]:
    """(nodes, edges) as the reference reports them: nodes = vertices with
    nonzero degree (graph_wrapper.h:75-77), edges = file records
    (max_edges/2 of the undirected-doubled graph, :79-81)."""
    deg = edges.degrees()
    return int((deg > 0).sum()), edges.num_edges


def print_tree(seq: np.ndarray, parent: np.ndarray, pst: np.ndarray) -> None:
    """``graph2tree -t`` / JTree::print grammar (lib/jtree.h:60-66,
    lib/jnode.h print: width:w pre:pre pst:pst -> [parent])."""
    for jnid in range(len(seq)):
        width = 1 + int(pst[jnid])
        print("%4d:%-8d%6d:w%6d:pre%6d:pst        ->[%4d]"
              % (jnid, int(seq[jnid]), width, 0, int(pst[jnid]),
                 int(np.uint32(parent[jnid]))))
