"""degree_sequence: whole-graph streaming degree sort (degree_sequence.cpp).

Streams the edge file without building adjacency (the reference's
fileSequence, lib/sequence.h:95-128 — the out-of-memory path), writes the
sequence, prints ``Sorted in: Nms``.
"""

from __future__ import annotations

import sys

from ..core.sequence import degree_sequence
from ..io.edges import load_edges
from ..io.seqfile import write_sequence
from .common import PhaseClock, print_phase_ms


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 2:
        print("USAGE: degree_sequence graph_file output_file", end="")
        return 1
    clock = PhaseClock()
    edges = load_edges(argv[0])
    seq = degree_sequence(edges.tail, edges.head)
    write_sequence(seq, argv[1])
    print_phase_ms("Sorted", clock.total_seconds())
    return 0


if __name__ == "__main__":
    sys.exit(main())
