"""degree_sequence: whole-graph streaming degree sort (degree_sequence.cpp).

Streams the edge file without building adjacency (the reference's
fileSequence, lib/sequence.h:95-128 — the out-of-memory path), writes the
sequence, prints ``Sorted in: Nms``.  Binary ``.dat`` files stream through
a memmap block iterator, text ``.net`` files through a chunked token
parser — only the degree array is resident either way (the reference
streams both formats, readerwriter.h suffix dispatch at sequence.h:124-128).
"""

from __future__ import annotations

import os
import sys

import numpy as np

from ..core.sequence import degree_sequence, degree_sequence_from_degrees
from ..io.edges import iter_dat_blocks, iter_net_blocks, load_edges
from ..io.seqfile import write_sequence
from .common import PhaseClock, print_phase_ms

_BLOCK = 1 << 24  # 16M records (~192MB) per streamed block


def _streamed_sequence(path: str) -> np.ndarray:
    from ..core.sequence import host_degree_histogram

    blocks = iter_dat_blocks(path, _BLOCK) if path.endswith(".dat") \
        else iter_net_blocks(path)
    deg = np.zeros(0, dtype=np.int64)
    n = 0
    for tail, head in blocks:
        n_blk = int(max(tail.max(initial=0), head.max(initial=0))) + 1
        n = max(n, n_blk)
        if n > len(deg):  # geometric growth: amortized O(n) total copying
            grown = np.zeros(max(n, 2 * len(deg)), dtype=np.int64)
            grown[: len(deg)] = deg
            deg = grown
        deg[:n_blk] += host_degree_histogram(tail, head, n_blk)
    if n == 0:
        return np.empty(0, dtype=np.uint32)
    return degree_sequence_from_degrees(deg[:n])


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    from .common import maybe_start_heartbeat
    _hb = maybe_start_heartbeat()  # noqa: F841 — beats while we stream
    if len(argv) != 2:
        print("USAGE: degree_sequence graph_file output_file", end="")
        return 1
    clock = PhaseClock()
    if os.environ.get("SHEEP_DDUP_GRAPH", "") != "1":
        # both formats stream (dedup needs the whole edge set in memory)
        seq = _streamed_sequence(argv[0])
    else:
        edges = load_edges(argv[0])
        seq = degree_sequence(edges.tail, edges.head)
    write_sequence(seq, argv[1])
    print_phase_ms("Sorted", clock.total_seconds())
    return 0


if __name__ == "__main__":
    sys.exit(main())
