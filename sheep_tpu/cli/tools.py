"""Analysis utilities — the reference's util/ binaries (SURVEY §2.3).

Each main mirrors one util tool's argument surface and output format:

  tree2dot        .tre -> graphviz digraph              (util/tree2dot.cpp)
  tree2adj        .tre -> METIS adj, sub/super weights  (util/tree2adj.cpp)
  graph2adj       graph -> METIS adj, degree-renumbered (util/graph2adj.cpp)
  vfennel         in-memory fennel + evaluate           (util/vfennel.cpp)
  efennel         streaming edge fennel                 (util/efennel.cpp)
  read_partition  re-evaluate a jnid partition file     (util/read_partition.cpp)
"""

from __future__ import annotations

import sys

import numpy as np

from .. import INVALID_JNID
from ..core.sequence import degree_sequence
from ..io.edges import load_edges
from ..io.trefile import read_tree
from ..partition.evaluate import evaluate_partition
from ..partition.fennel import fennel_edges, fennel_vertex
from ..partition.partition import Partition
from .common import PhaseClock, graph_stats, print_phase_ms


def tree2dot(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) < 2:
        print("USAGE: graph2dot input_graph output_graph")
        return 1
    clock = PhaseClock()
    parent, _ = read_tree(argv[0])
    print_phase_ms("Loaded", clock.phase_seconds())
    print()
    with open(argv[1], "w") as dot:
        dot.write("digraph {\n")
        for jnid in range(len(parent) - 1, -1, -1):
            line = f"\t{jnid}"
            if parent[jnid] != INVALID_JNID:
                line += f" -> {int(parent[jnid])}"
            dot.write(line + "\n")
        dot.write("}\n")
    print_phase_ms("Finished", clock.phase_seconds())
    return 0


def tree2adj(argv: list[str] | None = None) -> int:
    """METIS format with edge weights min(subtree, edge_width) +
    min(super-tree, edge_width) per tree edge (util/tree2adj.cpp:55-90)."""
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) < 2:
        print("USAGE: tree2adj input_tree output_graph")
        return 1
    clock = PhaseClock()
    parent, pst = read_tree(argv[0])
    print_phase_ms("Loaded", clock.phase_seconds())
    print()
    n = len(parent)
    par = parent.astype(np.int64)
    par[parent == INVALID_JNID] = -1
    edge_width = pst.astype(np.int64).copy()
    subt = np.ones(n, dtype=np.int64)
    supr = np.ones(n, dtype=np.int64)
    edge_count = 0
    for i in range(n):
        p = par[i]
        if p >= 0:
            edge_count += 1
            edge_width[p] += edge_width[i]  # pre_weight is 0 by default
            subt[p] += subt[i]
    for i in range(n - 1, -1, -1):
        if par[i] >= 0:
            supr[i] += supr[par[i]]
    kids: list[list[int]] = [[] for _ in range(n)]
    for i in range(n):
        if par[i] >= 0:
            kids[par[i]].append(i)
    with open(argv[1], "w") as adj:
        adj.write(f"{n} {edge_count} 011\n")
        for i in range(n):
            fields = ["1"]
            if par[i] >= 0:
                w = min(subt[i], edge_width[i]) + \
                    min(supr[par[i]], edge_width[i])
                fields.append(f"{par[i] + 1} {w}")
            for k in kids[i]:
                w = min(subt[k], edge_width[k]) + min(supr[i], edge_width[k])
                fields.append(f"{k + 1} {w}")
            adj.write(" ".join(fields) + "\n")
    print_phase_ms("Finished", clock.phase_seconds())
    return 0


def graph2adj(argv: list[str] | None = None) -> int:
    """METIS format, vertices renumbered by the degree sequence
    (util/graph2adj.cpp:55-87); vertex weight = degree, self-loops skipped
    in adjacency."""
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) < 2:
        print("USAGE: graph2adj input_graph output_graph")
        return 1
    clock = PhaseClock()
    edges = load_edges(argv[0])
    print_phase_ms("Loaded", clock.phase_seconds())
    print()
    seq = degree_sequence(edges.tail, edges.head)
    index = np.zeros(int(seq.max()) + 1 if len(seq) else 0, dtype=np.int64)
    index[seq] = np.arange(1, len(seq) + 1)

    deg = edges.degrees()
    src = np.concatenate([edges.tail, edges.head]).astype(np.int64)
    dst = np.concatenate([edges.head, edges.tail]).astype(np.int64)
    order = np.argsort(src, kind="stable")
    src_s, dst_s = src[order], dst[order]
    offs = np.zeros(len(deg) + 1, dtype=np.int64)
    np.add.at(offs, src_s + 1, 1)
    np.cumsum(offs, out=offs)

    edge_cnt = int((np.minimum(edges.tail, edges.head)
                    < np.maximum(edges.tail, edges.head)).sum())
    with open(argv[1], "w") as adj:
        adj.write(f"{len(seq)} {edge_cnt} 010\n")
        for v in seq.tolist():
            nbrs = dst_s[offs[v]:offs[v + 1]]
            nbrs = nbrs[nbrs != v]
            fields = [str(int(deg[v]))] + [str(int(index[y])) for y in nbrs]
            adj.write(" ".join(fields) + "\n")
    print_phase_ms("Finished", clock.phase_seconds())
    return 0


def vfennel(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) < 2:
        print("USAGE: vfennel graph parts [parts...]")
        return 1
    clock = PhaseClock()
    edges = load_edges(argv[0])
    print_phase_ms("Loaded", clock.phase_seconds())
    nodes, nedges = graph_stats(edges)
    print(f"Nodes:{nodes} Edges:{nedges}")
    for parts_arg in argv[1:]:
        num_parts = int(parts_arg)
        pclock = PhaseClock()
        parts = fennel_vertex(edges.tail, edges.head, num_parts,
                              max_vid=edges.max_vid)
        Partition(parts, num_parts).print()
        print(f"Partitioning took: {int(pclock.phase_seconds() * 1000)}ms")
        evaluate_partition(parts, edges.tail, edges.head, None, num_parts,
                           max_vid=edges.max_vid,
                           file_edges=edges.num_edges).print(with_seq=False)
    print_phase_ms("Finished", clock.total_seconds())
    return 0


def efennel(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) < 2:
        print("USAGE: fennel graph parts [parts...]")
        return 1
    clock = PhaseClock()
    edges = load_edges(argv[0])
    for parts_arg in argv[1:]:
        num_parts = int(parts_arg)
        pclock = PhaseClock()
        eparts = fennel_edges(edges.tail, edges.head, num_parts,
                              max_vid=edges.max_vid)
        Partition(eparts, num_parts).print()
        print(f"Partitioning took: {int(pclock.phase_seconds() * 1000)}ms")
    print_phase_ms("Finished", clock.total_seconds())
    return 0


def read_partition(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) < 2:
        print("USAGE: read_partition graph partition [partition...]")
        return 1
    clock = PhaseClock()
    edges = load_edges(argv[0])
    seq = degree_sequence(edges.tail, edges.head)
    print_phase_ms("Loaded", clock.phase_seconds())
    nodes, nedges = graph_stats(edges)
    print(f"Nodes:{nodes} Edges:{nedges}")
    for fname in argv[1:]:
        part = Partition.from_file(seq, fname)
        evaluate_partition(part.parts, edges.tail, edges.head, seq,
                           part.num_parts, max_vid=edges.max_vid,
                           file_edges=edges.num_edges).print()
    print_phase_ms("Finished", clock.phase_seconds())
    return 0
