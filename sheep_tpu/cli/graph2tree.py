"""graph2tree: load graph -> sequence -> elimination forest [-> partition].

Flag surface and control flow mirror graph2tree.cpp:44-246; the MPI switches
are re-targeted at the TPU mesh (this is the one deliberate redesign):

  -i / -r   In the reference these pick MPI collectives across ranks
            (mpiSequence / mpi_merge).  Here either switch runs the fused
            SPMD build over a ``jax.sharding.Mesh`` of all local devices in
            ONE process — edge records are sharded over the 'workers' axis,
            the degree sort is a psum'd histogram, and the tree reduce is an
            all_gather + associative rebuild (sheep_tpu.parallel).  The
            worker count is the device count (override: SHEEP_WORKERS).
  -l n/k    partial file load for the multi-process file path (map-worker).

Everything else is host-native: the C++ runtime (sheep_tpu.native) does the
streaming insert and FFD partition exactly like the reference's serial path.
"""

from __future__ import annotations

import getopt
import os
import sys

import numpy as np

from ..core.facts import compute_facts
from ..core.forest import Forest, build_forest
from ..core.sequence import degree_sequence
from ..core.validate import is_valid_forest
from ..io.edges import load_edges
from ..io.seqfile import read_sequence, write_sequence
from ..io.trefile import write_tree
from ..partition.partition import Partition
from .common import PhaseClock, graph_stats, print_phase, print_tree

USAGE = "USAGE: graph2tree input_graph [options ...]"


def _tree_sig(seq) -> str:
    """Input signature stamped into .tre sidecars: identifies the
    (n, sequence) the tree was built over.  Partial trees of one build
    share it, so merge_trees can refuse a cross-build tournament — the
    real compatibility requirement is "same sequence", which edge bytes
    cannot express (each worker sees a different slice)."""
    import numpy as np

    from ..runtime.snapshot import input_signature
    seq = np.asarray(seq, dtype=np.uint32)
    return input_signature(len(seq), seq)


def _make_jopts(make_kids, make_pst, make_jxn, memory_limit, width_limit,
                find_max_width):
    from ..core.jxn import JxnOptions
    return JxnOptions(make_kids=make_kids, make_pst=make_pst,
                      make_jxn=make_jxn,
                      memory_limit=memory_limit or (1 << 30),
                      width_limit=width_limit,
                      find_max_width=find_max_width)


def _finish_sort(seq, use_mesh_sort, sequence_filename, clock,
                 leader=True, writer=True):
    """Write the sequence when `-i -s` asked for it and emit the Sorted
    phase line per the reference grammar (graph2tree.cpp:177-184).
    ``leader``/``writer`` gate the print / shared-fs write in multi-host
    runs (non-leader processes compute the same replicated results)."""
    if use_mesh_sort and sequence_filename and writer:
        write_sequence(seq, sequence_filename)
    if (use_mesh_sort or sequence_filename == "") and leader:
        print_phase("Sorted", clock.phase_seconds())


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    from .common import maybe_start_heartbeat
    _hb = maybe_start_heartbeat()  # noqa: F841 — beats while we build
    try:
        # Long options are the fault-tolerance surface (sheep_tpu.runtime):
        # they have no reference counterpart, so they take GNU spellings
        # instead of burning more single-letter flags.
        opts, args = getopt.gnu_getopt(
            argv, "irl:p:s:o:vkejm:w:xfdtc",
            ["checkpoint-dir=", "resume", "max-retries=", "ext",
             "distext"])
    except getopt.GetoptError as exc:
        if (exc.opt or "").startswith(("checkpoint-dir", "max-retries",
                                       "resume", "ext", "distext")):
            print(f"Option --{exc.opt}: {exc.msg}.")
            return 1
        o = (exc.opt or "?")[:1]
        if o in ("s", "o", "l"):
            print(f"Option -{o} requires a string.")
        elif o in ("m", "w", "p"):
            print(f"Option -{o} requires a long long.")
        else:
            print(f"Unknown option character '{o}'.")
        return 1

    use_mesh_sort = use_mesh_reduce = False
    part = num_parts = 0
    partitions = 0
    sequence_filename = ""
    output_filename = ""
    verbose = False
    make_kids = make_pst = make_jxn = False
    memory_limit = 0
    width_limit = 0
    find_max_width = False
    do_faqs = do_print = do_validate = False
    use_ext = False
    use_distext = False

    for o, a in opts:
        if o == "-i":
            use_mesh_sort = not use_mesh_sort
        elif o == "-r":
            use_mesh_reduce = not use_mesh_reduce
        elif o == "-l":
            part_s, num_s = a.split("/")
            part, num_parts = int(part_s), int(num_s)
        elif o == "-p":
            partitions = int(a)
        elif o == "-s":
            sequence_filename = a
        elif o == "-o":
            output_filename = a
        elif o == "-v":
            verbose = not verbose
        elif o == "-k":
            make_kids = not make_kids
        elif o == "-e":
            make_pst = not make_pst
        elif o == "-j":
            make_jxn = not make_jxn
        elif o == "-m":
            memory_limit = int(a) * (1 << 20)
        elif o == "-w":
            width_limit = int(a)
        elif o == "-x":
            find_max_width = not find_max_width
        elif o == "-f":
            do_faqs = not do_faqs
        elif o == "-t":
            do_print = not do_print
        elif o == "-c":
            do_validate = not do_validate
        elif o == "--ext":
            use_ext = True
        elif o == "--distext":
            use_distext = True

    if not args:
        print(USAGE)
        return 1
    graph_filename = args[0]

    from .common import runtime_config_from_opts
    rt_cfg = runtime_config_from_opts(opts)

    clock = PhaseClock()
    use_mesh = use_mesh_sort or use_mesh_reduce
    is_leader = use_mesh or sequence_filename == ""
    proc0 = True  # this process writes shared-filesystem outputs
    if use_mesh:
        # Multi-host launch (the reference's mpiexec across nodes): join
        # the coordination service before any backend work; only process 0
        # is the leader (rank-0 logic, graph2tree.cpp:158-159).  Unlike
        # the reference's per-rank partial writes, every process here
        # computes the full (replicated) result, so non-leaders skip
        # writes entirely rather than racing on the same files.
        from .common import ensure_jax_platform, maybe_init_distributed
        ensure_jax_platform()
        if maybe_init_distributed() != 0:
            is_leader = False
            proc0 = False

    jxn_mode = make_kids or make_pst or make_jxn or width_limit or \
        find_max_width

    # External-memory routing (ISSUE 9): decided BEFORE the load — the
    # whole point is that the edge list never enters RAM.  --ext forces;
    # SHEEP_EXT_BLOCK is the env twin; a configured SHEEP_MEM_BUDGET the
    # in-RAM load cannot fit routes automatically.  Only the serial
    # whole-file .dat path streams (mesh/jxn/partial loads keep their
    # in-RAM semantics), and a partitioned-graph copy (-p with -o) still
    # needs the records — say so and fall back instead of surprising.
    if not use_mesh and not jxn_mode and not num_parts \
            and graph_filename.endswith(".dat"):
        # Distributed routing first (ISSUE 13): --distext forces;
        # SHEEP_DISTEXT_LEGS is the env twin; auto when even the ext
        # rung's single-leg stream cannot meet the budget.  The job
        # needs -o (the supervisor exports the final tree there) and no
        # partition request (same limitation as --ext, plus the records
        # live across N legs) — say so and fall back, never surprise.
        if not use_distext:
            from ..ops.distext import should_use_distext
            use_distext = should_use_distext(graph_filename)
        if use_distext and (partitions or not output_filename):
            print("warning: the distributed out-of-core build needs -o "
                  "and cannot partition (the edge records never load in "
                  "one process); falling back to the single-process "
                  "path", file=sys.stderr)
            use_distext = False
        if not use_ext and not use_distext:
            from ..ops.extmem import should_use_extmem
            use_ext = should_use_extmem(graph_filename)
        if use_ext and partitions and output_filename:
            print("warning: the external-memory build cannot write a "
                  "partitioned graph copy (the edge records never load); "
                  "falling back to the in-RAM path", file=sys.stderr)
            use_ext = False
    else:
        use_ext = False
        use_distext = False

    if use_distext:
        # The supervised distributed job (ops/distext.run_distext): the
        # supervisor owns the whole hist -> Allreduce -> map -> merge
        # lifecycle and prints the reference phase grammar itself.  The
        # state dir doubles as the checkpoint surface (--checkpoint-dir
        # redirects it), so a rerun resumes off the fsck'd survivors.
        from ..integrity.errors import IntegrityError
        from ..ops.distext import run_distext
        from ..supervisor import (SupervisionFailed, SupervisorKilled,
                                  SupervisorConfig)
        state_dir = (rt_cfg.checkpoint_dir if rt_cfg is not None else
                     None) or output_filename + ".distext"
        try:
            run_distext(graph_filename, state_dir,
                        SupervisorConfig.from_env(),
                        out_file=output_filename)
        except (SupervisionFailed, SupervisorKilled, IntegrityError,
                OSError) as exc:
            print(f"graph2tree: distext: {exc}", file=sys.stderr)
            return 1
        if verbose:
            print_phase("Finished", clock.total_seconds())
        return 0

    if verbose:
        print(f"Loading {graph_filename}...")
    if use_mesh and num_parts:
        # The reference clobbers -l under MPI: part/num_parts become the
        # rank mapping (graph2tree.cpp:134-143), so every record is still
        # processed across the job.  The mesh path is that whole job in one
        # process; a user-supplied -l cannot be honored — say so instead of
        # silently processing the full graph.
        print(f"warning: -l {part}/{num_parts} is superseded by -i/-r "
              f"(the mesh processes all records, like the reference's "
              f"MPI rank mapping); ignoring -l", file=sys.stderr)
    if use_ext:
        edges = None  # the stream IS the load; downstream guards on None
    else:
        edges = load_edges(graph_filename, part, num_parts) \
            if not use_mesh else load_edges(graph_filename)
        if verbose:
            nodes, nedges = graph_stats(edges)
            print(f"Nodes:{nodes} Edges:{nedges}")
    if is_leader:
        print_phase("Loaded graph", clock.phase_seconds())

    widths = None

    map_only = False
    if use_mesh and jxn_mode:
        # The kids/pst/jxn tables are a host-side feature (dynamic shapes;
        # SURVEY §7); with -i/-r they run as the single-worker equivalent:
        # device degree sort, then the host parameterized insert — matching
        # a 1-rank MPI run of the reference with the same jopts.
        from .common import ensure_jax_platform
        ensure_jax_platform()
        from ..core.jxn import build_forest_jxn
        from ..ops.sort import degree_sequence_device
        if not use_mesh_sort and sequence_filename:
            seq = read_sequence(sequence_filename)
        else:
            seq = degree_sequence_device(edges.tail, edges.head)
        _finish_sort(seq, use_mesh_sort, sequence_filename, clock,
                     leader=is_leader, writer=proc0)
        jopts = _make_jopts(make_kids, make_pst, make_jxn, memory_limit,
                            width_limit, find_max_width)
        forest, seq, widths = build_forest_jxn(
            edges.tail, edges.head, seq, jopts)
        if is_leader:
            print_phase("Mapped", clock.phase_seconds())
            if use_mesh_reduce:
                print_phase("Reduced", clock.phase_seconds())
    elif use_mesh:
        # Fused SPMD program over the device mesh: sort + map [+ reduce].
        from .common import ensure_jax_platform
        ensure_jax_platform()
        import jax

        from ..parallel.build import build_graph_distributed
        # SHEEP_WORKERS (set by the scripts to $WORKERS) fixes the logical
        # worker count; the mesh itself is capped by the device count — the
        # merged result is identical for any mesh size.
        workers = int(os.environ.get("SHEEP_WORKERS") or 0) \
            or len(jax.devices())
        mesh_workers = min(workers, len(jax.devices()))
        if jax.process_count() > 1:
            # Multi-host: every process participates in the SPMD program,
            # so the mesh must span all global devices — a smaller mesh
            # would exclude later hosts' devices while those processes
            # still drive the program (no addressable shards -> crash).
            mesh_workers = len(jax.devices())
        given_seq = None
        if not use_mesh_sort and sequence_filename:
            given_seq = read_sequence(sequence_filename)
        # -i without -r: save exactly `workers` partial trees for the
        # file-path reduce tournament (reference rank-suffixed %02dr0.tre
        # naming, graph2tree.cpp:146-149).  When the worker count fits the
        # mesh, partials are built on device in one SPMD dispatch (each
        # mesh shard is a partial graph); with more workers than devices
        # the host builds partial_range slices instead (the reference's
        # OOM regime, where ranks outnumber cores too).
        map_only = (use_mesh_sort and not use_mesh_reduce
                    and output_filename != "" and partitions == 0)
        if map_only:
            from ..io.edges import EdgeList, partial_range
            from ..ops.sort import degree_sequence_device
            seq = given_seq if given_seq is not None else \
                degree_sequence_device(edges.tail, edges.head)
            _finish_sort(seq, use_mesh_sort, sequence_filename, clock,
                         leader=is_leader, writer=proc0)
            max_vid = edges.max_vid
            # Multi-host: the device path's mesh must span all global
            # devices (a smaller mesh would exclude later hosts' devices
            # while their processes still drive the program); any other
            # worker count takes the host fallback, which has no
            # collectives and keeps the W-partials file contract.
            mesh_ok = jax.process_count() == 1 \
                or workers == len(jax.devices())
            if workers <= len(jax.devices()) and len(edges.tail) and mesh_ok:
                from ..parallel.build import map_graph_distributed
                _, partials = map_graph_distributed(
                    edges.tail, edges.head, num_workers=workers, seq=seq)
                if proc0:
                    sig = _tree_sig(seq)
                    for w, f in enumerate(partials):
                        write_tree(f"{output_filename}{w:02d}r0.tre",
                                   f.parent, f.pst_weight, sig=sig)
                # -f/-c/-t report worker 0's partial view, like the
                # reference's rank 0 with its partial graph load.
                forest = partials[0]
                shard = -(-len(edges.tail) // workers)
                a0, b0 = 0, min(shard, len(edges.tail))
            else:
                forest = None
                sig = _tree_sig(seq)
                for w in range(workers):
                    a, b = partial_range(edges.num_edges, w + 1, workers)
                    f = build_forest(edges.tail[a:b], edges.head[a:b], seq,
                                     max_vid=max_vid)
                    if proc0:
                        write_tree(f"{output_filename}{w:02d}r0.tre",
                                   f.parent, f.pst_weight, sig=sig)
                    if forest is None:
                        forest = f
                        a0, b0 = a, b
                    if not proc0:
                        # non-leader process: this host loop has no
                        # collectives, and all writes are dropped — only
                        # worker 0's view (for -f/-t/-c) is needed
                        break
            edges = EdgeList(edges.tail[a0:b0], edges.head[a0:b0],
                             file_edges=edges.file_edges, start=a0)
        elif rt_cfg is not None:
            # Fault-tolerant build (--checkpoint-dir / SHEEP_CHECKPOINT_DIR):
            # checkpointed chunk loops, retry-with-backoff, and the
            # mesh -> single-chip -> host degradation ladder.  Bit-identical
            # results; the pipelined fast paths are traded for survivability.
            from ..runtime.driver import build_graph_resilient
            seq, forest = build_graph_resilient(
                edges.tail, edges.head, num_workers=mesh_workers,
                seq=given_seq, max_vid=edges.max_vid, config=rt_cfg)
            _finish_sort(seq, use_mesh_sort, sequence_filename, clock,
                         leader=is_leader, writer=proc0)
        else:
            seq, forest = build_graph_distributed(
                edges.tail, edges.head, num_workers=mesh_workers,
                seq=given_seq)
            _finish_sort(seq, use_mesh_sort, sequence_filename, clock,
                         leader=is_leader, writer=proc0)
        if is_leader:
            print_phase("Mapped", clock.phase_seconds())
            if use_mesh_reduce:
                print_phase("Reduced", clock.phase_seconds())
    elif use_ext:
        # Out-of-core serial path (ISSUE 9): two streamed passes, no jax,
        # no in-RAM edge list.  Same phase grammar as the serial path.
        from ..ops.extmem import build_forest_extmem, \
            streaming_degree_sequence
        ext_kw: dict = {}
        if rt_cfg is not None:
            ext_kw = dict(checkpoint_dir=rt_cfg.checkpoint_dir,
                          resume=rt_cfg.resume,
                          max_retries=rt_cfg.max_retries,
                          backoff_base_s=rt_cfg.backoff_base_s,
                          checkpoint_every=rt_cfg.checkpoint_every,
                          integrity=rt_cfg.integrity,
                          governor=rt_cfg.governor)
        if sequence_filename:
            seq = read_sequence(sequence_filename)
        else:
            seq, _, _ = streaming_degree_sequence(graph_filename)
        if is_leader:
            print_phase("Sorted", clock.phase_seconds())
        seq, forest = build_forest_extmem(graph_filename, seq=seq, **ext_kw)
        if is_leader:
            print_phase("Mapped", clock.phase_seconds())
    else:
        if sequence_filename:
            seq = read_sequence(sequence_filename)
        else:
            seq = degree_sequence(edges.tail, edges.head)
        if is_leader:
            print_phase("Sorted", clock.phase_seconds())
        if jxn_mode:
            from ..core.jxn import build_forest_jxn
            jopts = _make_jopts(make_kids, make_pst, make_jxn, memory_limit,
                                width_limit, find_max_width)
            forest, seq, widths = build_forest_jxn(
                edges.tail, edges.head, seq, jopts)
        elif rt_cfg is not None:
            # Serial path with fault tolerance: the single-chip chunked
            # driver under checkpoint/retry, degrading to the host oracle
            # (no mesh rung — the user did not ask for -i/-r).
            import dataclasses

            from .common import ensure_jax_platform
            ensure_jax_platform()
            from ..runtime.driver import build_graph_resilient
            serial_cfg = dataclasses.replace(
                rt_cfg, ladder=("single", "host"))
            _, forest = build_graph_resilient(
                edges.tail, edges.head, seq=seq, max_vid=edges.max_vid,
                config=serial_cfg)
        else:
            forest = build_forest(edges.tail, edges.head, seq,
                                  max_vid=edges.max_vid)
        if is_leader:
            print_phase("Mapped", clock.phase_seconds())

    # under --ext the records never loaded; every vid with a record has
    # nonzero degree and is therefore in the sequence, so seq.max() IS
    # the file's max vid
    max_vid = edges.max_vid if edges is not None else \
        (int(np.asarray(seq).max()) if len(seq) else 0)
    if partitions != 0:
        p = Partition.from_forest(seq, forest, partitions,
                                  max_vid=max_vid)
        if output_filename:
            if proc0:
                prefix = output_filename + \
                    ("-w0000-p" if use_mesh_reduce else "")
                p.write_partitioned_graph(edges.tail, edges.head, seq,
                                          prefix, max_vid=max_vid)
        elif is_leader:
            p.print()
    elif output_filename and not map_only and proc0:
        # Serial fast path builds straight into the output file
        # (graph2tree.cpp:185-188); with -r only the leader saves (:217-218).
        write_tree(output_filename, forest.parent, forest.pst_weight,
                   sig=_tree_sig(seq))

    # Diagnostics print from process 0 only in multi-host runs (rank-0
    # grammar; every process holds the same replicated result anyway).
    # Single-process behavior is unchanged — proc0 is True there even for
    # non-leader map workers.
    if verbose and proc0:
        print_phase("Built", clock.total_seconds())

    if do_faqs and proc0:
        compute_facts(forest, widths=widths).print()
    if do_print and proc0:
        print_tree(seq, forest.parent, forest.pst_weight)
    if do_validate and proc0:
        if edges is None:
            print("warning: -c needs the in-RAM edge list; skipped under "
                  "the external-memory build", file=sys.stderr)
        elif is_valid_forest(forest, edges.tail, edges.head, seq,
                             max_vid=edges.max_vid):
            print("Tree is valid.")
        else:
            print("ERROR: Tree is not valid.")

    if verbose and proc0:
        print_phase("Finished", clock.total_seconds())
    return 0


if __name__ == "__main__":
    sys.exit(main())
