"""partition_tree: load a .tre + sequence, partition, evaluate or write.

Flag surface and the three modes mirror partition_tree.cpp:40-171:
partition-only print (no -g), partition+evaluate (-g), partition+write
(-g -o).  When no weight flag is given, pst weights are the default
(partition_tree.cpp:95-96).  One intended-behavior fix: the reference's
partition-only loop re-reads ``argv[optind + 2]`` for every trailing parts
argument (an evident indexing slip at :117); here each parts argument is
honored.
"""

from __future__ import annotations

import getopt
import sys

from ..core.facts import compute_facts
from ..core.forest import Forest, pre_weights
from ..core.sequence import degree_sequence
from ..io.edges import load_edges
from ..io.seqfile import read_sequence
from ..io.trefile import read_tree
from ..partition.evaluate import evaluate_partition
from ..partition.partition import Partition
from ..partition.tree_partition import TreePartitionOptions
from .common import PhaseClock, print_phase

USAGE = "USAGE: partition_tree [options] input_sequence input_tree parts [parts...]"

#: .dat record count beyond which the evaluate mode streams blocks through
#: the O(n)-memory evaluator (override: SHEEP_EVAL_STREAM=1 forces it on,
#: =0 off, SHEEP_EVAL_STREAM_THRESHOLD sets the record count).
_STREAM_THRESHOLD = 1 << 27


def _streamed_eval_wanted(graph_filename: str, sequence_filename: str) -> bool:
    import os

    if not graph_filename.endswith(".dat") or sequence_filename == "-":
        return False
    if os.environ.get("SHEEP_DDUP_GRAPH", "") == "1":
        # The block reader streams raw records (no load-level dedup), which
        # would silently change every metric; keep the dense path.
        return False
    forced = os.environ.get("SHEEP_EVAL_STREAM")
    if forced is not None:
        return forced == "1"
    threshold = int(os.environ.get("SHEEP_EVAL_STREAM_THRESHOLD",
                                   _STREAM_THRESHOLD))
    try:
        records = os.path.getsize(graph_filename) // 12  # XS1 record size
    except OSError:
        return False
    return records > threshold


def _evaluate_streamed(graph_filename, sequence_filename, forest, popts,
                       pre_weight, parts_args, verbose,
                       block_edges: int = 1 << 24) -> None:
    import numpy as np

    from ..core.sequence import sequence_positions
    from ..io.edges import iter_dat_blocks
    from ..partition.evaluate import evaluate_partition_streamed

    seq = read_sequence(sequence_filename)
    if pre_weight:
        print("warning: -u is unavailable in streamed evaluation "
              "(pre weights need the in-memory link build); using pst",
              file=sys.stderr)
    # One cheap streaming pass for the vid space + record count.
    mx = len(seq) and int(seq.max())
    file_edges = 0
    for t, h in iter_dat_blocks(graph_filename, block_edges):
        file_edges += len(t)
        mx = max(mx, int(t.max(initial=0)), int(h.max(initial=0)))
    pos = sequence_positions(seq, mx).astype(np.int64)
    factory = lambda: iter_dat_blocks(graph_filename, block_edges)
    for parts_arg in parts_args:
        num_parts = int(parts_arg)
        pclock = PhaseClock()
        part = Partition.from_forest(seq, forest, num_parts, popts,
                                     max_vid=mx)
        if verbose:
            print(f"Partitioning took: {pclock.phase_seconds():f} seconds")
        part.print()
        evaluate_partition_streamed(part.parts, factory, pos, num_parts,
                                    file_edges).print()


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    try:
        opts, args = getopt.gnu_getopt(argv, "vfb:xdug:o:")
    except getopt.GetoptError as exc:
        o = (exc.opt or "?")[:1]
        if o == "b":
            print(f"Option -{o} requires a double.")
        elif o in ("g", "o"):
            print(f"Option -{o} requires a string.")
        else:
            print(f"Unknown option character '{o}'.")
        return 1

    verbose = True
    do_faqs = False
    balance_factor = 1.03
    vtx_weight = pst_weight = pre_weight = False
    graph_filename = ""
    output_filename = ""

    for o, a in opts:
        if o == "-v":
            verbose = not verbose
        elif o == "-f":
            do_faqs = not do_faqs
        elif o == "-b":
            balance_factor = float(a)
        elif o == "-x":
            vtx_weight = True
        elif o == "-d":
            pst_weight = True
        elif o == "-u":
            pre_weight = True
        elif o == "-g":
            graph_filename = a
        elif o == "-o":
            output_filename = a

    if not (vtx_weight or pst_weight or pre_weight):
        pst_weight = True
    popts = TreePartitionOptions(balance_factor=balance_factor,
                                 vtx_weight=vtx_weight,
                                 pst_weight=pst_weight,
                                 pre_weight=pre_weight)

    if len(args) < 3:
        print(USAGE)
        return 1
    sequence_filename, tree_filename = args[0], args[1]

    clock = PhaseClock()
    parent, pst = read_tree(tree_filename)
    forest = Forest(parent, pst)
    if verbose:
        print_phase("Loaded tree", clock.phase_seconds())
    if do_faqs:
        compute_facts(forest).print()

    if graph_filename == "":
        # Partition-only print.  Without a graph, pre weights cannot be
        # recomputed (the 2-field .tre stores none, like the reference's
        # default non-USE_PRE_WEIGHT build where pre_weight() reads 0) — say
        # so instead of a silent no-op.
        if pre_weight:
            print("warning: -u without -g contributes zero pre_weight "
                  "(pre weights are recomputed from the graph; pass -g)",
                  file=sys.stderr)
        seq = read_sequence(sequence_filename)
        for parts_arg in args[2:]:
            num_parts = int(parts_arg)
            part = Partition.from_forest(seq, forest, num_parts, popts)
            part.print()
    elif output_filename == "":
        # Partition and evaluate.  Large .dat graphs stream through the
        # O(n)-memory evaluator instead of materializing doubled key arrays
        # (which peak ~50 GB at twitter scale); same numbers either way.
        if _streamed_eval_wanted(graph_filename, sequence_filename):
            _evaluate_streamed(graph_filename, sequence_filename, forest,
                               popts, pre_weight, args[2:], verbose)
        else:
            edges = load_edges(graph_filename)
            seq = degree_sequence(edges.tail, edges.head) \
                if sequence_filename == "-" else read_sequence(sequence_filename)
            pre = pre_weights(edges.tail, edges.head, seq,
                              max_vid=edges.max_vid) if pre_weight else None
            for parts_arg in args[2:]:
                num_parts = int(parts_arg)
                pclock = PhaseClock()
                part = Partition.from_forest(seq, forest, num_parts, popts,
                                             max_vid=edges.max_vid, pre=pre)
                if verbose:
                    print(f"Partitioning took: {pclock.phase_seconds():f} "
                          f"seconds")
                part.print()
                evaluate_partition(part.parts, edges.tail, edges.head, seq,
                                   num_parts, max_vid=edges.max_vid,
                                   file_edges=edges.num_edges).print()
    else:
        # Partition and write per-part edge files
        edges = load_edges(graph_filename)
        seq = degree_sequence(edges.tail, edges.head) \
            if sequence_filename == "-" else read_sequence(sequence_filename)
        pre = pre_weights(edges.tail, edges.head, seq,
                          max_vid=edges.max_vid) if pre_weight else None
        num_parts = int(args[2])
        pclock = PhaseClock()
        part = Partition.from_forest(seq, forest, num_parts, popts,
                                     max_vid=edges.max_vid, pre=pre)
        if verbose:
            print(f"Partitioning took: {pclock.phase_seconds():f} seconds")
        part.print()
        part.write_partitioned_graph(edges.tail, edges.head, seq,
                                     output_filename, max_vid=edges.max_vid)

    if verbose:
        print_phase("Finished", clock.total_seconds())
    return 0


if __name__ == "__main__":
    sys.exit(main())
