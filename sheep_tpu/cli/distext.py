"""distext: one leg of the distributed out-of-core build (ISSUE 13).

No reference counterpart — the reference's MPI ranks each load their
slice in RAM; a distext leg STREAMS its contiguous record slice of the
whole-input ``.dat`` through the external-memory pipeline (ops/extmem)
under its own ``SHEEP_MEM_BUDGET``, so N legs build a graph no single
budget can hold.  The tournament supervisor dispatches these
(supervisor/supervise._leg_argv, job kind "distext"); they also run by
hand for rehearsal:

    bin/distext hist graph.dat -r 0:500000 -o part00.hist
    bin/distext map  graph.dat -r 0:500000 -s shared.seq -o part00.tre \\
        --checkpoint-dir ck-r0.00 --resume --perf-out r0.00.perf.json

Verbs:
  hist   pass 1: stream the range, accumulate the int64 degree
         histogram per block (native kernel), publish it as a sealed
         ``.hist`` artifact (ops/distext.write_histogram) — the input
         of the supervisor's Allreduce-shaped merge.
  map    pass 2: the ext carry fold over the range, over the SHARED
         sequence (every leg must build in one position space), with
         block-boundary checkpoints in the leg's own dir — the record
         slice is folded into the checkpoint identity, so a resumed
         attempt under a different shard map is refused, never wrong.

``--perf-out`` writes the leg's self-report: the ext perf dict
(read/fold overlap_frac, per-strategy picks, retries) plus this
subprocess's ``obs.metrics.proc_status`` capture (VmHWM, affinity) — so
a multi-core host can re-judge per-leg budgets and overlap from the
bench record alone (DISTEXTBENCH).

Exit codes: 0 leg complete, 1 failure (typed integrity/resource/IO
errors), 2 usage error.  Jax-free by construction, like everything on
the out-of-core path.
"""

from __future__ import annotations

import getopt
import json
import sys

USAGE = ("USAGE: distext hist|map graph.dat -r start:end -o out "
         "[-s seq_file] [--checkpoint-dir DIR] [--resume] "
         "[--perf-out PATH]")


def _parse_range(spec: str) -> tuple[int, int]:
    a_s, b_s = spec.split(":", 1)
    a, b = int(a_s), int(b_s)
    if a < 0 or b < a:
        raise ValueError(f"range {spec!r} must be 0 <= start <= end")
    return a, b


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    from .common import maybe_start_heartbeat
    _hb = maybe_start_heartbeat()  # noqa: F841 — beats while we stream
    try:
        opts, args = getopt.gnu_getopt(
            argv, "r:o:s:v",
            ["checkpoint-dir=", "resume", "perf-out="])
    except getopt.GetoptError as exc:
        print(f"Unknown option character '{(exc.opt or '?')[:1]}'.")
        return 2

    rng = None
    out = ""
    seq_file = ""
    ckpt_dir = None
    resume = False
    perf_out = None
    verbose = False
    for o, a in opts:
        if o == "-r":
            try:
                rng = _parse_range(a)
            except ValueError as exc:
                print(f"distext: {exc}", file=sys.stderr)
                return 2
        elif o == "-o":
            out = a
        elif o == "-s":
            seq_file = a
        elif o == "-v":
            verbose = True
        elif o == "--checkpoint-dir":
            ckpt_dir = a
        elif o == "--resume":
            resume = True
        elif o == "--perf-out":
            perf_out = a

    if len(args) != 2 or args[0] not in ("hist", "map") or not out \
            or rng is None:
        print(USAGE)
        return 2
    verb, graph = args
    if verb == "map" and not seq_file:
        print("distext map: -s seq_file is required (every leg builds "
              "over the shared whole-input sequence)", file=sys.stderr)
        return 2
    a, b = rng

    from ..integrity.errors import IntegrityError
    from ..obs import trace as obs
    from ..resources.errors import ResourceError
    try:
        perf: dict = {}
        with obs.span("distext.leg", verb=verb, start_edge=a, end_edge=b):
            if verb == "hist":
                from ..ops.distext import write_histogram
                from ..ops.extmem import range_degree_histogram
                deg, max_vid, records = range_degree_histogram(
                    graph, start_edge=a, end_edge=b, perf=perf)
                write_histogram(out, deg, records, max_vid, a, b)
                if verbose:
                    print(f"hist [{a}:{b}): {records} records, "
                          f"max_vid {max_vid}", flush=True)
            else:
                from ..io.seqfile import read_sequence
                from ..io.trefile import write_tree
                from ..ops.extmem import build_forest_extmem
                from .graph2tree import _tree_sig
                seq = read_sequence(seq_file)
                seq, forest = build_forest_extmem(
                    graph, seq=seq, start_edge=a, end_edge=b,
                    checkpoint_dir=ckpt_dir, resume=resume, perf=perf)
                write_tree(out, forest.parent, forest.pst_weight,
                           sig=_tree_sig(seq))
                if verbose:
                    print(f"map [{a}:{b}): {perf.get('ext_blocks')} "
                          f"block(s), strategies "
                          f"{perf.get('strategies')}", flush=True)
    except (IntegrityError, ResourceError, OSError, ValueError) as exc:
        print(f"distext {verb}: {exc}", file=sys.stderr)
        return 1
    if perf_out:
        # the leg's self-report: perf + this subprocess's /proc capture
        # (the shared reader, obs/metrics.py) — written ATOMICALLY so a
        # kill mid-report never leaves a torn JSON for the bench to read
        from ..io.atomic import atomic_write
        from ..obs.metrics import proc_status
        with atomic_write(perf_out, "w") as f:
            json.dump({"verb": verb, "range": [a, b], "perf": perf,
                       "proc_status": proc_status()}, f, indent=1,
                      sort_keys=True)
            f.write("\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
