"""sheep route: the consistent-hash router over serve clusters.

No reference counterpart — the reference has no serving tier at all;
this daemon fronts N replicated serve clusters (serve/router.py) and
speaks the same line grammar, so any serve client points at the router
instead of a daemon and gains tenant placement, read spreading, and
epoch-safe failover retries for free.

    bin/route --cluster lead/,f1/ -p 7700               # one cluster
    bin/route --cluster a@la/,fa/ --cluster b@lb/,fb/   # named shards
    SHEEP_ROUTE_CLUSTERS="la/,fa/;lb/,fb/" bin/route -d rdir/

Options:
  --cluster SPEC   one cluster as [name@]peer,peer (repeatable; peers
                   are host:port, a serve state dir, or an addr file —
                   serve/cluster.py grammar).  Default: the env.
  -d DIR           state dir: router.addr is published there (like
                   serve.addr) for scripts that need the bound port
  -p PORT          listen port (default 0 = ephemeral, printed)
  -H HOST          bind host (default 127.0.0.1)
  --vnodes N       ring points per cluster (default 64; also
                   SHEEP_ROUTE_VNODES)

Env: SHEEP_ROUTE_CLUSTERS (";"-separated clusters of ","-separated
peers), SHEEP_ROUTE_VNODES.  SHEEP_REBALANCE=1 additionally starts the
self-rebalancer (serve/rebalance.py): the router watches its own fleet
scrape and live-migrates the busiest tenant off a sustained-hot
cluster — hysteresis, min-qps, one-migration-at-a-time, and a cooldown
keep it from flapping (SHEEP_REBALANCE_* knobs).

Exit codes: 0 clean shutdown, 1 startup failure, 2 usage error.
"""

from __future__ import annotations

import getopt
import os
import signal
import sys

USAGE = ("USAGE: route [--cluster [name@]peer,peer ...] [-d dir]"
         " [-p port] [-H host] [--vnodes n]")


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    try:
        opts, args = getopt.gnu_getopt(argv, "d:p:H:",
                                       ["cluster=", "vnodes="])
    except getopt.GetoptError as exc:
        print(f"Unknown option character '{(exc.opt or '?')[:1]}'.")
        return 2

    from ..serve.router import CLUSTERS_ENV, VNODES_ENV, Router, \
        parse_clusters

    state_dir = None
    port = 0
    host = "127.0.0.1"
    cluster_args: list[str] = []
    vnodes = int(os.environ.get(VNODES_ENV, "64") or "64")
    for o, a in opts:
        if o == "-d":
            state_dir = a
        elif o == "-p":
            port = int(a)
        elif o == "-H":
            host = a
        elif o == "--cluster":
            cluster_args.append(a.strip())
        elif o == "--vnodes":
            vnodes = int(a)
    if args:
        print(USAGE)
        return 2

    spec = ";".join(cluster_args) if cluster_args \
        else os.environ.get(CLUSTERS_ENV, "")
    try:
        clusters = parse_clusters(spec)
    except ValueError as exc:
        print(f"route: {exc}", file=sys.stderr)
        return 2

    try:
        router = Router(clusters, host=host, port=port,
                        state_dir=state_dir, vnodes=vnodes).start()
    except OSError as exc:
        print(f"route: {exc}", file=sys.stderr)
        return 1
    h, p = router.address
    print(f"route: listening on {h}:{p}", flush=True)
    print(f"route: ready clusters={len(clusters)} "
          f"({', '.join(sorted(clusters))})", flush=True)

    from ..serve import rebalance
    if rebalance.enabled():
        router.rebalancer = rebalance.Rebalancer(router).start()
        print(f"route: rebalancer on (interval "
              f"{router.rebalancer.interval_s:g}s, hysteresis "
              f"{router.rebalancer.hysteresis:g}x, cooldown "
              f"{router.rebalancer.cooldown_s:g}s)", flush=True)

    def _term(signum, frame):
        if router.rebalancer is not None:
            router.rebalancer.stop()
        router.shutdown()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    try:
        router.run_forever()
    finally:
        router.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
