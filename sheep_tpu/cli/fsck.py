"""sheep fsck: verify artifacts (or whole trial directories) and exit
nonzero on ANY corruption.

No reference counterpart — the reference trusts its bytes; this tool is
the operational face of the integrity layer (ISSUE 2).  The shell
pipeline runs it on every worker tree before a merge tournament
(scripts/horizontal-dist.sh), and operators run it by hand on anything a
flaky disk or interrupted copy may have touched:

    bin/fsck trial-dir/                      # every artifact underneath
    bin/fsck graph.dat out.tre ckpt/sheep-ckpt.npz
    bin/fsck -m repair damaged.net           # report what repair would keep

Exit codes: 0 all clean, 1 corruption found, 2 usage error.
"""

from __future__ import annotations

import getopt
import sys

from ..integrity.fsck import fsck_paths
from ..integrity.sidecar import POLICIES

USAGE = "USAGE: fsck [-q] [-m strict|repair|trust] path [path ...]"


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    try:
        opts, args = getopt.gnu_getopt(argv, "qm:v")
    except getopt.GetoptError as exc:
        print(f"Unknown option character '{(exc.opt or '?')[:1]}'.")
        return 2

    quiet = False
    mode = None
    for o, a in opts:
        if o == "-q":
            quiet = True
        elif o == "-m":
            if a not in POLICIES:
                print(f"fsck: -m {a!r} must be one of {'/'.join(POLICIES)}")
                return 2
            mode = a
        elif o == "-v":
            quiet = False

    if not args:
        print(USAGE)
        return 2

    import warnings
    with warnings.catch_warnings():
        # repair-mode salvage warnings become part of the report lines
        warnings.simplefilter("ignore")
        results, failures = fsck_paths(args, mode)
    for path, ok, detail in results:
        if ok and not quiet:
            print(f"OK   {path}: {detail}")
        elif not ok:
            print(f"FAIL {path}: {detail}")
    checked = len(results)
    print(f"fsck: {checked} artifact(s) checked, {len(failures)} bad")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
