"""sheep fsck: verify artifacts (or whole trial directories) and exit
nonzero on ANY corruption.

No reference counterpart — the reference trusts its bytes; this tool is
the operational face of the integrity layer (ISSUE 2).  The shell
pipeline runs it on every worker tree before a merge tournament
(scripts/horizontal-dist.sh), and operators run it by hand on anything a
flaky disk or interrupted copy may have touched:

    bin/fsck trial-dir/                      # every artifact underneath
    bin/fsck graph.dat out.tre ckpt/sheep-ckpt.npz
    bin/fsck -m repair damaged.net           # report what repair would keep
    bin/fsck -R copied.tre                   # reseal a lost/wrong sidecar

``-R`` / ``--repair-sidecar`` reseals the ``.sum`` sidecar of every
artifact that structurally verifies but whose sidecar is lost or wrong
(integrity.fsck.repair_sidecar) — the recovery for a foreign copy or the
crash window between the artifact and sidecar renames.  Artifacts that
fail their structural checks are still reported FAIL, never resealed.

Exit codes: 0 all clean (or resealed), 1 corruption found, 2 usage error.
"""

from __future__ import annotations

import getopt
import sys

from ..integrity.errors import IntegrityError
from ..integrity.fsck import (collect_artifacts, fsck_file, fsck_paths,
                              repair_sidecar)
from ..integrity.sidecar import POLICIES, read_sidecar

USAGE = ("USAGE: fsck [-q] [-m strict|repair|trust] [-R|--repair-sidecar] "
         "path [path ...]")


def _repair_run(args: list[str], quiet: bool) -> int:
    """The --repair-sidecar pass: verify strictly; on any failure (or a
    clean artifact with no sidecar to vouch for it) attempt a structural
    reseal.  Only artifacts that refuse to parse stay FAIL."""
    resealed = failures = checked = 0
    for root in args:
        targets = collect_artifacts(root)
        if not targets:
            print(f"FAIL {root}: no artifacts found")
            failures += 1
            continue
        for path in targets:
            checked += 1
            try:
                detail = fsck_file(path, "strict")
                missing = read_sidecar(path) is None
            except (IntegrityError, OSError):
                detail, missing = None, True
            if detail is not None and not missing:
                if not quiet:
                    print(f"OK   {path}: {detail}")
                continue
            try:
                summary = repair_sidecar(path)
                resealed += 1
                print(f"SEAL {path}: {summary} (sidecar resealed)")
            except (IntegrityError, OSError) as exc:
                failures += 1
                print(f"FAIL {path}: {exc}")
    print(f"fsck: {checked} artifact(s) checked, {resealed} resealed, "
          f"{failures} bad")
    return 1 if failures else 0


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    try:
        opts, args = getopt.gnu_getopt(argv, "qm:vR", ["repair-sidecar"])
    except getopt.GetoptError as exc:
        print(f"Unknown option character '{(exc.opt or '?')[:1]}'.")
        return 2

    quiet = False
    mode = None
    reseal = False
    for o, a in opts:
        if o == "-q":
            quiet = True
        elif o == "-m":
            if a not in POLICIES:
                print(f"fsck: -m {a!r} must be one of {'/'.join(POLICIES)}")
                return 2
            mode = a
        elif o == "-v":
            quiet = False
        elif o in ("-R", "--repair-sidecar"):
            reseal = True

    if not args:
        print(USAGE)
        return 2

    import warnings
    with warnings.catch_warnings():
        # repair-mode salvage warnings become part of the report lines
        warnings.simplefilter("ignore")
        if reseal:
            return _repair_run(args, quiet)
        results, failures = fsck_paths(args, mode)
    for path, ok, detail in results:
        if ok and not quiet:
            print(f"OK   {path}: {detail}")
        elif not ok:
            print(f"FAIL {path}: {detail}")
    checked = len(results)
    print(f"fsck: {checked} artifact(s) checked, {len(failures)} bad")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
