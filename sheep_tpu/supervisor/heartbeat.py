"""Worker liveness via heartbeat files + deadlines.

The supervisor and its workers share nothing but a filesystem (the same
contract the phase scripts already poll through, scripts/lib.sh), so
liveness is a file: every worker attempt owns ``<tmp-output>.hb`` and
touches it every ``interval_s`` while it is making progress.  The beat is
the file's **mtime** — which is what makes the protocol trivially
implementable from any worker shape: a Python CLI starts a
:class:`HeartbeatWriter` daemon thread (cli/common.maybe_start_heartbeat,
env ``SHEEP_HEARTBEAT_FILE``), a shell worker runs a background ``touch``
loop (scripts/lib.sh ``sheep_heartbeat_start``).  The content (pid +
wall-clock) is diagnostics only, never parsed for liveness.

The supervisor's side is :func:`last_beat_s`: "when did this attempt last
prove it was alive?" — the heartbeat mtime when one exists, else the
fallback the caller provides (the attempt's launch time; a worker that
never manages its first beat must still be declared dead by deadline, not
trusted forever).  A worker whose beat goes stale past the deadline is
treated as DEAD no matter what its process state says: a hung dispatch, a
livelocked poll loop, and a SIGKILLed process all look the same from the
filesystem, and the recovery (re-dispatch the leg) is the same too.
"""

from __future__ import annotations

import os
import threading
import time

HEARTBEAT_SUFFIX = ".hb"

#: env var a worker checks to know where to beat (set per attempt by the
#: supervisor's subprocess runner; see cli/common.maybe_start_heartbeat)
HEARTBEAT_FILE_ENV = "SHEEP_HEARTBEAT_FILE"
HEARTBEAT_INTERVAL_ENV = "SHEEP_HEARTBEAT_S"

DEFAULT_INTERVAL_S = 1.0


def beat(path: str) -> None:
    """One heartbeat: (re)write ``path`` and bump its mtime.  Plain
    truncate+write, not atomic_write — the mtime is the signal and a torn
    diagnostic payload is harmless, while a tempfile dance would double
    the syscall cost of the hottest liveness operation."""
    with open(path, "w") as f:
        f.write(f"{os.getpid()} {time.time():.3f}\n")


def last_beat_s(path: str, fallback: float) -> float:
    """Wall-clock time of the last beat at ``path``; ``fallback`` (the
    attempt's launch time) when no beat has landed yet."""
    try:
        return os.path.getmtime(path)
    except OSError:
        return fallback


def is_stale(path: str, launched_at: float, deadline_s: float,
             now: float | None = None) -> bool:
    """True when the worker behind ``path`` has not proven liveness within
    ``deadline_s`` — counting from its last beat, or from launch if it
    never beat at all."""
    now = time.time() if now is None else now
    return now - last_beat_s(path, launched_at) > deadline_s


class HeartbeatWriter:
    """Daemon thread beating ``path`` every ``interval_s`` until stopped.

    Used by in-process workers (the supervisor's inline runner) and by the
    CLI mains when the supervisor launched them with
    ``SHEEP_HEARTBEAT_FILE`` in the environment.  Note what this can and
    cannot prove: the thread beats as long as the *process* is scheduled,
    so a worker hung inside one blocking call still beats — that failure
    shape is covered by the supervisor's speculation path (straggler
    re-execution), while the heartbeat deadline covers dead/frozen/
    SIGKILLed processes.
    """

    def __init__(self, path: str, interval_s: float = DEFAULT_INTERVAL_S):
        self.path = path
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> "HeartbeatWriter":
        beat(self.path)  # first beat lands before any work does
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"heartbeat:{self.path}")
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                beat(self.path)
            except OSError:
                return  # state dir removed under us: the run is over

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.interval_s)

    def __enter__(self) -> "HeartbeatWriter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


def maybe_start_from_env() -> HeartbeatWriter | None:
    """Start beating the file named by ``SHEEP_HEARTBEAT_FILE`` (set by the
    supervisor's subprocess runner), if any.  Returns the writer (the CLI
    keeps it alive for the process lifetime) or None."""
    path = os.environ.get(HEARTBEAT_FILE_ENV)
    if not path:
        return None
    interval = float(os.environ.get(HEARTBEAT_INTERVAL_ENV, "")
                     or DEFAULT_INTERVAL_S)
    try:
        return HeartbeatWriter(path, interval).start()
    except OSError:
        return None  # an unwritable heartbeat must not kill the worker
