"""Deterministic fault injection for the tournament supervisor.

``SHEEP_FAULT_PLAN`` names exactly which legs of the tournament get hurt
and how — the orchestration-layer sibling of the chunk runtime's
``SHEEP_FAULT_INJECT`` (runtime/faults.py).  Grammar::

    SHEEP_FAULT_PLAN = entry[,entry...]
    entry            = kind @ round : leg
    kind             = kill | corrupt | hang | stop
    round            = integer (0 = map, 1.. = merge rounds) | "sort"
    leg              = slot index within the round

e.g. ``SHEEP_FAULT_PLAN=kill@0:2,corrupt@1:0,hang@2:0``.  Each entry fires
exactly ONCE, on the first dispatch of the named leg, so the supervisor's
recovery (re-dispatch, relaunch, speculation) then runs against a healthy
worker — which is what lets the kill/corrupt/hang-at-every-round property
test assert bit-identical output (tests/test_supervisor.py).

The kinds model the four distinct failure shapes the supervisor must
survive, each driving a DIFFERENT recovery path:

  kill     the worker dies mid-write: a torn, sidecar-less partial lands
           at the attempt's temp name and the attempt reports failure.
           Recovery: retry-with-backoff re-dispatch.
  corrupt  the worker "succeeds" but its artifact is damaged after the
           write (bit rot, torn copy): bytes flipped under an unchanged
           sidecar.  Recovery: the supervisor's publish-time fsck rejects
           the artifact and re-dispatches the leg.
  hang     the worker freezes: one heartbeat at launch, then silence,
           never completing.  Recovery: the heartbeat deadline declares it
           dead and a fresh attempt is dispatched.
  stop     the SUPERVISOR dies right after this leg publishes (raises
           :class:`SupervisorKilled`, caught by nobody).  Recovery: a new
           supervisor resumes off the manifest, fscks the surviving
           artifacts, and re-dispatches only the dirty/missing legs.

Faults are applied at the supervisor's dispatch boundary, not inside the
worker, so the same plan is byte-for-byte deterministic under every
runner (inline threads and real subprocesses alike).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

FAULT_PLAN_ENV = "SHEEP_FAULT_PLAN"

KINDS = ("kill", "corrupt", "hang", "stop")

#: the round number the grammar word "sort" maps to (manifest.Leg.round)
SORT_ROUND = -1


class SupervisorKilled(RuntimeError):
    """Simulated supervisor death (kind="stop").  Never caught inside the
    supervisor: tests and the chaos smoke catch it at top level and start
    a NEW supervisor over the same state dir, exactly like a restarted
    process."""


@dataclass
class ChaosFault:
    kind: str
    round: int
    leg: int


@dataclass
class ChaosPlan:
    """A parsed fault plan; entries are popped as they fire."""

    faults: list[ChaosFault] = field(default_factory=list)

    def _take(self, kinds: tuple[str, ...], round: int,
              leg: int) -> str | None:
        for i, f in enumerate(self.faults):
            if f.kind in kinds and f.round == round and f.leg == leg:
                del self.faults[i]
                return f.kind
        return None

    def take_dispatch(self, round: int, leg: int) -> str | None:
        """The kill/corrupt/hang fault (if any) armed for this leg's next
        dispatch; popped so recovery dispatches run clean."""
        return self._take(("kill", "corrupt", "hang"), round, leg)

    def take_stop(self, round: int, leg: int) -> bool:
        """True when the supervisor is scheduled to die after this leg
        publishes."""
        return self._take(("stop",), round, leg) is not None


def parse_fault_plan(spec: str) -> ChaosPlan:
    """Parse the ``SHEEP_FAULT_PLAN`` grammar (module docstring)."""
    faults = []
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        try:
            kind, at = entry.split("@", 1)
            rnd_s, leg_s = at.split(":", 1)
        except ValueError:
            raise ValueError(
                f"SHEEP_FAULT_PLAN entry {entry!r}: want kind@round:leg "
                f"(e.g. kill@0:2)")
        kind = kind.strip()
        if kind not in KINDS:
            raise ValueError(
                f"SHEEP_FAULT_PLAN entry {entry!r}: kind {kind!r} must be "
                f"one of {'/'.join(KINDS)}")
        rnd_s = rnd_s.strip()
        rnd = SORT_ROUND if rnd_s == "sort" else int(rnd_s)
        faults.append(ChaosFault(kind=kind, round=rnd, leg=int(leg_s)))
    return ChaosPlan(faults=faults)


def plan_from_env() -> ChaosPlan | None:
    """The env-configured plan, or None when chaos is off (the default)."""
    spec = os.environ.get(FAULT_PLAN_ENV, "")
    if not spec:
        return None
    return parse_fault_plan(spec)
