"""The tournament supervisor: heartbeat-supervised, fsck-gated, resumable.

This is the orchestration layer the ROADMAP flags as the remaining
single point of failure in the distributed file path: the bash tournament
(scripts/horizontal-dist.sh) is fire-and-forget — one dead, hung, or
corrupted worker forces a full re-run.  The supervisor owns the
sort -> map -> merge-tournament lifecycle end to end and survives any
single-point failure:

  worker dies       the attempt's exit status (or vanished process) fails
                    the leg; it is re-dispatched with the PR-1
                    retry/backoff policy (runtime/retry.RetryPolicy).
  worker hangs      every attempt owns a heartbeat file
                    (supervisor/heartbeat.py); a beat stale past the
                    deadline declares the attempt dead and the leg is
                    re-dispatched.  A straggler that still beats can be
                    speculatively re-executed (``speculate_after_s``):
                    first finisher publishes, the loser's artifact is
                    discarded (sig-checked, never merged).
  artifact corrupt  every attempt writes to a private temp name; the
                    supervisor fscks the temp artifact (sidecar checksum
                    + structural checks, integrity/fsck.py) and checks
                    its input signature against the manifest BEFORE the
                    atomic publish — a bad artifact is a failed attempt,
                    never a tournament input.
  supervisor dies   all durable state lives in the checksummed manifest
                    (supervisor/manifest.py), rewritten atomically after
                    every dispatch and publish.  A new supervisor resumes
                    by fsck-ing the artifacts the manifest claims done
                    and re-dispatching ONLY the dirty/missing legs — a
                    clean ``NNr0.tre`` is never re-mapped.

Publish protocol (the same ordering as scripts/lib.sh sheep_mv_artifact):
sidecar first, artifact second, both via atomic rename — a consumer that
sees an artifact under its final name also sees its matching checksum.

Everything above is property-tested by deterministic chaos
(supervisor/chaos.py, ``SHEEP_FAULT_PLAN``): a kill, corrupt, or hang
injected at EVERY tournament round must yield a final tree bit-identical
to the fault-free run, re-dispatching only the faulted leg.
"""

from __future__ import annotations

import os
import re
import shutil
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Callable

from ..integrity.errors import IntegrityError
from ..integrity.sidecar import read_sidecar, resolve_policy
from ..resources import ResourceGovernor, gc_orphan_temps, retention_gc
from ..runtime.retry import RetryPolicy
from .chaos import ChaosPlan, SupervisorKilled, plan_from_env
from .heartbeat import (HEARTBEAT_FILE_ENV, HEARTBEAT_INTERVAL_ENV,
                        HeartbeatWriter, beat, is_stale)
from .manifest import (DONE, PENDING, Leg, Manifest, load_manifest,
                       manifest_path, plan_tournament, save_manifest)


class SupervisionFailed(RuntimeError):
    """The tournament cannot make progress (a leg exhausted its dispatch
    budget, or an input can never appear).  The manifest stays on disk —
    the condition may be transient (full disk, sick node) and a later
    ``sheep supervise`` of the same state dir resumes where this one
    stopped."""


@dataclass
class SupervisorConfig:
    """One supervised tournament's knobs (env: SHEEP_WORKERS / REDUCTION /
    SHEEP_DEADLINE_S / SHEEP_HEARTBEAT_S / SHEEP_SPECULATE_S /
    SHEEP_MAX_RETRIES / SHEEP_BACKOFF_BASE / SHEEP_INTEGRITY /
    SHEEP_FAULT_PLAN)."""

    workers: int = 2
    reduction: int = 2
    #: a worker whose heartbeat is older than this is dead
    deadline_s: float = 30.0
    #: deterministic staleness (ISSUE 15 deflake): when > 0, a silent
    #: worker is declared dead after this many CONSECUTIVE supervisor
    #: polls observed its heartbeat mtime unchanged, instead of by the
    #: wall-clock deadline.  Wall-clock staleness races the scheduler: a
    #: loaded 1-core host can stall a healthy worker's beat past a short
    #: deadline and double-dispatch it (the distext chaos sweep's
    #: 1-in-3 flake).  Poll counting is robust to exactly that — when
    #: the whole process stalls, the supervisor's polls stall with the
    #: beats, so no poll observes a silent interval that never happened.
    stale_after_polls: int = 0
    #: how often workers beat (exported to subprocess workers)
    heartbeat_s: float = 1.0
    #: age at which a still-beating attempt gets a speculative twin
    #: (None = speculation off)
    speculate_after_s: float | None = None
    #: remote build workers (ISSUE 16): (host, port) list the distext
    #: job may ship hist/distmap legs to (env SHEEP_WORKER_ADDRS);
    #: empty = single-host dispatch only
    worker_addrs: list = field(default_factory=list)
    #: wire heartbeat interval for remote legs (BEAT frames;
    #: env SHEEP_WORKER_BEAT_S)
    worker_beat_s: float = 1.0
    #: wire-beat SILENCE age at which a remote leg gets a speculative
    #: twin on another worker (env SHEEP_WORKER_SPECULATE_S; None = only
    #: the generic speculate_after_s straggler rule applies).  Keyed on
    #: the last beat, not the launch: a worker that streams BEATs for an
    #: hour then goes mute is the failure shape this knob names.
    worker_speculate_s: float | None = None
    max_retries: int = 3
    backoff_base_s: float = 0.05
    backoff_cap_s: float = 2.0
    poll_s: float = 0.05
    #: max concurrent attempts (0 = unthrottled; the bash driver's CORES)
    cores: int = 0
    #: CPU cores each leg may use (env SHEEP_LEG_CORES; 0 = unmanaged).
    #: Caps concurrency at host_cores // leg_cores — so a speculative
    #: twin can never oversubscribe the host it shares with the straggler
    #: it is racing — and the subprocess runner pins each attempt to its
    #: own rotating core slice + thread-count env caps.
    leg_cores: int = 0
    #: disk/memory budgets (SHEEP_DISK_BUDGET / SHEEP_MEM_BUDGET); None =
    #: from env.  Under a disk budget the supervisor GCs retired
    #: intermediates (outputs no pending leg consumes — everything it
    #: deletes is re-creatable by a resume) when the state dir trips the
    #: cap, and sweeps write debris on every failure.
    governor: ResourceGovernor | None = None
    integrity: str | None = None
    #: print the reference phase grammar ("Mapped in N seconds.") that
    #: data/make-parallel.sh greps
    grammar: bool = True
    chaos: ChaosPlan | None = None
    # injectable for tests
    sleep: Callable[[float], None] = field(default=time.sleep)
    #: observable trace: ("dispatch", key, n), ("publish", key),
    #: ("leg-failed", key, reason), ("stale", key), ("speculate", key),
    #: ("discard", key, why), ("resume", clean, dirty), ("complete",)
    events: list = field(default_factory=list)

    @classmethod
    def from_env(cls, **overrides) -> "SupervisorConfig":
        env = os.environ
        kw: dict = dict(
            workers=int(env.get("SHEEP_WORKERS", "2") or 2),
            reduction=int(env.get("REDUCTION", "2") or 2),
            deadline_s=float(env.get("SHEEP_DEADLINE_S", "30")),
            stale_after_polls=int(env.get("SHEEP_STALE_POLLS", "0") or 0),
            heartbeat_s=float(env.get("SHEEP_HEARTBEAT_S", "1")),
            max_retries=int(env.get("SHEEP_MAX_RETRIES", "3")),
            backoff_base_s=float(env.get("SHEEP_BACKOFF_BASE", "0.05")),
            leg_cores=int(env.get("SHEEP_LEG_CORES", "0") or 0),
            governor=ResourceGovernor.from_env(),
            integrity=env.get("SHEEP_INTEGRITY") or None,
            chaos=plan_from_env(),
        )
        if env.get("SHEEP_SPECULATE_S"):
            kw["speculate_after_s"] = float(env["SHEEP_SPECULATE_S"])
        if env.get("SHEEP_WORKER_ADDRS"):
            from ..serve.worker import parse_worker_addrs
            kw["worker_addrs"] = parse_worker_addrs(
                env["SHEEP_WORKER_ADDRS"])
        kw["worker_beat_s"] = float(env.get("SHEEP_WORKER_BEAT_S", "1")
                                    or 1)
        if env.get("SHEEP_WORKER_SPECULATE_S"):
            kw["worker_speculate_s"] = \
                float(env["SHEEP_WORKER_SPECULATE_S"])
        kw.update(overrides)
        return cls(**kw)

    @property
    def max_dispatches(self) -> int:
        return self.max_retries + 1

    def policy(self) -> RetryPolicy:
        return RetryPolicy(max_retries=self.max_retries,
                           backoff_base_s=self.backoff_base_s,
                           backoff_cap_s=self.backoff_cap_s)


# ---------------------------------------------------------------------------
# Attempt handles + runners.  A runner turns a leg's argv into a running
# attempt; the supervisor only ever sees the handle (poll / cancel), so
# the inline (thread) and subprocess runners — and the chaos fakes — are
# interchangeable and the recovery logic cannot fork between them.
# ---------------------------------------------------------------------------


class _ThreadHandle:
    """An attempt running on a thread (inline runner + internal copies)."""

    def __init__(self, target: Callable[[], int]):
        self._rc: int | None = None
        self._done = threading.Event()

        def run():
            try:
                self._rc = int(target() or 0)
            except BaseException:  # the supervisor retries; never crashes
                self._rc = 1
            finally:
                self._done.set()

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()

    def poll(self) -> int | None:
        return self._rc if self._done.is_set() else None

    def cancel(self) -> None:
        # threads cannot be interrupted; the attempt is abandoned (daemon)
        # and its temp output ignored — exactly how a real orphan behaves
        pass


class _DeadHandle:
    """Chaos "kill": the worker died immediately (rc 137)."""

    def poll(self) -> int | None:
        return 137

    def cancel(self) -> None:
        pass


class _HangHandle:
    """Chaos "hang": the worker never completes and never beats again."""

    def poll(self) -> int | None:
        return None

    def cancel(self) -> None:
        pass


class _SubprocessHandle:
    def __init__(self, proc, log_f):
        self._proc = proc
        self._log_f = log_f

    def poll(self) -> int | None:
        rc = self._proc.poll()
        if rc is not None and self._log_f is not None:
            self._log_f.close()
            self._log_f = None
        return rc

    def cancel(self) -> None:
        if self._proc.poll() is None:
            self._proc.kill()
            try:
                self._proc.wait(timeout=10)
            except Exception:
                pass
        if self._log_f is not None:
            self._log_f.close()
            self._log_f = None


class InlineRunner:
    """Run legs in-process on threads — the fast path for tests and the
    chaos smoke (no interpreter start-up per leg).  Workers heartbeat via
    a HeartbeatWriter wrapped around the CLI main."""

    def __init__(self, interval_s: float = 0.1):
        self.interval_s = interval_s

    def start(self, argv: list[str], hb_path: str, log_path: str):
        import importlib

        def target() -> int:
            hb = HeartbeatWriter(hb_path, self.interval_s).start()
            try:
                mod = importlib.import_module(f"sheep_tpu.cli.{argv[0]}")
                return int(mod.main(argv[1:]) or 0)
            except SystemExit as exc:
                return int(exc.code or 0)
            except BaseException as exc:
                try:
                    with open(log_path, "a") as f:
                        f.write(f"{type(exc).__name__}: {exc}\n")
                except OSError:
                    pass
                return 1
            finally:
                hb.stop()

        return _ThreadHandle(target)


class SubprocessRunner:
    """Run legs as real CLI subprocesses — the production path.  Each
    child gets SHEEP_HEARTBEAT_FILE pointing at its attempt's heartbeat
    (cli/common.maybe_start_heartbeat) and logs to the state dir.

    ``leg_cores`` (env SHEEP_LEG_CORES): pin each child to its own
    rotating ``leg_cores``-wide slice of the host's affinity mask and cap
    its math-library thread counts to match — the per-leg cores budget
    that keeps a speculative twin from oversubscribing the host it
    shares with the straggler it is racing (the supervisor separately
    caps CONCURRENCY at host_cores // leg_cores)."""

    _THREAD_ENVS = ("OMP_NUM_THREADS", "OPENBLAS_NUM_THREADS",
                    "MKL_NUM_THREADS", "NUMEXPR_NUM_THREADS")

    def __init__(self, interval_s: float = 1.0, env: dict | None = None,
                 leg_cores: int = 0):
        self.interval_s = interval_s
        self.env = env
        self.leg_cores = leg_cores
        self._slot = 0

    def _pin(self, env: dict):
        """(preexec_fn, env) for the next attempt's core slice; (None,
        env) when unmanaged or the platform lacks affinity control."""
        k = self.leg_cores
        if not k or not hasattr(os, "sched_setaffinity"):
            return None, env
        try:
            host = sorted(os.sched_getaffinity(0))
        except OSError:
            host = list(range(os.cpu_count() or 1))
        slots = max(1, len(host) // k)
        at = (self._slot % slots) * k
        self._slot += 1
        cpus = set(host[at: at + k]) or set(host)
        for var in self._THREAD_ENVS:
            env[var] = str(k)

        def preexec():  # runs in the child, pre-exec
            try:
                os.sched_setaffinity(0, cpus)
            except OSError:
                pass
        return preexec, env

    def start(self, argv: list[str], hb_path: str, log_path: str):
        import subprocess

        import sheep_tpu
        repo = os.path.dirname(os.path.dirname(
            os.path.abspath(sheep_tpu.__file__)))
        env = dict(self.env if self.env is not None else os.environ)
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        env[HEARTBEAT_FILE_ENV] = hb_path
        env[HEARTBEAT_INTERVAL_ENV] = str(self.interval_s)
        preexec, env = self._pin(env)
        log_f = open(log_path, "ab")
        proc = subprocess.Popen(
            [sys.executable, "-m", f"sheep_tpu.cli.{argv[0]}"] + argv[1:],
            stdout=log_f, stderr=subprocess.STDOUT, env=env,
            preexec_fn=preexec)
        return _SubprocessHandle(proc, log_f)


@dataclass
class _Attempt:
    leg: Leg
    number: int          # this leg's dispatch ordinal (1-based)
    tmp: str
    hb: str
    handle: object
    started: float
    corrupt_on_success: bool = False
    cancelled: bool = False
    # poll-count staleness state (SupervisorConfig.stale_after_polls):
    # the last observed beat mtime and how many consecutive polls saw it
    # unchanged
    hb_mtime: float | None = None
    quiet_polls: int = 0


# ---------------------------------------------------------------------------
# Validation + publish
# ---------------------------------------------------------------------------


def _artifact_checker(final_path: str):
    from ..integrity.fsck import _CHECKERS
    for suffix, checker in _CHECKERS.items():
        if final_path.endswith(suffix):
            return checker
    raise SupervisionFailed(f"{final_path}: not a checkable artifact class")


def _validate_artifact(tmp: str, final_path: str, mode: str) -> str | None:
    """fsck the temp artifact as its final class would be checked; returns
    the sidecar's input signature (if any).  Raises IntegrityError."""
    _artifact_checker(final_path)(tmp, mode)
    sc = read_sidecar(tmp) if mode != "trust" else None
    return sc.get("sig") if sc else None


def _discard(*paths: str) -> None:
    for p in paths:
        try:
            os.unlink(p)
        except OSError:
            pass


#: attempt-private files: <output>.aN plus its .sum / .hb companions
_ATTEMPT_DEBRIS_RE = re.compile(r"\.a\d+(\.sum|\.hb)?$")


def sweep_attempt_debris(state_dir: str) -> list[str]:
    """Remove the attempt temps a DEAD supervisor stranded (ISSUE 5).
    Only safe when no attempts are in flight — run_supervised calls it
    before constructing the supervisor.  Attempt files are by protocol
    unpublished (the publish is the rename away from the ``.aN`` name),
    so a resume never reads one; left behind they only eat the budget."""
    removed = []
    try:
        names = os.listdir(state_dir)
    except OSError:
        return removed
    for name in names:
        if _ATTEMPT_DEBRIS_RE.search(name):
            path = os.path.join(state_dir, name)
            try:
                os.unlink(path)
                removed.append(path)
            except OSError:
                pass
    return removed


def _corrupt_bytes(path: str) -> None:
    """Chaos "corrupt": flip one payload byte under the unchanged sidecar
    (bit rot after a successful write — exactly what fsck exists for)."""
    with open(path, "r+b") as f:
        f.seek(5 if os.path.getsize(path) > 5 else 0)
        b = f.read(1)
        f.seek(-1, 1)
        f.write(bytes([b[0] ^ 0xFF]))


# ---------------------------------------------------------------------------
# The supervisor proper
# ---------------------------------------------------------------------------


class TournamentSupervisor:
    def __init__(self, manifest: Manifest, state_dir: str,
                 config: SupervisorConfig, runner=None):
        self.manifest = manifest
        self.state_dir = state_dir
        self.config = config
        self.runner = runner if runner is not None \
            else SubprocessRunner(interval_s=config.heartbeat_s,
                                  leg_cores=config.leg_cores)
        self.policy = config.policy()
        self.governor = config.governor if config.governor is not None \
            else ResourceGovernor.from_env()
        self.mode = resolve_policy(config.integrity)
        self.events = config.events
        self.log_dir = os.path.join(state_dir, "logs")
        os.makedirs(self.log_dir, exist_ok=True)
        self._running: dict[str, list[_Attempt]] = {}
        self._backoff_until: dict[str, float] = {}
        #: artifact path -> the leg that produces it; a consumer is ready
        #: only when its producers are DONE, not merely when bytes exist
        #: under the input name (a resume may have marked the producer
        #: dirty while its corrupt artifact still sits on disk)
        self._producer: dict[str, Leg] = {
            leg.output: leg for leg in manifest.legs}
        #: dispatches this supervisor LIFE launched per leg — the retry
        #: budget is per-life so a many-times-resumed run is never
        #: permanently bricked by its history
        self._life: dict[str, int] = {}

    # -- resource budgets --------------------------------------------------

    def _slots(self) -> int:
        """Max concurrent attempts: the explicit ``cores`` throttle
        AND the per-leg cores budget (host_cores // leg_cores) — the
        tighter wins; 0 = unthrottled."""
        slots = self.config.cores
        if self.config.leg_cores:
            try:
                avail = len(os.sched_getaffinity(0))
            except (AttributeError, OSError):
                avail = os.cpu_count() or 1
            by_budget = max(1, avail // self.config.leg_cores)
            slots = min(slots, by_budget) if slots else by_budget
        return slots

    def _inflight(self) -> int:
        return sum(len(a) for a in self._running.values())

    def _maybe_gc(self, force: bool = False) -> int:
        """Reclaim retired intermediates when the state dir trips the
        ``SHEEP_DISK_BUDGET`` cap (or on ``force``: an attempt just
        failed with what may be a full disk).  Keep-resumable: the
        manifest, the final tree, the sequence, every pending leg's
        inputs/output, and all in-flight attempt files are protected;
        everything deleted is re-creatable by a resume (reconcile marks
        the producers of a missing-but-needed artifact pending again)."""
        gov = self.governor
        if gov.disk_budget is None and not force:
            return 0
        deficit = gov.dir_budget_deficit(self.state_dir, 0)
        if deficit <= 0 and not force:
            return 0
        protect = {manifest_path(self.state_dir),
                   self.manifest.final_tree, self.manifest.seq_file}
        for leg in self.manifest.legs:
            if leg.state != DONE:
                protect.add(leg.output)
                protect.update(leg.inputs)
        for atts in self._running.values():
            for att in atts:
                protect.update((att.tmp, att.hb))
        freed, removed = retention_gc(self.state_dir, protect=protect,
                                      keep_last=0, need=max(0, deficit),
                                      live_bases=self._live_temp_bases())
        if removed:
            self.events.append(("gc", len(removed), freed))
        return freed

    # -- dispatch ----------------------------------------------------------

    def _leg_argv(self, leg: Leg) -> list[str]:
        m = self.manifest
        if leg.kind == "sort":
            return ["degree_sequence", m.graph, "@OUT@"]
        if leg.kind == "map":
            return ["graph2tree", m.graph,
                    "-l", f"{leg.index + 1}/{m.workers}",
                    "-s", m.seq_file, "-o", "@OUT@"]
        if leg.kind == "hist":
            # distext pass 1 (ISSUE 13): this shard's degree histogram
            a, b = m.shards[leg.index]
            return ["distext", "hist", m.graph, "-r", f"{a}:{b}",
                    "-o", "@OUT@"]
        if leg.kind == "distmap":
            # distext pass 2: the ext carry fold over this shard, under
            # the leg's own budget, checkpointing at block boundaries in
            # a per-leg dir (the slice is folded into the checkpoint
            # identity, so a re-dispatch resumes — and a foreign shard
            # map is refused); the leg self-reports perf + proc_status
            from ..ops.distext import leg_checkpoint_dir, leg_perf_path
            a, b = m.shards[leg.index]
            return ["distext", "map", m.graph, "-r", f"{a}:{b}",
                    "-s", m.seq_file, "-o", "@OUT@",
                    "--checkpoint-dir",
                    leg_checkpoint_dir(self.state_dir, leg.key),
                    "--resume",
                    "--perf-out", leg_perf_path(self.state_dir, leg.key)]
        if leg.kind == "merge":
            argv = ["merge_trees"] + list(leg.inputs) + ["-o", "@OUT@"]
            if m.sig:
                argv += ["--expect-sig", m.sig]
            return argv
        raise SupervisionFailed(f"{leg.key}: unknown leg kind {leg.kind!r}")

    def _start_copy(self, leg: Leg, tmp: str, hb_path: str):
        src = leg.inputs[0]

        def target() -> int:
            beat(hb_path)
            if os.path.exists(src + ".sum"):
                shutil.copyfile(src + ".sum", tmp + ".sum")
            shutil.copyfile(src, tmp)
            return 0

        return _ThreadHandle(target)

    def _start_histsum(self, leg: Leg, tmp: str, hb_path: str):
        """The distext Allreduce (ISSUE 13), serviced by the supervisor
        itself like a copy leg: sum the published per-range histograms
        (integer adds commute — the result is the whole-file histogram
        bit for bit), counting-sort it, and publish the shared sequence
        every pass-2 leg builds over.  A stale histogram from a foreign
        shard map is a failed attempt here (merge_histograms checks each
        input against the manifest's shard map), never a wrong sequence.
        """
        inputs = list(leg.inputs)
        shards = self.manifest.shards
        integrity = self.config.integrity

        def target() -> int:
            from ..obs import trace as obs
            beat(hb_path)
            with obs.span("distext.hist_merge", legs=len(inputs)):
                from ..core.sequence import degree_sequence_from_degrees
                from ..io.seqfile import write_sequence
                from ..ops.distext import merge_histograms, read_histogram
                hists = [read_histogram(p, integrity=integrity)
                         for p in inputs]
                deg = merge_histograms(hists, expect_shards=shards)
                write_sequence(degree_sequence_from_degrees(deg), tmp)
            return 0

        return _ThreadHandle(target)

    def _launch(self, leg: Leg, now: float, speculative: bool = False):
        leg.dispatches += 1
        self._life[leg.key] = self._life.get(leg.key, 0) + 1
        n = leg.dispatches
        tmp = f"{leg.output}.a{n}"
        hb = tmp + ".hb"
        log = os.path.join(self.log_dir, f"{leg.key}.a{n}.log")
        _discard(tmp, tmp + ".sum", hb)

        fault = None
        if self.config.chaos is not None and not speculative:
            fault = self.config.chaos.take_dispatch(leg.round, leg.index)
        if fault == "kill":
            # died mid-write: a torn, sidecar-less partial at the temp name
            with open(tmp, "wb") as f:
                f.write(b"\x00" * 3)
            handle = _DeadHandle()
        elif fault == "hang":
            beat(hb)  # one beat at launch, then silence forever
            handle = _HangHandle()
        elif leg.kind == "copy":
            handle = self._start_copy(leg, tmp, hb)
        elif leg.kind == "histsum":
            handle = self._start_histsum(leg, tmp, hb)
        else:
            argv = [a.replace("@OUT@", tmp) for a in self._leg_argv(leg)]
            handle = self.runner.start(argv, hb, log)
        att = _Attempt(leg=leg, number=n, tmp=tmp, hb=hb, handle=handle,
                       started=now, corrupt_on_success=(fault == "corrupt"))
        self._running.setdefault(leg.key, []).append(att)
        self.events.append(("dispatch", leg.key, n)
                           if not speculative else ("speculate", leg.key, n))
        from ..obs import trace as obs
        obs.event("supervise.dispatch", key=leg.key, kind=leg.kind,
                  round=leg.round, attempt=n, speculative=speculative)
        save_manifest(self.manifest, self.state_dir)

    # -- completion --------------------------------------------------------

    def _publish(self, att: _Attempt) -> None:
        leg = att.leg
        if os.path.exists(att.tmp + ".sum"):
            os.replace(att.tmp + ".sum", leg.output + ".sum")
        os.replace(att.tmp, leg.output)
        _discard(att.hb)
        leg.state = DONE
        self.events.append(("publish", leg.key))
        from ..obs import trace as obs
        obs.event("supervise.publish", key=leg.key, kind=leg.kind,
                  round=leg.round)
        save_manifest(self.manifest, self.state_dir)
        self._maybe_gc()
        # siblings (speculative twins) lost the race: cancel + discard
        for other in self._running.get(leg.key, []):
            if other is not att:
                other.cancelled = True
                other.handle.cancel()
                _discard(other.tmp, other.tmp + ".sum", other.hb)
                self.events.append(("discard", leg.key, "lost-race"))
        self._running.pop(leg.key, None)

    def _complete(self, att: _Attempt) -> None:
        leg = att.leg
        if leg.state == DONE:
            # a speculative loser finishing after the publish
            _discard(att.tmp, att.tmp + ".sum", att.hb)
            self.events.append(("discard", leg.key, "lost-race"))
            return
        if att.corrupt_on_success:
            _corrupt_bytes(att.tmp)
        try:
            sig = _validate_artifact(att.tmp, leg.output, self.mode)
        except (IntegrityError, OSError) as exc:
            self._failed(att, f"fsck: {exc}")
            return
        if leg.output.endswith(".tre") and sig:
            if self.manifest.sig is None:
                self.manifest.sig = sig
            elif sig != self.manifest.sig:
                # an artifact from a DIFFERENT build (stale file, foreign
                # speculation loser): never merged, always a failed attempt
                self._failed(att, f"sig {sig[:12]}... != manifest "
                                  f"{self.manifest.sig[:12]}...")
                return
        self._publish(att)

    def _live_temp_bases(self) -> set[str]:
        """Final basenames of every still-running attempt's output (and
        its sidecar), plus its side-channel files (the distext leg's
        ``--perf-out`` self-report lands in the state dir root too):
        their atomic-write dot-temps are live rename sources a mid-run
        sweep must not reclaim (resources/gc.py is_live_temp — the
        InlineRunner runs sibling legs in THIS process, so a sweep after
        one leg's fault races their writes; a reclaimed perf temp failed
        the healthy sibling's os.replace and double-dispatched it —
        the distext chaos sweep's 1-in-3 flake, ISSUE 15)."""
        out: set[str] = set()
        for atts in self._running.values():
            for a in atts:
                base = os.path.basename(a.tmp)
                out.add(base)
                out.add(base + ".sum")
                # the leg's perf self-report (ops/distext.leg_perf_path)
                out.add(f"{a.leg.key}.perf.json")
        return out

    def _failed(self, att: _Attempt, reason: str) -> None:
        leg = att.leg
        _discard(att.tmp, att.tmp + ".sum", att.hb)
        self.events.append(("leg-failed", leg.key, reason))
        # an attempt that died on a full disk leaves the condition in
        # place for its retry: sweep write debris, and reclaim retired
        # intermediates (all re-creatable) before dispatching again —
        # sparing the dot-temps sibling attempts are writing RIGHT NOW
        gc_orphan_temps(self.state_dir,
                        live_bases=self._live_temp_bases())
        if "ENOSPC" in reason or "No space" in reason:
            self._maybe_gc(force=True)
        self._running[leg.key] = [
            a for a in self._running.get(leg.key, []) if a is not att]
        if self._running[leg.key]:
            return  # a twin is still in flight; it may still win
        self._running.pop(leg.key, None)
        life = self._life.get(leg.key, 0)
        if life >= self.config.max_dispatches:
            raise SupervisionFailed(
                f"{leg.key}: {life} dispatch(es) failed this run "
                f"(last: {reason}) — budget {self.config.max_dispatches} "
                f"spent; state kept in {self.state_dir} for a later resume")
        self._backoff_until[leg.key] = \
            time.time() + self.policy.backoff(life - 1)

    # -- the loop ----------------------------------------------------------

    def _poll_attempts(self, now: float) -> None:
        for key in list(self._running):
            for att in list(self._running.get(key, [])):
                if att.cancelled:
                    continue
                rc = att.handle.poll()
                if rc is None:
                    if self._attempt_stale(att, now):
                        att.cancelled = True
                        att.handle.cancel()
                        self.events.append(("stale", key, att.number))
                        self._failed(att, "heartbeat deadline exceeded")
                    elif self._should_speculate(att, key, now):
                        self._launch(att.leg, now, speculative=True)
                elif rc == 0:
                    self._complete(att)
                else:
                    self._failed(att, f"exit status {rc}")
                if att.leg.state == DONE and self.config.chaos is not None \
                        and self.config.chaos.take_stop(att.leg.round,
                                                        att.leg.index):
                    self._die(att.leg)

    def _should_speculate(self, att: _Attempt, key: str,
                          now: float) -> bool:
        """Launch a speculative twin for this still-running attempt?
        Two triggers share the guards: the generic straggler rule
        (``speculate_after_s`` since launch) and, for REMOTE attempts,
        the silent-worker rule (``worker_speculate_s`` since the last
        wire beat — a worker that beats for an hour then goes mute gets
        its twin without waiting out the whole straggler age)."""
        if (len(self._running.get(key, [])) != 1
                or self._life.get(key, 0) >= self.config.max_dispatches
                # the cores budget binds speculation too: a twin that
                # would oversubscribe the host only slows the straggler
                # it is meant to beat
                or (self._slots()
                    and self._inflight() >= self._slots())):
            return False
        s = self.config.speculate_after_s
        if s is not None and now - att.started > s:
            return True
        ws = self.config.worker_speculate_s
        if ws is not None and getattr(att.handle, "remote", False):
            from .heartbeat import last_beat_s
            return now - last_beat_s(att.hb, att.started) > ws
        return False

    def _attempt_stale(self, att: _Attempt, now: float) -> bool:
        """Is this still-running attempt dead-by-silence?  Default: the
        wall-clock heartbeat deadline (is_stale).  With
        ``stale_after_polls`` set, staleness is counted in SUPERVISOR
        POLLS that observed the beat mtime unchanged — deterministic
        under whole-process stalls (config field doc), which is what the
        chaos sweeps need to assert exact dispatch counts."""
        polls = self.config.stale_after_polls
        if not polls:
            return is_stale(att.hb, att.started,
                            self.config.deadline_s, now)
        from .heartbeat import last_beat_s
        m = last_beat_s(att.hb, att.started)
        if att.hb_mtime is not None and m <= att.hb_mtime:
            att.quiet_polls += 1
        else:
            att.hb_mtime = m
            att.quiet_polls = 0
        return att.quiet_polls >= polls

    def _die(self, leg: Leg) -> None:
        """Chaos "stop": this supervisor is dead.  Real death would orphan
        the children; the simulation cancels them so tests do not leak."""
        for atts in self._running.values():
            for att in atts:
                att.handle.cancel()
        self.events.append(("supervisor-killed", leg.key))
        raise SupervisorKilled(
            f"injected supervisor death after {leg.key} published")

    def _launch_ready(self, now: float) -> int:
        launched = 0
        slots = self._slots()
        for leg in sorted(self.manifest.pending(),
                          key=lambda l: (l.round, l.index)):
            if leg.key in self._running:
                continue
            if self._backoff_until.get(leg.key, 0) > now:
                continue
            if not all(os.path.exists(p) for p in leg.inputs):
                continue
            if any(p in self._producer and self._producer[p].state != DONE
                   for p in leg.inputs):
                continue
            if slots and self._inflight() >= slots:
                break
            self._launch(leg, now)
            launched += 1
        return launched

    def run(self) -> Manifest:
        cfg = self.config
        t0 = time.time()
        phase_done = {-1: False, 0: False}
        while not self.manifest.done():
            now = time.time()
            launched = self._launch_ready(now)
            self._poll_attempts(now)
            if cfg.grammar:
                self._phase_grammar(phase_done, t0)
            if not self._running and not launched \
                    and not self.manifest.done():
                future = [t for t in self._backoff_until.values() if t > now]
                if not future and not self._launch_ready(time.time()):
                    missing = sorted({
                        p for leg in self.manifest.pending()
                        for p in leg.inputs if not os.path.exists(p)})
                    raise SupervisionFailed(
                        "tournament cannot make progress — missing "
                        "inputs with no producer: " + ", ".join(missing))
            cfg.sleep(cfg.poll_s)
        if cfg.grammar:
            self._phase_grammar(phase_done, t0)
            print(f"Reduced in {time.time() - t0:.8f} seconds.", flush=True)
        self.events.append(("complete",))
        return self.manifest

    def _phase_grammar(self, phase_done: dict, t0: float) -> None:
        """The reference phase lines data/make-parallel.sh greps, emitted
        when a phase's last leg publishes."""
        rounds = self.manifest.rounds()
        for rnd, label in ((-1, "Sorted"), (0, "Mapped")):
            legs = rounds.get(rnd, [])
            if legs and not phase_done[rnd] \
                    and all(leg.state == DONE for leg in legs):
                phase_done[rnd] = True
                print(f"{label} in {time.time() - t0:.8f} seconds.",
                      flush=True)


# ---------------------------------------------------------------------------
# Resume: fsck the surviving artifacts, keep the clean legs
# ---------------------------------------------------------------------------


def _artifact_clean(path: str, mode: str, expect_sig: str | None) -> bool:
    if not os.path.exists(path):
        return False
    try:
        sig = _validate_artifact(path, path, mode)
    except (IntegrityError, OSError, SupervisionFailed):
        return False
    if expect_sig and sig and sig != expect_sig:
        return False
    return True


def reconcile(manifest: Manifest, mode: str) -> tuple[int, int]:
    """Mark dirty/missing done-legs pending again; returns
    (clean_kept, redispatched).  Only artifacts still NEEDED are checked —
    a corrupt intermediate whose consumers all finished costs nothing."""
    cache: dict[str, bool] = {}

    def clean(path: str) -> bool:
        if path not in cache:
            cache[path] = _artifact_clean(path, mode, manifest.sig)
        return cache[path]

    changed = True
    while changed:
        changed = False
        required = {manifest.final_tree}
        required.update(p for leg in manifest.legs
                        if leg.state != DONE for p in leg.inputs)
        for leg in manifest.legs:
            if leg.state == DONE and leg.output in required \
                    and not clean(leg.output):
                leg.state = PENDING
                changed = True
    dirty = sum(1 for leg in manifest.legs if leg.state != DONE)
    return len(manifest.legs) - dirty, dirty


def run_supervised(graph: str, state_dir: str,
                   config: SupervisorConfig | None = None, runner=None,
                   seq_file: str | None = None,
                   out_file: str | None = None) -> Manifest:
    """Run (or resume) one supervised tournament; returns the completed
    manifest.  ``state_dir`` holds the manifest, ALL tournament artifacts
    (including the final tree — so a resume never depends on a caller's
    possibly-cleaned trial dir), and worker logs; rerunning with the same
    dir resumes off the fsck'd survivors.  ``seq_file``: an existing
    sequence (skip the sort leg).  ``out_file``: where to export a copy
    of the final tree (+ sidecar) after completion — an export, not the
    durable copy, so reruns and multi-trial drivers can point it anywhere.
    """
    config = config or SupervisorConfig.from_env()
    os.makedirs(state_dir, exist_ok=True)
    # a dead predecessor's write debris: atomic-write temps and attempt
    # files are unpublished by construction — reclaim before they count
    # against the disk budget (and before attempt names could collide)
    gc_orphan_temps(state_dir)
    sweep_attempt_debris(state_dir)
    base = os.path.basename(graph)
    for suffix in (".dat", ".net"):
        if base.endswith(suffix):
            base = base[: -len(suffix)]
    prefix = os.path.join(state_dir, base)
    final = prefix + ".tre"

    if os.path.exists(manifest_path(state_dir)):
        manifest = load_manifest(state_dir, config.integrity)
        size = os.path.getsize(graph) if os.path.exists(graph) else -1
        if manifest.graph != graph or manifest.graph_bytes != size:
            raise SupervisionFailed(
                f"{state_dir}: manifest belongs to a different build "
                f"({manifest.graph}, {manifest.graph_bytes} bytes; this "
                f"run: {graph}, {size} bytes) — refusing to resume; use "
                f"a fresh state dir")
        clean, dirty = reconcile(manifest, resolve_policy(config.integrity))
        config.events.append(("resume", clean, dirty))
    else:
        manifest = plan_tournament(graph, prefix, final, config.workers,
                                   config.reduction, seq_file)
    save_manifest(manifest, state_dir)
    manifest = TournamentSupervisor(manifest, state_dir, config,
                                    runner).run()
    if out_file and out_file != manifest.final_tree:
        # export copy, sidecar first (the sheep_mv_artifact ordering)
        if os.path.exists(manifest.final_tree + ".sum"):
            shutil.copyfile(manifest.final_tree + ".sum", out_file + ".sum")
        shutil.copyfile(manifest.final_tree, out_file)
    return manifest
