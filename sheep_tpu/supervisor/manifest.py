"""The durable tournament manifest: what the supervisor knows, on disk.

One JSON file (``<state-dir>/manifest.json``) records the whole planned
map -> merge-tournament bracket and each leg's progress, sealed with the
same ``.sum`` sidecar every other artifact carries (integrity.sidecar) and
rewritten atomically after every state change.  A supervisor that dies
mid-tournament is therefore resumable by construction: the next supervisor
loads the manifest, **fscks** every artifact the manifest claims is done,
and re-dispatches only the legs whose artifacts are missing or dirty — the
crash-safe partial-forest restart the ROADMAP asks for ("skip re-mapping
workers whose NNr0.tre fsck clean").

The bracket mirrors scripts/horizontal-dist.sh exactly (same slot
ownership, same ``{prefix}{NN}r{S}.tre`` artifact names): round 0 is the
map phase (one partial tree per worker over the shared sequence), round
``s+1`` merges round ``s``'s trees with slot ``i`` owning inputs
``{i, i+W', i+2W', ...}`` where ``W' = ceil(W/reduction)``.  A one-input
slot is a plain rename in the shell driver; here it is a ``copy`` leg the
supervisor services itself.  The LAST leg's output is the final tree path
directly — there is no separate finalize step to crash in the middle of.
"""

from __future__ import annotations

import json
import os
from dataclasses import asdict, dataclass, field

from ..integrity.errors import MalformedArtifact
from ..integrity.sidecar import checksummed_write, verify_file

MANIFEST_NAME = "manifest.json"
MANIFEST_VERSION = 1

#: leg lifecycle: pending -> done.  "running" is supervisor-local (an
#: attempt in flight), never persisted — a manifest read by a NEW
#: supervisor must treat any non-done leg as pending (the old attempt is
#: dead with its supervisor).
PENDING = "pending"
DONE = "done"

#: the round number of the distext histogram legs (ISSUE 13): they run
#: BEFORE the sort round (-1), which for a distext job is the
#: supervisor-serviced histogram merge
HIST_ROUND = -2


@dataclass
class Leg:
    """One unit of dispatchable work in the tournament."""

    key: str             # "sort", "r0.00", "r2.01", ...
    kind: str            # "sort" | "map" | "merge" | "copy"
    round: int           # -1 sort, 0 map, >= 1 merge rounds
    index: int           # slot within the round
    inputs: list[str]    # artifact paths consumed (empty for sort/map)
    output: str          # artifact path produced
    state: str = PENDING
    dispatches: int = 0  # attempts launched across ALL supervisor lives


@dataclass
class Manifest:
    """The durable state of one supervised tournament."""

    graph: str
    workers: int
    reduction: int
    seq_file: str          # the shared sequence every map leg reads
    final_tree: str
    graph_bytes: int       # guards resume against a swapped input file
    version: int = MANIFEST_VERSION
    sig: str | None = None  # input signature shared by every .tre artifact
    #: the distext shard map (ISSUE 13): one [start_edge, end_edge)
    #: record slice per leg, in leg-index order — None for a plain
    #: tournament.  Durable because it IS the resume identity: a leg's
    #: checkpoint folds its slice into its input_sig, so a manifest
    #: resumed under a different shard map could never publish anyway;
    #: persisting the map makes the refusal explicit and up-front.
    shards: list | None = None
    legs: list[Leg] = field(default_factory=list)

    def leg(self, key: str) -> Leg:
        for leg in self.legs:
            if leg.key == key:
                return leg
        raise KeyError(key)

    def pending(self) -> list[Leg]:
        return [leg for leg in self.legs if leg.state != DONE]

    def done(self) -> bool:
        return not self.pending()

    def rounds(self) -> dict[int, list[Leg]]:
        out: dict[int, list[Leg]] = {}
        for leg in self.legs:
            out.setdefault(leg.round, []).append(leg)
        return out


def tournament_rounds(workers: int, reduction: int) -> list[list[list[int]]]:
    """The merge bracket as input-index lists: ``rounds[s][i]`` is the list
    of round-``s`` tree indices merged by slot ``i`` of round ``s+1`` —
    the exact slot-ownership arithmetic of scripts/horizontal-dist.sh
    (STEP_SIZE / WORKERS / REDUCTION loop)."""
    if reduction < 2:
        raise ValueError(f"reduction {reduction} must be >= 2")
    rounds = []
    step_size = workers
    w = (workers + reduction - 1) // reduction
    while step_size != 1:
        rounds.append([list(range(i, step_size, w)) for i in range(w)])
        step_size = w
        w = (w + reduction - 1) // reduction
    return rounds


def plan_tournament(graph: str, prefix: str, final_tree: str, workers: int,
                    reduction: int, seq_file: str | None = None) -> Manifest:
    """Plan the full sort -> map -> merge-tournament leg graph.

    ``prefix`` names the intermediate artifacts (``{prefix}{NN}r{S}.tre``,
    ``{prefix}.seq``) — callers point it into the supervisor state dir so
    intermediates survive a trial-dir cleanup and a rerun can resume.
    ``seq_file``: an EXISTING sequence to build over (no sort leg planned);
    None plans a sort leg producing ``{prefix}.seq``.
    """
    if workers < 1:
        raise ValueError(f"workers {workers} must be >= 1")
    legs: list[Leg] = []
    if seq_file is None:
        seq_file = f"{prefix}.seq"
        legs.append(Leg(key="sort", kind="sort", round=-1, index=0,
                        inputs=[], output=seq_file))
    legs += _bracket_legs(prefix, final_tree, workers, reduction,
                          seq_file, "map")
    return Manifest(graph=graph, workers=workers, reduction=reduction,
                    seq_file=seq_file, final_tree=final_tree,
                    graph_bytes=_graph_bytes(graph), legs=legs)


def plan_distext(graph: str, prefix: str, final_tree: str,
                 shards: list, reduction: int) -> Manifest:
    """Plan the distributed out-of-core job (ISSUE 13): one ``hist`` leg
    per record shard (pass 1: the per-range degree histogram, a sealed
    ``.hist`` artifact), the supervisor-serviced ``histsum`` merge (the
    Allreduce: integer adds commute, so the summed histogram — and the
    counting-sorted sequence it publishes — is bit-identical to the
    single-host pass), one ``distmap`` leg per shard (pass 2: the ext
    pipeline over the range under the leg's own budget), then the
    SAME merge tournament every other tree takes.

    ``shards`` is the [start_edge, end_edge) record cover, one slice per
    leg; it persists in the manifest because it is the resume identity
    (Manifest.shards)."""
    if not shards:
        raise ValueError("distext needs at least one shard")
    legs: list[Leg] = []
    for i, (a, b) in enumerate(shards):
        legs.append(Leg(key=f"h.{i:02d}", kind="hist", round=HIST_ROUND,
                        index=i, inputs=[],
                        output=f"{prefix}{i:02d}.hist"))
    seq_file = f"{prefix}.seq"
    legs.append(Leg(key="sort", kind="histsum", round=-1, index=0,
                    inputs=[leg.output for leg in legs], output=seq_file))
    legs += _bracket_legs(prefix, final_tree, len(shards), reduction,
                          seq_file, "distmap")
    return Manifest(graph=graph, workers=len(shards), reduction=reduction,
                    seq_file=seq_file, final_tree=final_tree,
                    graph_bytes=_graph_bytes(graph),
                    shards=[[int(a), int(b)] for a, b in shards],
                    legs=legs)


def _graph_bytes(graph: str) -> int:
    try:
        return os.path.getsize(graph)
    except OSError:
        return -1


def _bracket_legs(prefix: str, final_tree: str, workers: int,
                  reduction: int, seq_file: str,
                  map_kind: str) -> list[Leg]:
    """The map + merge-tournament legs shared by the plain tournament
    (``map`` legs: partial in-RAM loads) and the distext job (``distmap``
    legs: streamed record slices) — identical bracket arithmetic, so the
    merge tournament cannot fork between them."""

    def tre(idx: int, rnd: int) -> str:
        return f"{prefix}{idx:02d}r{rnd}.tre"

    legs: list[Leg] = []
    rounds = tournament_rounds(workers, reduction) if workers > 1 else []
    for i in range(workers):
        # a 1-worker "tournament" maps straight into the final tree
        out = tre(i, 0) if rounds else final_tree
        legs.append(Leg(key=f"r0.{i:02d}", kind=map_kind, round=0, index=i,
                        inputs=[seq_file], output=out))
    for s, slots in enumerate(rounds):
        last = s == len(rounds) - 1
        for i, src in enumerate(slots):
            out = final_tree if last and i == 0 else tre(i, s + 1)
            legs.append(Leg(
                key=f"r{s + 1}.{i:02d}",
                kind="merge" if len(src) > 1 else "copy",
                round=s + 1, index=i,
                inputs=[tre(j, s) for j in src], output=out))
    return legs


def manifest_path(state_dir: str) -> str:
    return os.path.join(state_dir, MANIFEST_NAME)


def save_manifest(manifest: Manifest, state_dir: str) -> str:
    """Persist atomically + sealed: a supervisor killed mid-save leaves
    the previous complete manifest (and its matching sidecar) in place."""
    path = manifest_path(state_dir)
    with checksummed_write(path, "w") as f:
        f.write(json.dumps(asdict(manifest), indent=1, sort_keys=True))
        f.write("\n")
    return path


def load_manifest(state_dir: str, integrity: str | None = None) -> Manifest:
    """Load + verify the manifest.  Raises MalformedArtifact on a corrupt
    or wrong-version file — a supervisor must never resume off a manifest
    it cannot vouch for (the caller decides whether to replan fresh)."""
    path = manifest_path(state_dir)
    verify_file(path, integrity)
    try:
        with open(path, "r") as f:
            raw = json.load(f)
        if int(raw.get("version", -1)) != MANIFEST_VERSION:
            raise ValueError(f"manifest version {raw.get('version')} "
                             f"!= supported {MANIFEST_VERSION}")
        legs = [Leg(**leg) for leg in raw.pop("legs")]
        manifest = Manifest(legs=legs, **raw)
        for leg in manifest.legs:
            if leg.state not in (PENDING, DONE):
                # "running" from a dead supervisor, or garbage: both mean
                # "not provably complete" -> pending
                leg.state = PENDING
    except (ValueError, TypeError, KeyError, json.JSONDecodeError) as exc:
        raise MalformedArtifact(
            f"{path}: corrupt manifest ({type(exc).__name__}: {exc})")
    return manifest
