"""The distext supervisor's remote dispatch arm (ISSUE 16).

:class:`RemoteRunner` is a drop-in runner (the ``start(argv, hb_path,
log_path) -> handle`` seam of supervisor/supervise.py): ``distext``
hist/map legs ship over the wire to ``sheep worker`` daemons
(serve/worker.py), everything else — merge legs, the sort — falls
through to the local base runner.  Because the remote attempt is just
another handle, the supervisor's recovery machinery cannot fork: retry/
backoff, staleness, speculation, sig arbitration, and the fsck-gated
publish all apply to remote legs verbatim.

The three bridges that make that true:

  heartbeats   the worker's ``BEAT`` frames touch the attempt's LOCAL
               ``.hb`` file (supervisor/heartbeat.beat) on receipt — the
               mtime the supervisor already polls, so wall-clock
               deadlines AND ``stale_after_polls`` counting carry over
               unchanged.  A silent worker looks exactly like a silent
               subprocess.
  exit status  connection loss, a refused leg, and a crc-mismatched
               return all surface as a nonzero ``poll()`` with the
               typed reason appended to the attempt's log — the same
               "exit status != 0" path a crashed subprocess takes,
               feeding the same per-leg retry/backoff budget.
  admission    the fetched sidecar lands at ``tmp + ".sum"`` first, the
               artifact's bytes at a private ``.fetch`` name, renamed to
               ``tmp`` only after the wire crc verified END-TO-END — so
               a torn transfer never exists at the temp name and the
               supervisor's fsck gate (_validate_artifact) remains the
               single admission decision for local and remote artifacts
               alike.

Worker selection rotates round-robin and skips addresses that recently
failed at the CONNECTION level (a refused connect or a mid-stream link
loss marks the address dead for ``dead_cooldown_s``); a leg-level
failure (ERR legfail) does not — the worker is alive and answering, the
leg is the problem.  Each dispatch records worker/attempt/speculation
provenance in ``wire-<artifact>.json`` beside the manifest, which is
what ``sheep supervise --status`` renders for remote legs.
"""

from __future__ import annotations

import json
import os
import re
import socket
import threading
import time

from ..runtime.retry import RetryPolicy

#: attempt-temp suffix (supervise._launch: "<output>.aN")
_ATTEMPT_RE = re.compile(r"^(?P<final>.+)\.a(?P<n>\d+)$")


def wire_status_path(state_dir: str, final_output: str) -> str:
    """Where a leg's remote-dispatch provenance lands (one JSON per leg,
    keyed by the artifact's basename — status.py joins on the same)."""
    return os.path.join(state_dir,
                        f"wire-{os.path.basename(final_output)}.json")


def _parse_distext_argv(argv: list) -> dict | None:
    """Recover the leg spec from the supervisor's argv (_leg_argv) —
    None when this argv is not a shippable distext leg."""
    if not argv or argv[0] != "distext" or len(argv) < 3 \
            or argv[1] not in ("hist", "map"):
        return None
    spec = {"kind": "hist" if argv[1] == "hist" else "distmap",
            "graph": argv[2], "seq": None, "out": None, "perf": None}
    i = 3
    while i < len(argv):
        tok = argv[i]
        if tok == "-r" and i + 1 < len(argv):
            a, b = argv[i + 1].split(":", 1)
            spec["start"], spec["end"] = int(a), int(b)
            i += 2
        elif tok == "-s" and i + 1 < len(argv):
            spec["seq"] = argv[i + 1]
            i += 2
        elif tok == "-o" and i + 1 < len(argv):
            spec["out"] = argv[i + 1]
            i += 2
        elif tok in ("--checkpoint-dir", "--perf-out") and i + 1 < len(argv):
            if tok == "--perf-out":
                spec["perf"] = argv[i + 1]
            i += 2
        else:
            i += 1
    if spec.get("out") is None or "start" not in spec:
        return None
    m = _ATTEMPT_RE.match(spec["out"])
    if m is None:
        return None
    spec["final"] = m.group("final")
    spec["attempt"] = int(m.group("n"))
    spec["key"] = os.path.basename(spec["final"])
    return spec


class _RemoteHandle:
    """One remote attempt: a session thread that ships the leg, relays
    BEATs into the local heartbeat file, and lands the returned artifact
    at the attempt temp — crc-gated.  ``poll()``/``cancel()`` are the
    whole contract the supervisor sees."""

    remote = True

    def __init__(self, runner: "RemoteRunner", spec: dict, addr: tuple,
                 hb_path: str, log_path: str):
        self._runner = runner
        self._spec = spec
        self._hb = hb_path
        self._log = log_path
        self._rc: int | None = None
        self._lock = threading.Lock()
        self._socks: list = []
        self.cancelled = False
        self.worker = f"{addr[0]}:{addr[1]}"
        self._threads = [threading.Thread(
            target=self._session, args=(addr, False), daemon=True,
            name=f"remote:{spec['key']}.a{spec['attempt']}")]
        self._threads[0].start()

    # -- the runner contract ----------------------------------------------

    def poll(self) -> int | None:
        return self._rc

    def cancel(self) -> None:
        self.cancelled = True
        with self._lock:
            socks = list(self._socks)
        for s in socks:
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass

    # -- internals ---------------------------------------------------------

    def _note(self, msg: str) -> None:
        try:
            with open(self._log, "a") as f:
                f.write(msg.rstrip() + "\n")
        except OSError:
            pass

    def _finish(self, rc: int, msg: str | None = None) -> bool:
        """First finisher wins (the netfault ``dup`` path runs two
        sessions for one attempt); returns whether THIS call won."""
        with self._lock:
            if self._rc is not None:
                return False
            if msg:
                self._note(msg)
            self._rc = rc
        self._runner.attempt_done(self._spec["final"])
        return True

    def _connect(self, addr: tuple):
        """Typed retry/backoff on connection loss — a worker mid-restart
        is latency, a dead one a failed attempt (and a dead-marked
        address the rotation skips)."""
        policy: RetryPolicy = self._runner.policy
        last: Exception | None = None
        for attempt in range(policy.max_retries + 1):
            if self.cancelled:
                raise ConnectionError("attempt cancelled")
            try:
                sock = socket.create_connection(
                    addr, timeout=self._runner.connect_timeout_s)
                sock.settimeout(None)
                with self._lock:
                    self._socks.append(sock)
                return sock
            except OSError as exc:
                last = exc
                time.sleep(policy.backoff(attempt))
        raise ConnectionError(
            f"worker {addr[0]}:{addr[1]} unreachable after "
            f"{policy.max_retries + 1} connect attempt(s): {last}")

    def _session(self, addr: tuple, is_dup_twin: bool) -> None:
        from ..serve.netfaults import SLOW_S, arm
        spec = self._spec
        try:
            sock = self._connect(addr)
        except ConnectionError as exc:
            self._runner.mark_dead(addr)
            self._finish(1, f"remote: {exc}")
            return
        try:
            fault = None if is_dup_twin else arm("wleg")
            if fault == "partition":
                try:
                    sock.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                self._finish(1, "remote: netfault partition@wleg (link "
                                "died before dispatch)")
                return
            if fault == "dup":
                # duplicate delivery: the same leg lands on a second
                # worker too; first finisher wins, the loser is discarded
                twin_addr = self._runner.next_addr()
                t = threading.Thread(target=self._session,
                                     args=(twin_addr, True), daemon=True,
                                     name="remote-dup-twin")
                self._threads.append(t)
                t.start()
            if fault == "slow":
                time.sleep(SLOW_S)
            if fault != "drop":
                self._ship(sock, spec)
            else:
                self._note("remote: netfault drop@wleg (LEG frame never "
                           "sent; staleness will redispatch)")
            self._receive(sock, spec)
        except (OSError, ValueError) as exc:
            if not self.cancelled:
                self._runner.mark_dead(addr)
            self._finish(1, f"remote: wire lost ({type(exc).__name__}: "
                            f"{exc})")
        finally:
            try:
                sock.close()
            except OSError:
                pass

    def _ship(self, sock, spec: dict) -> None:
        from ..serve.worker import file_crc, send_file
        a, b = spec["start"], spec["end"]
        nbytes = (b - a) * 12
        crc = file_crc(spec["graph"], offset=a * 12, length=nbytes)
        seq_len = seq_crc = 0
        if spec["kind"] == "distmap":
            seq_len = os.path.getsize(spec["seq"])
            seq_crc = file_crc(spec["seq"])
        head = (f"LEG key={spec['key']} kind={spec['kind']} start={a} "
                f"end={b} beat={self._runner.beat_s} bytes={nbytes} "
                f"crc={crc} seqbytes={seq_len} seqcrc={seq_crc}\n")
        sock.sendall(head.encode("ascii"))
        send_file(sock, spec["graph"], offset=a * 12, length=nbytes)
        if seq_len:
            send_file(sock, spec["seq"])

    def _receive(self, sock, spec: dict) -> None:
        from ..serve.protocol import MAX_LINE
        from ..serve.replicate import recv_exact
        from ..serve.worker import parse_result_header, payload_crc
        from .heartbeat import beat
        rf = sock.makefile("rb")
        while True:
            raw = rf.readline(MAX_LINE)
            if not raw:
                raise ConnectionError("worker closed the link mid-leg")
            line = raw.decode("utf-8", "replace").strip()
            if line.startswith("BEAT"):
                try:
                    beat(self._hb)
                except OSError:
                    pass
                continue
            head = parse_result_header(line)  # ConnectionError on ERR
            sum_bytes = recv_exact(rf, head["sumbytes"])
            art_bytes = recv_exact(rf, head["bytes"])
            perf_bytes = recv_exact(rf, head["perfbytes"]) \
                if head["perfbytes"] else b""
            if payload_crc(sum_bytes) != head["sumcrc"] \
                    or payload_crc(art_bytes) != head["crc"] \
                    or (perf_bytes
                        and payload_crc(perf_bytes) != head["perfcrc"]):
                raise ConnectionError(
                    "artifact return failed its crc — torn or corrupted "
                    "on the wire, refused")
            self._land(spec, sum_bytes, art_bytes, perf_bytes)
            return

    def _land(self, spec: dict, sum_bytes: bytes, art_bytes: bytes,
              perf_bytes: bytes) -> None:
        """Publish the VERIFIED return to the attempt temp, sidecar
        first; the winner gate keeps a dup twin from racing the write."""
        with self._lock:
            if self._rc is not None:
                return  # a twin already finished; this copy is discarded
            tmp = spec["out"]
            with open(tmp + ".sum", "wb") as f:
                f.write(sum_bytes)
            fetch = tmp + ".fetch"
            with open(fetch, "wb") as f:
                f.write(art_bytes)
            os.replace(fetch, tmp)
            if perf_bytes and spec.get("perf"):
                from ..io.atomic import atomic_write
                try:
                    report = json.loads(perf_bytes.decode("utf-8"))
                    report["range"] = [spec["start"], spec["end"]]
                    report["verb"] = ("hist" if spec["kind"] == "hist"
                                      else "map")
                    report["worker"] = self.worker
                    with atomic_write(spec["perf"], "w") as f:
                        json.dump(report, f, indent=1, sort_keys=True)
                        f.write("\n")
                except (ValueError, OSError):
                    pass  # the perf report is best-effort telemetry
            self._rc = 0
        self._runner.attempt_done(spec["final"])


class RemoteRunner:
    """Route distext hist/map legs to remote workers; delegate the rest.

    ``addrs``: the worker fleet ((host, port) list, SHEEP_WORKER_ADDRS).
    ``base``: the local runner for non-shippable legs (merge legs read
    supervisor-local artifacts; default SubprocessRunner).
    """

    remote = True

    def __init__(self, addrs: list, base=None, beat_s: float = 1.0,
                 connect_timeout_s: float = 5.0, max_retries: int = 2,
                 backoff_base_s: float = 0.05,
                 dead_cooldown_s: float = 30.0):
        if not addrs:
            raise ValueError("RemoteRunner needs at least one worker "
                             "address (SHEEP_WORKER_ADDRS)")
        self.addrs = [tuple(a) for a in addrs]
        if base is None:
            from .supervise import SubprocessRunner
            base = SubprocessRunner()
        self.base = base
        self.beat_s = beat_s
        self.connect_timeout_s = connect_timeout_s
        self.policy = RetryPolicy(max_retries=max_retries,
                                  backoff_base_s=backoff_base_s)
        self.dead_cooldown_s = dead_cooldown_s
        self._slot = 0
        self._dead: dict = {}
        self._lock = threading.Lock()
        #: per-leg dispatch bookkeeping for wire-<leg>.json
        self._dispatches: dict = {}
        self._speculations: dict = {}
        self._live: dict = {}

    # -- worker selection --------------------------------------------------

    def mark_dead(self, addr: tuple) -> None:
        with self._lock:
            self._dead[tuple(addr)] = time.monotonic()

    def next_addr(self) -> tuple:
        """Round-robin over the fleet, skipping addresses inside their
        dead cooldown — unless every address is dead, in which case the
        least-recently-failed one gets the probe (someone must)."""
        with self._lock:
            now = time.monotonic()
            for _ in range(len(self.addrs)):
                addr = self.addrs[self._slot % len(self.addrs)]
                self._slot += 1
                died = self._dead.get(addr)
                if died is None or now - died > self.dead_cooldown_s:
                    return addr
            return min(self.addrs, key=lambda a: self._dead.get(a, 0.0))

    # -- the runner contract ----------------------------------------------

    def start(self, argv: list, hb_path: str, log_path: str):
        spec = _parse_distext_argv(argv)
        if spec is None:
            return self.base.start(argv, hb_path, log_path)
        addr = self.next_addr()
        self._record_dispatch(spec, addr)
        handle = _RemoteHandle(self, spec, addr, hb_path, log_path)
        return handle

    def _record_dispatch(self, spec: dict, addr: tuple) -> None:
        """wire-<artifact>.json: the remote provenance --status renders
        (worker address, dispatch/speculation counts; the wire-beat age
        is the attempt .hb file's, fed by BEAT frames)."""
        key = spec["final"]
        with self._lock:
            self._dispatches[key] = self._dispatches.get(key, 0) + 1
            live = self._live.get(key, 0)
            if live > 0:
                # a second live session for the same leg IS speculation
                # (or a netfault dup) — first finisher wins either way
                self._speculations[key] = \
                    self._speculations.get(key, 0) + 1
            self._live[key] = live + 1
            row = {"worker": f"{addr[0]}:{addr[1]}",
                   "attempt": spec["attempt"],
                   "dispatches": self._dispatches[key],
                   "speculations": self._speculations.get(key, 0)}
        from ..io.atomic import atomic_write
        try:
            state_dir = os.path.dirname(key) or "."
            with atomic_write(wire_status_path(state_dir, key), "w") as f:
                json.dump(row, f, indent=1, sort_keys=True)
                f.write("\n")
        except OSError:
            pass

    def attempt_done(self, spec_final: str) -> None:
        with self._lock:
            self._live[spec_final] = max(0,
                                         self._live.get(spec_final, 1) - 1)


__all__ = ["RemoteRunner", "wire_status_path"]
