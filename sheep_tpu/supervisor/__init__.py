"""Chaos-hardened tournament supervisor for the distributed file path.

Four modules, layered bottom-up:

  heartbeat.py  worker liveness: heartbeat files + deadlines (mtime-based,
                so shell and Python workers implement one protocol)
  manifest.py   the durable tournament manifest: planned bracket +
                per-leg state, atomic + checksummed — what makes a
                crashed run resumable
  chaos.py      deterministic fault injection (SHEEP_FAULT_PLAN grammar:
                kill/corrupt/hang a leg, stop the supervisor)
  supervise.py  the orchestrator: dispatch, fsck-gated publish,
                retry/backoff, deadline relaunch, speculative
                re-execution, fsck-driven resume, disk-budget GC and
                per-leg cores budgeting (ISSUE 5)
  status.py     ``sheep supervise --status``: the manifest + heartbeat +
                budget-headroom operator report (read-only)
  remote.py     the remote dispatch arm (ISSUE 16): RemoteRunner ships
                distext legs to ``sheep worker`` daemons over the fleet
                wire behind the same runner seam

See supervise.py's docstring for the failure model; the acceptance
property (a fault at EVERY tournament round yields a bit-identical final
tree, re-dispatching only the faulted leg) lives in
tests/test_supervisor.py.
"""

from .chaos import (ChaosFault, ChaosPlan, SupervisorKilled, parse_fault_plan,
                    plan_from_env)
from .heartbeat import HeartbeatWriter, beat, is_stale, last_beat_s
from .manifest import (Leg, Manifest, load_manifest, manifest_path,
                       plan_distext, plan_tournament, save_manifest,
                       tournament_rounds)
from .remote import RemoteRunner, wire_status_path
from .status import render_status, status_rows
from .supervise import (InlineRunner, SubprocessRunner, SupervisionFailed,
                        SupervisorConfig, TournamentSupervisor, reconcile,
                        run_supervised, sweep_attempt_debris)

__all__ = [
    "ChaosFault",
    "ChaosPlan",
    "HeartbeatWriter",
    "InlineRunner",
    "Leg",
    "Manifest",
    "RemoteRunner",
    "SubprocessRunner",
    "SupervisionFailed",
    "SupervisorConfig",
    "SupervisorKilled",
    "TournamentSupervisor",
    "beat",
    "is_stale",
    "last_beat_s",
    "load_manifest",
    "manifest_path",
    "parse_fault_plan",
    "plan_from_env",
    "plan_distext",
    "plan_tournament",
    "reconcile",
    "render_status",
    "run_supervised",
    "save_manifest",
    "status_rows",
    "sweep_attempt_debris",
    "tournament_rounds",
    "wire_status_path",
]
