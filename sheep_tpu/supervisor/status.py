"""``sheep supervise --status``: the manifest as an operator table.

A crashed or long-running supervised tournament is a directory of state
(manifest.json, per-leg artifacts, heartbeat files, logs) that until now
only the supervisor itself could interpret.  This module renders it for a
human: per-leg state / dispatch counts / artifact presence / heartbeat
age, plus the resource headroom the ISSUE-5 budgets track (disk usage vs
``SHEEP_DISK_BUDGET`` and free space, RSS vs ``SHEEP_MEM_BUDGET``).

SERVE state dirs (ISSUES 6+7) report too: pointed at a directory holding
serve snapshots instead of a manifest, ``--status`` asks the live daemon
over the wire (``STATS`` — role, epoch, applied seqno, per-follower
replication lag) and falls back to the daemon's persisted
``serve.status.json`` plus heartbeat age when the process is down — so
an outside monitor can alert on a dead, lagging, or fenced replica with
one command either way.

Read-only by design: --status never mutates the state dir (no GC, no
debris sweep, no manifest rewrite), so an operator can inspect a LIVE
run another supervisor owns without racing it.
"""

from __future__ import annotations

import glob
import os
import re
import time

from ..resources.governor import (ResourceGovernor, dir_usage, disk_free,
                                  rss_bytes)
from .manifest import DONE, Manifest, load_manifest, manifest_path


def _fmt_bytes(n: int | None) -> str:
    if n is None:
        return "-"
    for unit, shift in (("G", 30), ("M", 20), ("K", 10)):
        if abs(n) >= (1 << shift):
            return f"{n / (1 << shift):.1f}{unit}"
    return f"{n}B"


def _fmt_age(s: float | None) -> str:
    if s is None:
        return "-"
    if s < 120:
        return f"{s:.0f}s"
    if s < 7200:
        return f"{s / 60:.0f}m"
    return f"{s / 3600:.1f}h"


def _newest_heartbeat_age(output: str, now: float) -> float | None:
    """Age of the freshest beat among this leg's attempt heartbeats
    (``<output>.aN.hb``) — None when no attempt ever beat.  Stale files
    from dead attempts age honestly: a huge number reads as 'dead'."""
    newest = None
    for hb in glob.glob(glob.escape(output) + ".a*.hb"):
        try:
            m = os.path.getmtime(hb)
        except OSError:
            continue
        newest = m if newest is None else max(newest, m)
    return None if newest is None else max(0.0, now - newest)


def newest_trace_rollup(state_dir: str) -> dict | None:
    """Phase rollup of the newest ``*.trace`` flight-recorder file in
    the state dir (ISSUE 10), or None when there is none.  Read in
    repair mode — the trace of a KILLED run is exactly what an operator
    inspecting a state dir wants to see — torn tails reported, never
    fatal to the status view."""
    import warnings
    newest, newest_m = None, None
    try:
        for name in os.listdir(state_dir):
            if not name.endswith(".trace"):
                continue
            path = os.path.join(state_dir, name)
            try:
                m = os.path.getmtime(path)
            except OSError:
                continue
            if newest_m is None or m > newest_m:
                newest, newest_m = path, m
    except OSError:
        return None
    if newest is None:
        return None
    from ..obs.trace import read_trace, rollup
    try:
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            records, _, torn = read_trace(newest, "repair")
    except Exception:
        return {"path": newest, "error": "unreadable"}
    return {"path": newest, "torn": torn,
            "age_s": round(max(0.0, time.time() - newest_m), 3),
            "phases": rollup(records)}


def _trace_lines(state_dir: str) -> list[str]:
    """The human face of :func:`newest_trace_rollup` (top phases by
    total time), empty when the dir holds no trace."""
    roll = newest_trace_rollup(state_dir)
    if roll is None:
        return []
    lines = [f"trace: {os.path.basename(roll['path'])}"
             + (" [torn tail]" if roll.get("torn") else "")
             + (f"  ({_fmt_age(roll.get('age_s'))} old)"
                if roll.get("age_s") is not None else "")]
    phases = dict(roll.get("phases") or {})
    phases.pop("_events", None)
    top = sorted(phases.items(), key=lambda kv: -kv[1]["total_s"])[:6]
    for name, p in top:
        lines.append(f"      {name:<26} x{p['count']:<5} "
                     f"{p['total_s']:.3f}s")
    return lines


#: the block size a leg's ext checkpoint was written at, recoverable
#: from its input_sig (ops/extmem: ``...|ext:b{block}|range:{a}:{b}``)
_SIG_BLOCK_RE = re.compile(r"\|ext:b(\d+)\b")


def _ext_progress(manifest: Manifest, leg, state_dir: str | None):
    """(blocks_done, blocks_total) of a distmap leg, read from the leg's
    own block-boundary checkpoint (ISSUE 13) — None when the leg never
    checkpointed (not yet dispatched, or already finished and cleared)
    or the dir is unknown.  Read in trust mode: a status view reports, a
    resume verifies."""
    if leg.kind != "distmap" or state_dir is None \
            or manifest.shards is None:
        return None
    from ..ops.distext import leg_checkpoint_dir
    from ..runtime.snapshot import SNAPSHOT_NAME, load_snapshot
    path = os.path.join(leg_checkpoint_dir(state_dir, leg.key),
                        SNAPSHOT_NAME)
    try:
        snap = load_snapshot(path, integrity="trust")
    except Exception:
        return None
    m = _SIG_BLOCK_RE.search(snap.input_sig)
    if m is None:
        return None
    block = int(m.group(1))
    a, b = manifest.shards[leg.index]
    total = -(-max(0, int(b) - int(a)) // block) if block else 0
    return snap.rounds, total


def _wire_provenance(leg, state_dir: str | None) -> dict | None:
    """A remote leg's dispatch provenance (supervisor/remote.py writes
    ``wire-<artifact>.json`` per dispatch) — None for local legs.  The
    wire-beat age is NOT here: BEAT frames touch the attempt's .hb file,
    so ``heartbeat_age_s`` already tells that story for remote legs."""
    if state_dir is None:
        return None
    from .remote import wire_status_path
    try:
        import json
        with open(wire_status_path(state_dir, leg.output)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def status_rows(manifest: Manifest, now: float | None = None,
                state_dir: str | None = None) -> list[dict]:
    """One dict per leg: key/kind/round/state/dispatches/artifact bytes
    (None = absent)/heartbeat age seconds (None = never beat).  distmap
    legs (the distributed out-of-core build, ISSUE 13) additionally
    report ``ext_blocks_done``/``ext_blocks_total`` from their own
    block-boundary checkpoint when ``state_dir`` is given — mid-leg
    progress an operator can read next to the heartbeat age.  Legs
    dispatched over the worker wire (ISSUE 16) gain ``worker`` (the
    remote address), ``wire_dispatches``, and ``speculations`` from the
    dispatch provenance RemoteRunner records; their ``heartbeat_age_s``
    is the last WIRE beat's age (BEAT frames feed the same .hb file)."""
    now = time.time() if now is None else now
    rows = []
    for leg in manifest.legs:
        try:
            size = os.path.getsize(leg.output)
        except OSError:
            size = None
        row = dict(
            key=leg.key, kind=leg.kind, round=leg.round, state=leg.state,
            dispatches=leg.dispatches, artifact_bytes=size,
            heartbeat_age_s=_newest_heartbeat_age(leg.output, now))
        prog = _ext_progress(manifest, leg, state_dir)
        if prog is not None:
            row["ext_blocks_done"], row["ext_blocks_total"] = prog
        wire = _wire_provenance(leg, state_dir)
        if wire is not None:
            row["worker"] = wire.get("worker")
            row["wire_dispatches"] = wire.get("dispatches")
            row["speculations"] = wire.get("speculations")
        rows.append(row)
    return rows


def status_json(state_dir: str, integrity: str | None = None,
                governor: ResourceGovernor | None = None,
                now: float | None = None) -> dict:
    """The operator report as one JSON-safe dict (``--status --json``):
    what the table renders, minus the formatting — so the serve daemon's
    liveness probe and outside monitors consume leg states, heartbeat
    ages, and budget headroom without scraping the human table.  Same
    read-only contract as :func:`render_status`."""
    manifest = load_manifest(state_dir, integrity)
    gov = governor if governor is not None else ResourceGovernor.from_env()
    now = time.time() if now is None else now
    rows = status_rows(manifest, now, state_dir)
    usage = dir_usage(state_dir)
    rss = rss_bytes()
    out = {
        "graph": manifest.graph,
        "state_dir": state_dir,
        "workers": manifest.workers,
        "reduction": manifest.reduction,
        "done": manifest.done(),
        "legs_done": sum(1 for r in rows if r["state"] == DONE),
        "legs_total": len(rows),
        "dispatches": sum(r["dispatches"] for r in rows),
        "legs": rows,
        "disk": {
            "state_dir_bytes": usage,
            "free_bytes": disk_free(state_dir),
            "budget_bytes": gov.disk_budget,
            "headroom_bytes": (gov.disk_budget - usage
                               if gov.disk_budget is not None else None),
        },
        "mem": {
            "rss_bytes": rss,
            "budget_bytes": gov.mem_budget,
            "headroom_bytes": (gov.mem_budget - rss
                               if gov.mem_budget is not None else None),
        },
        # the newest flight-recorder file's phase rollup (ISSUE 10) —
        # what the run was DOING, next to the heartbeat ages that say
        # whether it still is
        "trace": newest_trace_rollup(state_dir),
    }
    return out


def render_status(state_dir: str, integrity: str | None = None,
                  governor: ResourceGovernor | None = None,
                  now: float | None = None) -> str:
    """The full operator report for one state dir.  Raises
    IntegrityError/OSError when the manifest is missing or corrupt —
    a status view must never invent a healthier story than fsck would."""
    manifest = load_manifest(state_dir, integrity)
    gov = governor if governor is not None else ResourceGovernor.from_env()
    now = time.time() if now is None else now
    rows = status_rows(manifest, now, state_dir)
    done = sum(1 for r in rows if r["state"] == DONE)
    dispatches = sum(r["dispatches"] for r in rows)

    # the remote columns appear only when some leg actually went over
    # the worker wire (ISSUE 16) — a purely local run's table is
    # byte-stable across this feature
    remote = any("worker" in r for r in rows)
    head = f"{'LEG':<8} {'KIND':<7} {'STATE':<8} {'DISP':>4} " \
           f"{'ARTIFACT':>9} {'HEARTBEAT':>9} {'PROGRESS':>9}"
    if remote:
        head += f" {'WORKER':<21} {'WDISP':>5} {'SPEC':>4}"
    lines = [
        f"supervised tournament: {manifest.graph}",
        f"state dir: {state_dir}",
        f"workers {manifest.workers}  reduction {manifest.reduction}  "
        f"legs {done}/{len(rows)} done  dispatches {dispatches}",
        "",
        head,
        "-" * len(head),
    ]
    for r in rows:
        # distmap legs show blocks-done/total from their own checkpoint
        # (ISSUE 13): mid-leg progress next to the liveness signal
        prog = "-"
        if "ext_blocks_done" in r:
            prog = f"{r['ext_blocks_done']}/{r['ext_blocks_total']}blk"
        line = (
            f"{r['key']:<8} {r['kind']:<7} {r['state']:<8} "
            f"{r['dispatches']:>4} "
            f"{_fmt_bytes(r['artifact_bytes']):>9} "
            f"{_fmt_age(r['heartbeat_age_s']):>9} {prog:>9}")
        if remote:
            spec = r.get("speculations")
            line += (f" {r.get('worker') or '-':<21} "
                     f"{r.get('wire_dispatches') or '-':>5} "
                     f"{spec if spec is not None else '-':>4}")
        lines.append(line)

    usage = dir_usage(state_dir)
    free = disk_free(state_dir)
    lines += ["", f"disk: state dir {_fmt_bytes(usage)} used, "
                  f"{_fmt_bytes(free)} free on filesystem"]
    if gov.disk_budget is not None:
        lines.append(f"      budget {_fmt_bytes(gov.disk_budget)} "
                     f"(headroom {_fmt_bytes(gov.disk_budget - usage)})")
    rss = rss_bytes()
    mem = f"mem:  rss {_fmt_bytes(rss)}"
    if gov.mem_budget is not None:
        mem += f", budget {_fmt_bytes(gov.mem_budget)} " \
               f"(headroom {_fmt_bytes(gov.mem_budget - rss)})"
    lines.append(mem)
    lines += _trace_lines(state_dir)
    if not manifest.done():
        lines.append("resume: rerun `sheep supervise <graph> -d "
                     + state_dir + "` to fsck survivors and finish")
    return "\n".join(lines) + "\n"


def is_serve_dir(state_dir: str) -> bool:
    """Does this directory hold serve-daemon state (snapshots / WAL /
    status file) rather than a tournament manifest?"""
    from ..serve.state import snap_paths
    if snap_paths(state_dir):
        return True
    return any(os.path.exists(os.path.join(state_dir, name))
               for name in ("serve.wal", "serve.status.json"))


def serve_status_json(state_dir: str) -> dict:
    """One serve node's operator report: role / epoch / applied seqno /
    replication lag, live over the wire when the daemon answers, else
    from its persisted status file — plus heartbeat age and the newest
    snapshot, so "down" and "fenced" are both visible."""
    from ..serve.daemon import HEARTBEAT_FILE, STATUS_FILE
    out: dict = {"state_dir": state_dir, "kind": "serve", "alive": False}
    hb = os.path.join(state_dir, HEARTBEAT_FILE)
    try:
        out["heartbeat_age_s"] = round(
            max(0.0, time.time() - os.path.getmtime(hb)), 3)
    except OSError:
        out["heartbeat_age_s"] = None
    addr_file = os.path.join(state_dir, "serve.addr")
    try:
        host, port = open(addr_file).read().split()
        out["addr"] = f"{host}:{port}"
    except (OSError, ValueError):
        host = None
    if host is not None:
        try:
            from ..serve.protocol import ServeClient
            with ServeClient(host, int(port), timeout_s=2.0) as c:
                out["stats"] = c.kv("STATS")
                out["alive"] = True
                for key in ("role", "epoch", "applied_seqno", "repl_lag",
                            "followers", "node", "leader", "moved_dest",
                            "mig_phase", "mig_lag", "migrating",
                            "seq_drift", "reseqs", "seq_gen",
                            "diverged", "scrub_runs",
                            "scrub_quarantined", "scrub_repaired",
                            "quarantine_heals"):
                    if key in out["stats"]:
                        out[key] = out["stats"][key]
        except Exception:
            pass
    if not out["alive"]:
        # the daemon's last persisted self-report (daemon.status_dict)
        try:
            import json
            with open(os.path.join(state_dir, STATUS_FILE)) as f:
                last = json.load(f)
            out["last_status"] = last
            for key in ("role", "epoch", "applied_seqno", "node",
                        "leader"):
                if key in last:
                    out[key] = last[key]
        except (OSError, ValueError):
            pass
        from ..serve.state import load_serve_snapshot, snap_paths
        snaps = snap_paths(state_dir)
        if snaps:
            out["newest_snapshot"] = os.path.basename(snaps[-1])
            if "epoch" not in out:
                try:
                    snap = load_serve_snapshot(snaps[-1],
                                               integrity="trust")
                    out["epoch"] = snap.epoch
                    out["applied_seqno"] = snap.applied_seqno
                except Exception:
                    pass
    # an in-flight re-sequence manifest (ISSUE 18) is visible whether or
    # not the daemon answers — a down node mid-rebuild is exactly when
    # the operator needs to see it
    try:
        from ..serve import reseq as reseq_mod
        man = reseq_mod.load_manifest(state_dir)
        if man is not None and man.get("phase") not in reseq_mod.DONE_PHASES:
            out["reseq_phase"] = man.get("phase")
    except Exception:
        pass
    # a durable quarantine marker (ISSUE 20) is likewise visible with
    # the daemon down — the operator must know this replica's state is
    # divergent BEFORE deciding to restart or promote it
    try:
        from ..serve import scrub as scrub_mod
        quar = scrub_mod.read_quarantine(state_dir)
        if quar is not None:
            out["quarantine_phase"] = quar.get("phase")
            out["diverged"] = 1
    except Exception:
        pass
    out["trace"] = newest_trace_rollup(state_dir)
    return out


def render_serve_status(state_dir: str) -> str:
    rec = serve_status_json(state_dir)
    lines = [f"serve node: {state_dir}",
             f"alive: {'yes' if rec['alive'] else 'NO (daemon down)'}"
             f"  heartbeat {_fmt_age(rec.get('heartbeat_age_s'))}"]
    for key in ("node", "role", "epoch", "applied_seqno", "leader",
                "repl_lag", "followers", "addr", "newest_snapshot",
                "moved_dest", "mig_phase", "mig_lag", "migrating",
                "seq_drift", "reseqs", "seq_gen", "reseq_phase",
                "diverged", "scrub_runs", "scrub_quarantined",
                "scrub_repaired", "quarantine_heals",
                "quarantine_phase"):
        if key in rec and rec[key] is not None:
            lines.append(f"{key}: {rec[key]}")
    st = rec.get("stats", {})
    lags = {k[4:]: v for k, v in st.items() if k.startswith("lag_")}
    if lags:
        lines.append("follower lag (records):")
        for node, lag in sorted(lags.items()):
            lines.append(f"  {node}: {lag}")
    lines += _trace_lines(state_dir)
    return "\n".join(lines) + "\n"


def main_status(state_dir: str, integrity: str | None = None,
                as_json: bool = False) -> int:
    """The CLI face: print the report (human table, or one JSON object
    with ``--json``); exit 0 when the manifest loads (even mid-run), 1
    when the state dir has no readable manifest.  Serve state dirs get
    the replication report instead (role/epoch/applied/lag)."""
    import sys
    if not os.path.exists(manifest_path(state_dir)):
        if os.path.isdir(state_dir) and is_serve_dir(state_dir):
            if as_json:
                import json
                json.dump(serve_status_json(state_dir), sys.stdout,
                          indent=2, sort_keys=True)
                sys.stdout.write("\n")
            else:
                sys.stdout.write(render_serve_status(state_dir))
            return 0
        print(f"supervise: no manifest in {state_dir}", file=sys.stderr)
        return 1
    try:
        if as_json:
            import json
            json.dump(status_json(state_dir, integrity), sys.stdout,
                      indent=2, sort_keys=True)
            sys.stdout.write("\n")
        else:
            sys.stdout.write(render_status(state_dir, integrity))
    except (ValueError, OSError) as exc:
        print(f"supervise: {exc}", file=sys.stderr)
        return 1
    return 0
