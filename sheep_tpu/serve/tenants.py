"""Multi-tenancy: N serve state dirs behind one daemon (ISSUE 11).

One daemon process used to mean one graph.  Production framing — many
graphs, many users — wants N graphs behind one selectors loop instead of
N processes each paying a listener, a worker pool, and an idle-time RSS
floor.  A *tenant* is exactly one PR-6 serve state dir (snapshots + WAL
+ drift accounting), and everything crash-safety already proved about
one state dir holds per tenant by construction: the cores never share a
single array, WAL, or admission slot pool.

    SHEEP_SERVE_TENANTS = entry[,entry...]
    entry               = name=state_dir[:graph[:num_parts]]

(also ``--tenant name=dir[:graph[:k]]``, repeatable, on ``bin/serve``).
The ``default`` tenant is the daemon's ``-d`` state dir and is what a
connection talks to until it selects otherwise — the PR-7 wire grammar
is byte-identical for it.  ``TENANT <name>`` is connection-scoped: it
re-points THAT connection's verbs at another tenant's core (the router
issues it once per upstream connection).

**Memory: governor-priced eviction.**  Resident tenants are priced by
:func:`~sheep_tpu.resources.governor.serve_tenant_nbytes`; when the
process crosses the ``SHEEP_MEM_BUDGET`` soft threshold (the same
signal that turns inserts read-only) — or the operator capped resident
tenants with ``SHEEP_SERVE_MAX_RESIDENT`` — the coldest evictable
tenant is sealed to a snapshot generation and dropped from memory.
Eviction is the clean-shutdown path (seal + close), so the evicted
state is bit-identical by the same argument a restart is; the next verb
that touches the tenant lazily restores it through ``ServeCore.open`` —
the exact crash-recovery path, exercised on every eviction cycle.  A
tenant with attached replication streams never evicts (its followers
would have to re-handshake for nothing); the default tenant never
evicts (it IS the daemon's published identity).
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass

from ..resources.governor import ResourceGovernor, serve_tenant_nbytes
from .state import ServeCore, snap_paths

TENANTS_ENV = "SHEEP_SERVE_TENANTS"
MAX_RESIDENT_ENV = "SHEEP_SERVE_MAX_RESIDENT"

DEFAULT_TENANT = "default"

#: the migration fence marker (ISSUE 17): a tenant state dir holding
#: this file has been MOVED — its daemon refuses every client verb with
#: ``ERR moved dest=<cluster>`` even across restarts, so a kill -9'd
#: source can never resurrect as a second owner of a migrated tenant
MOVED_MARKER = "tenant.moved"

#: adopted-tenant registry (ISSUE 17): migration targets persist the
#: tenants they adopted (they are not in SHEEP_SERVE_TENANTS) so a
#: kill -9 mid-migration leaves the tenant registered after restart
ADOPTED_FILE = "tenants.adopted.json"


def moved_marker_path(state_dir: str) -> str:
    return os.path.join(state_dir, MOVED_MARKER)


def read_moved_marker(state_dir: str) -> str | None:
    """The destination cluster named by a tenant dir's fence marker, or
    None when the tenant was never migrated away."""
    try:
        with open(moved_marker_path(state_dir)) as f:
            rec = json.load(f)
        return str(rec["dest"])
    except (OSError, ValueError, KeyError):
        return None


def write_moved_marker(state_dir: str, dest: str) -> None:
    """Durably fence a tenant dir: tmp + fsync + rename, so the fence
    either fully exists or does not — a torn fence is no fence, and the
    cutover driver retries until the marker reads back."""
    path = moved_marker_path(state_dir)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"dest": dest, "at": time.time()}, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def clear_moved_marker(state_dir: str) -> None:
    try:
        os.unlink(moved_marker_path(state_dir))
    except OSError:
        pass


@dataclass
class TenantSpec:
    """One parsed ``name=dir[:graph[:k]]`` entry."""

    name: str
    state_dir: str
    graph: str | None = None
    num_parts: int = 2


def parse_tenant_specs(spec: str) -> list[TenantSpec]:
    """``SHEEP_SERVE_TENANTS`` / ``--tenant`` grammar -> specs.  Raises
    ValueError on garbage — a misspelled tenant must never silently
    vanish from the fleet."""
    out: list[TenantSpec] = []
    seen = set()
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        name, sep, rest = entry.partition("=")
        name = name.strip()
        if not sep or not name or not rest:
            raise ValueError(
                f"tenant entry {entry!r}: want name=dir[:graph[:k]]")
        if name == DEFAULT_TENANT:
            raise ValueError(
                f"tenant entry {entry!r}: {DEFAULT_TENANT!r} is the "
                f"daemon's -d state dir, not a named tenant")
        if name in seen:
            raise ValueError(f"tenant {name!r} named twice")
        seen.add(name)
        parts = rest.split(":")
        state_dir = parts[0]
        graph = parts[1] if len(parts) > 1 and parts[1] else None
        k = int(parts[2]) if len(parts) > 2 and parts[2] else 2
        if not state_dir:
            raise ValueError(f"tenant entry {entry!r}: empty state dir")
        out.append(TenantSpec(name=name, state_dir=state_dir,
                              graph=graph, num_parts=k))
    return out


class UnknownTenant(KeyError):
    """``TENANT x`` named a tenant this daemon does not host."""

    def __init__(self, name: str, known):
        super().__init__(name)
        self.name = name
        self.message = (f"unknown tenant {name!r} (hosting: "
                        f"{'/'.join(sorted(known))})")


class Tenant:
    """One tenant's runtime state inside the daemon."""

    __slots__ = ("name", "state_dir", "graph", "num_parts", "core",
                 "admission", "hub", "replicator", "last_touch",
                 "evictions", "restores", "moved_dest", "mig")

    def __init__(self, name: str, state_dir: str, graph: str | None,
                 num_parts: int, core: ServeCore | None):
        self.name = name
        self.state_dir = state_dir
        self.graph = graph
        self.num_parts = num_parts
        self.core = core
        self.admission = None      # set by the daemon (per-tenant slots)
        self.hub = None            # leader-side ReplicationHub
        self.replicator = None     # follower-side Replicator
        self.last_touch = time.monotonic()
        self.evictions = 0
        self.restores = 0
        # migration state (ISSUE 17): moved_dest is the fence — set =
        # every client verb refuses ``ERR moved dest=<cluster>``; it is
        # re-read from the durable marker so restarts stay fenced.  mig
        # is the TARGET side's live migration record (phase / source /
        # delta puller) while an adoption is in flight, None otherwise.
        self.moved_dest = read_moved_marker(state_dir)
        self.mig = None

    def fence_moved(self, dest: str) -> None:
        """Durably fence this tenant as moved to ``dest`` (idempotent)."""
        write_moved_marker(self.state_dir, dest)
        self.moved_dest = dest

    def unfence_moved(self) -> None:
        """Abort path: lift the fence — legal ONLY while the target has
        not advanced the tenant epoch (the cutover driver's invariant)."""
        clear_moved_marker(self.state_dir)
        self.moved_dest = None

    @property
    def resident(self) -> bool:
        return self.core is not None

    def evictable(self) -> bool:
        """Cold-evictable: resident, not the default, and no replication
        machinery would be stranded by dropping the core.  A tenant with
        an in-flight re-sequence (ISSUE 18) is pinned too: sealing it
        out of memory would orphan the rebuild mid-phase.  A quarantined
        tenant (ISSUE 20) is pinned hardest of all: eviction SEALS the
        in-memory state to a snapshot, and its state is exactly what the
        quarantine says not to trust — sealing it would launder the
        divergence into a sidecar-vouched artifact."""
        if self.name == DEFAULT_TENANT or self.core is None:
            return False
        if self.replicator is not None or self.mig is not None:
            return False
        if getattr(self.core, "quarantined", False):
            return False
        if self.core.state_dir:
            from .reseq import active
            if active(self.core.state_dir):
                return False
            from .scrub import read_quarantine
            if read_quarantine(self.core.state_dir) is not None:
                return False
        return self.hub is None or self.hub.follower_count() == 0

    def priced_nbytes(self) -> int:
        core = self.core
        if core is None:
            return 0
        return serve_tenant_nbytes(len(core.seq), len(core.parts),
                                   len(core.ins_tail))


class TenantManager:
    """The daemon's tenant table: selection, lazy restore, and the
    governor-priced eviction policy.  Thread-safe: one RLock guards the
    table; restore/evict run under it (restores are rare and bounded by
    snapshot load time)."""

    def __init__(self, default_core: ServeCore,
                 specs: list[TenantSpec] | None = None,
                 governor: ResourceGovernor | None = None,
                 open_kw: dict | None = None,
                 max_resident: int | None = None):
        self.governor = governor if governor is not None \
            else default_core.governor
        self.open_kw = dict(open_kw or {})
        if max_resident is None and os.environ.get(MAX_RESIDENT_ENV):
            max_resident = int(os.environ[MAX_RESIDENT_ENV])
        self.max_resident = max_resident
        self._lock = threading.RLock()
        self._tenants: dict[str, Tenant] = {}
        dflt = Tenant(DEFAULT_TENANT, default_core.state_dir, None, 2,
                      default_core)
        self._tenants[DEFAULT_TENANT] = dflt
        for spec in specs or []:
            self._tenants[spec.name] = Tenant(
                spec.name, spec.state_dir, spec.graph, spec.num_parts,
                None)
        # re-register tenants a previous incarnation adopted mid-
        # migration (ISSUE 17): spec'd names win — an operator adding
        # the tenant to SHEEP_SERVE_TENANTS after the move is the
        # steady-state ending of a migration story
        self._adopted_path = os.path.join(default_core.state_dir,
                                          ADOPTED_FILE)
        for rec in self._load_adopted():
            name = rec.get("name")
            if name and name not in self._tenants:
                self._tenants[name] = Tenant(
                    name, rec["state_dir"], rec.get("graph"),
                    int(rec.get("num_parts", 2)), None)

    @classmethod
    def from_env(cls, default_core: ServeCore, extra_specs=None,
                 **kw) -> "TenantManager":
        specs = list(extra_specs or [])
        env = os.environ.get(TENANTS_ENV, "")
        if env:
            names = {s.name for s in specs}
            specs += [s for s in parse_tenant_specs(env)
                      if s.name not in names]
        return cls(default_core, specs, **kw)

    # -- adoption (migration targets, ISSUE 17) ----------------------------

    def _load_adopted(self) -> list[dict]:
        try:
            with open(self._adopted_path) as f:
                recs = json.load(f)
            return recs if isinstance(recs, list) else []
        except (OSError, ValueError):
            return []

    def _save_adopted(self, recs: list[dict]) -> None:
        tmp = self._adopted_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(recs, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._adopted_path)

    def adopt(self, name: str, state_dir: str, graph: str | None = None,
              num_parts: int = 2) -> Tenant:
        """Dynamically register ``name`` (a migration target adopting an
        inbound tenant).  Durable BEFORE the tenant exists in memory —
        kill -9 between the registry write and the snapshot landing
        leaves a registered-but-empty tenant the resumed migration
        re-bootstraps, never an unregistered state dir.  Idempotent:
        re-adopting an already-registered tenant returns the entry."""
        if name == DEFAULT_TENANT:
            raise ValueError("cannot adopt the default tenant")
        with self._lock:
            t = self._tenants.get(name)
            if t is not None:
                return t
            recs = [r for r in self._load_adopted()
                    if r.get("name") != name]
            recs.append({"name": name, "state_dir": state_dir,
                         "graph": graph, "num_parts": num_parts})
            self._save_adopted(recs)
            t = Tenant(name, state_dir, graph, num_parts, None)
            self._tenants[name] = t
            return t

    def drop(self, name: str) -> bool:
        """Unregister an ADOPTED tenant (migration abort: the target
        discards its partial copy).  Spec'd/default tenants refuse —
        only what adopt() added can be dropped.  The state dir is left
        on disk for the driver to discard; False when not adopted."""
        with self._lock:
            t = self._tenants.get(name)
            if t is None:
                return False
            recs = self._load_adopted()
            if not any(r.get("name") == name for r in recs):
                return False
            if t.core is not None:
                t.core.close()
                t.core = None
            self._save_adopted([r for r in recs
                                if r.get("name") != name])
            del self._tenants[name]
            return True

    # -- lookups -----------------------------------------------------------

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._tenants)

    def __len__(self) -> int:
        return len(self._tenants)

    def get(self, name: str) -> Tenant:
        """The tenant entry (resident or not); UnknownTenant if this
        daemon does not host ``name``."""
        with self._lock:
            t = self._tenants.get(name)
            if t is None:
                raise UnknownTenant(name, self._tenants)
            return t

    def resident_names(self) -> list[str]:
        with self._lock:
            return sorted(n for n, t in self._tenants.items()
                          if t.resident)

    # -- the touch path ----------------------------------------------------

    def core_of(self, name: str, _count_restore: bool = True) -> ServeCore:
        """The tenant's live core, lazily restored (or first-touch
        bootstrapped from its spec'd graph) when evicted.  The ONE entry
        point the request path uses — every touch stamps LRU time."""
        with self._lock:
            t = self.get(name)
            t.last_touch = time.monotonic()
            if t.core is None:
                t.core = self._open(t)
                if _count_restore:
                    t.restores += 1
            return t.core

    def _open(self, t: Tenant) -> ServeCore:
        if os.path.isdir(t.state_dir) and snap_paths(t.state_dir):
            return ServeCore.open(t.state_dir, governor=self.governor,
                                  **self.open_kw)
        if t.graph is None:
            raise FileNotFoundError(
                f"tenant {t.name!r}: {t.state_dir} holds no snapshots "
                f"and no graph was spec'd to bootstrap from")
        return ServeCore.bootstrap(t.state_dir, graph_path=t.graph,
                                   num_parts=t.num_parts,
                                   governor=self.governor,
                                   **self.open_kw)

    def open_all(self) -> None:
        """Eagerly open/bootstrap every tenant (daemon start on a leader
        or standalone: followers must be able to HELLO immediately).
        The start-time open is not a "restore" — that counter tracks
        evict/lazy-restore cycles.  An adopted-but-empty tenant (kill -9
        landed between the adoption registry write and the snapshot
        fetch) stays cold — the resumed migration re-bootstraps it."""
        for name in self.names():
            with self._lock:
                t = self.get(name)
                if t.core is None and t.graph is None \
                        and not (os.path.isdir(t.state_dir)
                                 and snap_paths(t.state_dir)):
                    continue
            self.core_of(name, _count_restore=False)

    # -- eviction ----------------------------------------------------------

    def evict(self, name: str) -> bool:
        """Seal ``name`` to a snapshot generation and drop its core.
        False when it is not evictable (default, already cold, or has
        replication attached); raises OSError when the seal itself
        fails — the tenant then STAYS resident (nothing was lost)."""
        with self._lock:
            t = self.get(name)
            if not t.evictable():
                return False
            core = t.core
            core.seal_snapshot()  # OSError propagates, core untouched
            core.close()
            t.core = None
            t.evictions += 1
            return True

    def priced_resident_nbytes(self) -> int:
        with self._lock:
            return sum(t.priced_nbytes()
                       for t in self._tenants.values())

    def maybe_evict_cold(self) -> list[str]:
        """The pressure valve, called after state-growing requests:
        while the governor reports memory pressure (or the resident
        count exceeds ``SHEEP_SERVE_MAX_RESIDENT``), seal-and-drop the
        coldest evictable tenant.  Returns the names evicted (empty
        almost always).  A failed seal stops the sweep — disk trouble
        must not cascade into a tenant massacre."""
        evicted: list[str] = []
        while True:
            with self._lock:
                over_count = (
                    self.max_resident is not None
                    and sum(1 for t in self._tenants.values()
                            if t.resident) > self.max_resident)
                if not over_count and not self.governor.mem_pressure():
                    return evicted
                victims = sorted(
                    (t for t in self._tenants.values() if t.evictable()),
                    key=lambda t: t.last_touch)
                if not victims:
                    return evicted
                try:
                    if not self.evict(victims[0].name):
                        return evicted
                except OSError:
                    return evicted
                evicted.append(victims[0].name)

    def close_all(self) -> None:
        with self._lock:
            for t in self._tenants.values():
                if t.core is not None:
                    t.core.close()
                    t.core = None
