"""Multi-tenancy: N serve state dirs behind one daemon (ISSUE 11).

One daemon process used to mean one graph.  Production framing — many
graphs, many users — wants N graphs behind one selectors loop instead of
N processes each paying a listener, a worker pool, and an idle-time RSS
floor.  A *tenant* is exactly one PR-6 serve state dir (snapshots + WAL
+ drift accounting), and everything crash-safety already proved about
one state dir holds per tenant by construction: the cores never share a
single array, WAL, or admission slot pool.

    SHEEP_SERVE_TENANTS = entry[,entry...]
    entry               = name=state_dir[:graph[:num_parts]]

(also ``--tenant name=dir[:graph[:k]]``, repeatable, on ``bin/serve``).
The ``default`` tenant is the daemon's ``-d`` state dir and is what a
connection talks to until it selects otherwise — the PR-7 wire grammar
is byte-identical for it.  ``TENANT <name>`` is connection-scoped: it
re-points THAT connection's verbs at another tenant's core (the router
issues it once per upstream connection).

**Memory: governor-priced eviction.**  Resident tenants are priced by
:func:`~sheep_tpu.resources.governor.serve_tenant_nbytes`; when the
process crosses the ``SHEEP_MEM_BUDGET`` soft threshold (the same
signal that turns inserts read-only) — or the operator capped resident
tenants with ``SHEEP_SERVE_MAX_RESIDENT`` — the coldest evictable
tenant is sealed to a snapshot generation and dropped from memory.
Eviction is the clean-shutdown path (seal + close), so the evicted
state is bit-identical by the same argument a restart is; the next verb
that touches the tenant lazily restores it through ``ServeCore.open`` —
the exact crash-recovery path, exercised on every eviction cycle.  A
tenant with attached replication streams never evicts (its followers
would have to re-handshake for nothing); the default tenant never
evicts (it IS the daemon's published identity).
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass

from ..resources.governor import ResourceGovernor, serve_tenant_nbytes
from .state import ServeCore, snap_paths

TENANTS_ENV = "SHEEP_SERVE_TENANTS"
MAX_RESIDENT_ENV = "SHEEP_SERVE_MAX_RESIDENT"

DEFAULT_TENANT = "default"


@dataclass
class TenantSpec:
    """One parsed ``name=dir[:graph[:k]]`` entry."""

    name: str
    state_dir: str
    graph: str | None = None
    num_parts: int = 2


def parse_tenant_specs(spec: str) -> list[TenantSpec]:
    """``SHEEP_SERVE_TENANTS`` / ``--tenant`` grammar -> specs.  Raises
    ValueError on garbage — a misspelled tenant must never silently
    vanish from the fleet."""
    out: list[TenantSpec] = []
    seen = set()
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        name, sep, rest = entry.partition("=")
        name = name.strip()
        if not sep or not name or not rest:
            raise ValueError(
                f"tenant entry {entry!r}: want name=dir[:graph[:k]]")
        if name == DEFAULT_TENANT:
            raise ValueError(
                f"tenant entry {entry!r}: {DEFAULT_TENANT!r} is the "
                f"daemon's -d state dir, not a named tenant")
        if name in seen:
            raise ValueError(f"tenant {name!r} named twice")
        seen.add(name)
        parts = rest.split(":")
        state_dir = parts[0]
        graph = parts[1] if len(parts) > 1 and parts[1] else None
        k = int(parts[2]) if len(parts) > 2 and parts[2] else 2
        if not state_dir:
            raise ValueError(f"tenant entry {entry!r}: empty state dir")
        out.append(TenantSpec(name=name, state_dir=state_dir,
                              graph=graph, num_parts=k))
    return out


class UnknownTenant(KeyError):
    """``TENANT x`` named a tenant this daemon does not host."""

    def __init__(self, name: str, known):
        super().__init__(name)
        self.name = name
        self.message = (f"unknown tenant {name!r} (hosting: "
                        f"{'/'.join(sorted(known))})")


class Tenant:
    """One tenant's runtime state inside the daemon."""

    __slots__ = ("name", "state_dir", "graph", "num_parts", "core",
                 "admission", "hub", "replicator", "last_touch",
                 "evictions", "restores")

    def __init__(self, name: str, state_dir: str, graph: str | None,
                 num_parts: int, core: ServeCore | None):
        self.name = name
        self.state_dir = state_dir
        self.graph = graph
        self.num_parts = num_parts
        self.core = core
        self.admission = None      # set by the daemon (per-tenant slots)
        self.hub = None            # leader-side ReplicationHub
        self.replicator = None     # follower-side Replicator
        self.last_touch = time.monotonic()
        self.evictions = 0
        self.restores = 0

    @property
    def resident(self) -> bool:
        return self.core is not None

    def evictable(self) -> bool:
        """Cold-evictable: resident, not the default, and no replication
        machinery would be stranded by dropping the core."""
        if self.name == DEFAULT_TENANT or self.core is None:
            return False
        if self.replicator is not None:
            return False
        return self.hub is None or self.hub.follower_count() == 0

    def priced_nbytes(self) -> int:
        core = self.core
        if core is None:
            return 0
        return serve_tenant_nbytes(len(core.seq), len(core.parts),
                                   len(core.ins_tail))


class TenantManager:
    """The daemon's tenant table: selection, lazy restore, and the
    governor-priced eviction policy.  Thread-safe: one RLock guards the
    table; restore/evict run under it (restores are rare and bounded by
    snapshot load time)."""

    def __init__(self, default_core: ServeCore,
                 specs: list[TenantSpec] | None = None,
                 governor: ResourceGovernor | None = None,
                 open_kw: dict | None = None,
                 max_resident: int | None = None):
        self.governor = governor if governor is not None \
            else default_core.governor
        self.open_kw = dict(open_kw or {})
        if max_resident is None and os.environ.get(MAX_RESIDENT_ENV):
            max_resident = int(os.environ[MAX_RESIDENT_ENV])
        self.max_resident = max_resident
        self._lock = threading.RLock()
        self._tenants: dict[str, Tenant] = {}
        dflt = Tenant(DEFAULT_TENANT, default_core.state_dir, None, 2,
                      default_core)
        self._tenants[DEFAULT_TENANT] = dflt
        for spec in specs or []:
            self._tenants[spec.name] = Tenant(
                spec.name, spec.state_dir, spec.graph, spec.num_parts,
                None)

    @classmethod
    def from_env(cls, default_core: ServeCore, extra_specs=None,
                 **kw) -> "TenantManager":
        specs = list(extra_specs or [])
        env = os.environ.get(TENANTS_ENV, "")
        if env:
            names = {s.name for s in specs}
            specs += [s for s in parse_tenant_specs(env)
                      if s.name not in names]
        return cls(default_core, specs, **kw)

    # -- lookups -----------------------------------------------------------

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._tenants)

    def __len__(self) -> int:
        return len(self._tenants)

    def get(self, name: str) -> Tenant:
        """The tenant entry (resident or not); UnknownTenant if this
        daemon does not host ``name``."""
        with self._lock:
            t = self._tenants.get(name)
            if t is None:
                raise UnknownTenant(name, self._tenants)
            return t

    def resident_names(self) -> list[str]:
        with self._lock:
            return sorted(n for n, t in self._tenants.items()
                          if t.resident)

    # -- the touch path ----------------------------------------------------

    def core_of(self, name: str, _count_restore: bool = True) -> ServeCore:
        """The tenant's live core, lazily restored (or first-touch
        bootstrapped from its spec'd graph) when evicted.  The ONE entry
        point the request path uses — every touch stamps LRU time."""
        with self._lock:
            t = self.get(name)
            t.last_touch = time.monotonic()
            if t.core is None:
                t.core = self._open(t)
                if _count_restore:
                    t.restores += 1
            return t.core

    def _open(self, t: Tenant) -> ServeCore:
        if os.path.isdir(t.state_dir) and snap_paths(t.state_dir):
            return ServeCore.open(t.state_dir, governor=self.governor,
                                  **self.open_kw)
        if t.graph is None:
            raise FileNotFoundError(
                f"tenant {t.name!r}: {t.state_dir} holds no snapshots "
                f"and no graph was spec'd to bootstrap from")
        return ServeCore.bootstrap(t.state_dir, graph_path=t.graph,
                                   num_parts=t.num_parts,
                                   governor=self.governor,
                                   **self.open_kw)

    def open_all(self) -> None:
        """Eagerly open/bootstrap every tenant (daemon start on a leader
        or standalone: followers must be able to HELLO immediately).
        The start-time open is not a "restore" — that counter tracks
        evict/lazy-restore cycles."""
        for name in self.names():
            self.core_of(name, _count_restore=False)

    # -- eviction ----------------------------------------------------------

    def evict(self, name: str) -> bool:
        """Seal ``name`` to a snapshot generation and drop its core.
        False when it is not evictable (default, already cold, or has
        replication attached); raises OSError when the seal itself
        fails — the tenant then STAYS resident (nothing was lost)."""
        with self._lock:
            t = self.get(name)
            if not t.evictable():
                return False
            core = t.core
            core.seal_snapshot()  # OSError propagates, core untouched
            core.close()
            t.core = None
            t.evictions += 1
            return True

    def priced_resident_nbytes(self) -> int:
        with self._lock:
            return sum(t.priced_nbytes()
                       for t in self._tenants.values())

    def maybe_evict_cold(self) -> list[str]:
        """The pressure valve, called after state-growing requests:
        while the governor reports memory pressure (or the resident
        count exceeds ``SHEEP_SERVE_MAX_RESIDENT``), seal-and-drop the
        coldest evictable tenant.  Returns the names evicted (empty
        almost always).  A failed seal stops the sweep — disk trouble
        must not cascade into a tenant massacre."""
        evicted: list[str] = []
        while True:
            with self._lock:
                over_count = (
                    self.max_resident is not None
                    and sum(1 for t in self._tenants.values()
                            if t.resident) > self.max_resident)
                if not over_count and not self.governor.mem_pressure():
                    return evicted
                victims = sorted(
                    (t for t in self._tenants.values() if t.evictable()),
                    key=lambda t: t.last_touch)
                if not victims:
                    return evicted
                try:
                    if not self.evict(victims[0].name):
                        return evicted
                except OSError:
                    return evicted
                evicted.append(victims[0].name)

    def close_all(self) -> None:
        with self._lock:
            for t in self._tenants.values():
                if t.core is not None:
                    t.core.close()
                    t.core = None
