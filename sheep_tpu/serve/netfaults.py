"""Deterministic NETWORK fault injection for the replicated serve
cluster.

The service-level plan (serve/faults.py) hurts one node's request
lifecycle; replication adds failure shapes that live BETWEEN nodes — a
frame lost on the wire, a link partitioned mid-stream, a slow segment, a
duplicate delivery.  ``SHEEP_SERVE_NETFAULT_PLAN`` makes each one fire on
cue at a named frame boundary of the leader's send path, so every
follower recovery claim (gap-triggered re-sync, idempotent dup drop,
heartbeat-deadline failover, reconnect-and-resume) is rehearsed
deterministically — the same discipline as ``SHEEP_IO_FAULT_PLAN`` and
``SHEEP_SERVE_FAULT_PLAN``.  Grammar::

    SHEEP_SERVE_NETFAULT_PLAN = entry[,entry...]
    entry                     = kind @ site : nth
    kind                      = drop | partition | slow | dup
    site                      = repl | hb | wleg | wbeat | wart
                              | msnap | mdelta | mcut | *
    nth                       = 0-based index of that site's firing

Sites are outbound frame classes — the replication leader's, plus the
build-worker wire's (ISSUE 16, serve/worker.py):

  repl   one REPL APPEND frame (a replicated WAL record) about to be
         sent to one follower
  hb     one REPL PING frame (the replication-stream heartbeat that
         carries the leader's latest seqno)
  wleg   one LEG dispatch (the supervisor shipping a distext leg's
         slice to a remote build worker); drop = the job never arrives
         (staleness redispatches), partition = the link dies before
         dispatch, dup = duplicate delivery to a second worker —
         first-finisher-wins arbitration must discard the loser
  wbeat  one worker->supervisor BEAT frame (the wire heartbeat);
         partition here kills the link mid-leg
  wart   the worker's artifact return; partition here tears the
         transfer mid-payload — the crc gate must refuse it and the
         supervisor redispatch exactly one leg
  msnap  one migration snapshot fetch (ISSUE 17, serve/migrate.py
         phase 1: the target leader pulling the tenant's crc-verified
         snapshot from the source); drop/partition = the fetch dies and
         the phase retries from scratch (sidecar-first landing means a
         torn fetch admits nothing), dup = the bootstrap runs twice —
         idempotent by the tmp+rename landing
  mdelta one migration delta frame (phase 2: a REPL APPEND sent to the
         migration-attached follower on the target); the recovery
         paths are the repl site's — gap-NACK re-stream, idempotent dup
         drop, reconnect-and-resume — exercised on the migration stream
         specifically
  mcut   one cutover RPC (phase 3: the router's MIG SEAL/CUT/remap
         legs); every cutover verb is idempotent, so drop/partition =
         the driver retries or aborts cleanly back to the source, dup =
         the verb lands twice and the second is a no-op
  reseq  one replicated re-sequence announce (ISSUE 18: the REPL RESEQ
         frame broadcasting the swap); drop = the follower trips the
         ``gen=`` stamp on the next APPEND instead and snapshot-adopts
         then, partition = reconnect re-HELLOs into the sig-mismatch
         snapshot answer, dup = the second frame finds the follower
         already on the announced generation and ACKs idempotently —
         every arm converges on whole-generation adoption, never a
         half-swapped tree

Kinds model the distinct network failure shapes, each driving a
DIFFERENT follower recovery path:

  drop       the frame vanishes (never sent).  The follower sees the
             seqno gap on the NEXT frame (append or ping) and answers
             ``REPL NACK`` — the leader re-streams from the follower's
             applied seqno; an insert waiting on the follower's ack
             rides through as latency, not loss.
  partition  the link dies: the connection to that follower is closed
             from the nth frame on.  The follower reconnects with a
             fresh HELLO and resumes (or, if the leader's WAL moved
             past it, snapshot-bootstraps); a partition that outlives
             the failover deadline triggers leader election instead.
  slow       the frame is delayed (the congested-link shape feeding the
             bounded-staleness accounting).
  dup        the frame is delivered twice; the follower must drop the
             second idempotently by seqno.

Counters are per-site and reset per plan install (io/faultfs.py
discipline), so "drop replication frame 3" names the same frame on every
run.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

NETFAULT_PLAN_ENV = "SHEEP_SERVE_NETFAULT_PLAN"

KINDS = ("drop", "partition", "slow", "dup")
SITES = ("repl", "hb", "wleg", "wbeat", "wart",
         "msnap", "mdelta", "mcut", "reseq", "*")

#: how long a "slow" network fault delays one frame
SLOW_S = 0.05


@dataclass
class NetFault:
    kind: str
    site: str
    nth: int

    def matches(self, site: str, index: int) -> bool:
        return (self.site == "*" or self.site == site) and index == self.nth


@dataclass
class NetFaultPlan:
    """Parsed plan; entries pop as they fire (recovery frames run
    clean)."""

    faults: list[NetFault] = field(default_factory=list)

    def take(self, site: str, index: int) -> str | None:
        for i, f in enumerate(self.faults):
            if f.matches(site, index):
                del self.faults[i]
                return f.kind
        return None


def parse_netfault_plan(spec: str) -> NetFaultPlan:
    faults = []
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        try:
            kind, at = entry.split("@", 1)
            site, nth = at.split(":", 1)
        except ValueError:
            raise ValueError(
                f"{NETFAULT_PLAN_ENV} entry {entry!r}: want kind@site:nth "
                f"(e.g. drop@repl:3)")
        kind = kind.strip()
        site = site.strip()
        if kind not in KINDS:
            raise ValueError(
                f"{NETFAULT_PLAN_ENV} entry {entry!r}: kind {kind!r} must "
                f"be one of {'/'.join(KINDS)}")
        if site not in SITES:
            raise ValueError(
                f"{NETFAULT_PLAN_ENV} entry {entry!r}: site {site!r} must "
                f"be one of {'/'.join(SITES)}")
        faults.append(NetFault(kind=kind, site=site, nth=int(nth)))
    return NetFaultPlan(faults=faults)


_plan: NetFaultPlan | None = None
_env_spec: str | None = None
_counters: dict[str, int] = {}


def install_plan(plan: NetFaultPlan | None) -> None:
    """Install (or with None, clear) the active plan and reset
    counters."""
    global _plan, _env_spec
    _plan = plan
    _env_spec = None
    _counters.clear()


def clear_plan() -> None:
    install_plan(None)


def _active_plan() -> NetFaultPlan | None:
    global _plan, _env_spec
    if _plan is not None:
        return _plan
    spec = os.environ.get(NETFAULT_PLAN_ENV, "")
    if not spec:
        return None
    if spec != _env_spec:
        _plan = parse_netfault_plan(spec)
        _env_spec = spec
        return _plan
    return None


def arm(site: str) -> str | None:
    """Count one firing of ``site`` and return the fault kind armed for
    it (None = healthy).  The caller executes the fault — dropping,
    duplicating, delaying, or closing is a SEND-path decision the
    injection layer cannot make generically."""
    index = _counters.get(site, 0)
    _counters[site] = index + 1
    plan = _active_plan()
    if plan is None:
        return None
    return plan.take(site, index)
