"""Deterministic service-level fault injection for the serve daemon.

The offline fault plans cover writes (``SHEEP_IO_FAULT_PLAN``) and
tournament legs (``SHEEP_FAULT_PLAN``); a long-lived server adds failure
shapes neither can name — the PROCESS dying between "the insert is
durable" and "the client heard OK", a handler hanging past its deadline, a
slow client squatting on a slot.  ``SHEEP_SERVE_FAULT_PLAN`` makes each
one fire on cue, at a named request boundary, so every recovery path the
daemon claims (WAL replay, typed timeout refusals, admission shedding) is
rehearsed deterministically — the same discipline as PRs 1-5.  Grammar::

    SHEEP_SERVE_FAULT_PLAN = entry[,entry...]
    entry                  = kind @ site : nth
    kind                   = kill | hang | slow
    site                   = req | query | insert | wal | apply | *
    nth                    = 0-based index of that site's firing

The sites are the boundaries of one request's lifecycle:

  req     any request, counted at dispatch (before the handler runs)
  query   a read request (part/ecv/subtree/...), at dispatch
  insert  an insert request, at dispatch — BEFORE its WAL append, so a
          kill here loses an unacknowledged insert (allowed: it was never
          acknowledged)
  wal     immediately after the insert's WAL record is fsync'd (under
          group commit: after the SHARED group fsync that covers it),
          before the OK is written — the critical boundary: a kill here
          MUST recover the insert from the log
          (kill-at-every-insert-boundary property, tests/test_serve.py)
  apply   after the in-memory apply is durable, before the OK is
          written — a kill here must change nothing on replay (the
          record is already applied; replay must be idempotent by seqno)

The leader group-commit path (ISSUE 19) adds the two boundaries that
exist BEFORE the shared fsync — both may lose the record, and both are
allowed to, because the OK is only written after the fsync:

  gc-append    inside the critical section, before the deferred
               (sync=False) WAL append — a kill here loses the insert
               entirely; it was never appended, applied, or acked
  gc-unsynced  after the deferred append + in-memory apply, before the
               shared group fsync — the record is in the OS file but
               not durable; a power cut here tears the group tail and
               replay stops at the last synced boundary (never acked,
               so nothing acked is lost)

The re-sequence job (ISSUE 18, serve/reseq.py) adds its four phase
boundaries — each one a point where kill -9 must resume or abort
cleanly off the durable reseq manifest (mid-FOLD kills are the extmem
checkpoint boundaries: ``SHEEP_FAULT_PLAN``'s ``ext-boundary`` site):

  reseq-hist  before the histogram/counting-sort sequence rebuild
              (manifest just durable at phase "hist")
  reseq-fold  before the streamed fold over .dat + WAL'd inserts
              (manifest at phase "fold", new sig pinned)
  reseq-swap  before the in-memory swap (pending tree artifact + phase
              "swap" durable; a kill here must redo the swap from the
              pending artifact, bit-identically)
  reseq-seal  after the swap, before the sealing snapshot — the
              in-memory state is new, the disk is old: a kill here
              restarts on the OLD generation and resumes the rebuild

The anti-entropy machinery (ISSUE 20, serve/scrub.py +
replicate._heal_quarantine) adds its phase boundaries — the quarantine
marker is durable BEFORE each fires, so a kill at any of them restarts
into the same phase, reads stay refused throughout, and divergent data
is never served:

  quar-enter   right after the durable quarantine marker lands on a
               VERIFY mismatch (phase "diverged")
  quar-resync  after the marker advances to phase "resync", before the
               leader snapshot fetch — a kill re-fetches idempotently
  quar-verify  after the adopted state is in place and the marker
               records the rejoin crc (phase "verify"), before the
               durable clear — a kill re-runs the (idempotent) re-sync
  quar-clear   after the marker is cleared and reads are re-admitted
  scrub-quar   right after the artifact scrubber renames a failed
               artifact to ``*.quarantined`` (before its repair)
  scrub-repair after a successful repair publishes, before the scrub
               manifest records it

Kinds:

  kill    the daemon dies instantly (``os._exit(137)`` — no atexit, no
          flushing: kill -9).  In-process harnesses install a plan with
          ``kill_mode="raise"`` and catch :class:`ServeKilled` instead,
          exactly like the supervisor's SupervisorKilled.
  hang    the handler stalls (sleeps past the request deadline, bounded
          by ``hang_cap_s``): the deadline/timeout refusal shape.
  slow    the handler stalls briefly while HOLDING its admission slot:
          the slow-client shape that drives shedding under load.

Counters are per-site and reset per plan install (same discipline as
io/faultfs.py) so "hurt insert 3" names the same request on every run.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field

SERVE_FAULT_PLAN_ENV = "SHEEP_SERVE_FAULT_PLAN"

KINDS = ("kill", "hang", "slow")
SITES = ("req", "query", "insert", "gc-append", "gc-unsynced", "wal",
         "apply", "reseq-hist", "reseq-fold", "reseq-swap", "reseq-seal",
         "quar-enter", "quar-resync", "quar-verify", "quar-clear",
         "scrub-quar", "scrub-repair",
         "*")

#: how long a "slow" fault stalls while holding its slot
SLOW_S = 0.25


class ServeKilled(RuntimeError):
    """Simulated daemon death (kill_mode="raise").  Never caught inside
    the serve stack: harnesses catch it at top level and re-open the
    state dir, exactly like a restarted process."""


@dataclass
class ServeFault:
    kind: str
    site: str
    nth: int

    def matches(self, site: str, index: int) -> bool:
        return (self.site == "*" or self.site == site) and index == self.nth


@dataclass
class ServeFaultPlan:
    """Parsed plan; entries pop as they fire (recovery requests run
    clean).  ``kill_mode``: "exit" (daemon: os._exit(137)) or "raise"
    (in-process harnesses: ServeKilled)."""

    faults: list[ServeFault] = field(default_factory=list)
    kill_mode: str = "exit"

    def take(self, site: str, index: int) -> str | None:
        for i, f in enumerate(self.faults):
            if f.matches(site, index):
                del self.faults[i]
                return f.kind
        return None


def parse_serve_fault_plan(spec: str,
                           kill_mode: str = "exit") -> ServeFaultPlan:
    faults = []
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        try:
            kind, at = entry.split("@", 1)
            site, nth = at.split(":", 1)
        except ValueError:
            raise ValueError(
                f"{SERVE_FAULT_PLAN_ENV} entry {entry!r}: want "
                f"kind@site:nth (e.g. kill@wal:3)")
        kind = kind.strip()
        site = site.strip()
        if kind not in KINDS:
            raise ValueError(
                f"{SERVE_FAULT_PLAN_ENV} entry {entry!r}: kind {kind!r} "
                f"must be one of {'/'.join(KINDS)}")
        if site not in SITES:
            raise ValueError(
                f"{SERVE_FAULT_PLAN_ENV} entry {entry!r}: site {site!r} "
                f"must be one of {'/'.join(SITES)}")
        faults.append(ServeFault(kind=kind, site=site, nth=int(nth)))
    return ServeFaultPlan(faults=faults, kill_mode=kill_mode)


_plan: ServeFaultPlan | None = None
_env_spec: str | None = None
_counters: dict[str, int] = {}


def install_plan(plan: ServeFaultPlan | None) -> None:
    """Install (or with None, clear) the active plan and reset counters."""
    global _plan, _env_spec
    _plan = plan
    _env_spec = None
    _counters.clear()


def clear_plan() -> None:
    install_plan(None)


def _active_plan() -> ServeFaultPlan | None:
    """The installed plan, else the env plan — parsed once per spec so
    fired entries and counters survive across requests (io/faultfs.py
    discipline)."""
    global _plan, _env_spec
    if _plan is not None:
        return _plan
    spec = os.environ.get(SERVE_FAULT_PLAN_ENV, "")
    if not spec:
        return None
    if spec != _env_spec:
        _plan = parse_serve_fault_plan(spec)
        _env_spec = spec
        return _plan
    return None


def arm(site: str) -> str | None:
    """Count one firing of ``site`` and return the fault kind armed for
    it (None = healthy)."""
    index = _counters.get(site, 0)
    _counters[site] = index + 1
    plan = _active_plan()
    if plan is None:
        return None
    return plan.take(site, index)


def fire(site: str, hang_s: float = 0.0) -> str | None:
    """Arm ``site`` and execute the armed fault in place:

      kill  never returns (os._exit or ServeKilled per kill_mode)
      hang  sleeps ``hang_s`` (the caller passes its deadline remainder)
      slow  sleeps SLOW_S

    Returns the kind that fired (None = healthy) so callers can account
    for it (the daemon's stats count injected faults honestly)."""
    kind = arm(site)
    if kind is None:
        return None
    from ..obs import trace as obs
    obs.event("serve.fault", site=site, kind=kind)
    if kind == "kill":
        plan = _active_plan()
        if plan is not None and plan.kill_mode == "raise":
            raise ServeKilled(f"injected kill at serve site {site!r} "
                              f"({SERVE_FAULT_PLAN_ENV})")
        os._exit(137)  # kill -9: no cleanup, no flushing, no goodbye
    if kind == "hang":
        time.sleep(max(0.0, hang_s))
    elif kind == "slow":
        time.sleep(SLOW_S)
    return kind
