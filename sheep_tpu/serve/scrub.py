"""Anti-entropy for the serving fleet (ISSUE 20): divergence markers,
the artifact scrubber, and the quarantine convention they share.

Replication (PRs 7/16) makes a follower a byte-for-byte function of the
leader's stream — but only at apply time.  Two failure shapes escape
that proof and silently rot afterwards:

  in-memory divergence   a follower whose applied STATE drifts from the
                         leader's (cosmic bit-flip, a heisenbug in one
                         build, torn memory) keeps ACKing appends
                         forever; every read it serves is a lie.
  at-rest rot            a sealed artifact (.snap, archived epoch WAL,
                         .tre/.seq/.hist) whose bytes decay after the
                         sidecar vouched for them.  Nothing re-reads a
                         sealed file until the worst moment: restart,
                         failover, bootstrap.

Both get the same answer: CONTINUOUS re-verification, a DURABLE
quarantine that refuses to serve suspect data across kill -9, and
self-healing from a replica that still proves clean.

Stream anti-entropy
  The leader stamps ``REPL VERIFY epoch= seqno= crc=`` frames into the
  replication stream every ``SHEEP_SCRUB_VERIFY_N`` records (the crc is
  :meth:`ServeCore.state_crc` captured inside the apply critical
  section, so it names exactly one state).  A follower compares its own
  state_crc at the same applied seqno; a mismatch lands the durable
  quarantine marker (phase "diverged") BEFORE the stream tears, then
  replicate._heal_quarantine walks the marker through resync -> verify
  -> clear.  kill -9 at any boundary restarts into the recorded phase;
  the daemon refuses reads (``ERR diverged``) the whole way.

The quarantine marker (``quarantine.json``)
  One JSON object, landed tmp+fsync+rename (tenants.write_moved_marker
  discipline) so it fully exists or does not.  ``phase`` walks
  diverged -> resync -> verify; :func:`clear_quarantine` unlinks it.
  The marker is the single source of truth: daemon startup sweeps it
  into ``core.quarantined``, the replicator heals off it, STATS/METRICS
  export it, and the router excludes marked members from read spread.

The artifact scrubber
  :func:`run_scrub` walks a state dir's SEALED artifacts (snapshots,
  epoch-archived WALs, worker leg outputs) re-running the exact fsck
  checkers.  A failure is renamed to ``*.quarantined`` (sidecar rides
  along as ``*.quarantined.sum``) so no loader can ever pick it up,
  then repaired: snapshots reseal from the live core or fetch
  crc-verified from the leader over the replication wire; leg artifacts
  re-derive from surviving inputs (.dat -> .seq -> .tre, the sidecar's
  recorded range -> .hist); archived WALs retire when a clean
  later-epoch snapshot already covers their records.  Every run appends
  a hash-chained record to ``scrub.json`` — fsck validates the chain,
  so a tampered-with scrub history is itself detectable.

Rehearsal: ``SHEEP_IO_FAULT_PLAN``'s post-seal ``rot@site:nth`` kind
(io/faultfs.py) flips one published byte deterministically, and the
serve fault sites quar-*/scrub-* (serve/faults.py) kill at every phase
boundary.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import time

from ..integrity.errors import IntegrityError, MalformedArtifact

# -- knobs -------------------------------------------------------------------

VERIFY_N_ENV = "SHEEP_SCRUB_VERIFY_N"
INTERVAL_ENV = "SHEEP_SCRUB_INTERVAL_S"
PACE_ENV = "SHEEP_SCRUB_PACE_S"
#: gates the CORRUPT verb (serve/daemon.py) — the bench/test-only
#: live-divergence injector a production daemon must refuse
ALLOW_CORRUPT_ENV = "SHEEP_SCRUB_ALLOW_CORRUPT"

DEFAULT_VERIFY_N = 256


def verify_cadence() -> int:
    """VERIFY-frame cadence in applied records (0 disables stamping).
    Cost scales as state_crc/N per insert on the leader — see
    PERF_NOTES.md before tightening it."""
    try:
        return max(0, int(os.environ.get(VERIFY_N_ENV, DEFAULT_VERIFY_N)))
    except ValueError:
        return DEFAULT_VERIFY_N


def scrub_interval_s() -> float:
    """Background scrub period in seconds (0 = background scrubbing
    off; ``sheep serve-ctl SCRUB`` still runs one inline)."""
    try:
        return max(0.0, float(os.environ.get(INTERVAL_ENV, "0")))
    except ValueError:
        return 0.0


def scrub_pace_s() -> float:
    """Sleep between artifacts inside one scrub pass — the pacing that
    keeps a big state dir's re-read from starving foreground I/O."""
    try:
        return max(0.0, float(os.environ.get(PACE_ENV, "0")))
    except ValueError:
        return 0.0


# -- the durable quarantine marker -------------------------------------------

QUARANTINE_NAME = "quarantine.json"

PHASE_DIVERGED = "diverged"
PHASE_RESYNC = "resync"
PHASE_VERIFY = "verify"
PHASES = (PHASE_DIVERGED, PHASE_RESYNC, PHASE_VERIFY)


def quarantine_path(state_dir: str) -> str:
    return os.path.join(state_dir, QUARANTINE_NAME)


def _land_json(path: str, rec: dict) -> None:
    """tmp + fsync + atomic rename: the marker fully exists or does not
    (a torn marker is no marker — tenants.write_moved_marker)."""
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(rec, f, indent=1, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def read_quarantine(state_dir: str) -> dict | None:
    """The dir's quarantine marker, or None when it serves clean.  An
    unreadable marker is treated as QUARANTINED with an unknown phase:
    when the evidence of divergence is itself damaged, refusing reads
    is the only honest answer."""
    path = quarantine_path(state_dir)
    if not os.path.exists(path):
        return None
    try:
        with open(path, encoding="utf-8") as f:
            rec = json.load(f)
        if not isinstance(rec, dict) or "phase" not in rec:
            raise ValueError("missing phase")
    except (OSError, ValueError):
        return {"phase": PHASE_DIVERGED, "reason": "unreadable-marker"}
    return rec


def enter_quarantine(state_dir: str, reason: str, seqno: int = 0,
                     epoch: int = 0, expect_crc: int = 0,
                     got_crc: int = 0) -> dict:
    """Durably mark ``state_dir`` diverged (phase "diverged").  Called
    BEFORE the stream tears / the caller fires its fault site, so a
    kill -9 one instruction later restarts already-quarantined.
    Idempotent: an existing marker is kept (the first divergence wins;
    re-entering must not rewind a marker already at resync/verify)."""
    existing = None
    if os.path.exists(quarantine_path(state_dir)):
        existing = read_quarantine(state_dir)
    if existing is not None and existing.get("phase") in PHASES:
        return existing
    rec = {"phase": PHASE_DIVERGED, "reason": reason,
           "seqno": int(seqno), "epoch": int(epoch),
           "expect_crc": int(expect_crc), "got_crc": int(got_crc),
           "at": time.time()}
    _land_json(quarantine_path(state_dir), rec)
    from ..obs import trace as obs
    obs.event("serve.diverged", reason=reason, seqno=int(seqno),
              expect_crc=int(expect_crc), got_crc=int(got_crc))
    return rec


def mark_phase(state_dir: str, phase: str, **fields) -> dict:
    """Advance the marker to ``phase`` (durable before the caller fires
    the matching fault site).  Extra ``fields`` (rejoin crc/seqno at
    phase "verify") land in the marker for the post-mortem trail."""
    if phase not in PHASES:
        raise ValueError(f"unknown quarantine phase {phase!r} "
                         f"(want one of {'/'.join(PHASES)})")
    rec = read_quarantine(state_dir) or {"reason": "direct"}
    rec["phase"] = phase
    rec["phase_at"] = time.time()
    for k, v in fields.items():
        rec[k] = v
    _land_json(quarantine_path(state_dir), rec)
    return rec


def clear_quarantine(state_dir: str) -> None:
    """Durably re-admit the dir (unlink is atomic; the fsync'd parent
    is the caller's restart path's problem, and a resurrected marker
    after power loss only re-runs an idempotent heal)."""
    try:
        os.unlink(quarantine_path(state_dir))
    except OSError:
        pass


# -- the quarantine naming convention ----------------------------------------

QUAR_SUFFIX = ".quarantined"

_ARCHIVE_RE = re.compile(r"^serve-e(\d{6})\.wal$")


def quarantined_paths(root: str) -> list[str]:
    """Every ``*.quarantined`` artifact under ``root`` (sidecars ride
    along as ``*.quarantined.sum`` and are not listed separately)."""
    out = []
    for dirpath, _, names in os.walk(root):
        for name in sorted(names):
            if name.endswith(QUAR_SUFFIX):
                out.append(os.path.join(dirpath, name))
    return sorted(out)


def quarantine_artifact(path: str) -> str:
    """Rename ``path`` (and its sidecar) out of every loader's sight:
    ``x.tre`` -> ``x.tre.quarantined``, ``x.tre.sum`` ->
    ``x.tre.quarantined.sum``.  The sidecar keeps pairing with the
    renamed artifact, so fsck can still say exactly HOW the bytes lie
    and a reclaim (``sheep fsck --repair``) can verify + rename back.
    Returns the quarantined name."""
    from ..integrity.sidecar import sidecar_path
    qpath = path + QUAR_SUFFIX
    side = sidecar_path(path)
    if os.path.exists(side):
        os.replace(side, sidecar_path(qpath))
    os.replace(path, qpath)
    from ..io.atomic import _fsync_dir
    _fsync_dir(path)
    return qpath


def reclaim_quarantined(qpath: str) -> str:
    """``sheep fsck --repair``'s reclaim: a quarantined artifact whose
    bytes NOW verify (the rot was transient — a flaky controller, a
    restored volume) is renamed back under its real name.  Verification
    runs on the quarantined name first; anything still corrupt raises
    and stays quarantined."""
    from ..integrity.fsck import fsck_file
    from ..integrity.sidecar import sidecar_path
    if not qpath.endswith(QUAR_SUFFIX):
        raise ValueError(f"{qpath}: not a *{QUAR_SUFFIX} artifact")
    path = qpath[:-len(QUAR_SUFFIX)]
    if os.path.exists(path):
        raise IntegrityError(
            f"{qpath}: {os.path.basename(path)} already exists — the "
            f"repair that replaced it won; refusing to clobber")
    detail = fsck_file(qpath, "strict")  # raises if still corrupt
    qside = sidecar_path(qpath)
    if os.path.exists(qside):
        os.replace(qside, sidecar_path(path))
    os.replace(qpath, path)
    from ..io.atomic import _fsync_dir
    _fsync_dir(path)
    return detail


# -- the hash-chained scrub manifest -----------------------------------------

SCRUB_MANIFEST = "scrub.json"
SCRUB_CHAIN_KEEP = 64


def scrub_manifest_path(state_dir: str) -> str:
    return os.path.join(state_dir, SCRUB_MANIFEST)


def _record_hash(rec: dict) -> str:
    body = {k: v for k, v in rec.items() if k != "hash"}
    blob = json.dumps(body, sort_keys=True).encode("utf-8")
    return hashlib.sha256(blob).hexdigest()


def load_scrub_manifest(state_dir: str) -> list[dict]:
    """The dir's scrub run history, oldest first (empty when never
    scrubbed).  Unparseable raises — the landing is atomic, so garbage
    is tampering or rot, never a crash."""
    path = scrub_manifest_path(state_dir)
    if not os.path.exists(path):
        return []
    try:
        with open(path, encoding="utf-8") as f:
            runs = json.load(f)
    except (OSError, ValueError) as exc:
        raise MalformedArtifact(f"{path}: unreadable scrub manifest "
                                f"({exc})")
    if not isinstance(runs, list):
        raise MalformedArtifact(f"{path}: scrub manifest is not a list")
    return runs


def append_scrub_record(state_dir: str, rec: dict) -> dict:
    """Chain + land one run record: ``prev`` is the last record's hash
    (\"\" for the first), ``hash`` covers the whole record, and the list
    is trimmed to SCRUB_CHAIN_KEEP with the trimmed prefix's hash kept
    as the anchor, so the retained chain still verifies."""
    runs = load_scrub_manifest(state_dir)
    rec = dict(rec)
    rec["prev"] = runs[-1]["hash"] if runs else ""
    rec["hash"] = _record_hash(rec)
    runs.append(rec)
    if len(runs) > SCRUB_CHAIN_KEEP:
        runs = runs[-SCRUB_CHAIN_KEEP:]
    _land_json(scrub_manifest_path(state_dir), runs)
    return rec


def verify_scrub_chain(state_dir: str) -> str:
    """fsck's scrub-history check: every retained record's hash must
    cover its body, and every link's ``prev`` must equal its
    predecessor's hash.  Returns a detail string; raises on a broken
    chain.  (The oldest retained record's ``prev`` is unverifiable
    after trimming — that anchor is accepted as-is, like a git shallow
    clone's boundary.)"""
    runs = load_scrub_manifest(state_dir)
    prev = None
    for i, rec in enumerate(runs):
        if not isinstance(rec, dict) or "hash" not in rec:
            raise MalformedArtifact(
                f"{scrub_manifest_path(state_dir)}: run {i} is not a "
                f"hashed record")
        if _record_hash(rec) != rec["hash"]:
            raise MalformedArtifact(
                f"{scrub_manifest_path(state_dir)}: run {i} hash does "
                f"not cover its body — edited after landing")
        if prev is not None and rec.get("prev") != prev:
            raise MalformedArtifact(
                f"{scrub_manifest_path(state_dir)}: run {i} does not "
                f"chain to run {i - 1} — a record was dropped or forged")
        prev = rec["hash"]
    return f"scrub-chain runs={len(runs)} chain-ok"


# -- the scrubber ------------------------------------------------------------

#: sealed artifact kinds one scrub pass re-verifies (the live WAL is
#: mid-append and belongs to crash recovery, not anti-entropy)
SEALED_SUFFIXES = (".snap", ".tre", ".seq", ".hist")


def sealed_artifacts(state_dir: str) -> list[str]:
    """The dir's sealed artifacts: snapshots + worker leg outputs by
    suffix, plus epoch-archived WALs (the LIVE WAL is excluded — it is
    legitimately mid-append)."""
    from .wal import archived_wal_paths
    out = []
    try:
        names = sorted(os.listdir(state_dir))
    except OSError:
        return []
    for name in names:
        if name.endswith(SEALED_SUFFIXES):
            out.append(os.path.join(state_dir, name))
    out.extend(archived_wal_paths(state_dir))
    return sorted(set(out))


def _sibling(path: str, suffix: str) -> str | None:
    """The input artifact a re-derivation needs: same stem first
    (``x.seq`` -> ``x.dat``), else the dir's UNIQUE file of that suffix
    (a state dir with one graph), else None (ambiguity is not repair)."""
    stem = os.path.splitext(path)[0] + suffix
    if os.path.exists(stem):
        return stem
    d = os.path.dirname(path) or "."
    hits = [os.path.join(d, n) for n in sorted(os.listdir(d))
            if n.endswith(suffix) and not n.endswith(QUAR_SUFFIX)]
    return hits[0] if len(hits) == 1 else None


def _repair_snap(path: str, core=None, leader=None,
                 tenant: str | None = None) -> str:
    """A rotted snapshot generation: the STATE is fine (it lives in
    memory / the WAL), only this sealed copy lies.  A live core reseals
    a fresh generation; otherwise fetch the leader's crc-verified blob
    over the replication wire (the bootstrap shape)."""
    if core is not None:
        core.seal_snapshot()
        return "resealed-from-live-core"
    if leader is None:
        raise IntegrityError(f"{path}: no live core and no leader to "
                             f"repair a snapshot from")
    from ..integrity.sidecar import write_sidecar
    from .replicate import fetch_snapshot
    from .state import load_serve_snapshot, snap_name
    host, port = leader
    blob, seqno, epoch, sig = fetch_snapshot(host, port, tenant=tenant)
    out = os.path.join(os.path.dirname(path), snap_name(seqno))
    tmp = out + ".fetch"
    with open(tmp, "wb") as f:
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    snap = load_serve_snapshot(tmp, integrity="trust")
    snap.validate()
    if sig and snap.sig != sig:
        os.unlink(tmp)
        raise IntegrityError(
            f"repair snapshot sig {snap.sig[:12]}... does not match the "
            f"advertised {sig[:12]}...")
    os.replace(tmp, out)
    write_sidecar(out)
    return f"fetched-from-leader seqno={seqno}"


def _repair_wal_archive(qpath: str) -> str:
    """An epoch-archived WAL exists only to prove the seqno hand-off
    across its promotion boundary; any clean LATER-epoch snapshot
    covers every record it held by construction.  Repair is therefore
    coverage retirement: find that snapshot, leave the rotted archive
    quarantined."""
    from .state import load_serve_snapshot, snap_paths
    m = _ARCHIVE_RE.match(os.path.basename(qpath[:-len(QUAR_SUFFIX)]))
    epoch = int(m.group(1)) if m else -1
    root = os.path.dirname(qpath)
    for snap_path in snap_paths(root):
        try:
            snap = load_serve_snapshot(snap_path, integrity="strict")
        except (IntegrityError, OSError):
            continue
        if snap.epoch > epoch:
            return (f"retired-by-snapshot "
                    f"{os.path.basename(snap_path)} epoch={snap.epoch}")
    raise IntegrityError(
        f"{qpath}: no clean later-epoch snapshot covers archived epoch "
        f"{epoch} — the archive's records are not provably redundant")


def _repair_seq(path: str) -> str:
    from ..core.sequence import degree_sequence
    from ..io.edges import load_edges
    from ..io.seqfile import write_sequence
    dat = _sibling(path, ".dat")
    if dat is None:
        raise IntegrityError(f"{path}: no sibling .dat to re-derive the "
                             f"sequence from")
    edges = load_edges(dat)
    seq = degree_sequence(edges.tail, edges.head)
    write_sequence(seq, path)
    return f"re-derived-from {os.path.basename(dat)}"


def _repair_tre(path: str) -> str:
    from ..cli.graph2tree import _tree_sig
    from ..io.seqfile import read_sequence
    from ..io.trefile import write_tree
    from ..ops.extmem import build_forest_extmem
    dat = _sibling(path, ".dat")
    seq_path = _sibling(path, ".seq")
    if dat is None or seq_path is None:
        raise IntegrityError(f"{path}: need sibling .dat + .seq to "
                             f"rebuild the tree")
    seq = read_sequence(seq_path, binary="auto")
    seq, forest = build_forest_extmem(dat, seq=seq)
    write_tree(path, forest.parent, forest.pst_weight, sig=_tree_sig(seq))
    return (f"rebuilt-from {os.path.basename(dat)}+"
            f"{os.path.basename(seq_path)}")


def _repair_hist(path: str, qpath: str) -> str:
    """The surviving sidecar (renamed along with the artifact) records
    the leg's TRUE range — re-run pass 1 over exactly that slice."""
    from ..integrity.sidecar import read_sidecar
    from ..ops.distext import write_histogram
    from ..ops.extmem import range_degree_histogram
    side = read_sidecar(qpath)
    rng = (side or {}).get("range", "")
    try:
        a, b = (int(x) for x in rng.split(":"))
    except ValueError:
        raise IntegrityError(
            f"{path}: quarantined sidecar records no range — cannot "
            f"name the slice to re-derive")
    dat = _sibling(path, ".dat")
    if dat is None:
        raise IntegrityError(f"{path}: no sibling .dat to re-derive the "
                             f"histogram from")
    # a worker's slice file holds records [a, b) at LOCAL offsets
    # [0, b-a) (worker._run_leg); a whole-graph .dat needs the true range
    lo, hi = (0, b - a) if ".slice." in os.path.basename(dat) else (a, b)
    deg, max_vid, records = range_degree_histogram(
        dat, start_edge=lo, end_edge=hi)
    write_histogram(path, deg, records, max_vid, a, b)
    return f"re-derived-from {os.path.basename(dat)} range={a}:{b}"


def repair_artifact(qpath: str, core=None, leader=None,
                    tenant: str | None = None) -> str:
    """Repair one quarantined artifact back under its real name (the
    quarantined copy STAYS — it is the evidence).  Raises IntegrityError
    when no repair input survives; the artifact then remains quarantined
    and reported, never silently dropped."""
    path = qpath[:-len(QUAR_SUFFIX)]
    if path.endswith(".snap"):
        detail = _repair_snap(path, core=core, leader=leader,
                              tenant=tenant)
    elif path.endswith(".wal"):
        detail = _repair_wal_archive(qpath)
    elif path.endswith(".seq"):
        detail = _repair_seq(path)
    elif path.endswith(".tre"):
        detail = _repair_tre(path)
    elif path.endswith(".hist"):
        detail = _repair_hist(path, qpath)
    else:
        raise IntegrityError(f"{qpath}: no repair recipe for this "
                             f"artifact kind")
    if os.path.exists(path):
        from ..integrity.fsck import fsck_file
        fsck_file(path, "strict")  # a repair that does not verify raises
    return detail


def run_scrub(state_dir: str, core=None, leader=None,
              tenant: str | None = None, pace_s: float | None = None,
              fire_faults: bool = True) -> dict:
    """One scrub pass over ``state_dir``: re-verify every sealed
    artifact, quarantine + repair failures, chain the run record.

    ``core``: the live ServeCore over this dir (enables snapshot
    resealing).  ``leader``: (host, port) of a replica to fetch
    snapshots from when there is no live core.  ``pace_s``: sleep
    between artifacts (None: the SHEEP_SCRUB_PACE_S knob).

    Returns counts: checked/failed/quarantined/repaired/unrepaired,
    plus per-artifact ``events`` [(path, verdict, detail)].
    """
    from ..integrity.fsck import fsck_file
    from ..obs import trace as obs
    from . import faults as serve_faults
    if pace_s is None:
        pace_s = scrub_pace_s()
    counts = {"checked": 0, "failed": 0, "quarantined": 0,
              "repaired": 0, "unrepaired": 0, "events": []}
    with obs.span("serve.scrub", dir=os.path.basename(state_dir)):
        # resume first: a kill between quarantine and repair on a
        # previous pass left a *.quarantined with NO real-name artifact
        # — it no longer matches the sealed walk below, so it must be
        # swept explicitly or it stays unrepaired forever
        for qpath in quarantined_paths(state_dir):
            path = qpath[:-len(QUAR_SUFFIX)]
            if os.path.exists(path):
                continue  # repaired already, or a fresh scrub's work
            if path.endswith(".wal"):
                # archive "repair" is coverage retirement: it restores
                # no real-name artifact, so re-sweeping it would
                # re-prove (and re-count) the same retirement forever.
                # The rename already IS the containment.
                continue
            if path.endswith(".snap"):
                # snapshot repair may reseal under a DIFFERENT seqno
                # filename; any surviving real-name snapshot in the dir
                # already supersedes the quarantined generation
                from .state import snap_paths
                if snap_paths(os.path.dirname(qpath) or "."):
                    continue
            try:
                detail = repair_artifact(qpath, core=core, leader=leader,
                                         tenant=tenant)
            except (IntegrityError, OSError) as exc:
                counts["unrepaired"] += 1
                counts["events"].append((path, "unrepaired", str(exc)))
                continue
            counts["repaired"] += 1
            counts["events"].append((path, "repaired",
                                     f"resumed: {detail}"))
            obs.event("scrub.repair", path=os.path.basename(path))
            if fire_faults:
                serve_faults.fire("scrub-repair")
        for path in sealed_artifacts(state_dir):
            if pace_s:
                time.sleep(pace_s)
            counts["checked"] += 1
            try:
                fsck_file(path, "strict")
                continue
            except (IntegrityError, OSError) as exc:
                counts["failed"] += 1
                verdict = str(exc)
            obs.event("scrub.rot", path=os.path.basename(path))
            qpath = quarantine_artifact(path)
            counts["quarantined"] += 1
            if fire_faults:
                serve_faults.fire("scrub-quar")
            try:
                detail = repair_artifact(qpath, core=core, leader=leader,
                                         tenant=tenant)
            except (IntegrityError, OSError) as exc:
                counts["unrepaired"] += 1
                counts["events"].append(
                    (path, "unrepaired", f"{verdict}; {exc}"))
                continue
            counts["repaired"] += 1
            counts["events"].append((path, "repaired", detail))
            obs.event("scrub.repair", path=os.path.basename(path))
            if fire_faults:
                serve_faults.fire("scrub-repair")
        append_scrub_record(state_dir, {
            "at": time.time(),
            "checked": counts["checked"],
            "failed": counts["failed"],
            "repaired": counts["repaired"],
            "unrepaired": counts["unrepaired"],
            "detail": [(os.path.basename(p), v, d)
                       for p, v, d in counts["events"]],
        })
    return counts
