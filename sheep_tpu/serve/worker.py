"""Remote build workers: distext legs over the fleet wire (ISSUE 16).

PR 13's distributed out-of-core build is single-host — its "legs" are
subprocesses sharing one filesystem with the supervisor.  This module is
the multi-host arm: a ``sheep worker`` daemon with its OWN state dir and
no shared filesystem accepts ``LEG`` jobs over the same line-protocol
family the serve tier speaks, runs the existing ``hist``/``distmap`` leg
code (ops/extmem, ops/distext) under its own ``SHEEP_MEM_BUDGET``, and
streams the sealed artifact back — crc-checked end to end, so a remote
artifact can never be admitted torn.

Wire shape (one connection per leg job, the replication snapshot-transfer
discipline: kv header naming byte counts + crcs, then exactly that many
raw bytes — serve/replicate.fetch_snapshot):

  supervisor -> worker
    LEG key=K kind=hist|distmap start=A end=B beat=S
        bytes=N crc=C seqbytes=M seqcrc=C2\\n
    <N raw .dat record-slice bytes> <M raw sequence-file bytes>

  worker -> supervisor (same connection)
    BEAT key=K\\n                     every ``beat`` seconds while the leg
                                     runs — the WIRE heartbeat; the
                                     supervisor touches the attempt's
                                     local .hb file on receipt, so the
                                     existing mtime staleness machinery
                                     (``stale_after_polls`` included)
                                     carries over verbatim
    OK key=K sumbytes=S sumcrc=CS bytes=N crc=C perfbytes=P perfcrc=CP\\n
    <S sidecar bytes> <N artifact bytes> <P perf-report bytes>

The sidecar travels FIRST (the sheep_mv_artifact ordering): a receiver
that verified the artifact crc also holds its matching checksum, and the
supervisor still fscks the fetched temp before the atomic publish — the
wire adds a transfer-integrity layer, it never replaces the admission
gate.

Identity: the worker receives records ``[A, B)`` of the original file as
a standalone slice, streams it locally as ``[0, B-A)``, and labels the
artifact with the TRUE range — per-range histograms are pure functions
of the records (write_histogram(start, end) is a label, not an offset
into the local file), and a partial forest over the shared sequence
depends only on the records and the sequence, so the returned artifact
is byte-identical to the one a shared-filesystem leg writes.  Shipping
the slice costs one wire crossing; the planner prices that against the
local-disk dispatch (plan/model.plan_transport).

Fault surface: ``SHEEP_SERVE_NETFAULT_PLAN`` gains the worker-wire sites
``wleg`` (the supervisor's LEG send), ``wbeat`` (a worker BEAT), and
``wart`` (the worker's artifact return) — drop/partition/slow/dup at
each, executed by the sender exactly like ReplicationHub._transmit.
``METRICS`` answers the standard scrape (``sheep_worker_*`` + process
gauges) so ``sheep top`` sees build workers next to serve tenants.
"""

from __future__ import annotations

import os
import socket
import threading
import time
import zlib

from .netfaults import SLOW_S, arm
from .protocol import MAX_LINE, BadRequest, err_line, parse_kv_args
from .replicate import recv_exact

#: comma list of remote build workers ("host:port[,host:port...]") the
#: distext supervisor may ship legs to
WORKER_ADDRS_ENV = "SHEEP_WORKER_ADDRS"
#: wire heartbeat interval for remote legs (BEAT frames)
WORKER_BEAT_ENV = "SHEEP_WORKER_BEAT_S"

#: address discovery for scripts (the serve.addr idiom): "host port\n"
#: in the worker's state dir, rewritten on every start
WORKER_ADDR_FILE = "worker.addr"

DEFAULT_BEAT_S = 1.0

#: chunk size for streaming slice/artifact bytes over the wire — the
#: supervisor and worker both stay O(chunk), never O(slice)
WIRE_CHUNK = 1 << 20


def payload_crc(data: bytes) -> int:
    return zlib.crc32(data) & 0xFFFFFFFF


def parse_worker_addrs(spec: str) -> list:
    """``host:port[,host:port...]`` -> [(host, port), ...] (the
    SHEEP_WORKER_ADDRS grammar; blanks skipped)."""
    out = []
    for entry in (spec or "").split(","):
        entry = entry.strip()
        if not entry:
            continue
        host, sep, port = entry.rpartition(":")
        if not sep or not host:
            raise ValueError(
                f"{WORKER_ADDRS_ENV} entry {entry!r}: want host:port")
        out.append((host, int(port)))
    return out


def file_crc(path: str, offset: int = 0, length: int | None = None) -> int:
    """Streaming crc32 of ``length`` bytes of ``path`` from ``offset``
    (None = to EOF) — the pre-pass that lets a sender put the crc in the
    header without holding the payload."""
    crc = 0
    remaining = length
    with open(path, "rb") as f:
        f.seek(offset)
        while remaining is None or remaining > 0:
            want = WIRE_CHUNK if remaining is None \
                else min(WIRE_CHUNK, remaining)
            chunk = f.read(want)
            if not chunk:
                break
            crc = zlib.crc32(chunk, crc)
            if remaining is not None:
                remaining -= len(chunk)
    if remaining:
        raise ConnectionError(
            f"{path}: short read ({remaining} byte(s) missing at "
            f"offset {offset})")
    return crc & 0xFFFFFFFF


def send_file(sock: socket.socket, path: str, offset: int = 0,
              length: int | None = None) -> int:
    """Stream ``length`` bytes of ``path`` from ``offset`` down the
    socket in O(chunk) memory; returns bytes sent."""
    sent = 0
    remaining = length
    with open(path, "rb") as f:
        f.seek(offset)
        while remaining is None or remaining > 0:
            want = WIRE_CHUNK if remaining is None \
                else min(WIRE_CHUNK, remaining)
            chunk = f.read(want)
            if not chunk:
                break
            sock.sendall(chunk)
            sent += len(chunk)
            if remaining is not None:
                remaining -= len(chunk)
    return sent


def parse_leg_header(line: str) -> dict:
    """The LEG request line -> validated job dict.  Raises BadRequest on
    anything malformed — a worker must refuse garbage before it reads a
    single payload byte (the byte counts come from this line)."""
    toks = line.split()
    if not toks or toks[0] != "LEG":
        raise BadRequest(f"expected LEG, got {line!r}")
    kv = parse_kv_args(toks[1:])
    for field in ("key", "kind", "start", "end", "bytes", "crc"):
        if field not in kv:
            raise BadRequest(f"LEG missing {field}=")
    if kv["kind"] not in ("hist", "distmap"):
        raise BadRequest(f"LEG kind {kv['kind']!r} must be hist|distmap")
    try:
        job = {
            "key": kv["key"],
            "kind": kv["kind"],
            "start": int(kv["start"]),
            "end": int(kv["end"]),
            "bytes": int(kv["bytes"]),
            "crc": int(kv["crc"]),
            "seqbytes": int(kv.get("seqbytes", "0")),
            "seqcrc": int(kv.get("seqcrc", "0")),
            "beat": float(kv.get("beat", str(DEFAULT_BEAT_S))),
        }
    except ValueError as exc:
        raise BadRequest(f"LEG bad numeric field: {exc}")
    if job["start"] < 0 or job["end"] < job["start"]:
        raise BadRequest(f"LEG bad range [{job['start']}:{job['end']})")
    if job["bytes"] != (job["end"] - job["start"]) * 12:
        raise BadRequest(
            f"LEG bytes={job['bytes']} != 12 x {job['end'] - job['start']} "
            f"records")
    if job["kind"] == "distmap" and job["seqbytes"] <= 0:
        raise BadRequest("LEG distmap needs seqbytes= (the shared "
                         "sequence every leg builds over)")
    return job


def parse_result_header(line: str) -> dict:
    """The worker's OK line -> field dict (ConnectionError on ERR/garbage
    so the supervisor's typed retry path fires)."""
    toks = line.split()
    if not toks or toks[0] != "OK":
        raise ConnectionError(f"worker refused leg: {line.strip()!r}")
    kv = parse_kv_args(toks[1:])
    for field in ("key", "sumbytes", "sumcrc", "bytes", "crc"):
        if field not in kv:
            raise ConnectionError(f"worker result missing {field}=: "
                                  f"{line.strip()!r}")
    return {"key": kv["key"], "sumbytes": int(kv["sumbytes"]),
            "sumcrc": int(kv["sumcrc"]), "bytes": int(kv["bytes"]),
            "crc": int(kv["crc"]), "perfbytes": int(kv.get("perfbytes",
                                                           "0")),
            "perfcrc": int(kv.get("perfcrc", "0"))}


class _WireBeater:
    """Daemon thread sending ``BEAT key=K`` frames every ``interval_s``
    until stopped — the wire twin of supervisor/heartbeat.HeartbeatWriter.
    Each send is a ``wbeat`` netfault site; a ``partition`` there closes
    the connection (the leg keeps running, the supervisor sees the link
    die).  Send errors stop the beater silently: the final artifact send
    will surface the broken link as a typed failure."""

    def __init__(self, sock: socket.socket, wlock: threading.Lock,
                 key: str, interval_s: float):
        self._sock = sock
        self._wlock = wlock
        self._key = key
        self.interval_s = max(0.01, interval_s)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.partitioned = False

    def start(self) -> "_WireBeater":
        self._send()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"wire-beat:{self._key}")
        self._thread.start()
        return self

    def _send(self) -> None:
        fault = arm("wbeat")
        if fault == "drop":
            return
        if fault == "partition":
            self.partitioned = True
            try:
                self._sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            self._stop.set()
            return
        if fault == "slow":
            time.sleep(SLOW_S)
        frame = f"BEAT key={self._key}\n".encode("ascii")
        with self._wlock:
            self._sock.sendall(frame)
            if fault == "dup":
                self._sock.sendall(frame)

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self._send()
            except OSError:
                return

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.interval_s)


class WorkerDaemon:
    """One remote build worker: accept loop + thread-per-leg execution.

    Shares NOTHING with the supervisor but the wire: slices land in (and
    artifacts are read back from) ``state_dir``, budgets come from this
    process's own environment (``SHEEP_MEM_BUDGET`` — the whole point of
    shipping a leg is that it folds under the worker's budget, not the
    supervisor's)."""

    def __init__(self, state_dir: str, host: str = "127.0.0.1",
                 port: int = 0, beat_s: float | None = None,
                 integrity: str | None = None):
        self.state_dir = state_dir
        self.host = host
        self.port = port
        env_beat = os.environ.get(WORKER_BEAT_ENV, "")
        self.beat_s = beat_s if beat_s is not None \
            else float(env_beat or DEFAULT_BEAT_S)
        self.integrity = integrity
        self.started_at = time.monotonic()  # uptime-gauge origin
        self._listener: socket.socket | None = None
        self._stop = threading.Event()
        self._accept_thread: threading.Thread | None = None
        from ..obs.metrics import Registry
        self.registry = Registry()
        self._inflight = self.registry.gauge(
            "sheep_worker_legs_inflight",
            "build legs currently executing on this worker")
        self._done = self.registry.counter(
            "sheep_worker_legs_done",
            "build legs completed (artifact streamed back)")
        self._shipped = self.registry.counter(
            "sheep_worker_bytes_shipped",
            "payload bytes over the leg wire, both directions")

    # -- lifecycle ---------------------------------------------------------

    @property
    def address(self) -> tuple:
        assert self._listener is not None, "worker not started"
        return self._listener.getsockname()[:2]

    def start(self) -> "WorkerDaemon":
        os.makedirs(self.state_dir, exist_ok=True)
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET,
                                  socket.SO_REUSEADDR, 1)
        self._listener.bind((self.host, self.port))
        self._listener.listen(16)
        self._listener.settimeout(0.2)
        host, port = self.address
        with open(os.path.join(self.state_dir, WORKER_ADDR_FILE),
                  "w") as f:
            f.write(f"{host} {port}\n")
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="worker-accept")
        self._accept_thread.start()
        return self

    def run_forever(self) -> None:
        while not self._stop.wait(0.5):
            pass

    def shutdown(self) -> None:
        self._stop.set()
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5.0)
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True, name="worker-conn").start()

    # -- one connection ----------------------------------------------------

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            conn.settimeout(None)
            rf = conn.makefile("rb")
            raw = rf.readline(MAX_LINE)
            if not raw:
                return
            line = raw.decode("utf-8", "replace").strip()
            verb = line.split(None, 1)[0] if line else ""
            if verb == "PING":
                conn.sendall(b"OK pong\n")
            elif verb == "METRICS":
                body = self._metrics_body().encode("utf-8")
                conn.sendall(f"OK bytes={len(body)}\n".encode("ascii"))
                conn.sendall(body)
            elif verb == "QUIT":
                conn.sendall(b"OK bye\n")
                self._stop.set()
            elif verb == "LEG":
                self._serve_leg(conn, rf, line)
            else:
                conn.sendall(
                    (err_line("badreq", f"unknown verb {verb!r}") + "\n")
                    .encode("utf-8"))
        except (OSError, ValueError):
            pass  # a dead peer mid-anything: nothing to answer
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _metrics_body(self) -> str:
        from ..obs.metrics import set_process_gauges
        set_process_gauges(self.registry, self.started_at)
        return self.registry.render()

    def _serve_leg(self, conn: socket.socket, rf, line: str) -> None:
        try:
            job = parse_leg_header(line)
        except BadRequest as exc:
            conn.sendall((err_line("badreq", str(exc)) + "\n")
                         .encode("utf-8"))
            return
        # receive + crc-verify the payloads BEFORE any disk write: a
        # torn or corrupted slice is a refusal, never a wrong artifact
        slice_bytes = recv_exact(rf, job["bytes"])
        seq_bytes = recv_exact(rf, job["seqbytes"]) if job["seqbytes"] \
            else b""
        if payload_crc(slice_bytes) != job["crc"]:
            conn.sendall((err_line("badleg", "slice crc mismatch") + "\n")
                         .encode("utf-8"))
            return
        if seq_bytes and payload_crc(seq_bytes) != job["seqcrc"]:
            conn.sendall((err_line("badleg", "sequence crc mismatch")
                          + "\n").encode("utf-8"))
            return

        self._inflight.inc(1)
        self._shipped.inc(len(slice_bytes) + len(seq_bytes))
        wlock = threading.Lock()
        beater = _WireBeater(conn, wlock, job["key"], job["beat"])
        try:
            beater.start()
            out, perf = self._run_leg(job, slice_bytes, seq_bytes)
            beater.stop()
            if beater.partitioned:
                return  # the link was netfault-killed; nothing to send
            self._send_result(conn, wlock, job["key"], out, perf)
            self._done.inc(1)
        except Exception as exc:  # noqa: BLE001 — becomes a typed wire err
            beater.stop()
            try:
                with wlock:
                    conn.sendall(
                        (err_line("legfail",
                                  f"{type(exc).__name__}: {exc}") + "\n")
                        .encode("utf-8"))
            except OSError:
                pass
        finally:
            self._inflight.inc(-1)

    # -- leg execution -----------------------------------------------------

    def _run_leg(self, job: dict, slice_bytes: bytes,
                 seq_bytes: bytes) -> tuple:
        """Run one hist/distmap leg over the LOCAL slice and return
        (artifact path, perf dict).  The slice holds records [start, end)
        of the original file at local offsets [0, end-start); artifacts
        are labeled with the TRUE range, so they are byte-identical to a
        shared-filesystem leg's (module docstring)."""
        from ..integrity.sidecar import checksummed_write
        key, kind = job["key"], job["kind"]
        a, b = job["start"], job["end"]
        local = os.path.join(self.state_dir, f"{key}.slice.dat")
        with checksummed_write(local, "wb",
                               expect_bytes=len(slice_bytes)) as f:
            f.write(slice_bytes)
        perf: dict = {}
        if kind == "hist":
            from ..ops.distext import write_histogram
            from ..ops.extmem import range_degree_histogram
            out = os.path.join(self.state_dir, f"{key}.hist")
            deg, max_vid, records = range_degree_histogram(
                local, start_edge=0, end_edge=b - a, perf=perf)
            write_histogram(out, deg, records, max_vid, a, b)
            return out, perf
        from ..cli.graph2tree import _tree_sig
        from ..io.seqfile import read_sequence
        from ..io.trefile import write_tree
        from ..ops.extmem import build_forest_extmem
        seq_path = os.path.join(self.state_dir, f"{key}.seq")
        with checksummed_write(seq_path, "wb",
                               expect_bytes=len(seq_bytes)) as f:
            f.write(seq_bytes)
        out = os.path.join(self.state_dir, f"{key}.tre")
        seq = read_sequence(seq_path)
        ck = os.path.join(self.state_dir, f"ck-{key}")
        seq, forest = build_forest_extmem(
            local, seq=seq, start_edge=0, end_edge=b - a,
            checkpoint_dir=ck, resume=True, integrity=self.integrity,
            perf=perf)
        write_tree(out, forest.parent, forest.pst_weight,
                   sig=_tree_sig(seq))
        return out, perf

    def _send_result(self, conn: socket.socket, wlock: threading.Lock,
                     key: str, out: str, perf: dict) -> None:
        """Stream the sealed artifact home, sidecar-first, each span
        crc'd in the header.  ``wart`` is the netfault site: a
        ``partition`` here closes the link mid-payload — the torn-return
        shape the supervisor's crc gate must catch."""
        import json

        from ..obs.metrics import proc_status
        with open(out + ".sum", "rb") as f:
            sum_bytes = f.read()
        art_len = os.path.getsize(out)
        art_crc = file_crc(out)
        perf_bytes = json.dumps(
            {"range": None, "perf": perf, "proc_status": proc_status()},
            sort_keys=True).encode("utf-8")
        head = (f"OK key={key} sumbytes={len(sum_bytes)} "
                f"sumcrc={payload_crc(sum_bytes)} bytes={art_len} "
                f"crc={art_crc} perfbytes={len(perf_bytes)} "
                f"perfcrc={payload_crc(perf_bytes)}\n").encode("ascii")
        fault = arm("wart")
        with wlock:
            if fault == "drop":
                return  # never sent; the supervisor's staleness redispatches
            if fault == "slow":
                time.sleep(SLOW_S)
            if fault == "partition":
                # close mid-artifact: a torn return, never admitted
                conn.sendall(head)
                conn.sendall(sum_bytes)
                send_file(conn, out, length=art_len // 2)
                try:
                    conn.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                return
            reps = 2 if fault == "dup" else 1
            for _ in range(reps):
                conn.sendall(head)
                conn.sendall(sum_bytes)
                sent = send_file(conn, out)
                conn.sendall(perf_bytes)
                self._shipped.inc(len(sum_bytes) + sent + len(perf_bytes))


def read_worker_addr(state_dir: str) -> tuple:
    """The worker's published (host, port) — the serve.addr idiom."""
    with open(os.path.join(state_dir, WORKER_ADDR_FILE)) as f:
        host, port = f.read().split()
    return host, int(port)


__all__ = [
    "DEFAULT_BEAT_S",
    "WORKER_ADDRS_ENV",
    "WORKER_ADDR_FILE",
    "WORKER_BEAT_ENV",
    "WorkerDaemon",
    "file_crc",
    "parse_leg_header",
    "parse_result_header",
    "parse_worker_addrs",
    "payload_crc",
    "read_worker_addr",
    "send_file",
]
