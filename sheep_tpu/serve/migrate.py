"""Live tenant migration: epoch-fenced cutover, zero acked-insert loss.

A tenant is nailed to the cluster the router's hash ring first picked;
this module moves one LIVE — under routed insert+read traffic — to
another cluster (ISSUE 17).  Three phases, each resumable or cleanly
abortable back to the source across kill -9 at any boundary:

  phase 1  SNAP    the target leader adopts the tenant and bootstraps
                   its state dir via the crc-verified snapshot transfer
                   (replicate.bootstrap_state_dir: sidecar-first
                   landing, fsck as the sole admission gate) — the
                   ``msnap`` netfault site guards the fetch
  phase 2  DELTA   the target streams the source leader's delta WAL as
                   a migration follower (``REPL HELLO mig=1`` → the
                   same APPEND framing with per-frame crc, gap-NACK
                   re-stream, idempotent-by-seqno dup handling; APPENDs
                   arm the ``mdelta`` site) until lag ~ 0
  phase 3  CUTOVER the epoch-fenced handover, in this exact order:
                   (a) the source seals + durably fences the tenant —
                   every later client verb answers a typed ``ERR moved
                   dest=<cluster>``, never a silent drop; (b) the delta
                   stream drains to the source's FINAL applied seqno
                   (re-confirmed against the source after the target
                   catches up, so no acked insert can hide in flight);
                   (c) the target advances the tenant epoch DURABLY
                   before accepting its first write (MIG CUT); (d) the
                   router remaps the tenant atomically and replays
                   in-flight writes — a write refused by the fence was
                   never applied at the source, so its replay at the
                   target is a first apply, not a double one.  Cutover
                   RPCs arm the ``mcut`` site.

Ownership invariant: a tenant is never unowned and never dual-owned in
the same epoch.  The fence is durable (tenants.MOVED_MARKER) before the
remap; the target's epoch advance is durable before its first write;
and while the migration is in flight the target refuses writes to the
inbound tenant (daemon INSERT guard) because it still holds the
SOURCE's epoch.  Abort is legal exactly until MIG CUT succeeds: drop
the target's adopted copy, lift the source fence, nothing was lost
because nothing ever acked anywhere but the source.  After CUT the only
way out is forward — the driver finishes the remap instead of
un-advancing an epoch (epochs only advance).

The router persists one manifest per migration (``migrate-<tenant>
.json``, tmp+fsync+rename) so a kill -9'd router resumes where it
stopped; every daemon-side MIG op is idempotent so resuming means
re-issuing, not reconstructing.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time

from . import netfaults
from .protocol import ServeError

#: overall per-migration budget; past it the driver aborts back to the
#: source (or, after CUT, keeps pushing the remap forward)
TIMEOUT_ENV = "SHEEP_MIGRATE_TIMEOUT_S"
DEFAULT_TIMEOUT_S = 120.0
#: delta lag (records) at or under which the driver enters cutover
LAG_CUT_ENV = "SHEEP_MIGRATE_LAG_CUT"
DEFAULT_LAG_CUT = 8
#: driver poll cadence while watching the delta lag drain
POLL_ENV = "SHEEP_MIGRATE_POLL_S"
DEFAULT_POLL_S = 0.05
#: wire-leg retry budget per migration RPC (each retry is a counted
#: re-dispatch; exhausting it aborts the migration)
RETRIES_ENV = "SHEEP_MIGRATE_RETRIES"
DEFAULT_RETRIES = 8

#: adopted tenants land under the target daemon's own state dir
MIG_DIR_PREFIX = "mig-"

PHASE_SNAP = "snap"
PHASE_DELTA = "delta"
PHASE_CUTOVER = "cutover"
PHASE_DONE = "done"
PHASE_ABORTED = "aborted"

#: how long the source-side seal waits for pre-fence inserts to drain
#: (applied seqno stable across polls); the driver's re-confirm loop is
#: the exact gate, this is the fast path
_SEAL_STABLE_S = 0.15
_SEAL_CAP_S = 3.0


class MigrationError(RuntimeError):
    """A migration step this node cannot honor right now (typed
    ``ERR unavailable`` on the wire; the driver retries or aborts)."""


def _knob_float(env: str, default: float) -> float:
    try:
        return float(os.environ.get(env, "") or default)
    except ValueError:
        return default


def _knob_int(env: str, default: int) -> int:
    try:
        return int(os.environ.get(env, "") or default)
    except ValueError:
        return default


# -- daemon-side ops (serve/daemon.py MIG verb delegates here) --------------


def _msnap_bootstrap(state_dir: str, host: str, port: int,
                     tenant: str) -> int:
    """Phase-1 snapshot landing with the ``msnap`` netfault site armed.
    drop/partition kill the fetch (the driver retries the whole phase —
    the tmp+rename landing means a torn fetch admitted nothing); dup
    fetches twice and lands once (idempotent by construction)."""
    from .replicate import bootstrap_state_dir, fetch_snapshot
    timeout_s = _knob_float(TIMEOUT_ENV, DEFAULT_TIMEOUT_S)
    kind = netfaults.arm("msnap")
    if kind == "slow":
        time.sleep(netfaults.SLOW_S)
    if kind in ("drop", "partition"):
        raise MigrationError(f"netfault: msnap {kind}")
    seqno = bootstrap_state_dir(state_dir, host, port,
                                timeout_s=timeout_s, tenant=tenant)
    if kind == "dup":
        # duplicate delivery: the second blob arrives and is discarded
        # (the landed snapshot already passed crc + fsck)
        fetch_snapshot(host, port, timeout_s=timeout_s, tenant=tenant)
    return seqno


def target_adopt(daemon, name: str, host: str, port: int) -> dict:
    """MIG ADOPT on the target leader: register + bootstrap + start the
    delta stream.  Idempotent and the resume entry point — re-issuing
    after a kill -9 skips whatever already landed and re-pins the delta
    stream to ``host:port`` (which may be a NEW source leader after a
    source-side failover)."""
    from .replicate import Replicator
    from .state import snap_paths
    mgr = daemon.tenants
    try:
        t = mgr.get(name)
        if t.graph is not None or (t.mig is None and t.core is not None
                                   and t.moved_dest is None
                                   and snap_paths(t.state_dir)
                                   and not _is_adopted(mgr, name)):
            raise MigrationError(
                f"target already hosts tenant {name!r}; refusing to "
                f"overwrite it with a migrated copy")
    except KeyError:
        t = mgr.adopt(name, os.path.join(daemon.core.state_dir,
                                         MIG_DIR_PREFIX + name))
    src = f"{host}:{port}"
    if not (os.path.isdir(t.state_dir) and snap_paths(t.state_dir)):
        t.mig = {"phase": PHASE_SNAP, "src": src, "replicator": None}
        _msnap_bootstrap(t.state_dir, host, port, name)
    core = mgr.core_of(name, _count_restore=False)
    old = t.mig or {}
    rep = old.get("replicator")
    if rep is not None and old.get("src") != src:
        rep.stop()  # the source leader moved: re-pin the stream
        rep = None
    if rep is None:
        rep = Replicator(core, daemon.node_id + ":mig",
                         lambda: (host, port), hb_s=daemon.cluster.hb_s,
                         events=daemon.config.events, tenant=name,
                         mig=True).start()
    t.mig = {"phase": PHASE_DELTA, "src": src, "replicator": rep}
    return {"tenant": name, "phase": PHASE_DELTA,
            "applied": core.applied_seqno, "epoch": core.epoch}


def _is_adopted(mgr, name: str) -> bool:
    return any(r.get("name") == name for r in mgr._load_adopted())


def source_seal(daemon, name: str, dest: str) -> dict:
    """MIG SEAL on the source leader: durably fence the tenant as moved
    to ``dest`` (typed ``ERR moved`` refusals from here on), seal its
    snapshot, and report the applied seqno AFTER pre-fence inserts
    drain — the number the cutover must see on the target.  Idempotent:
    re-sealing an already-fenced tenant re-reports."""
    mgr = daemon.tenants
    t = mgr.get(name)
    core = mgr.core_of(name, _count_restore=False)
    t.fence_moved(dest)
    # drain: an insert that passed the fence check before the fence
    # landed may still be applying; wait for the applied seqno to go
    # quiet so the reported cut target covers every acked insert (the
    # driver's source re-confirm loop is the exact backstop)
    deadline = time.monotonic() + _SEAL_CAP_S
    last, quiet_since = core.applied_seqno, time.monotonic()
    while time.monotonic() < deadline:
        cur = core.applied_seqno
        if cur != last:
            last, quiet_since = cur, time.monotonic()
        elif time.monotonic() - quiet_since >= _SEAL_STABLE_S:
            break
        time.sleep(0.01)
    try:
        core.seal_snapshot()
    except OSError as exc:
        raise MigrationError(f"seal failed ({exc}); tenant stays "
                             f"fenced — retry or UNSEAL to abort")
    return {"tenant": name, "dest": dest,
            "applied": core.applied_seqno, "epoch": core.epoch}


def source_unseal(daemon, name: str) -> dict:
    """MIG UNSEAL on the source leader: lift the fence (migration
    abort).  The DRIVER guarantees this is never issued after the
    target's epoch advanced — that ordering is the dual-ownership
    guard, not anything this function can check locally."""
    t = daemon.tenants.get(name)
    if t.moved_dest is None:
        return {"tenant": name, "already": 1}
    t.unfence_moved()
    return {"tenant": name, "unfenced": 1}


def target_cut(daemon, name: str, epoch: int, expect: int) -> dict:
    """MIG CUT on the target leader: verify the delta drained to
    ``expect``, stop the migration stream, and advance the tenant epoch
    DURABLY — only then do writes open (the daemon's INSERT guard keys
    on ``t.mig``).  Idempotent: a re-issued CUT against an already-
    advanced epoch reports success."""
    mgr = daemon.tenants
    t = mgr.get(name)
    core = mgr.core_of(name, _count_restore=False)
    if core.epoch >= epoch:
        _stop_mig_stream(t)
        t.mig = None
        return {"tenant": name, "epoch": core.epoch,
                "applied": core.applied_seqno, "already": 1}
    if core.applied_seqno < expect:
        raise MigrationError(
            f"delta not drained: applied {core.applied_seqno} < "
            f"expect {expect} (lag "
            f"{expect - core.applied_seqno})")
    _stop_mig_stream(t)
    try:
        core.advance_epoch(epoch)
    except OSError as exc:
        # epoch NOT advanced (advance_epoch restored it): stay fenced
        # against writes so the driver can retry or abort
        t.mig = {"phase": PHASE_DELTA, "src": (t.mig or {}).get("src"),
                 "replicator": None}
        raise MigrationError(f"epoch seal failed ({exc})")
    t.mig = None
    return {"tenant": name, "epoch": core.epoch,
            "applied": core.applied_seqno}


def _stop_mig_stream(t) -> None:
    if t.mig is not None:
        rep = t.mig.get("replicator")
        if rep is not None:
            rep.stop()
            t.mig["replicator"] = None


def target_drop(daemon, name: str) -> dict:
    """MIG DROP on the target leader: discard an adopted copy
    (migration abort).  Refuses tenants this daemon hosts for any
    reason other than adoption; idempotent on a never-adopted name."""
    mgr = daemon.tenants
    try:
        t = mgr.get(name)
    except KeyError:
        return {"tenant": name, "dropped": 0, "already": 1}
    _stop_mig_stream(t)
    t.mig = None
    return {"tenant": name, "dropped": int(mgr.drop(name))}


def mig_stat(daemon, name: str) -> dict:
    """MIG STAT anywhere: the tenant's migration-relevant numbers."""
    mgr = daemon.tenants
    t = mgr.get(name)
    rec: dict = {"tenant": name, "role": daemon.role}
    core = t.core
    if core is None:
        from .state import snap_paths
        if os.path.isdir(t.state_dir) and snap_paths(t.state_dir):
            core = mgr.core_of(name, _count_restore=False)
    if core is not None:
        rec["applied"] = core.applied_seqno
        rec["epoch"] = core.epoch
        rec["crc"] = core.state_crc()
    else:
        rec["applied"] = 0
        rec["epoch"] = 0
    if t.mig is not None:
        rec["phase"] = t.mig.get("phase", "?")
        rep = t.mig.get("replicator")
        rec["lag"] = rep.lag if rep is not None else -1
    elif t.moved_dest is not None:
        rec["phase"] = "moved"
        rec["dest"] = t.moved_dest
    else:
        rec["phase"] = "-"
    return rec


# -- the router-side driver -------------------------------------------------


def manifest_path(state_dir: str, tenant: str) -> str:
    return os.path.join(state_dir, f"migrate-{tenant}.json")


def load_manifests(state_dir: str) -> list[dict]:
    """Every persisted migration manifest in the router's state dir
    (resume scan); unreadable files are skipped, never fatal."""
    out = []
    try:
        names = os.listdir(state_dir)
    except OSError:
        return out
    for n in sorted(names):
        if not (n.startswith("migrate-") and n.endswith(".json")):
            continue
        try:
            with open(os.path.join(state_dir, n)) as f:
                rec = json.load(f)
            if isinstance(rec, dict) and rec.get("tenant"):
                out.append(rec)
        except (OSError, ValueError):
            continue
    return out


class Migration:
    """One tenant's migration, driven from the router.  ``run()`` walks
    the phases; every wire leg goes through :meth:`_rpc` (retried, and
    armed at the ``mcut`` site during cutover) and every phase change
    persists the manifest first, so kill -9 anywhere resumes."""

    def __init__(self, router, tenant: str, dest: str,
                 resume: dict | None = None):
        self.router = router
        self.tenant = tenant
        self.dest = dest
        rec = resume or {}
        self.src = rec.get("src") or router.placement_of(tenant)
        self.phase = rec.get("phase", PHASE_SNAP)
        self.cut_done = bool(rec.get("cut_done"))
        self.seal_epoch = rec.get("seal_epoch")
        self.seal_applied = rec.get("seal_applied")
        self.redispatches = 0
        self.last_lag: int | None = None
        self.error: str | None = None
        self.done = threading.Event()
        self.thread: threading.Thread | None = None
        self.timeout_s = _knob_float(TIMEOUT_ENV, DEFAULT_TIMEOUT_S)
        self.lag_cut = _knob_int(LAG_CUT_ENV, DEFAULT_LAG_CUT)
        self.poll_s = _knob_float(POLL_ENV, DEFAULT_POLL_S)
        self.retries = _knob_int(RETRIES_ENV, DEFAULT_RETRIES)

    # -- persistence -------------------------------------------------------

    def to_dict(self) -> dict:
        return {"tenant": self.tenant, "src": self.src,
                "dest": self.dest, "phase": self.phase,
                "cut_done": self.cut_done,
                "seal_epoch": self.seal_epoch,
                "seal_applied": self.seal_applied,
                "redispatches": self.redispatches,
                "error": self.error}

    def _save(self) -> None:
        sd = self.router.state_dir
        if sd is None:
            return
        path = manifest_path(sd, self.tenant)
        tmp = path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(self.to_dict(), f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except OSError:
            pass  # non-durable router: still migrates, cannot resume

    # -- wire legs ---------------------------------------------------------

    def _leader_of(self, cid: str, refresh: bool = False):
        """(cluster, (host, port)) of ``cid``'s current leader."""
        cluster = self.router.cluster_by_id(cid)
        if cluster is None:
            raise MigrationError(f"unknown cluster {cid!r}")
        leader = cluster.leader(refresh=refresh)
        if leader is None:
            raise MigrationError(f"cluster {cid} has no reachable "
                                 f"leader")
        return cluster, leader

    def _rpc(self, cid: str, line: str, site: str | None = None) -> dict:
        """One migration RPC to ``cid``'s leader, retried across
        netfaults/dead leaders; each retry counts a re-dispatch.  A
        typed ERR other than notleader surfaces as MigrationError."""
        last = "?"
        for attempt in range(self.retries + 1):
            if attempt:
                self.redispatches += 1
                time.sleep(min(0.25, self.poll_s * (1 + attempt)))
            refresh = attempt > 0
            try:
                cluster, (host, port) = self._leader_of(
                    cid, refresh=refresh)
            except MigrationError as exc:
                last = str(exc)
                continue
            kind = netfaults.arm(site) if site else None
            if kind == "slow":
                time.sleep(netfaults.SLOW_S)
            if kind in ("drop", "partition"):
                last = f"netfault: {site} {kind}"
                continue
            try:
                with socket.create_connection(
                        (host, port), timeout=10.0) as s:
                    rf = s.makefile("rb")
                    payload = (line + "\n").encode("ascii")
                    s.sendall(payload)
                    if kind == "dup":
                        s.sendall(payload)  # idempotent second landing
                    resp = rf.readline().decode("ascii").strip()
                    if not resp:
                        raise ConnectionError("peer closed mid-RPC")
            except (OSError, ConnectionError) as exc:
                cluster.forget_leader()
                last = str(exc)
                continue
            toks = resp.split()
            if toks and toks[0] == "OK":
                out = {}
                for f in toks[1:]:
                    k, _, v = f.partition("=")
                    out[k] = v
                return out
            code = toks[1] if len(toks) > 1 else "?"
            if code == "notleader":
                hint = toks[2] if len(toks) > 2 else "-"
                if hint != "-":
                    cluster.set_leader_hint(hint)
                else:
                    cluster.forget_leader()
                last = resp
                continue
            if code == "unavailable":
                last = resp  # transient (lag not drained, seal retry)
                continue
            raise MigrationError(f"{line.split()[0]} "
                                 f"{line.split()[1]}: {resp}")
        raise MigrationError(
            f"migration RPC exhausted {self.retries} retries "
            f"({line.split(None, 2)[:2]}): {last}")

    # -- the drive ---------------------------------------------------------

    def start(self) -> "Migration":
        self.thread = threading.Thread(
            target=self.run, daemon=True,
            name=f"migrate:{self.tenant}")
        self.thread.start()
        return self

    def run(self) -> None:
        try:
            self._run()
        except MigrationError as exc:
            self._abort(str(exc))
        except Exception as exc:  # never leave a migration undecided
            self._abort(f"{type(exc).__name__}: {exc}")
        finally:
            self.router.migration_finished(self)
            self.done.set()

    def _src_leader_hostport(self) -> tuple[str, int]:
        _, (host, port) = self._leader_of(self.src, refresh=False)
        return host, port

    def _run(self) -> None:
        deadline = time.monotonic() + self.timeout_s
        if self.cut_done:
            # resume after kill -9 between CUT and remap: forward only
            self._finish()
            return
        # phases 1+2: adopt (idempotent: skips what already landed,
        # re-pins the delta stream) then drain the lag
        self.phase = PHASE_SNAP if self.phase == PHASE_SNAP \
            else PHASE_DELTA
        self._save()
        host, port = self._src_leader_hostport()
        self._rpc(self.dest,
                  f"MIG ADOPT {self.tenant} host={host} port={port}")
        self.phase = PHASE_DELTA
        self._save()
        last_applied, stuck_since = -1, time.monotonic()
        while True:
            if time.monotonic() > deadline:
                raise MigrationError(
                    f"delta lag did not drain inside {self.timeout_s:g}s "
                    f"(last lag {self.last_lag})")
            st = self._rpc(self.dest, f"MIG STAT {self.tenant}")
            lag = int(st.get("lag", -1))
            applied = int(st.get("applied", 0))
            self.last_lag = max(0, lag)
            if 0 <= lag <= self.lag_cut:
                break
            if applied > last_applied:
                last_applied, stuck_since = applied, time.monotonic()
            elif time.monotonic() - stuck_since > max(1.0,
                                                      20 * self.poll_s):
                # no progress: the source leader may have moved (kill
                # -9 / failover) — re-resolve and re-pin the stream
                try:
                    _, (h, p) = self._leader_of(self.src, refresh=True)
                    self._rpc(self.dest, f"MIG ADOPT {self.tenant} "
                                         f"host={h} port={p}")
                except MigrationError:
                    pass  # keep polling; the deadline is the backstop
                stuck_since = time.monotonic()
            time.sleep(self.poll_s)
        # phase 3: fence -> drain-to-final -> epoch -> remap
        self.phase = PHASE_CUTOVER
        self._save()
        seal = self._rpc(self.src,
                         f"MIG SEAL {self.tenant} dest={self.dest}",
                         site="mcut")
        self.seal_epoch = int(seal["epoch"])
        self.seal_applied = int(seal["applied"])
        self._save()
        expect = self.seal_applied
        while True:
            if time.monotonic() > deadline:
                raise MigrationError(
                    f"cutover drain did not reach seqno {expect} "
                    f"inside {self.timeout_s:g}s")
            st = self._rpc(self.dest, f"MIG STAT {self.tenant}")
            if int(st.get("applied", 0)) >= expect:
                # re-confirm against the source: an insert that slipped
                # in before the fence landed moves the goalpost once,
                # never silently
                s2 = self._rpc(self.src, f"MIG STAT {self.tenant}")
                src_applied = int(s2.get("applied", 0))
                if src_applied <= expect:
                    break
                expect = src_applied
                self.seal_applied = expect
                self._save()
            self.last_lag = max(0, expect - int(st.get("applied", 0)))
            time.sleep(self.poll_s)
        self._rpc(self.dest,
                  f"MIG CUT {self.tenant} epoch={self.seal_epoch + 1} "
                  f"expect={expect}", site="mcut")
        self.cut_done = True
        self._save()
        self._finish()

    def _finish(self) -> None:
        self.router.remap(self.tenant, self.dest)
        self.phase = PHASE_DONE
        self.last_lag = 0
        self._save()

    def _abort(self, why: str) -> None:
        self.error = why
        if self.cut_done:
            # the target's epoch advanced: abort-back would dual-own
            # the tenant, so the only exit is forward
            try:
                self._finish()
                return
            except Exception:
                pass  # manifest keeps cut_done: the next resume retries
            return
        # back to source: drop the target's copy, lift the fence —
        # order matters (the fence lifts LAST, so at no instant is the
        # tenant writable in two places)
        for cid, line in ((self.dest, f"MIG DROP {self.tenant}"),
                          (self.src, f"MIG UNSEAL {self.tenant}")):
            try:
                self._rpc(cid, line, site="mcut")
            except MigrationError:
                pass  # best-effort; idempotent on resume/retry
        self.phase = PHASE_ABORTED
        self._save()
