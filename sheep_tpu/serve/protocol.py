"""The serve wire protocol: one line in, one line out, always typed.

Line-oriented text over a stream socket — trivially speakable from any
language, ``nc``, or a shell heredoc, and every response is a SINGLE line
so a reader never blocks mid-response.  Requests::

    [DEADLINE=<seconds>] [RID=<hex>] VERB [args...]

Prefix tokens (ISSUE 12) may appear in any order before the verb:
``DEADLINE=`` is the per-request deadline (below), ``RID=`` is the
trace-context id the router stamps so every process a request crosses
records joinable spans (obs/merge.py), and UNKNOWN ``KEY=`` prefix
tokens are skipped silently — a newer router may stamp tokens this
daemon has never heard of and the request must still parse.  Requests
carrying no prefix tokens are byte-identical to the PR-6 grammar.

    PART v [v...]        -> OK p [p...]          (-1 = vertex has no part)
    PARENT v [v...]      -> OK t [t...]   (t = <vid> | root | absent;
                            single-vid responses unchanged from PR 6)
    SUBTREE v            -> OK size=<n> pst=<w>
    SUBTREE v v [v...]   -> OK s:w [s:w...]      (absent = vid not in the
                            sequence; batches answer positionally, only
                            the single-vid form refuses typed notfound)
    TENANT [name]        -> OK tenant=<name>     (connection-scoped
                            selector, ISSUE 11: re-points THIS
                            connection's verbs at another hosted
                            tenant; with no arg reports the selection)
    EVICT name           -> OK tenant=<name> resident=0  (seal the
                            tenant to its snapshot + drop from memory;
                            next touch lazily restores)
    ECV                  -> OK ecv_down=<n> baseline=<n> drift_cut=<n>
                            parts=<k>
    INSERT u v [u v...]  -> OK seq=<wal seqno> applied=<k>
    STATS                -> OK key=value ...  (role/epoch/lag, plus the
                            per-verb req_* counts and p50_*/p99_* request
                            latencies derived from the metrics registry)
    METRICS              -> OK bytes=<n>, followed by <n> raw bytes of
                            Prometheus text exposition format (counters,
                            gauges, per-verb latency histograms —
                            obs/metrics.py; the snapshot-transfer shape:
                            one header line + length-prefixed payload)
    SNAPSHOT             -> OK snap=<filename>
    MIG OP tenant [k=v]  -> migration plumbing (ISSUE 17; the router's
                            MIGRATE verb drives these on the two
                            leaders): ADOPT bootstraps + delta-streams
                            the tenant here, SEAL fences the source
                            (``ERR moved dest=``), CUT advances the
                            target's tenant epoch durably before its
                            first write, UNSEAL aborts back to source,
                            DROP discards an adopted copy, STAT reports
                            phase/lag
    REPARTITION          -> OK parts=<k> baseline=<n>
    PING                 -> OK pong
    QUIT                 -> OK bye (connection closes)

Replication (ISSUE 7) speaks the same line grammar under one verb; see
serve/replicate.py for the frame codec and the stream lifecycle::

    REPL HELLO node=<id> epoch=<e> seqno=<s> sig=<sig>
        -> OK mode=stream epoch=<E> seqno=<S>     (conn becomes a stream)
        -> OK mode=snapshot bytes=<n> seqno=<S> epoch=<E> crc=<c>
           followed by <n> raw snapshot bytes, then the stream
    REPL SNAPSHOT [tenant=<t>]
                         -> OK bytes=<n> seqno=<S> epoch=<E> crc=<c>
                            sig=<sig>, followed by <n> raw bytes
                            (bootstrap fetch; conn stays line-mode)
    REPL VOTE epoch=<e> candidate=<id> seqno=<s>
                         -> OK grant=0|1 epoch=<mine> node=<me>
                            (quorum-vote election ballot, ISSUE 11:
                            one grant per epoch per voter; conn stays
                            line-mode.  HELLO takes tenant=<t> too —
                            one stream per tenant per follower.)
    leader -> follower stream frames (one line each):
        REPL APPEND epoch=<E> seqno=<n> crc=<c> data=<base64>
        REPL PING epoch=<E> seqno=<S>
    follower -> leader on the stream connection:
        REPL ACK seqno=<n>        (everything <= n durable + applied here)
        REPL NACK expect=<n>      (gap/corrupt frame; re-stream from n)
        REPL FENCED epoch=<e>     (your term is over: I live in epoch e)

``DEADLINE=`` overrides the daemon's default per-request deadline; a
request that cannot finish inside it gets ``ERR timeout ...`` — a typed
refusal, never a silent stall (the client's clock is the one that
matters, so the server refuses rather than answers late).

Errors are ``ERR <code> <message>`` with codes::

    badreq      unparseable request (client bug)
    timeout     deadline exceeded (typed timeout refusal)
    overload    admission shed this request (retry with backoff)
    readonly    inserts refused: explicit flag or memory pressure
    notfound    the named vertex is not in the sequence
    notleader   this node is a follower; the payload is the leader's
                ``host:port`` (or ``-`` while unknown) — writes redirect
                there instead of splitting the brain
    moved       the tenant has been migrated away (ISSUE 17): the
                payload carries ``dest=<cluster>`` naming the new home.
                A router re-resolves the tenant's placement and replays
                the request there — the same retry shape as notleader.
                Never a silent drop: a fenced source REFUSES so no
                write can land on a tenant that lives elsewhere
    stale       this follower's replication lag exceeds the configured
                bound (SHEEP_SERVE_MAX_LAG); reads refuse rather than
                silently answer from the past
    fenced      a replication peer spoke with a LATER epoch: this
                node's term is over and it is demoting
    badrepl     a replication handshake/frame this node cannot honor
                (sig mismatch, unparseable frame)
    unavailable a dependency is missing (no graph edges for ECV; the
                disk refused a WAL append or snapshot; a replication
                quorum did not acknowledge in time)
    internal    unexpected server-side failure (bug; logged server-side)

PART and INSERT batch naturally: many vertices / edge pairs per line, one
round-trip.  :class:`ServeClient` is the reference client used by the
tests, the tier-1 smoke, and scripts/servebench.py.
"""

from __future__ import annotations

import socket
import time
from dataclasses import dataclass, field

#: verbs that read state (admission kind "query"); TENANT is the
#: connection-scoped selector (ISSUE 11) and never holds a slot.  CRC
#: (ISSUE 20) answers the tenant's state_crc at its applied seqno — the
#: anti-entropy comparison point benches and smokes key divergence on
QUERY_VERBS = ("PART", "PARENT", "SUBTREE", "ECV", "STATS", "METRICS",
               "PING", "TENANT", "CRC")
#: verbs that mutate state (admission kind "insert", shed first)
INSERT_VERBS = ("INSERT",)
#: operator verbs (admitted as queries; SNAPSHOT/REPARTITION do their own
#: locking in the core, EVICT seals a cold tenant out of memory).  MIG
#: (ISSUE 17) is the daemon-side migration surface the router's MIGRATE
#: verb drives: ``MIG ADOPT|SEAL|UNSEAL|CUT|DROP|STAT <tenant> [k=v...]``.
#: RESEQ (ISSUE 18) forces the crash-safe re-sequence rebuild the
#: sequence-drift detector would otherwise trigger on its own.  SCRUB
#: (ISSUE 20) forces one inline anti-entropy pass over the tenant's
#: sealed artifacts; CORRUPT flips one live byte (refused unless
#: SHEEP_SCRUB_ALLOW_CORRUPT=1 — a bench/test-only divergence injector)
ADMIN_VERBS = ("SNAPSHOT", "REPARTITION", "RESEQ", "SCRUB", "CORRUPT",
               "EVICT", "MIG", "QUIT")
#: the replication family (serve/replicate.py): handled OUTSIDE admission
#: — a configured replica is cluster plumbing, not client load, and
#: shedding it would turn an overload into a lag spiral
REPL_VERBS = ("REPL",)
#: the build-worker wire (ISSUE 16, serve/worker.py): LEG ships a distext
#: leg's slice to a ``sheep worker`` daemon, BEAT is the worker's wire
#: heartbeat back.  Spoken only between a distext supervisor and worker
#: daemons (which also answer PING/METRICS/QUIT in the shared grammar) —
#: a serve daemon refuses them like any unknown verb
WORKER_VERBS = ("LEG", "BEAT")

#: protocol line-length cap: a request that does not fit is a bad request,
#: not an invitation to buffer without bound
MAX_LINE = 1 << 20


class BadRequest(Exception):
    """Unparseable request line; maps to ``ERR badreq``."""


class DeadlineExceeded(Exception):
    """The request ran past its deadline; maps to ``ERR timeout``."""


@dataclass
class Request:
    verb: str
    args: list[str] = field(default_factory=list)
    deadline_s: float | None = None  # None: the daemon default applies
    rid: str | None = None           # trace-context id (RID= prefix token)

    @property
    def kind(self) -> str:
        return "insert" if self.verb in INSERT_VERBS else "query"


#: rid charset: hex (what routers mint) plus ``-`` so foreign tracing
#: systems can forward their ids; anything else is a typed badreq
#: (compiled: per-request validation must price like a token)
import re as _re
_RID_RE = _re.compile(r"[0-9a-fA-F-]{1,64}\Z")
MAX_RID_LEN = 64


def split_prefix_tokens(toks: list[str]):
    """The optional-prefix grammar (ISSUE 12): leading ``KEY=value``
    tokens (KEY alphabetic) precede the verb.  ``DEADLINE=`` and
    ``RID=`` are understood; UNKNOWN keys are skipped silently — a
    newer router may stamp tokens this daemon has never heard of, and
    the request must still parse (the grammar is byte-identical for
    requests carrying no prefix tokens).  Returns ``(deadline, rid,
    verb_index)``; raises BadRequest for malformed known tokens."""
    deadline = None
    rid = None
    i = 0
    for i, tok in enumerate(toks):
        eq = tok.find("=")
        if eq <= 0:
            return deadline, rid, i
        key = tok[:eq]
        if not (key.isascii() and key.isalpha()):
            return deadline, rid, i
        val = tok[eq + 1:]
        key = key.upper()
        if key == "DEADLINE":
            try:
                deadline = float(val)
            except ValueError:
                raise BadRequest(f"unparseable deadline {val!r}")
            if deadline < 0:
                raise BadRequest(f"negative deadline {val!r}")
        elif key == "RID":
            if _RID_RE.match(val) is None:
                raise BadRequest(f"unparseable request id {val!r}")
            rid = val
        # any other KEY= prefix token: ignored (forward compatibility)
    return deadline, rid, len(toks)


def parse_request(line: str) -> Request:
    toks = line.split()
    if not toks:
        raise BadRequest("empty request")
    deadline, rid, i = split_prefix_tokens(toks)
    toks = toks[i:]
    if not toks:
        raise BadRequest("prefix token(s) with no request")
    verb = toks[0].upper()
    if verb not in QUERY_VERBS + INSERT_VERBS + ADMIN_VERBS + REPL_VERBS:
        raise BadRequest(f"unknown verb {toks[0]!r}")
    return Request(verb=verb, args=toks[1:], deadline_s=deadline,
                   rid=rid)


def parse_kv_args(args: list[str]) -> dict:
    """``key=value`` argument tokens -> dict (REPL frames, HELLO)."""
    out = {}
    for tok in args:
        k, sep, v = tok.partition("=")
        if not sep or not k:
            raise BadRequest(f"expected key=value, got {tok!r}")
        out[k] = v
    return out


def parse_vids_batch(args: list[str]):
    """The vectorized vid-list decode (ISSUE 11): one numpy parse of the
    whole token list instead of a Python int() loop — the front half of
    the batched-verb fast path (state.part_batch is the back half).

    Errors carry the EXACT offending token and its 0-based position, and
    every bad batch is a typed ``ERR badreq`` with nothing answered —
    the same all-or-nothing contract as the scalar parser."""
    if not args:
        raise BadRequest("expected vertex ids")
    import numpy as np
    try:
        vids = np.array(args, dtype=np.int64)
    except (ValueError, OverflowError):
        # slow path: name the exact bad token, or clamp a valid-but-
        # oversized id (any id past int64 is outside every table, so it
        # answers the same absent sentinel the scalar path gave it)
        vids = np.empty(len(args), dtype=np.int64)
        for i, a in enumerate(args):
            try:
                v = int(a)
            except ValueError:
                raise BadRequest(
                    f"non-integer vertex id {a!r} at position {i}")
            if v < 0:
                raise BadRequest(
                    f"negative vertex id {args[i]} at position {i}")
            vids[i] = min(v, (1 << 63) - 1)
        return vids
    neg = np.flatnonzero(vids < 0)
    if neg.size:
        i = int(neg[0])
        raise BadRequest(f"negative vertex id {args[i]} at position {i}")
    return vids


def parse_vids(args: list[str], want_pairs: bool = False) -> list[int]:
    if not args:
        raise BadRequest("expected vertex ids")
    try:
        vids = [int(a) for a in args]
    except ValueError:
        raise BadRequest(f"non-integer vertex id in {args!r}")
    if any(v < 0 for v in vids):
        raise BadRequest("negative vertex id")
    if want_pairs and len(vids) % 2:
        raise BadRequest(f"INSERT wants u v pairs, got {len(vids)} ids")
    return vids


def ok_line(*fields) -> str:
    return " ".join(["OK"] + [str(f) for f in fields])


def ok_kv(**kv) -> str:
    return "OK " + " ".join(f"{k}={v}" for k, v in kv.items())


def err_line(code: str, msg: str) -> str:
    return f"ERR {code} " + " ".join(str(msg).split())


class ServeError(RuntimeError):
    """Client-side face of an ``ERR`` response."""

    def __init__(self, code: str, msg: str):
        super().__init__(f"{code}: {msg}")
        self.code = code
        self.detail = msg


class ServeClient:
    """Minimal blocking client for one connection.

    ``request`` returns the raw response line; the typed helpers raise
    :class:`ServeError` on ``ERR`` so tests and scripts cannot mistake a
    refusal for data.
    """

    def __init__(self, host: str, port: int, timeout_s: float = 30.0):
        self.sock = socket.create_connection((host, port),
                                             timeout=timeout_s)
        self._rf = self.sock.makefile("rb")

    def close(self) -> None:
        try:
            self._rf.close()
        finally:
            self.sock.close()

    def __enter__(self) -> "ServeClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def request(self, line: str) -> str:
        self.sock.sendall(line.encode("ascii") + b"\n")
        resp = self._rf.readline()
        if not resp:
            raise ConnectionError("server closed the connection "
                                  "(killed mid-request?)")
        return resp.decode("ascii").rstrip("\n")

    def _ok(self, line: str) -> list[str]:
        resp = self.request(line)
        toks = resp.split()
        if not toks or toks[0] != "OK":
            if toks and toks[0] == "ERR":
                raise ServeError(toks[1] if len(toks) > 1 else "unknown",
                                 " ".join(toks[2:]))
            raise ServeError("protocol", f"unparseable response {resp!r}")
        return toks[1:]

    def ping(self) -> None:
        self._ok("PING")

    def part(self, vids) -> list[int]:
        out = self._ok("PART " + " ".join(str(v) for v in vids))
        return [int(p) for p in out]

    def parent(self, vids) -> list:
        """Batched PARENT: per-vid parent vid, ``"root"``, or
        ``"absent"``."""
        out = self._ok("PARENT " + " ".join(str(v) for v in vids))
        return [t if t in ("root", "absent") else int(t) for t in out]

    def tenant(self, name: str) -> str:
        """Select ``name`` for every later verb on THIS connection."""
        out = self._ok(f"TENANT {name}")
        return dict(f.split("=", 1) for f in out)["tenant"]

    def insert(self, pairs) -> int:
        """pairs: iterable of (u, v); returns the batch's WAL seqno."""
        flat = " ".join(f"{int(u)} {int(v)}" for u, v in pairs)
        out = self._ok("INSERT " + flat)
        return int(dict(f.split("=", 1) for f in out)["seq"])

    def metrics(self) -> str:
        """``METRICS`` -> the Prometheus text scrape body (the header's
        ``bytes=`` count covers the payload including its final
        newline)."""
        out = self._ok("METRICS")
        n = int(dict(f.split("=", 1) for f in out)["bytes"])
        data = b""
        while len(data) < n:
            chunk = self._rf.read(n - len(data))
            if not chunk:
                raise ConnectionError("server closed mid-METRICS payload")
            data += chunk
        return data.decode("ascii")

    def kv(self, verb: str) -> dict:
        """STATS / ECV / REPARTITION-style key=value responses."""
        out = self._ok(verb)
        rec = {}
        for f in out:
            k, _, v = f.partition("=")
            try:
                rec[k] = int(v)
            except ValueError:
                rec[k] = v
        return rec


def connect_retry(host: str, port: int, timeout_s: float = 30.0,
                  poll_s: float = 0.05) -> ServeClient:
    """Connect + PING with retries — the 'wait for the daemon to come
    (back) up' helper the recovery tests and servebench time."""
    deadline = time.monotonic() + timeout_s
    last: Exception | None = None
    while time.monotonic() < deadline:
        try:
            c = ServeClient(host, port, timeout_s=max(1.0, poll_s * 10))
            try:
                c.ping()
                return c
            except Exception:
                c.close()
                raise
        except (OSError, ServeError) as exc:
            last = exc
            time.sleep(poll_s)
    raise TimeoutError(f"serve daemon at {host}:{port} not answering "
                       f"after {timeout_s}s ({last})")
