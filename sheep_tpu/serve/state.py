"""The serve daemon's resident state: snapshot + WAL = the whole truth.

``ServeCore`` owns everything below the socket: the elimination tree
(parent/pst over jnid space), the vid-indexed partition, the optional
resident edge list (for exact ECV), the write-ahead log, and the snapshot
lifecycle.  It is deliberately socket-free so property tests and the fsck
tool drive the exact code the daemon runs.

**Incremental insert.**  An arriving edge {u, v} maps to a link
(lo, hi) by sequence position and is folded into the live tree by the
union-find transform the whole framework is built on (core/forest.py):
the merge-associativity property says the post-insert tree equals a full
rebuild over (old links + new link), and because links only ever attach a
component's max element to a later vertex, that fold has a local form —
climb lo's parents to its maximal ancestor r below hi (the component
representative under threshold-hi connectivity), attach ``parent[r] = hi``,
and re-insert r's displaced old link, whose hi is strictly larger, so the
cascade terminates at a root ("Work-Efficient Parallel and Incremental
Graph Connectivity", PAPERS.md — no rebuild).  Absent endpoints follow the
offline contract (core/forest.edges_to_positions): one endpoint in the
sequence -> pst-only; both absent or a self-loop -> recorded but inert.
The transform is deterministic, which is what makes WAL replay
bit-identical (serve/wal.py).

**Durability order** (every insert): WAL append + fsync -> in-memory
apply -> acknowledge.  Snapshots seal sidecar-first through the PR-5
writers (integrity.sidecar.sealed_write, fault site ``snap``), then the
WAL is atomically replaced by a fresh one; a crash between the two leaves
already-applied records in the log, which replay skips by seqno.  Restart
= newest loadable snapshot + replay of records with larger seqnos.

**Partition drift.**  Inserts are counted against the partition they
arrive under (an insert whose endpoints live in different parts raises
ECV(down) by at most 1); when the accumulated cut count crosses the drift
threshold the owner (daemon) runs :meth:`repartition` in the background —
queries keep answering from the stale-but-consistent partition until the
new one swaps in atomically under the state lock.
"""

from __future__ import annotations

import glob
import os
import re
import struct
import threading
import time
import warnings
from collections import deque

import numpy as np

from .. import INVALID_JNID, INVALID_PART
from ..core.forest import Forest
from ..core.sequence import (host_degree_histogram, sequence_positions)
from ..integrity.errors import IntegrityError, MalformedArtifact
from ..integrity.sidecar import resolve_policy, sealed_write, sidecar_path
from ..obs import trace as _trace
from ..partition.tree_partition import (TreePartitionOptions,
                                        partition_forest)
from ..resources import ResourceGovernor, gc_orphan_temps
from ..runtime.snapshot import input_signature
from . import faults as serve_faults
from .wal import (WalAppender, archived_wal_name, create_wal, read_wal,
                  repair_wal, wal_path)

SNAP_VERSION = 1
SNAP_RE = re.compile(r"^snap-(\d{12})\.snap$")

#: sentinels of the vectorized parent gather (:meth:`ServeCore.
#: parent_batch`): an int64 lane that cannot be a vid encodes the two
#: non-vid answers of the scalar walk ("root" / "absent")
PARENT_ROOT = -1
PARENT_ABSENT = -2

#: serve state dirs keep this many sealed snapshots (the live one plus a
#: fallback the repair policy can reach for if the newest goes bad)
KEEP_SNAPSHOTS = 2

#: how many recent records the in-memory replication window retains; a
#: follower further behind than this bootstraps from a snapshot instead
REPL_TAIL_KEEP = 4096

#: lock-free read attempts before a seqlock read falls back to the state
#: lock (ISSUE 19) — bounds reader starvation under a write storm
_SEQLOCK_TRIES = 3


def snap_name(applied_seqno: int) -> str:
    return f"snap-{applied_seqno:012d}.snap"


def snap_paths(state_dir: str) -> list[str]:
    """Snapshot files in the dir, oldest first (by applied seqno)."""
    out = []
    for path in glob.glob(os.path.join(glob.escape(state_dir),
                                       "snap-*.snap")):
        if SNAP_RE.match(os.path.basename(path)):
            out.append(path)
    return sorted(out)


# -- insert payload codec ---------------------------------------------------

_PAIRS_HEAD = struct.Struct("<I")


def encode_inserts(pairs: np.ndarray) -> bytes:
    """(k, 2) uint32 edge array -> WAL record payload."""
    pairs = np.ascontiguousarray(pairs, dtype="<u4")
    if pairs.ndim != 2 or pairs.shape[1] != 2:
        raise ValueError(f"insert batch must be (k, 2), got {pairs.shape}")
    return _PAIRS_HEAD.pack(len(pairs)) + pairs.tobytes()


def decode_inserts(payload: bytes) -> np.ndarray:
    if len(payload) < _PAIRS_HEAD.size:
        raise MalformedArtifact(
            f"insert record payload of {len(payload)} bytes is shorter "
            f"than its count header")
    (k,) = _PAIRS_HEAD.unpack_from(payload, 0)
    body = payload[_PAIRS_HEAD.size:]
    if len(body) != 8 * k:
        raise MalformedArtifact(
            f"insert record claims {k} pairs but carries {len(body)} "
            f"payload bytes (want {8 * k})")
    return np.frombuffer(body, dtype="<u4").reshape(k, 2).copy()


# -- snapshot format --------------------------------------------------------


class ServeSnapshot:
    """One sealed serving state (see module docstring for why this tuple
    is complete): tree + partition + cumulative inserted edges + the WAL
    seqno folded in so far + the replication epoch that sealed it."""

    def __init__(self, seq, parent, pst, parts, num_parts, applied_seqno,
                 ins_tail, ins_head, drift_cut, baseline_ecv, graph_path,
                 sig, balance, epoch=0, epoch_base=0, deg=None,
                 deg_base=None, seq_drift=0, reseqs=0, seq_gen=0,
                 ins_base=0):
        self.seq = seq
        self.parent = parent
        self.pst = pst
        self.parts = parts
        self.num_parts = int(num_parts)
        self.applied_seqno = int(applied_seqno)
        self.ins_tail = ins_tail
        self.ins_head = ins_head
        self.drift_cut = int(drift_cut)
        self.baseline_ecv = int(baseline_ecv)
        self.graph_path = graph_path
        self.sig = sig
        self.balance = float(balance)
        self.epoch = int(epoch)
        #: the applied seqno at which this epoch began (the promotion
        #: boundary): an old-epoch replica at or below it shares our
        #: record prefix and may stream; past it, it may have a
        #: divergent tail and must snapshot-resync
        self.epoch_base = int(epoch_base)
        #: incremental degree histogram (ISSUE 18): vid-indexed int64
        #: counts over (graph + inserted) edges, maintained as two +1s
        #: per insert; None in a pre-reseq snapshot (recounted on load)
        self.deg = deg
        #: the histogram at the moment the CURRENT sequence was
        #: established (bootstrap or last re-sequence) — degree-rank
        #: movement is measured against it
        self.deg_base = deg_base
        self.seq_drift = int(seq_drift)
        self.reseqs = int(reseqs)
        #: sequence generation: bumped by every re-sequence swap; the
        #: reseq manifest chains (gen, sig) pairs so fsck and the
        #: replication handshake can tell a planned sequence change
        #: from corruption
        self.seq_gen = int(seq_gen)
        #: how many inserted edges the current sequence already covers
        #: (the re-sequence cut); the drift fraction is measured over
        #: inserts past it
        self.ins_base = int(ins_base)

    def validate(self) -> None:
        problems = []
        if self.epoch < 0:
            problems.append(f"negative epoch {self.epoch}")
        m = len(self.seq)
        if len(self.parent) != m or len(self.pst) != m:
            problems.append(
                f"tree arrays disagree with the sequence: "
                f"{len(self.parent)} parent / {len(self.pst)} pst / {m} seq")
        else:
            linked = self.parent != INVALID_JNID
            ids = np.arange(m, dtype=np.uint32)
            if bool((linked & (self.parent >= m)).any()):
                problems.append("parent pointer out of range")
            elif bool((linked & (self.parent <= ids)).any()):
                problems.append("non-monotone parent pointer "
                                "(parents must be strictly later)")
        if m and len(self.parts) <= int(self.seq.max()):
            problems.append(
                f"partition covers {len(self.parts)} vids but the "
                f"sequence names vid {int(self.seq.max())}")
        if len(self.ins_tail) != len(self.ins_head):
            problems.append(
                f"inserted-edge arrays disagree: {len(self.ins_tail)} "
                f"tails vs {len(self.ins_head)} heads")
        if self.applied_seqno < 0 or self.drift_cut < 0:
            problems.append("negative counters")
        if (self.seq_drift < 0 or self.reseqs < 0 or self.seq_gen < 0
                or self.ins_base < 0):
            problems.append("negative re-sequence counters")
        if self.ins_base > len(self.ins_tail):
            problems.append(
                f"re-sequence cut {self.ins_base} past the "
                f"{len(self.ins_tail)} inserted edges")
        if self.deg is not None and (
                len(self.deg) != len(self.parts)
                or self.deg_base is None
                or len(self.deg_base) != len(self.parts)):
            problems.append("degree histogram disagrees with the "
                            "vid tables")
        if self.num_parts < 1:
            problems.append(f"num_parts {self.num_parts} < 1")
        if problems:
            raise MalformedArtifact(
                "corrupt serve snapshot — " + "; ".join(problems))

    def nbytes_estimate(self) -> int:
        deg = 0 if self.deg is None \
            else self.deg.nbytes + self.deg_base.nbytes
        return (self.seq.nbytes + self.parent.nbytes + self.pst.nbytes
                + self.parts.nbytes + self.ins_tail.nbytes
                + self.ins_head.nbytes + deg + 4096)


def save_serve_snapshot(path: str, snap: ServeSnapshot,
                        governor: ResourceGovernor | None = None) -> None:
    """Seal one snapshot sidecar-first (integrity.sidecar.sealed_write):
    a crash or an injected ``snap``-site fault (io/faultfs.py) aborts
    with the previous snapshot generation intact."""
    snap.validate()
    est = snap.nbytes_estimate()
    gov = governor if governor is not None else ResourceGovernor.from_env()
    gov.check_dir_budget(os.path.dirname(os.path.abspath(path)) or ".",
                         est, "serve snapshot")
    fields = {}
    if snap.deg is not None:
        fields["deg"] = np.asarray(snap.deg, dtype=np.int64)
        fields["deg_base"] = np.asarray(snap.deg_base, dtype=np.int64)
    with sealed_write(path, "wb", expect_bytes=est) as f:
        np.savez(
            f,
            version=np.int64(SNAP_VERSION),
            seq=np.asarray(snap.seq, dtype=np.uint32),
            parent=np.asarray(snap.parent, dtype=np.uint32),
            pst=np.asarray(snap.pst, dtype=np.uint32),
            parts=np.asarray(snap.parts, dtype=np.int64),
            num_parts=np.int64(snap.num_parts),
            applied_seqno=np.int64(snap.applied_seqno),
            ins_tail=np.asarray(snap.ins_tail, dtype=np.uint32),
            ins_head=np.asarray(snap.ins_head, dtype=np.uint32),
            drift_cut=np.int64(snap.drift_cut),
            baseline_ecv=np.int64(snap.baseline_ecv),
            graph_path=np.str_(snap.graph_path or ""),
            sig=np.str_(snap.sig),
            balance=np.float64(snap.balance),
            epoch=np.int64(snap.epoch),
            epoch_base=np.int64(snap.epoch_base),
            seq_drift=np.int64(snap.seq_drift),
            reseqs=np.int64(snap.reseqs),
            seq_gen=np.int64(snap.seq_gen),
            ins_base=np.int64(snap.ins_base),
            **fields,
        )


def load_serve_snapshot(path: str,
                        integrity: str | None = None) -> ServeSnapshot:
    """Load + fully verify one serve snapshot (also the ``sheep fsck``
    checker for ``.snap``).  Like runtime checkpoints, a snapshot is
    never partially salvaged: the checksum check is strict even under the
    repair policy — repair's graceful path lives in ServeCore.open, which
    falls back to an older generation."""
    from ..integrity.sidecar import verify_file
    mode = resolve_policy(integrity)
    if mode != "trust":
        verify_file(path, "strict")
    try:
        with np.load(path) as z:
            if int(z["version"]) != SNAP_VERSION:
                raise MalformedArtifact(
                    f"{path}: serve snapshot version {int(z['version'])} "
                    f"!= supported {SNAP_VERSION}")
            snap = ServeSnapshot(
                seq=z["seq"].copy(), parent=z["parent"].copy(),
                pst=z["pst"].copy(), parts=z["parts"].copy(),
                num_parts=int(z["num_parts"]),
                applied_seqno=int(z["applied_seqno"]),
                ins_tail=z["ins_tail"].copy(), ins_head=z["ins_head"].copy(),
                drift_cut=int(z["drift_cut"]),
                baseline_ecv=int(z["baseline_ecv"]),
                graph_path=str(z["graph_path"]), sig=str(z["sig"]),
                balance=float(z["balance"]),
                # pre-replication snapshots predate epochs: term 0
                epoch=int(z["epoch"]) if "epoch" in z.files else 0,
                epoch_base=(int(z["epoch_base"])
                            if "epoch_base" in z.files else 0),
                # pre-reseq snapshots predate the incremental degree
                # histogram: None makes the core recount on load
                deg=z["deg"].copy() if "deg" in z.files else None,
                deg_base=(z["deg_base"].copy()
                          if "deg_base" in z.files else None),
                seq_drift=(int(z["seq_drift"])
                           if "seq_drift" in z.files else 0),
                reseqs=int(z["reseqs"]) if "reseqs" in z.files else 0,
                seq_gen=int(z["seq_gen"]) if "seq_gen" in z.files else 0,
                ins_base=(int(z["ins_base"])
                          if "ins_base" in z.files else 0))
    except IntegrityError:
        raise
    except Exception as exc:  # BadZipFile / KeyError / OSError / ValueError
        raise MalformedArtifact(
            f"{path}: corrupt serve snapshot "
            f"({type(exc).__name__}: {exc})")
    snap.validate()
    return snap


class ReplicationGap(RuntimeError):
    """A replicated record would leave a hole in the seqno chain; the
    follower must re-sync from its applied seqno (serve/replicate.py)."""

    def __init__(self, expected: int, got: int):
        super().__init__(f"replication gap: expected seqno {expected}, "
                         f"stream delivered {got}")
        self.expected = expected
        self.got = got


# -- the incremental transform ----------------------------------------------


def insert_link(parent: np.ndarray, lo: int, hi: int,
                skip: np.ndarray | None = None) -> int:
    """Fold one link (lo -> hi), lo < hi, into a live parent array.

    Exactly the merge replay localized (module docstring): climb lo to
    its component representative under threshold-hi connectivity, attach,
    cascade the displaced link upward.  Returns the number of parent
    pointers rewritten (0 = the edge was already implied by the tree).

    ``skip`` is an optional ancestor memo (ISSUE 19): ``skip[x]`` holds
    SOME ancestor of ``x`` in the tree, or INVALID.  Chains are strictly
    increasing (``parent[x] > x``, preserved by every attach below) and
    an attach only ever splices nodes INTO a chain — it never removes an
    ancestor relation — so between calls a recorded ancestor stays an
    ancestor for the tree's whole lifetime and the memo needs no
    invalidation.  MID-cascade there is one pending exception: the
    displaced link (lo -> old parent) is broken until this very round
    re-folds it, and lo's memo may still route through it — but every
    such stale entry is ``>= hi`` (the displaced parent and everything
    above), so jumping only through ``skip[x] < hi`` (STRICT) never
    consults one.  Strict jumps cannot overshoot either the stopping
    node or the ``parent == hi`` early-exit, because every node on the
    path to an ancestor ``a`` is ``< a``.  Climbs compress the visited
    path into the memo, so chain walks that are O(depth) cold become
    near-O(1) amortized — without it, sustained insert load degrades as
    accreted links deepen the chains (measured ~830 steps/climb after
    8k random inserts on hep-th).  The memo is a pure accelerator:
    parent outcomes and the rewrite count are bit-identical with or
    without it.
    """
    rewrites = 0
    while True:
        r = lo
        path = None
        while True:
            if skip is not None:
                s = int(skip[r])
                if s != INVALID_JNID and s < hi:
                    r = s
                    continue
            p = int(parent[r])
            if p == INVALID_JNID or p > hi:
                break
            if p == hi:
                if skip is not None and r != lo:
                    skip[lo] = r
                return rewrites  # lo's component already hangs off hi
            if skip is not None:
                if path is None:
                    path = [r]
                else:
                    path.append(r)
            r = p
        if path is not None:
            for x in path:  # r is an ancestor of every visited node
                skip[x] = r
        if skip is not None and r != lo:
            skip[lo] = r
        if r == hi:
            return rewrites
        p = int(parent[r])  # INVALID or > hi: the displaced link
        parent[r] = hi
        rewrites += 1
        if p == INVALID_JNID:
            return rewrites
        lo, hi = r, p


def ecv_down(parts: np.ndarray, tail: np.ndarray, head: np.ndarray,
             pos: np.ndarray) -> int:
    """ECV(down) — distinct (vertex, part-of-earlier-endpoint) pairs
    beyond each vertex's own, identical to partition.evaluate's
    ``ecv_down`` field but tolerant of INVALID_PART entries (vids inserted
    after the sequence was fixed have no part yet; evaluate's balance
    bincounts would reject them)."""
    t = tail.astype(np.int64)
    h = head.astype(np.int64)
    X = np.concatenate([t, h])
    Y = np.concatenate([h, t])
    pos64 = pos.astype(np.int64)
    pX = parts[X]
    pY = parts[Y]
    down = np.where(pos64[X] < pos64[Y], pX, pY)
    # distinct (X, down) keys; down in [-1, P) so shift by +1 into [0, P]
    P = int(parts.max(initial=0)) + 1
    key = X * np.int64(P + 2) + (down + 1)
    n_active = len(np.unique(X))
    return int(len(np.unique(key)) - n_active)


# -- the core ---------------------------------------------------------------


class ServeCore:
    """Resident serving state + WAL + snapshot lifecycle (socket-free).

    Thread-safe: every public method takes the state lock; the heavy
    repartition compute runs on copies outside it and swaps in under it.
    """

    def __init__(self, state_dir: str, snap: ServeSnapshot,
                 appender: WalAppender,
                 governor: ResourceGovernor | None = None,
                 snap_every: int = 256,
                 drift_frac: float = 0.1,
                 drift_min_cut: int = 64,
                 reseq_frac: float = 0.25,
                 reseq_min: int = 256,
                 reseq_rank: int = 8,
                 group_commit_max: int = 256,
                 group_commit_delay_s: float = 0.002):
        self.state_dir = state_dir
        self.governor = governor if governor is not None \
            else ResourceGovernor.from_env()
        self.snap_every = max(1, int(snap_every))
        self.drift_frac = float(drift_frac)
        self.drift_min_cut = max(1, int(drift_min_cut))
        # sequence-drift detector (ISSUE 18): an insert counts as
        # sequence drift when an endpoint is outside the sequence or its
        # degree rank moved >= reseq_rank since the sequence was fixed;
        # the detector fires at reseq_frac of the inserts past the cut,
        # floored at reseq_min
        self.reseq_frac = float(reseq_frac)
        self.reseq_min = max(1, int(reseq_min))
        self.reseq_rank = max(1, int(reseq_rank))
        self._lock = threading.RLock()
        self._wal = appender
        #: replication hook (serve/replicate.py): called with no args,
        #: under the state lock, after every durable append — the hub
        #: wakes its per-follower senders off it.  Never does I/O.
        self.on_append = None
        #: whether THIS core fires the SHEEP_SERVE_FAULT_PLAN sites; the
        #: multi-core in-process harnesses (tests) disable it on all but
        #: the node under test so "kill@wal:3" names one node's boundary
        self.fire_faults = True
        self.repartitions = 0
        self.snap_failures = 0
        # repartition ordering: a later-STARTED repartition (newer tree)
        # must never be overwritten by an earlier-started one finishing
        # late (the background thread racing a forced REPARTITION)
        self._repart_ticket = 0
        self._repart_applied = -1
        # same ordering discipline for re-sequences: a later-started
        # rebuild (fresher cut) must win over an earlier one landing late
        self._reseq_ticket = 0
        self._reseq_applied = -1
        # -- group commit (ISSUE 19): the leader-side analogue of the
        # follower burst seal.  Concurrent inserts append DEFERRED
        # (sync=False) under the state lock, then park here on a shared
        # commit ticket; one fsync covers the whole group and releases
        # every waiter at once.  A lone insert elects itself leader and
        # fsyncs immediately (idle latency unchanged); under concurrency
        # the next leader's fsync piggybacks everything appended while
        # the previous one was in flight, optionally stretched by
        # group_commit_delay_s up to group_commit_max records.
        self.group_commit_max = max(1, int(group_commit_max))
        self.group_commit_delay_s = max(0.0, float(group_commit_delay_s))
        self._gc_cv = threading.Condition()
        self._gc_leader = False
        self._gc_rids: list[tuple[int, str]] = []
        self._gc_err: BaseException | None = None
        self._gc_err_seq = 0
        self.gc_fsyncs = 0
        self.gc_records = 0
        self._gc_sizes: deque = deque(maxlen=512)
        # -- seqlock (ISSUE 19): reads are lock-free against a published
        # version counter.  Writers bump it to odd before mutating the
        # serving arrays and back to even after; readers snapshot the
        # counter + array refs, gather, re-check, bounded-retry, then
        # fall back to the lock.  CPython's GIL orders the plain
        # attribute reads/writes; the counter is only ever bumped under
        # the state lock, so "even" means "no writer mid-mutation".
        self._version = 0
        self.seqlock_retries = 0
        self.seqlock_fallbacks = 0
        # -- anti-entropy (ISSUE 20): with a verify-capable follower
        # attached, the leader captures state_crc at every verify_n-th
        # applied seqno INSIDE the apply critical section (the crc names
        # exactly that seqno's state — RLock makes state_crc re-entrant
        # here) into a small ring the replication hub stamps VERIFY
        # frames from.  0 = off: a leader with no verify-capable
        # follower pays nothing.
        self.verify_n = 0
        self._verify_crcs: dict[int, int] = {}
        self.verify_points = 0
        # mirrors the durable quarantine marker (serve/scrub.py): True
        # refuses reads typed (`ERR diverged`) until the snapshot
        # re-sync re-verifies and durably clears it
        self.quarantined = False
        self._load_snapshot(snap)

    def _load_snapshot(self, snap: ServeSnapshot) -> None:
        """(Re)build every piece of in-memory serving state from one
        snapshot — the shared tail of __init__ and the follower full
        resync (:meth:`reset_from_snapshot`)."""
        self._mut_begin()
        try:
            self._load_snapshot_inner(snap)
        finally:
            self._mut_end()

    def _load_snapshot_inner(self, snap: ServeSnapshot) -> None:
        self.seq = np.asarray(snap.seq, dtype=np.uint32)
        self.parent = np.asarray(snap.parent, dtype=np.uint32).copy()
        self.pst = np.asarray(snap.pst, dtype=np.uint32).copy()
        self.parts = np.asarray(snap.parts, dtype=np.int64).copy()
        self.num_parts = snap.num_parts
        self.balance = snap.balance
        self.applied_seqno = snap.applied_seqno
        # everything up to the snapshot boundary is durable by
        # definition; the group-commit coordinator advances this as its
        # shared fsyncs land, and replication senders never ship past it
        self.durable_seqno = snap.applied_seqno
        self.drift_cut = snap.drift_cut
        self.baseline_ecv = snap.baseline_ecv
        self.graph_path = snap.graph_path or None
        self.sig = snap.sig
        self.epoch = snap.epoch
        self.epoch_base = snap.epoch_base
        self.pos = sequence_positions(self.seq,
                                      max(len(self.parts) - 1, 0))
        self.ins_tail: list[int] = [int(x) for x in snap.ins_tail]
        self.ins_head: list[int] = [int(x) for x in snap.ins_head]
        self._inserts_since_snap = 0
        self._subtree_cache = None
        self._part_lut = None
        self._link_skip = None  # ancestor memo is per-tree: new tree, new memo
        # replication bookkeeping: an in-memory window of recent records
        # (seqno, payload) follower senders stream from without touching
        # the file.  Deliberately DECOUPLED from the WAL swap: a seal
        # must not strand a follower that is one record behind, so the
        # window survives seals and is trimmed by count instead
        # (repl_floor = the seqno just before the oldest retained
        # record; anything at or below it needs a snapshot bootstrap).
        self._wal_tail: list[tuple[int, bytes]] = []
        self.repl_floor = snap.applied_seqno
        # trace-context forwarding (ISSUE 12): seqno -> rid for records
        # still in the replication window, so the hub can stamp APPEND
        # frames with the originating request's id (trimmed with the
        # window — a bootstrapping follower has no rids to forward)
        self._rid_tail: dict[int, str] = {}

        self.edges_tail = None
        self.edges_head = None
        if self.graph_path:
            try:
                from ..io.edges import load_edges
                el = load_edges(self.graph_path)
                self.edges_tail = el.tail
                self.edges_head = el.head
            except (OSError, IntegrityError) as exc:
                warnings.warn(
                    f"serve: graph {self.graph_path} unavailable ({exc}); "
                    f"ECV queries and drift baselines are disabled")
                self.graph_path = None

        # the incremental degree histogram (ISSUE 18): adopt the
        # snapshot's when it carries one (and still matches the vid
        # domain), else recount from the resident edge set — the one-off
        # upgrade path for pre-reseq snapshots
        self.seq_gen = snap.seq_gen
        self.seq_drift = snap.seq_drift
        self.reseqs = snap.reseqs
        self.ins_base = min(snap.ins_base, len(self.ins_tail))
        n_v = len(self.parts)
        if snap.deg is not None and len(snap.deg) == n_v:
            self.deg = np.asarray(snap.deg, dtype=np.int64).copy()
            self.deg_base = np.asarray(snap.deg_base,
                                       dtype=np.int64).copy()
        else:
            tail, head = self._all_edges()
            self.deg = host_degree_histogram(tail, head, n_v)
            ins_deg = host_degree_histogram(
                np.asarray(self.ins_tail, dtype=np.uint32),
                np.asarray(self.ins_head, dtype=np.uint32), n_v)
            self.deg_base = self.deg - ins_deg

    # -- lifecycle ---------------------------------------------------------

    @classmethod
    def bootstrap(cls, state_dir: str,
                  tre_path: str | None = None,
                  seq_path: str | None = None,
                  graph_path: str | None = None,
                  parts_path: str | None = None,
                  num_parts: int = 2,
                  balance: float = 1.03,
                  integrity: str | None = None,
                  **core_kw) -> "ServeCore":
        """First start: load artifacts through the strict integrity
        readers, partition, seal generation 0, create the WAL, then enter
        through :meth:`open` so bootstrap exercises the exact recovery
        path every later restart takes."""
        from ..io.seqfile import read_sequence
        from ..io.trefile import read_tree
        if (tre_path is None) != (seq_path is None):
            raise ValueError("bootstrap needs BOTH -T tree and -s sequence "
                             "(or neither, with a graph to build from)")
        if tre_path is None:
            if graph_path is None:
                raise ValueError("bootstrap needs a tree+sequence or a "
                                 "graph to build them from")
            from ..core.forest import build_forest
            from ..core.sequence import degree_sequence
            from ..io.edges import load_edges
            el = load_edges(graph_path)
            seq = degree_sequence(el.tail, el.head)
            forest = build_forest(el.tail, el.head, seq,
                                  max_vid=el.max_vid)
            parent, pst = forest.parent, forest.pst_weight
            max_vid = el.max_vid
        else:
            seq = read_sequence(seq_path, binary="auto",
                                integrity=integrity)
            parent, pst = read_tree(tre_path, integrity=integrity)
            if len(parent) != len(seq):
                raise MalformedArtifact(
                    f"{tre_path}: tree has {len(parent)} nodes but "
                    f"{seq_path} orders {len(seq)} vertices — not a pair")
            max_vid = int(seq.max()) if len(seq) else 0
            if graph_path is not None:
                from ..io.edges import load_edges
                el = load_edges(graph_path)
                max_vid = max(max_vid, el.max_vid)

        n_v = max_vid + 1 if len(seq) else 0
        if parts_path is not None:
            from ..partition.partition import Partition
            part = Partition.from_file(seq, parts_path)
            parts = np.full(n_v, INVALID_PART, dtype=np.int64)
            parts[: len(part.parts)] = part.parts[:n_v]
            num_parts = part.num_parts
        else:
            jparts = partition_forest(
                Forest(parent, pst), num_parts,
                TreePartitionOptions(balance_factor=balance))
            parts = np.full(n_v, INVALID_PART, dtype=np.int64)
            parts[seq] = jparts

        sig = input_signature(len(seq), seq)
        baseline = -1
        if graph_path is not None:
            pos = sequence_positions(seq, n_v - 1 if n_v else None)
            baseline = ecv_down(parts, el.tail, el.head, pos)

        os.makedirs(state_dir, exist_ok=True)
        gc_orphan_temps(state_dir)
        snap = ServeSnapshot(
            seq=seq, parent=parent, pst=pst, parts=parts,
            num_parts=num_parts, applied_seqno=0,
            ins_tail=np.empty(0, np.uint32), ins_head=np.empty(0, np.uint32),
            drift_cut=0, baseline_ecv=baseline,
            graph_path=os.path.abspath(graph_path) if graph_path else "",
            sig=sig, balance=balance)
        save_serve_snapshot(os.path.join(state_dir, snap_name(0)), snap)
        create_wal(wal_path(state_dir), sig)
        return cls.open(state_dir, integrity=integrity, **core_kw)

    @classmethod
    def open(cls, state_dir: str, integrity: str | None = None,
             **core_kw) -> "ServeCore":
        """Restart: newest loadable snapshot + WAL replay.  strict (the
        default) refuses a torn WAL or a corrupt newest snapshot; repair
        truncates the tear / falls back a snapshot generation, warning
        either way."""
        mode = resolve_policy(integrity)
        snaps = snap_paths(state_dir)
        if not snaps:
            raise MalformedArtifact(
                f"{state_dir}: no serve snapshots — not a serve state dir "
                f"(bootstrap one with `sheep serve -d DIR <artifacts>`)")
        loaded = []
        errors = []
        for path in reversed(snaps):
            try:
                loaded.append(load_serve_snapshot(path, integrity=mode))
            except (IntegrityError, OSError) as exc:
                errors.append(f"{path}: {exc}")
                if mode == "strict":
                    raise
                warnings.warn(
                    f"serve: snapshot {path} unusable ({exc}); falling "
                    f"back a generation")
        if not loaded:
            raise MalformedArtifact(
                f"{state_dir}: every snapshot generation is corrupt — "
                + "; ".join(errors))
        # the epoch is the senior key: a promotion or follower re-sync
        # that crashed mid-swap can leave a HIGHER-epoch snapshot under a
        # lower applied-seqno filename, and the later term always wins
        snap = max(loaded, key=lambda s: (s.epoch, s.applied_seqno))

        wpath = wal_path(state_dir)
        if not os.path.exists(wpath):
            if mode == "strict":
                raise MalformedArtifact(
                    f"{wpath}: WAL missing — any insert acknowledged after "
                    f"the last snapshot is unrecoverable; repair mode "
                    f"restarts from the snapshot alone")
            warnings.warn(f"serve: {wpath} missing; restarting the log "
                          f"from the snapshot alone (repair mode)")
            create_wal(wpath, snap.sig)
        elif mode != "strict":
            dropped = repair_wal(wpath)
            if dropped:
                warnings.warn(f"serve: truncated {dropped} torn byte(s) "
                              f"off {wpath}")
        wal_sig, wal_epoch, records, _, _ = read_wal(wpath, mode)
        if wal_sig != snap.sig:
            # a re-sequence changes the input signature ON PURPOSE; the
            # crash window between the new-generation snapshot seal and
            # the WAL swap leaves an old-sig log whose every record is
            # already in the snapshot.  The durable reseq manifest is
            # the sanction: without it (or with records past the
            # snapshot boundary) this is the torn mid-swap state fsck
            # refuses.
            from .reseq import sanctions_sig_change
            sanctioned = (
                sanctions_sig_change(state_dir, wal_sig, snap.sig)
                and (not records
                     or records[-1][0] <= snap.applied_seqno))
            if not sanctioned:
                raise IntegrityError(
                    f"{wpath}: WAL belongs to a different build input "
                    f"(log sig {wal_sig[:12]}..., snapshot "
                    f"{snap.sig[:12]}...) — refusing to replay")
            warnings.warn(
                f"serve: {wpath} predates the sealed re-sequence "
                f"(manifest-sanctioned sig change); swapping in a fresh "
                f"generation-{snap.seq_gen} log")
            create_wal(wpath, snap.sig, epoch=snap.epoch)
            wal_sig, wal_epoch, records = snap.sig, snap.epoch, []
        if wal_epoch > snap.epoch:
            # only reachable when repair mode fell back a snapshot
            # generation ACROSS a promotion: the epoch-E log starts after
            # the epoch-E snapshot this dir no longer has a readable copy
            # of, so replaying it onto the older snapshot would skip the
            # gap silently.  No mode can bridge that.
            raise MalformedArtifact(
                f"{wpath}: WAL epoch {wal_epoch} is ahead of snapshot "
                f"epoch {snap.epoch} — the snapshot that sealed epoch "
                f"{wal_epoch} is missing or unreadable; recovery cannot "
                f"bridge a promotion boundary")
        if wal_epoch < snap.epoch:
            if records and records[-1][0] > snap.applied_seqno:
                raise MalformedArtifact(
                    f"{wpath}: cross-epoch seqno overlap — epoch "
                    f"{wal_epoch} log reaches seqno {records[-1][0]} past "
                    f"the epoch-{snap.epoch} snapshot boundary "
                    f"{snap.applied_seqno}; a fenced log may never extend "
                    f"a later epoch's history")
            # benign crash window between the promotion seal and the WAL
            # swap: every surviving record is already in the snapshot
            warnings.warn(
                f"serve: {wpath} carries the pre-promotion epoch "
                f"{wal_epoch} (snapshot is {snap.epoch}); swapping in a "
                f"fresh epoch-{snap.epoch} log")
            create_wal(wpath, snap.sig, epoch=snap.epoch)
            records = []

        appender = WalAppender(wpath, expect_sig=snap.sig)
        core = cls(state_dir, snap, appender, **core_kw)
        for seqno, payload in records:
            if seqno <= core.applied_seqno:
                continue  # already folded into the snapshot
            core._apply_pairs(decode_inserts(payload))
            core.applied_seqno = seqno
            core._tail_push(seqno, payload)
        # replayed records came off the durable log
        core.durable_seqno = core.applied_seqno
        # A crash between snapshot seal and WAL swap leaves a log whose
        # last seqno <= applied; new records must still sort AFTER the
        # snapshot or the next replay would skip them.
        core._wal.next_seqno = max(core._wal.next_seqno,
                                   core.applied_seqno + 1)
        return core

    def close(self) -> None:
        try:
            self._wal.sync()
            self.durable_seqno = self.applied_seqno
        except OSError:
            pass  # unsynced records were never acked; replay truncates
        self._wal.close()

    # -- queries -----------------------------------------------------------
    #
    # Reads are LOCK-FREE (ISSUE 19): a seqlock'd published version.  The
    # read loop snapshots the version counter (odd = a writer is
    # mid-mutation), gathers from locally captured array refs, then
    # re-checks the counter — a bump in between means the gather may mix
    # generations and the attempt is discarded.  After _SEQLOCK_TRIES
    # failed attempts the read falls back to the state lock (bounded
    # starvation under a write storm).  Mixed-generation refs can also
    # raise IndexError (a reseq swap replaces pos/parent with different
    # lengths); that is a retry, not an error.

    def _mut_begin(self) -> None:
        self._version += 1  # odd: lock-free readers retry

    def _mut_end(self) -> None:
        self._version += 1  # even: stable again

    def _read_enter(self) -> int:
        """One seqlock read attempt's opening: the current version, or
        -1 when a write is in flight."""
        v = self._version
        return -1 if (v & 1) else v

    def part(self, vid: int) -> int:
        """Part of ``vid`` (INVALID_PART = -1 when the vertex is absent
        from the partition — including vertices first seen by insert)."""
        for _ in range(_SEQLOCK_TRIES):
            v = self._read_enter()
            if v < 0:
                self.seqlock_retries += 1
                continue
            parts = self.parts
            res = int(parts[vid]) if 0 <= vid < len(parts) \
                else INVALID_PART
            if self._version == v:
                return res
            self.seqlock_retries += 1
        self.seqlock_fallbacks += 1
        with self._lock:
            if 0 <= vid < len(self.parts):
                return int(self.parts[vid])
            return INVALID_PART

    def parent_vid(self, vid: int):
        """Parent VERTEX of ``vid`` in the elimination tree: a vid,
        "root", or None when the vertex is not in the sequence."""
        for _ in range(_SEQLOCK_TRIES):
            v = self._read_enter()
            if v < 0:
                self.seqlock_retries += 1
                continue
            pos, parent, seq = self.pos, self.parent, self.seq
            try:
                res = self._parent_vid_from(vid, pos, parent, seq)
            except IndexError:  # mixed-generation refs mid-swap
                self.seqlock_retries += 1
                continue
            if self._version == v:
                return res
            self.seqlock_retries += 1
        self.seqlock_fallbacks += 1
        with self._lock:
            return self._parent_vid_from(vid, self.pos, self.parent,
                                         self.seq)

    @staticmethod
    def _parent_vid_from(vid, pos, parent, seq):
        if not (0 <= vid < len(pos)):
            return None
        j = int(pos[vid])
        if j == INVALID_JNID:
            return None
        p = int(parent[j])
        if p == INVALID_JNID:
            return "root"
        return int(seq[p])

    def subtree(self, vid: int):
        """(size, pst_total) of the subtree rooted at ``vid`` (inclusive),
        or None when the vertex is not in the sequence.  O(n) on the first
        query after a mutation, O(1) after (cached aggregates)."""
        for _ in range(_SEQLOCK_TRIES):
            v = self._read_enter()
            if v < 0:
                self.seqlock_retries += 1
                continue
            pos = self.pos
            try:
                if not (0 <= vid < len(pos)):
                    res = None
                else:
                    j = int(pos[vid])
                    if j == INVALID_JNID:
                        res = None
                    else:
                        agg = self._subtree_aggregates_at(v)
                        if agg is None:
                            self.seqlock_retries += 1
                            continue
                        size, wsum = agg
                        res = (int(size[j]), int(wsum[j]))
            except IndexError:
                self.seqlock_retries += 1
                continue
            if self._version == v:
                return res
            self.seqlock_retries += 1
        self.seqlock_fallbacks += 1
        with self._lock:
            if not (0 <= vid < len(self.pos)):
                return None
            j = int(self.pos[vid])
            if j == INVALID_JNID:
                return None
            size, wsum = self._subtree_aggregates()
            return int(size[j]), int(wsum[j])

    def _subtree_aggregates(self):
        """(size, wsum) per jnid, cached until the next mutation.  Caller
        holds the state lock (the version is therefore even and stable)."""
        return self._subtree_aggregates_at(self._version)

    def _subtree_aggregates_at(self, v: int):
        """(size, wsum) per jnid as of version ``v``, or None when a
        mutation raced the O(n) build.  The cache is keyed by the version
        it was built under, so a stale entry can never be served and a
        torn build is never stored."""
        cache = self._subtree_cache
        if cache is not None and cache[0] == v:
            return cache[1], cache[2]
        parent = self.parent
        pst = self.pst
        m = len(parent)
        if len(pst) != m:  # mixed-generation refs mid-swap
            return None
        size = np.ones(m, dtype=np.int64)
        wsum = pst.astype(np.int64)
        for k in range(m):  # parents strictly later: one pass
            p = parent[k]
            if p != INVALID_JNID:
                size[p] += size[k]
                wsum[p] += wsum[k]
        if self._version != v:
            return None
        self._subtree_cache = (v, size, wsum)
        return size, wsum

    # -- vectorized batch queries (ISSUE 11) -------------------------------
    #
    # The hot read path: one lock acquisition and one numpy gather per
    # BATCH instead of per vertex.  Answers are element-for-element what
    # the scalar methods return (the grammar property tests hold the two
    # paths bit-identical), sentinels included: INVALID_PART for a vid
    # outside the partition, PARENT_ABSENT/PARENT_ROOT for the tree walk.

    def part_batch(self, vids: np.ndarray) -> np.ndarray:
        """Vectorized :meth:`part`: int64 parts, INVALID_PART where the
        vid is outside the partition tables."""
        vids = np.asarray(vids, dtype=np.int64)
        for _ in range(_SEQLOCK_TRIES):
            v = self._read_enter()
            if v < 0:
                self.seqlock_retries += 1
                continue
            parts = self.parts
            out = np.full(vids.shape, INVALID_PART, dtype=np.int64)
            ok = (vids >= 0) & (vids < len(parts))
            out[ok] = parts[vids[ok]]
            if self._version == v:
                return out
            self.seqlock_retries += 1
        self.seqlock_fallbacks += 1
        with self._lock:
            out = np.full(vids.shape, INVALID_PART, dtype=np.int64)
            ok = (vids >= 0) & (vids < len(self.parts))
            out[ok] = self.parts[vids[ok]]
            return out

    def part_tokens(self, vids: np.ndarray) -> str:
        """:meth:`part_batch` rendered as the wire token list.  Part ids
        live in the tiny domain [-1, num_parts), so the render is a
        cached string-table lookup instead of 1000 ``str()`` calls —
        str() was half the batched PART budget once the gather
        vectorized."""
        out = self.part_batch(vids)
        lut = self._part_lut
        if lut is None or len(lut) < self.num_parts + 1:
            lut = self._part_lut = [str(i)
                                    for i in range(-1, self.num_parts)]
        try:
            return " ".join([lut[x] for x in (out + 1).tolist()])
        except IndexError:  # parts file named more parts than num_parts
            return " ".join(map(str, out.tolist()))

    @staticmethod
    def _parent_batch_from(vids, pos, parent, seq):
        out = np.full(vids.shape, PARENT_ABSENT, dtype=np.int64)
        ok = (vids >= 0) & (vids < len(pos))
        j = pos[vids[ok]].astype(np.int64)
        present = j != INVALID_JNID
        res = np.full(j.shape, PARENT_ABSENT, dtype=np.int64)
        pj = parent[j[present]].astype(np.int64)
        rooted = pj == INVALID_JNID
        val = seq[np.where(rooted, 0, pj)].astype(np.int64)
        res[present] = np.where(rooted, PARENT_ROOT, val)
        out[ok] = res
        return out

    def parent_batch(self, vids: np.ndarray) -> np.ndarray:
        """Vectorized :meth:`parent_vid`: int64 parent vids, with
        PARENT_ROOT (-1) for roots and PARENT_ABSENT (-2) where the vid
        is not in the sequence."""
        vids = np.asarray(vids, dtype=np.int64)
        for _ in range(_SEQLOCK_TRIES):
            v = self._read_enter()
            if v < 0:
                self.seqlock_retries += 1
                continue
            pos, parent, seq = self.pos, self.parent, self.seq
            try:
                out = self._parent_batch_from(vids, pos, parent, seq)
            except IndexError:  # mixed-generation refs mid-swap
                self.seqlock_retries += 1
                continue
            if self._version == v:
                return out
            self.seqlock_retries += 1
        self.seqlock_fallbacks += 1
        with self._lock:
            return self._parent_batch_from(vids, self.pos, self.parent,
                                           self.seq)

    def subtree_batch(self, vids: np.ndarray):
        """Vectorized :meth:`subtree`: (size, pst_total) int64 arrays,
        -1 in both where the vid is not in the sequence."""
        vids = np.asarray(vids, dtype=np.int64)

        def gather(pos, size, wsum):
            out_s = np.full(vids.shape, -1, dtype=np.int64)
            out_w = np.full(vids.shape, -1, dtype=np.int64)
            ok = (vids >= 0) & (vids < len(pos))
            j = pos[vids[ok]].astype(np.int64)
            present = j != INVALID_JNID
            s = np.full(j.shape, -1, dtype=np.int64)
            w = np.full(j.shape, -1, dtype=np.int64)
            s[present] = size[j[present]]
            w[present] = wsum[j[present]]
            out_s[ok] = s
            out_w[ok] = w
            return out_s, out_w

        for _ in range(_SEQLOCK_TRIES):
            v = self._read_enter()
            if v < 0:
                self.seqlock_retries += 1
                continue
            pos = self.pos
            agg = self._subtree_aggregates_at(v)
            if agg is None:
                self.seqlock_retries += 1
                continue
            try:
                out = gather(pos, agg[0], agg[1])
            except IndexError:  # mixed-generation refs mid-swap
                self.seqlock_retries += 1
                continue
            if self._version == v:
                return out
            self.seqlock_retries += 1
        self.seqlock_fallbacks += 1
        with self._lock:
            size, wsum = self._subtree_aggregates()
            return gather(self.pos, size, wsum)

    def state_crc(self) -> int:
        """crc32 over every serving-state array — the cheap bit-identity
        fingerprint the tenant isolation and evict/restore tests compare
        (two cores answer identically iff their crcs match)."""
        import zlib
        with self._lock:
            crc = 0
            for arr in (self.seq, self.parent, self.pst, self.parts,
                        np.asarray(self.ins_tail, dtype=np.uint32),
                        np.asarray(self.ins_head, dtype=np.uint32)):
                crc = zlib.crc32(np.ascontiguousarray(arr).tobytes(), crc)
            return crc & 0xFFFFFFFF

    # -- anti-entropy (ISSUE 20) ------------------------------------------

    def enable_verify(self, every_n: int) -> None:
        """Start (or retune) verify-point capture: every ``every_n``-th
        applied seqno gets its state_crc recorded for VERIFY stamping.
        Called by the hub when a verify-capable follower attaches."""
        with self._lock:
            self.verify_n = max(0, int(every_n))
            if not self.verify_n:
                self._verify_crcs.clear()

    def _capture_verify(self, seqno: int) -> None:
        """Under the state lock, right after ``applied_seqno`` advanced:
        record the crc that names this exact seqno's state."""
        n = self.verify_n
        if not n or seqno % n:
            return
        self._verify_crcs[seqno] = self.state_crc()
        self.verify_points += 1
        while len(self._verify_crcs) > 32:
            self._verify_crcs.pop(next(iter(self._verify_crcs)))

    def verify_crc(self, seqno: int) -> int | None:
        """The captured verify-point crc for ``seqno`` (None when the
        seqno is not a verify point or fell out of the ring)."""
        with self._lock:
            return self._verify_crcs.get(seqno)

    def corrupt_one_byte(self) -> int:
        """TEST/BENCH ONLY (the daemon gates the CORRUPT verb behind
        ``SHEEP_SCRUB_ALLOW_CORRUPT=1``): flip one bit of an inserted-edge
        endpoint in the live serving state — the silent-corruption shape
        the anti-entropy stream exists to catch.  state_crc changes; the
        WAL and snapshots do not (nothing was written), so only stream
        VERIFY can see it.  Returns the new state_crc.  Raises
        RuntimeError when there are no inserted edges to corrupt."""
        with self._lock:
            if not self.ins_head:
                raise RuntimeError("corrupt_one_byte: no inserted edges")
            self._mut_begin()
            try:
                self.ins_head[-1] = int(self.ins_head[-1]) ^ 0x1
            finally:
                self._mut_end()
            return self.state_crc()

    def ecv(self) -> dict:
        """Exact ECV(down) over (original + inserted) edges under the
        CURRENT partition, plus the drift accounting.  Raises
        RuntimeError when no graph edges are resident."""
        if self.edges_tail is None and self.graph_path is None:
            raise RuntimeError(
                "no graph edges resident (serve was started without a "
                "graph); ECV is unavailable")
        for _ in range(_SEQLOCK_TRIES):
            v = self._read_enter()
            if v < 0:
                self.seqlock_retries += 1
                continue
            parts, pos = self.parts, self.pos
            try:
                out = self._ecv_locked(parts, pos)
            except (IndexError, ValueError):
                # mixed-generation refs, or the ins lists grew between
                # the tail and head snapshots — discard and retry
                self.seqlock_retries += 1
                continue
            if self._version == v:
                return out
            self.seqlock_retries += 1
        self.seqlock_fallbacks += 1
        with self._lock:
            return self._ecv_locked(self.parts, self.pos)

    def _ecv_locked(self, parts, pos) -> dict:
        if self.edges_tail is None:
            raise RuntimeError(
                "no graph edges resident (serve was started without a "
                "graph); ECV is unavailable")
        tail, head = self._all_edges()
        if len(tail) != len(head):
            raise ValueError("torn ins tail/head snapshot")
        val = ecv_down(parts, tail, head, pos)
        return {"ecv_down": val, "baseline": self.baseline_ecv,
                "drift_cut": self.drift_cut,
                "seq_drift": self.seq_drift,
                "reseqs": self.reseqs,
                "parts": int(parts.max(initial=0)) + 1}

    def stats(self) -> dict:
        with self._lock:
            linked = int((self.parent != INVALID_JNID).sum())
            return {
                "n": len(self.seq), "links": linked,
                "vids": len(self.parts),
                "epoch": self.epoch,
                "wal_seqno": self._wal.next_seqno - 1,
                "applied_seqno": self.applied_seqno,
                "inserted": len(self.ins_tail),
                "drift_cut": self.drift_cut,
                "seq_drift": self.seq_drift,
                "reseqs": self.reseqs,
                "seq_gen": self.seq_gen,
                "baseline_ecv": self.baseline_ecv,
                "repartitions": self.repartitions,
                "snap_failures": self.snap_failures,
                "durable_seqno": self.durable_seqno,
                "gc_fsyncs": self.gc_fsyncs,
                "gc_records": self.gc_records,
                "gc_size_p50": self._gc_size_quantile(0.5),
                "gc_size_p99": self._gc_size_quantile(0.99),
                "seqlock_retries": self.seqlock_retries,
                "seqlock_fallbacks": self.seqlock_fallbacks,
            }

    def _gc_size_quantile(self, q: float) -> int:
        """Quantile of recent group-commit sizes (records per shared
        fsync) over a sliding window of the last 512 groups."""
        sizes = sorted(self._gc_sizes)
        if not sizes:
            return 0
        k = min(len(sizes) - 1, int(q * len(sizes)))
        return int(sizes[k])

    def _all_edges(self):
        ins_t = np.asarray(self.ins_tail, dtype=np.uint32)
        ins_h = np.asarray(self.ins_head, dtype=np.uint32)
        if self.edges_tail is None:
            return ins_t, ins_h
        return (np.concatenate([self.edges_tail, ins_t]),
                np.concatenate([self.edges_head, ins_h]))

    # -- inserts -----------------------------------------------------------

    def insert(self, pairs: np.ndarray, rid: str | None = None) -> int:
        """Accept one batch of edges: WAL append (DEFERRED fsync) +
        in-memory apply under a short critical section, then park on the
        shared group-commit ticket until one fsync seals the whole group
        (:meth:`_group_commit`) — the leader-side analogue of the
        follower burst seal (PR 8).  Returns the batch's seqno only
        AFTER the covering fsync: the durability contract is unchanged
        (nothing the caller acknowledges can be lost), only the fsync is
        amortized across every insert in flight.

        Fault sites (serve/faults): ``gc-append`` before the deferred
        append, ``gc-unsynced`` after append+apply but before the shared
        fsync (both may lose the never-acked record), then ``wal`` /
        ``apply`` after the fsync — the record is durable there, so a
        kill MUST recover it from the log.  A DiskExhausted/WriteFault
        from the append propagates with NOTHING applied or logged; a
        failed GROUP fsync propagates to every waiter it covered and
        none of them acknowledge.  ``rid`` (the request's trace-context
        id, ISSUE 12) is retained alongside the replication window so
        APPEND frames forward it, and the shared ``wal.fsync`` span is
        attributed to every rid it seals."""
        pairs = np.ascontiguousarray(pairs, dtype=np.uint32)
        if pairs.ndim != 2 or pairs.shape[1] != 2:
            raise ValueError(f"insert batch must be (k, 2), got "
                             f"{pairs.shape}")
        with self._lock:
            payload = encode_inserts(pairs)
            self._fire("gc-append")
            seqno = self._wal.append(payload, sync=False)
            self._apply_pairs(pairs)
            self.applied_seqno = seqno
            self._capture_verify(seqno)
            self._tail_push(seqno, payload, rid)
            self._fire("gc-unsynced")
            self._inserts_since_snap += 1
            if self._inserts_since_snap >= self.snap_every:
                self.maybe_seal()  # the seal itself makes the group durable
        self._group_commit(seqno, rid)
        self._fire("wal")
        self._fire("apply")
        return seqno

    def _group_commit(self, seqno: int, rid: str | None) -> None:
        """Park until ``seqno`` is durable.  One waiter elects itself
        group leader and pays the shared fsync for everything appended
        so far; the rest sleep on the ticket.  A lone insert becomes
        leader instantly and fsyncs with no window (idle latency
        unchanged); with company the leader stretches the window by up
        to ``group_commit_delay_s`` while the group is still under
        ``group_commit_max`` records.  A failed fsync propagates to
        EVERY waiter whose record it covered."""
        cv = self._gc_cv
        with cv:
            if rid is not None:
                self._gc_rids.append((seqno, rid))
            cv.notify_all()  # a delaying leader re-checks the group size
            while True:
                if self.durable_seqno >= seqno:
                    return
                err = self._gc_err
                if err is not None and seqno <= self._gc_err_seq:
                    raise err
                if not self._gc_leader:
                    self._gc_leader = True
                    break
                cv.wait(0.1)
        try:
            delay = self.group_commit_delay_s
            if delay > 0:
                deadline = time.monotonic() + delay
                with cv:
                    while True:
                        pending = self.applied_seqno - self.durable_seqno
                        if pending <= 1 or pending >= self.group_commit_max:
                            break  # lone insert or a full window: go now
                        left = deadline - time.monotonic()
                        if left <= 0:
                            break
                        cv.wait(left)
            prev = self.durable_seqno
            with cv:
                rids = [r for s, r in self._gc_rids if s > prev]
                del self._gc_rids[:]
            newest = rids[-1] if rids else None
            attrs = {"records": self.applied_seqno - prev}
            if rids:  # one span, many rids (ISSUE 19)
                attrs["rids"] = ",".join(rids[-32:])
            try:
                with _trace.rid_scope(newest):
                    self.wal_sync(**attrs)
            except OSError as exc:
                with cv:
                    # fail every waiter the attempted fsync covered:
                    # none of their records may be acknowledged
                    self._gc_err = exc
                    self._gc_err_seq = self.applied_seqno
                raise
            with cv:
                self._gc_err = None
                group = self.durable_seqno - prev
                if group > 0:
                    self.gc_fsyncs += 1
                    self.gc_records += group
                    self._gc_sizes.append(group)
        finally:
            with cv:
                self._gc_leader = False
                cv.notify_all()

    def _fire(self, site: str) -> None:
        if self.fire_faults:
            serve_faults.fire(site)

    def _tail_push(self, seqno: int, payload: bytes,
                   rid: str | None = None) -> None:
        self._wal_tail.append((seqno, payload))
        if rid is not None:
            self._rid_tail[seqno] = rid
        if len(self._wal_tail) > REPL_TAIL_KEEP:
            drop = len(self._wal_tail) - REPL_TAIL_KEEP
            del self._wal_tail[:drop]
            self.repl_floor = self._wal_tail[0][0] - 1
            if self._rid_tail:
                floor = self.repl_floor
                for s in [s for s in self._rid_tail if s <= floor]:
                    del self._rid_tail[s]

    def rid_for(self, seqno: int) -> str | None:
        """The trace-context id of a retained record (None when the
        insert carried none or the window moved past it)."""
        return self._rid_tail.get(seqno)

    def apply_replicated(self, seqno: int, payload: bytes,
                         sync: bool = True,
                         rid: str | None = None) -> str:
        """Fold one record shipped by the leader into a FOLLOWER's state
        (serve/replicate.py).  The record lands in the local WAL under
        the leader's seqno (same durability order as :meth:`insert`:
        append + fsync -> apply), so a follower crash recovers through
        the exact snapshot+replay path a leader does.

        ``sync=False`` defers the WAL fsync (batched follower acks): the
        applier folds a whole APPEND burst, then pays ONE
        :meth:`wal_sync` before its single cumulative ACK — nothing is
        ever acknowledged ahead of its fsync, and a crash mid-burst
        loses only unacknowledged records (recovery replays the durable
        prefix, a valid earlier boundary).

        Returns ``"applied"`` or ``"dup"`` (seqno already applied — a
        re-sent frame, dropped idempotently).  A seqno that would leave
        a gap raises :class:`ReplicationGap`: the stream lost a record
        (injected ``drop`` or a real torn connection) and the follower
        must re-sync from its applied seqno instead of corrupting order.
        """
        with self._lock:
            if seqno <= self.applied_seqno:
                return "dup"
            if seqno != self.applied_seqno + 1:
                raise ReplicationGap(self.applied_seqno + 1, seqno)
            pairs = decode_inserts(payload)  # refuse garbage pre-append
            self._wal.append_at(seqno, payload, sync=sync)
            self._fire("wal")
            self._apply_pairs(pairs)
            self.applied_seqno = seqno
            self._capture_verify(seqno)
            if sync:
                self.durable_seqno = seqno
            self._tail_push(seqno, payload, rid)
            if self.on_append is not None:
                self.on_append()  # chained replication / status hooks
            self._fire("apply")
            self._inserts_since_snap += 1
            if self._inserts_since_snap >= self.snap_every:
                self.maybe_seal()
            return "applied"

    def wal_sync(self, **attrs) -> None:
        """Seal a deferred-fsync burst (follower :meth:`apply_replicated`
        ``sync=False`` bursts and leader group commits alike): one fsync
        covering every unsynced append.  The caller acknowledges only
        after this returns.  ``attrs`` annotate the shared ``wal.fsync``
        span (group size, covered rids)."""
        with self._lock:
            self._wal.sync(**attrs)
            if self.durable_seqno != self.applied_seqno:
                self.durable_seqno = self.applied_seqno
                if self.on_append is not None:
                    self.on_append()  # durable advanced: wake the senders

    def records_from(self, seqno: int):
        """Replication backlog: every retained DURABLE record with a
        seqno beyond ``seqno``, or None when the request predates the
        retention window (the follower needs a snapshot bootstrap, not
        a stream).  Records past ``durable_seqno`` (appended but not yet
        group-fsync'd) are withheld: a follower must never hold a record
        its leader could still lose."""
        with self._lock:
            if seqno < self.repl_floor:
                return None
            durable = self.durable_seqno
            return [(s, p) for s, p in self._wal_tail
                    if seqno < s <= durable]

    def _apply_pairs(self, pairs: np.ndarray) -> None:
        """Fold one decoded batch into the live state (also the WAL
        replay path — keep it deterministic and side-effect-free beyond
        the state arrays).  Bumps the seqlock version around the whole
        batch so lock-free readers never observe a half-applied one.

        Vectorized (ISSUE 19): the per-pair bookkeeping — vid growth,
        the incremental degree histogram (two +1s per record, the
        bincount semantics of core.sequence.host_degree_histogram, so
        the counting-sort rebuild never needs a recount pass), position
        gathers, pst counts, and both drift detectors — runs as whole-
        batch numpy ops; only the order-dependent tree links still walk
        one at a time.  The rank-drift test therefore sees the BATCH's
        full degree counts rather than a mid-batch prefix — detection
        moves at most a few records earlier, and stays deterministic
        because every path (live insert, WAL replay, follower apply)
        folds identical record batches through this same code."""
        self._mut_begin()
        try:
            self._subtree_cache = None
            if len(pairs) == 0:
                return
            inv = int(INVALID_JNID)
            us = pairs[:, 0].astype(np.int64)
            vs = pairs[:, 1].astype(np.int64)
            self._ensure_vid(int(max(us.max(), vs.max())))
            self.ins_tail.extend(us.tolist())
            self.ins_head.extend(vs.tolist())
            np.add.at(self.deg, us, 1)
            np.add.at(self.deg, vs, 1)
            pu = self.pos[us].astype(np.int64)
            pv = self.pos[vs].astype(np.int64)
            nonself = us != vs
            absent = (pu == inv) | (pv == inv)
            moved = ((self.deg[us] - self.deg_base[us]
                      >= self.reseq_rank)
                     | (self.deg[vs] - self.deg_base[vs]
                        >= self.reseq_rank))
            self.seq_drift += int(np.count_nonzero(
                nonself & (absent | moved)))
            live = pu != pv  # self-loops and both-absent pairs are inert
            lo = np.minimum(pu, pv)[live]
            hi = np.maximum(pu, pv)[live]
            # pst counts at the present earlier endpoint (INVALID is the
            # uint32 max, so min() lands on the present one)
            np.add.at(self.pst, lo, 1)
            linkable = (hi != inv) & (hi < len(self.parent))
            if np.any(linkable):
                parent = self.parent
                skip = self._link_skip_for()
                for plo, phi in zip(lo[linkable].tolist(),
                                    hi[linkable].tolist()):
                    insert_link(parent, plo, phi, skip)
                lu = us[live][linkable]
                lv = vs[live][linkable]
                # drift: a cut insert raises ECV(down) by at most one
                self.drift_cut += int(np.count_nonzero(
                    self.parts[lu] != self.parts[lv]))
        finally:
            self._mut_end()

    def _link_skip_for(self) -> np.ndarray:
        """The tree's ancestor memo for :func:`insert_link`, allocated
        lazily and dropped whenever :attr:`parent` is swapped (snapshot
        load, re-sequence).  Caller holds the state lock."""
        skip = self._link_skip
        if skip is None or len(skip) != len(self.parent):
            skip = np.full(len(self.parent), INVALID_JNID, dtype=np.uint32)
            self._link_skip = skip
        return skip

    def _fold_edge(self, u: int, v: int) -> None:
        """The incremental transform for ONE edge already counted into
        ``deg`` and the ins lists: position mapping, pst, tree link, and
        both drift detectors.  Shared by the live insert/replay path and
        the post-cut replay of :meth:`reseq_swap` — the determinism of
        this fold is what makes a resumed re-sequence bit-identical."""
        pu = int(self.pos[u])
        pv = int(self.pos[v])
        if u != v:
            # sequence drift (distinct from cut drift): the edge landed
            # outside the fixed sequence, or an endpoint's degree rank
            # moved far enough that the fixed order is now lying
            if pu == INVALID_JNID or pv == INVALID_JNID:
                self.seq_drift += 1
            elif (self.deg[u] - self.deg_base[u] >= self.reseq_rank
                  or self.deg[v] - self.deg_base[v] >= self.reseq_rank):
                self.seq_drift += 1
        if pu == pv:
            return  # self-loop or both endpoints absent: inert
        lo, hi = min(pu, pv), max(pu, pv)
        self.pst[lo] += 1  # pst counts at the present earlier endpoint
        if hi != INVALID_JNID and hi < len(self.parent):
            insert_link(self.parent, lo, hi, self._link_skip_for())
            # drift: a cut insert raises ECV(down) by at most one
            part_u, part_v = int(self.parts[u]), int(self.parts[v])
            if part_u != part_v:
                self.drift_cut += 1

    def _ensure_vid(self, vid: int) -> None:
        """Grow the vid-indexed tables over a never-seen vertex (absent
        from the sequence: pst-only until a future re-sequence)."""
        if vid < len(self.parts):
            return
        grow = vid + 1 - len(self.parts)
        self.parts = np.concatenate(
            [self.parts, np.full(grow, INVALID_PART, dtype=np.int64)])
        self.pos = np.concatenate(
            [self.pos, np.full(grow, INVALID_JNID, dtype=np.uint32)])
        zeros = np.zeros(grow, dtype=np.int64)
        self.deg = np.concatenate([self.deg, zeros])
        self.deg_base = np.concatenate([self.deg_base, zeros])

    # -- snapshots ---------------------------------------------------------

    def seal_snapshot(self) -> str:
        """Seal the current state as a new snapshot generation, swap in a
        fresh WAL, and GC old generations (keep :data:`KEEP_SNAPSHOTS`).
        Raises on failure with the previous generation + log intact."""
        with self._lock:
            snap = ServeSnapshot(
                seq=self.seq, parent=self.parent, pst=self.pst,
                parts=self.parts, num_parts=self.num_parts,
                applied_seqno=self.applied_seqno,
                ins_tail=np.asarray(self.ins_tail, dtype=np.uint32),
                ins_head=np.asarray(self.ins_head, dtype=np.uint32),
                drift_cut=self.drift_cut, baseline_ecv=self.baseline_ecv,
                graph_path=self.graph_path or "", sig=self.sig,
                balance=self.balance, epoch=self.epoch,
                epoch_base=self.epoch_base, deg=self.deg,
                deg_base=self.deg_base, seq_drift=self.seq_drift,
                reseqs=self.reseqs, seq_gen=self.seq_gen,
                ins_base=self.ins_base)
            path = os.path.join(self.state_dir,
                                snap_name(self.applied_seqno))
            save_serve_snapshot(path, snap, self.governor)
            # the snapshot is durable: later records are redundant — swap
            # in a fresh log.  A crash between the two leaves <=applied
            # records in the old log, which replay skips by seqno.
            create_wal(wal_path(self.state_dir), self.sig,
                       epoch=self.epoch)
            self._wal.close()
            self._wal = WalAppender(wal_path(self.state_dir),
                                    expect_sig=self.sig)
            self._wal.next_seqno = self.applied_seqno + 1
            self._inserts_since_snap = 0
            # the durable snapshot covers every applied record, synced
            # or not: group-commit waiters parked on the old log are
            # released by the seal itself
            if self.durable_seqno != self.applied_seqno:
                self.durable_seqno = self.applied_seqno
                if self.on_append is not None:
                    self.on_append()
            with self._gc_cv:
                self._gc_cv.notify_all()
            # the replication window deliberately survives the swap:
            # followers one record behind keep streaming (trim is by
            # count, _tail_push), only the on-disk log starts fresh
            self._gc_snapshots(keep=KEEP_SNAPSHOTS)
            return path

    def maybe_seal(self) -> str | None:
        """Cadence-driven seal that refuses to die: a full disk or an
        injected snap/ENOSPC fault is counted and the daemon keeps
        serving off the WAL (which already holds every acked insert)."""
        try:
            return self.seal_snapshot()
        except OSError as exc:
            self.snap_failures += 1
            self._inserts_since_snap = 0  # retry at the NEXT cadence
            warnings.warn(f"serve: snapshot seal failed ({exc}); "
                          f"continuing on the WAL")
            return None

    def _gc_snapshots(self, keep: int) -> None:
        for path in snap_paths(self.state_dir)[:-keep]:
            for p in (path, sidecar_path(path)):
                try:
                    os.unlink(p)
                except OSError:
                    pass

    # -- replication epoch transitions -------------------------------------

    def advance_epoch(self, new_epoch: int) -> str:
        """Move this state into a later replication term: archive the
        outgoing epoch's log (the fsck audit trail for the promotion
        boundary), bump the epoch, and seal a snapshot so the boundary
        is durable before anyone is told about it.  Used by a follower
        PROMOTING to leader and by a follower ADOPTING a new leader's
        epoch mid-stream (serve/cluster.py, serve/replicate.py).

        Raises with the epoch UNCHANGED if the seal fails — a promotion
        that cannot persist its fence must not claim it."""
        with self._lock:
            if new_epoch <= self.epoch:
                raise ValueError(
                    f"epoch must advance: {new_epoch} <= {self.epoch}")
            wpath = wal_path(self.state_dir)
            arch = os.path.join(self.state_dir,
                                archived_wal_name(self.epoch))
            try:
                import shutil
                shutil.copyfile(wpath, arch)
                with open(arch, "rb") as f:
                    os.fsync(f.fileno())
                # the archive bypasses atomic_write (a straight copy), so
                # the post-seal rot seam fires here explicitly: archived
                # epoch WALs are scrubbable artifacts like any other
                from ..io import faultfs
                faultfs.rot_after_seal(arch)
            except OSError as exc:
                # the archive is an audit artifact, not a recovery
                # dependency (every record is in the sealed snapshot)
                warnings.warn(f"serve: could not archive epoch-"
                              f"{self.epoch} WAL ({exc})")
            old = self.epoch
            old_base = self.epoch_base
            self.epoch = new_epoch
            self.epoch_base = self.applied_seqno
            try:
                return self.seal_snapshot()
            except BaseException:
                self.epoch = old
                self.epoch_base = old_base
                raise

    def reset_from_snapshot(self, snap: ServeSnapshot,
                            allow_sig_change: bool = False,
                            allow_gen_rollback: bool = False,
                            allow_rollback: bool = False) -> None:
        """Follower full re-sync: discard the local chain and adopt a
        snapshot shipped by the leader (the stream could not be resumed
        — the follower lagged past the leader's WAL, or carries a fenced
        ex-leader's divergent tail).  Every intermediate crash window
        re-opens consistently: the local log is emptied FIRST (the local
        history is being discarded either way), the adopted snapshot is
        sealed under its own epoch, and only then is the stale chain
        removed — :meth:`open` prefers the higher epoch throughout.

        ``allow_sig_change`` — the leader re-sequenced (ISSUE 18): the
        adopted snapshot carries a LATER sequence generation under a new
        input signature.  The caller must have written the local reseq
        manifest sanctioning old->new first, or a crash mid-adoption
        leaves a sig mismatch :meth:`open` correctly refuses.

        ``allow_gen_rollback`` — the CLUSTER lost our generation (ISSUE
        19): this replica applied a re-sequence swap the failed leader
        never quorum-acked, and the surviving leader's chain has never
        seen our sig.  Rolling back to the leader's (older) generation
        is then the only exit that doesn't strand the replica in a
        ``badrepl`` retry loop.  It is sound because nothing a client
        was ever acked lives only in the orphaned generation (the swap
        itself carries no client writes, and the surviving leader holds
        every quorum-acked record); the caller MUST have written the
        adoption manifest (reseq.write_adoption) sanctioning the
        rollback first, same discipline as ``allow_sig_change``.

        ``allow_rollback`` — quarantine healing (ISSUE 20): the stream
        anti-entropy check proved this replica's tail DIVERGENT, so
        adopting the leader's (possibly older-seqno) snapshot and
        re-streaming from its boundary is the point, not an accident.
        Sound because every acked record past the snapshot boundary is
        in the leader's chain and re-ships on reconnect; the caller must
        hold the durable quarantine marker (serve/scrub.py) sanctioning
        the discard, same discipline as the other two flags."""
        snap.validate()
        with self._lock:
            if snap.sig != self.sig and not (
                    allow_sig_change and (snap.seq_gen > self.seq_gen
                                          or allow_gen_rollback)):
                raise IntegrityError(
                    f"replication snapshot belongs to a different build "
                    f"input (sig {snap.sig[:12]}..., ours "
                    f"{self.sig[:12]}...) — refusing to adopt")
            if (snap.epoch, snap.applied_seqno) < (self.epoch,
                                                   self.applied_seqno) \
                    and not (allow_gen_rollback or allow_rollback):
                raise IntegrityError(
                    f"replication snapshot (epoch {snap.epoch}, seqno "
                    f"{snap.applied_seqno}) is older than the local state "
                    f"(epoch {self.epoch}, seqno {self.applied_seqno}) — "
                    f"refusing to roll back")
            old_snaps = snap_paths(self.state_dir)
            from .wal import archived_wal_paths
            for p in archived_wal_paths(self.state_dir):
                try:
                    os.unlink(p)
                except OSError:
                    pass
            # 1. empty the local log (old epoch): the divergent/lagged
            #    tail is discarded by design, and the dir still opens
            self._wal.close()
            create_wal(wal_path(self.state_dir), self.sig,
                       epoch=self.epoch)
            # 2. seal the adopted snapshot; open() now picks it by epoch
            path = os.path.join(self.state_dir,
                                snap_name(snap.applied_seqno))
            save_serve_snapshot(path, snap, self.governor)
            # 3. fresh log for the adopted epoch (and, after a leader
            #    re-sequence, the adopted SIGNATURE), then drop the
            #    stale chain
            create_wal(wal_path(self.state_dir), snap.sig,
                       epoch=snap.epoch)
            self._wal = WalAppender(wal_path(self.state_dir),
                                    expect_sig=snap.sig)
            self._wal.next_seqno = snap.applied_seqno + 1
            for p in old_snaps:
                if p != path:
                    for q in (p, sidecar_path(p)):
                        try:
                            os.unlink(q)
                        except OSError:
                            pass
            self._load_snapshot(snap)

    def snapshot_bytes(self) -> tuple[bytes, int, int]:
        """Seal the current state and return ``(blob, applied_seqno,
        epoch)`` — the bootstrap payload a leader ships to a follower
        that cannot be served from the live WAL (serve/replicate.py)."""
        with self._lock:
            path = self.seal_snapshot()
            with open(path, "rb") as f:
                blob = f.read()
            return blob, self.applied_seqno, self.epoch

    # -- repartition -------------------------------------------------------

    def drift_exceeded(self) -> bool:
        """Has insert drift crossed the re-partition threshold?  The
        threshold is ``drift_frac`` of the baseline ECV(down) when one is
        known, floored at ``drift_min_cut`` cut inserts."""
        with self._lock:
            threshold = self.drift_min_cut
            if self.baseline_ecv > 0:
                threshold = max(threshold,
                                int(self.drift_frac * self.baseline_ecv))
            return self.drift_cut >= threshold

    def repartition(self) -> dict:
        """Re-run the tree partitioner over the CURRENT tree and swap the
        new part table in atomically.  The compute runs on copies outside
        the lock — queries keep answering from the stale partition until
        the swap."""
        with self._lock:
            forest = Forest(self.parent.copy(), self.pst.copy())
            num_parts = self.num_parts
            balance = self.balance
            ticket = self._repart_ticket
            self._repart_ticket += 1
        jparts = partition_forest(
            forest, num_parts, TreePartitionOptions(balance_factor=balance))
        with self._lock:
            if ticket <= self._repart_applied:
                # a repartition that STARTED later (fresher tree) already
                # swapped in; this stale result must not clobber it
                return {"parts": int(self.parts.max(initial=0)) + 1,
                        "baseline_ecv": self.baseline_ecv,
                        "stale": 1}
            self._repart_applied = ticket
            vparts = np.full(len(self.parts), INVALID_PART, dtype=np.int64)
            vparts[self.seq] = jparts
            self._mut_begin()
            try:
                self.parts = vparts
            finally:
                self._mut_end()
            self.drift_cut = 0
            self.repartitions += 1
            if self.edges_tail is not None:
                tail, head = self._all_edges()
                self.baseline_ecv = ecv_down(self.parts, tail, head,
                                             self.pos)
            # make the swap durable: without a seal a restart would
            # serve the PRE-repartition parts (the snapshot's) — legal
            # (stale-but-consistent) but a silent quality regression.
            # Best-effort: a full disk keeps the old generation and the
            # in-memory swap still serves.
            self.maybe_seal()
            return {"parts": int(vparts.max(initial=0)) + 1,
                    "baseline_ecv": self.baseline_ecv}

    # -- re-sequence (ISSUE 18) --------------------------------------------
    #
    # Repartition re-bins the EXISTING tree; it cannot recover quality
    # lost to inserts that landed outside the bootstrap-fixed sequence
    # (pst-only vertices never enter the tree).  The re-sequence path
    # rebuilds sequence + tree + partition from the durable edge set
    # (graph .dat + WAL'd inserts) under a degree order that reflects
    # the churn, and swaps it in under the same ticket discipline.  The
    # heavy fold runs in serve/reseq.py (durable manifest, extmem fold,
    # kill-safe phases); the core owns only the bookkeeping and the
    # atomic swap.

    def recount_degrees(self) -> np.ndarray:
        """Full recount of the degree histogram over the RESIDENT edge
        set — the parity oracle for the incremental counters (only
        meaningful while the graph edges are resident or the core never
        had a graph)."""
        with self._lock:
            tail, head = self._all_edges()
            return host_degree_histogram(tail, head, len(self.parts))

    def degree_parity(self) -> bool:
        """Does the incrementally maintained histogram equal a full
        recount?  Asserted by the reseq driver before trusting the
        incremental counts for a sequence rebuild."""
        with self._lock:
            return bool(np.array_equal(self.deg, self.recount_degrees()))

    def seq_drift_exceeded(self) -> bool:
        """Has SEQUENCE drift (inserts the fixed order mis-handles)
        crossed the re-sequence threshold?  ``reseq_frac`` of the
        inserts past the current cut, with at least ``reseq_min``
        inserts observed first."""
        with self._lock:
            since = len(self.ins_tail) - self.ins_base
            if since < self.reseq_min:
                return False
            return self.seq_drift >= max(1, int(self.reseq_frac * since))

    def reseq_begin(self) -> dict:
        """Capture the inputs of one re-sequence attempt under the lock:
        the ticket (later-started wins, exactly the repartition rule)
        and the CUT — how many inserted edges the rebuild will cover.
        (durable edges + cut) fully determine the rebuilt state, which
        is what makes a crash-resumed rebuild bit-identical."""
        with self._lock:
            ticket = self._reseq_ticket
            self._reseq_ticket += 1
            return {
                "ticket": ticket,
                "cut": len(self.ins_tail),
                "num_parts": self.num_parts,
                "balance": self.balance,
                "graph_path": self.graph_path,
                "old_sig": self.sig,
                "seq_gen": self.seq_gen,
                "epoch": self.epoch,
                "applied_seqno": self.applied_seqno,
                "seq_drift": self.seq_drift,
                "deg": self.deg.copy(),
            }

    def ins_slice(self, cut: int):
        """The first ``cut`` WAL'd inserts as uint32 arrays (copies)."""
        with self._lock:
            return (np.asarray(self.ins_tail[:cut], dtype=np.uint32),
                    np.asarray(self.ins_head[:cut], dtype=np.uint32))

    def reseq_swap(self, ticket: int, cut: int, new_seq: np.ndarray,
                   parent: np.ndarray, pst: np.ndarray,
                   jparts: np.ndarray, new_sig: str, gen: int) -> dict:
        """Swap a rebuilt (sequence, tree, partition) in atomically.
        The rebuild covers the durable edge set up to ``cut``; inserts
        that arrived DURING the rebuild are replayed through the
        incremental transform under the lock, so queries go from one
        consistent state to the other with no torn window.  Stale
        tickets (a later-started rebuild already swapped) are refused.
        NOT durable by itself — the driver seals right after (its own
        kill boundary)."""
        with self._lock:
            if ticket <= self._reseq_applied:
                return {"stale": 1}
            self._reseq_applied = ticket
            # any in-flight repartition was computed over the old jnid
            # space: its result must not land on the new tree
            self._repart_applied = self._repart_ticket - 1
            n_v = len(self.parts)
            # the whole multi-array swap + post-cut replay is ONE
            # seqlock write: lock-free readers either see the old
            # generation or the fully replayed new one, never a mix
            self._mut_begin()
            try:
                self.seq = np.asarray(new_seq, dtype=np.uint32)
                self.parent = np.asarray(parent, dtype=np.uint32)
                self._link_skip = None  # memo is per-tree: swapped, reset
                self.pst = np.asarray(pst, dtype=np.uint32)
                self.pos = sequence_positions(self.seq, max(n_v - 1, 0))
                vparts = np.full(n_v, INVALID_PART, dtype=np.int64)
                vparts[self.seq] = np.asarray(jparts, dtype=np.int64)
                self.parts = vparts
                self.sig = str(new_sig)
                self.seq_gen = int(gen)
                # the new sequence was established at the cut: rank
                # drift is measured against the histogram AS OF the cut
                post_t = np.asarray(self.ins_tail[cut:], dtype=np.uint32)
                post_h = np.asarray(self.ins_head[cut:], dtype=np.uint32)
                self.deg_base = self.deg - host_degree_histogram(
                    post_t, post_h, n_v)
                self.ins_base = int(cut)
                self.seq_drift = 0
                self.drift_cut = 0
                self._subtree_cache = None
                self._part_lut = None
                for u, v in zip(post_t.tolist(), post_h.tolist()):
                    self._fold_edge(int(u), int(v))
            finally:
                self._mut_end()
            if self.edges_tail is not None:
                tail, head = self._all_edges()
                self.baseline_ecv = ecv_down(self.parts, tail, head,
                                             self.pos)
            self.reseqs += 1
            return {"n": len(self.seq),
                    "parts": int(self.parts.max(initial=0)) + 1,
                    "baseline_ecv": self.baseline_ecv,
                    "seq_gen": self.seq_gen,
                    "replayed": len(post_t)}
