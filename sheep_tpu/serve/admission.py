"""Admission control: the daemon sheds load instead of dying under it.

Two pressure signals, two degradations, both typed:

  slots    at most ``max_inflight`` requests execute at once; past that,
           new work is REFUSED with a typed overload error, not queued
           into an unbounded backlog (queueing is the client's job — a
           refusal tells it so honestly).  Inserts shed FIRST: they stop
           being admitted at half the slot budget (``insert_watermark``),
           so a burst degrades write availability before read
           availability — the partition service's whole job is answering
           part(v).
  memory   once measured RSS crosses the soft fraction of
           ``SHEEP_MEM_BUDGET`` (resources/governor.py — the same signal
           the chunk drivers shrink under), the service degrades to
           READ-ONLY: inserts are refused with a typed readonly error
           (they grow the resident state; queries do not), and recovers
           automatically when pressure clears.  Dying was the
           alternative; the OOM killer does not send "ERR".

Refusals are exceptions (``Overloaded`` / ``ReadOnly``) so the protocol
layer maps them to one-line typed errors and nothing anywhere interprets
a shed request as success.
"""

from __future__ import annotations

import contextlib
import threading

from ..resources.governor import ResourceGovernor


class AdmissionRefused(Exception):
    """Base of every admission refusal; ``code`` is the protocol error
    token the client sees."""

    code = "refused"


class Overloaded(AdmissionRefused):
    code = "overload"


class ReadOnly(AdmissionRefused):
    code = "readonly"


class AdmissionController:
    """Slot accounting + memory-pressure policy for one daemon."""

    def __init__(self, max_inflight: int = 64,
                 governor: ResourceGovernor | None = None,
                 read_only: bool = False):
        if max_inflight < 1:
            raise ValueError(f"max_inflight {max_inflight} must be >= 1")
        self.max_inflight = max_inflight
        #: inserts stop being admitted here — queries get the other half
        self.insert_watermark = max(1, max_inflight // 2)
        self.governor = governor if governor is not None \
            else ResourceGovernor.from_env()
        self.read_only = read_only
        self._inflight = 0
        self._lock = threading.Lock()
        self.shed = 0
        self.readonly_refusals = 0

    @property
    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    def _enter(self, kind: str) -> None:
        if kind == "insert":
            if self.read_only:
                self.readonly_refusals += 1
                raise ReadOnly("service is read-only")
            if self.governor.mem_pressure():
                self.readonly_refusals += 1
                raise ReadOnly(
                    "memory pressure: service degraded to read-only "
                    "(rss past the SHEEP_MEM_BUDGET soft threshold); "
                    "retry when pressure clears")
        with self._lock:
            limit = (self.insert_watermark if kind == "insert"
                     else self.max_inflight)
            if self._inflight >= limit:
                self.shed += 1
                raise Overloaded(
                    f"{self._inflight} requests in flight (limit {limit} "
                    f"for {kind}); shedding - retry with backoff")
            self._inflight += 1

    def _exit(self) -> None:
        with self._lock:
            self._inflight -= 1

    @contextlib.contextmanager
    def admit(self, kind: str):
        """Hold one request slot for the duration of its handling (kind:
        "query" or "insert").  Raises Overloaded/ReadOnly instead of
        entering."""
        self._enter(kind)
        try:
            yield
        finally:
            self._exit()
