"""sheep serve: the crash-safe, replicated partition service (ISSUES 6+7).

Until now every caller paid a cold build; this package keeps the tree +
partition resident and answers over a line protocol, with incremental
edge inserts folded in by the same union-find transform the batch build
uses — WAL-first, so nothing acknowledged is ever lost.  ISSUE 7 ships
that WAL to followers: a leader streams acked records over the same
protocol, followers apply them through the same insert path and serve
reads, and failover is epoch-fenced promotion — no acknowledged insert
dies with the leader.

  wal.py        checksummed, fsync'd, epoch-stamped write-ahead log
  state.py      ServeCore: snapshot format, recovery (snapshot+replay),
                the incremental insert transform, queries, drift-
                triggered repartition, replicated apply + epoch fences
  admission.py  slot + memory-pressure shedding (inserts shed first,
                read-only under pressure)
  protocol.py   the wire grammar + reference client (REPL verbs)
  daemon.py     selectors I/O loop, deadlines, fault hooks, heartbeat
                liveness, cluster roles
  replicate.py  WAL shipping: frame codec, leader hub, follower applier
  cluster.py    membership, leader discovery, quorum-vote elections,
                epoch-fenced failover
  tenants.py    multi-tenancy (ISSUE 11): N state dirs behind one
                daemon, TENANT selector, governor-priced eviction
  router.py     the fleet tier (ISSUE 11): consistent-hash tenants
                onto clusters, read spreading, epoch-safe retries
  faults.py     SHEEP_SERVE_FAULT_PLAN (kill/hang/slow @ request sites)
  netfaults.py  SHEEP_SERVE_NETFAULT_PLAN (drop/partition/slow/dup @
                replication frame sites)

Operational face: ``bin/serve`` / ``sheep_tpu.cli.serve``; state dirs
are fsck-able (``sheep fsck state-dir/`` knows .wal and .snap, including
epoch chains across promotion boundaries).
"""

from .admission import AdmissionController, Overloaded, ReadOnly
from .cluster import (ClusterConfig, choose_successor, find_leader,
                      request_vote)
from .tenants import (DEFAULT_TENANT, TenantManager, TenantSpec,
                      UnknownTenant, parse_tenant_specs)
from .daemon import ServeConfig, ServeDaemon
from .faults import (SERVE_FAULT_PLAN_ENV, ServeKilled,
                     parse_serve_fault_plan)
from .netfaults import NETFAULT_PLAN_ENV, parse_netfault_plan
from .protocol import ServeClient, ServeError, connect_retry
from .replicate import (ReplApplier, ReplicationHub, Replicator,
                        bootstrap_state_dir, encode_append, parse_frame)
from .router import HashRing, Router, parse_clusters
from .state import (ReplicationGap, ServeCore, ecv_down, insert_link)
from .wal import WalAppender, create_wal, read_wal, repair_wal

__all__ = [
    "AdmissionController",
    "ClusterConfig",
    "DEFAULT_TENANT",
    "TenantManager",
    "TenantSpec",
    "UnknownTenant",
    "parse_tenant_specs",
    "request_vote",
    "HashRing",
    "Router",
    "parse_clusters",
    "NETFAULT_PLAN_ENV",
    "Overloaded",
    "ReadOnly",
    "ReplApplier",
    "ReplicationGap",
    "ReplicationHub",
    "Replicator",
    "SERVE_FAULT_PLAN_ENV",
    "ServeClient",
    "ServeConfig",
    "ServeCore",
    "ServeDaemon",
    "ServeError",
    "ServeKilled",
    "WalAppender",
    "bootstrap_state_dir",
    "choose_successor",
    "connect_retry",
    "create_wal",
    "ecv_down",
    "encode_append",
    "find_leader",
    "insert_link",
    "parse_frame",
    "parse_netfault_plan",
    "parse_serve_fault_plan",
    "read_wal",
    "repair_wal",
]
