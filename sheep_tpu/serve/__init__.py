"""sheep serve: the crash-safe long-lived partition service (ISSUE 6).

Until now every caller paid a cold build; this package keeps the tree +
partition resident and answers over a line protocol, with incremental
edge inserts folded in by the same union-find transform the batch build
uses — WAL-first, so nothing acknowledged is ever lost.

  wal.py        checksummed, fsync'd write-ahead log (torn-tail policy)
  state.py      ServeCore: snapshot format, recovery (snapshot+replay),
                the incremental insert transform, queries, drift-
                triggered repartition
  admission.py  slot + memory-pressure shedding (inserts shed first,
                read-only under pressure)
  protocol.py   the wire grammar + reference client
  daemon.py     sockets, deadlines, fault hooks, heartbeat liveness
  faults.py     SHEEP_SERVE_FAULT_PLAN (kill/hang/slow @ request sites)

Operational face: ``bin/serve`` / ``sheep_tpu.cli.serve``; state dirs
are fsck-able (``sheep fsck state-dir/`` knows .wal and .snap).
"""

from .admission import AdmissionController, Overloaded, ReadOnly
from .daemon import ServeConfig, ServeDaemon
from .faults import (SERVE_FAULT_PLAN_ENV, ServeKilled,
                     parse_serve_fault_plan)
from .protocol import ServeClient, ServeError, connect_retry
from .state import ServeCore, ecv_down, insert_link
from .wal import WalAppender, create_wal, read_wal, repair_wal

__all__ = [
    "AdmissionController",
    "Overloaded",
    "ReadOnly",
    "SERVE_FAULT_PLAN_ENV",
    "ServeClient",
    "ServeConfig",
    "ServeCore",
    "ServeDaemon",
    "ServeError",
    "ServeKilled",
    "WalAppender",
    "connect_retry",
    "create_wal",
    "ecv_down",
    "insert_link",
    "parse_serve_fault_plan",
    "read_wal",
    "repair_wal",
]
