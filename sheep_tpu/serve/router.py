"""The router tier: consistent-hash tenants onto serve clusters.

One serve cluster answers for the graphs it hosts; a FLEET needs a tier
above the clusters that (1) maps tenant -> cluster without a config
push per tenant, (2) spreads reads over every replica instead of
hammering the leader, and (3) rides through failover without the client
noticing — the sharded-serving shape "Scalable Edge Partitioning"
(PAPERS.md) assumes of any partitioner claiming production scale.

**Placement** is a consistent-hash ring (:class:`HashRing`): each
cluster contributes ``vnodes`` sha1 points, a tenant id hashes to the
first point at-or-after it.  Adding a cluster moves ~1/N of tenants,
removing one moves only its own — no rendezvous table to version.

**Request handling** speaks the serve line grammar verbatim, so every
existing client (and ``nc``) works through the router unchanged:

  TENANT x     handled locally: selects the tenant AND the cluster for
               this connection (forwarded as the upstream selector when
               the proxied connection is opened).
  reads        PART / PARENT / SUBTREE / ECV / PING round-robin over
               the tenant's cluster members — leader and followers
               alike (the follower bounded-staleness refusal is typed,
               so a stale follower re-routes instead of lying).
  writes       INSERT / REPARTITION / SNAPSHOT / EVICT go to the
               cluster's current leader.
  STATS        pinned to the leader (the authoritative view).
  METRICS      answered by the router itself (ISSUE 12): the FLEET
               scrape — fan-in from every reachable cluster member
               with instance/cluster labels + derived fleet gauges
               (:meth:`Router.fleet_metrics`).
  ROUTER       answered by the router itself: per-router counters.
  MIGRATE      answered by the router itself (ISSUE 17): ``MIGRATE
               <tenant> <cluster> [wait=<s>]`` starts the live
               migration driver (serve/migrate.py) — snapshot
               bootstrap, delta stream, epoch-fenced cutover — and
               remaps the tenant when the cutover lands.

**Placement overrides** (ISSUE 17): a completed migration pins the
tenant to its new cluster in ``tenant-map.json`` (durable, tmp+fsync+
rename) — consulted before the ring, survives router restarts, and is
also learned reactively: a member answering ``ERR moved dest=<cid>``
teaches the router the new placement and the request is replayed there
(the fence refused it BEFORE applying, so the replay is epoch-safe —
first apply, not double apply).

**Trace context** (ISSUE 12): forwarded requests carry a ``RID=<hex>``
prefix token (adaptive — see :data:`RID_ENV`) so every process the
request crosses records joinable spans; ``sheep trace --merge``
stitches them.

**Failover contract** (the epoch-safe retry rule): a request that died
with a TYPED refusal was not applied — ``notleader`` re-resolves and
retries transparently, ``stale`` tries the next replica.  A connection
that died AFTER an INSERT was sent with no response is ambiguous: the
insert may be durable on the old leader's chain, so the router NEVER
re-sends it to a new epoch on its own — it answers ``ERR unavailable
... outcome unknown`` and the client (who owns idempotency) decides.
Reads are safely re-sent anywhere.  ``ERR unavailable``/``fenced``
responses re-resolve the leader before the next request.
"""

from __future__ import annotations

import hashlib
import json
import os
import socket
import threading
import time

from ..obs import trace
from ..obs.metrics import (Registry, parse_prometheus, relabel,
                           set_process_gauges)
from .cluster import find_leader, resolve_peer
from .protocol import (BadRequest, ServeClient, ServeError, err_line,
                       ok_kv, split_prefix_tokens)
from .tenants import DEFAULT_TENANT

CLUSTERS_ENV = "SHEEP_ROUTE_CLUSTERS"
VNODES_ENV = "SHEEP_ROUTE_VNODES"
#: trace-context stamping (ISSUE 12).  Unset (the default) is ADAPTIVE:
#: write verbs always carry a minted ``RID=`` token (the follower-fsync
#: attribution chain is the point, and a WAL fsync dwarfs the token),
#: while reads are stamped only when this router's own trace recorder
#: is live — a read's rid is only readable through the router's span,
#: so stamping it blind is pure wire+parse cost (PERF_NOTES r10).
#: "1" forces stamping on every request; "0" disables minting entirely
#: (client-sent RID= tokens always forward regardless).
RID_ENV = "SHEEP_ROUTE_RID"

ADDR_FILE = "router.addr"
#: durable tenant->cluster placement overrides (migration landings)
TENANT_MAP_FILE = "tenant-map.json"

#: reads that spread across every cluster member
SPREAD_VERBS = ("PART", "PARENT", "SUBTREE", "ECV", "PING")
#: verbs pinned to the tenant's cluster leader (METRICS is NOT here
#: anymore: the router answers it itself with the fleet scrape)
LEADER_VERBS = ("INSERT", "REPARTITION", "SNAPSHOT", "EVICT", "STATS")


class HashRing:
    """Consistent hashing of tenant ids onto cluster ids."""

    def __init__(self, cluster_ids, vnodes: int = 64):
        if not cluster_ids:
            raise ValueError("a ring needs at least one cluster")
        self.cluster_ids = list(cluster_ids)
        self.vnodes = vnodes
        points = []
        for cid in self.cluster_ids:
            for i in range(vnodes):
                points.append((self._hash(f"{cid}#{i}"), cid))
        points.sort()
        self._points = points

    @staticmethod
    def _hash(key: str) -> int:
        return int.from_bytes(
            hashlib.sha1(key.encode("utf-8")).digest()[:8], "big")

    def lookup(self, key: str) -> str:
        """The cluster id owning ``key``: first ring point at or after
        the key's hash (wrapping)."""
        h = self._hash(key)
        pts = self._points
        lo, hi = 0, len(pts)
        while lo < hi:
            mid = (lo + hi) // 2
            if pts[mid][0] < h:
                lo = mid + 1
            else:
                hi = mid
        return pts[lo % len(pts)][1]


def parse_clusters(spec: str) -> dict[str, list[str]]:
    """``[name@]peer,peer[;...]`` -> {cluster_id: [peer specs]}.
    Unnamed clusters get positional ids c0, c1, ... (stable for a fixed
    spec; name clusters explicitly if the set will grow)."""
    out: dict[str, list[str]] = {}
    for i, entry in enumerate(s for s in spec.split(";") if s.strip()):
        entry = entry.strip()
        name, sep, peers = entry.partition("@")
        if not sep:
            name, peers = f"c{i}", entry
        name = name.strip()
        plist = [p.strip() for p in peers.split(",") if p.strip()]
        if not name or not plist:
            raise ValueError(
                f"cluster entry {entry!r}: want [name@]peer,peer")
        if name in out:
            raise ValueError(f"cluster {name!r} named twice")
        out[name] = plist
    if not out:
        raise ValueError(f"no clusters in {spec!r}")
    return out


class _Upstream:
    """One proxied connection to one backend node, tenant-stamped."""

    __slots__ = ("client", "tenant")

    def __init__(self, client: ServeClient):
        self.client = client
        self.tenant = DEFAULT_TENANT


class _Cluster:
    """One serve cluster as the router sees it: peer specs, a cached
    leader, and a read-spread cursor."""

    #: how long an ``ERR diverged`` keeps a member out of the read
    #: spread before it is re-tried (heals are snapshot-sized; the
    #: member itself re-admits reads the moment its quarantine clears)
    DIVERGED_TTL_S = 5.0

    def __init__(self, cid: str, peers: list[str],
                 poll_timeout_s: float = 2.0):
        self.cid = cid
        self.peers = peers
        self.poll_timeout_s = poll_timeout_s
        self._leader: tuple[str, int] | None = None
        self._rr = 0
        self._lock = threading.Lock()
        self._diverged: dict[tuple[str, int], float] = {}

    def nodes(self) -> list[tuple[str, int]]:
        out = []
        for spec in self.peers:
            addr = resolve_peer(spec)
            if addr is not None and addr not in out:
                out.append(addr)
        return out

    def leader(self, refresh: bool = False) -> tuple[str, int] | None:
        with self._lock:
            if self._leader is not None and not refresh:
                return self._leader
        found = find_leader(self.peers, self.poll_timeout_s)
        addr = None
        if found is not None:
            host, _, port = found[0].rpartition(":")
            addr = (host, int(port))
        with self._lock:
            self._leader = addr
        return addr

    def set_leader_hint(self, hint: str) -> None:
        """``ERR notleader host:port`` carried the answer — use it."""
        host, _, port = hint.rpartition(":")
        try:
            addr = (host, int(port))
        except ValueError:
            return
        with self._lock:
            self._leader = addr

    def forget_leader(self) -> None:
        with self._lock:
            self._leader = None

    def mark_diverged(self, addr: tuple[str, int]) -> None:
        """A member answered ``ERR diverged`` (ISSUE 20): keep it out
        of the read spread until the TTL lapses — every read it would
        get is a guaranteed refusal while it re-syncs."""
        with self._lock:
            self._diverged[addr] = time.monotonic() + self.DIVERGED_TTL_S

    def read_targets(self) -> list[tuple[str, int]]:
        """Cluster members, rotated one step per call — the read spread
        across followers AND leader; retries walk the rest of the
        list.  Members marked diverged are pushed to the BACK, not
        dropped: if every healthy member is unreachable they are still
        a typed answer, and their refusal re-confirms the mark."""
        nodes = self.nodes()
        if not nodes:
            return []
        now = time.monotonic()
        with self._lock:
            self._diverged = {a: t for a, t in self._diverged.items()
                              if t > now}
            bad = set(self._diverged)
            self._rr = (self._rr + 1) % len(nodes)
            k = self._rr
        rotated = nodes[k:] + nodes[:k]
        if not bad:
            return rotated
        return ([a for a in rotated if a not in bad]
                + [a for a in rotated if a in bad])


class Router:
    """The daemon: thread-per-connection proxy over the cluster map.

    Deliberately simpler than the serve daemon's selectors loop — the
    router holds no graph state, so a stalled connection costs one
    thread, not a tenant; and the bench measures it as its own process
    (pinned separately, scripts/servebench.py) so its cost never hides
    inside a daemon's numbers.
    """

    def __init__(self, clusters: dict[str, list[str]],
                 host: str = "127.0.0.1", port: int = 0,
                 state_dir: str | None = None, vnodes: int = 64,
                 retries: int = 4, poll_timeout_s: float = 2.0):
        self.clusters = {cid: _Cluster(cid, peers, poll_timeout_s)
                         for cid, peers in clusters.items()}
        self.ring = HashRing(sorted(self.clusters), vnodes=vnodes)
        self.host = host
        self.port = port
        self.state_dir = state_dir
        self.retries = retries
        self.poll_timeout_s = poll_timeout_s
        _rid_env = os.environ.get(RID_ENV, "")
        self.rid_enabled = _rid_env != "0"
        self.rid_always = _rid_env == "1"
        self._listener: socket.socket | None = None
        self._accept_thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self.started_at = time.monotonic()
        self.counters = {"conns": 0, "requests": 0, "reads": 0,
                         "writes": 0, "retries": 0, "reroutes": 0,
                         "errors": 0, "insert_unknown": 0,
                         "scrapes": 0, "scrape_errors": 0,
                         "moved_reroutes": 0, "diverged_skips": 0}
        # the router's own registry (ISSUE 12): its counters + process
        # self-accounting ride the fleet scrape like any member's
        self.metrics = Registry()
        # live migration state (ISSUE 17): placement overrides beat the
        # ring, one driver per tenant, completion/abort tallies
        self._overrides: dict[str, str] = self._load_overrides()
        self._migrations: dict[str, object] = {}
        self.mig_completed = 0
        self.mig_aborted = 0
        # set by cli/route.py when SHEEP_REBALANCE=1
        self.rebalancer = None

    # -- lifecycle ---------------------------------------------------------

    @property
    def address(self) -> tuple[str, int]:
        assert self._listener is not None, "router not started"
        return self._listener.getsockname()[:2]

    def start(self) -> "Router":
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((self.host, self.port))
        self._listener.listen(128)
        if self.state_dir:
            os.makedirs(self.state_dir, exist_ok=True)
            h, p = self.address
            with open(os.path.join(self.state_dir, ADDR_FILE), "w") as f:
                f.write(f"{h} {p}\n")
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="route-accept")
        self._accept_thread.start()
        self.resume_migrations()
        return self

    def run_forever(self) -> None:
        while not self._stop.wait(0.5):
            pass

    def shutdown(self) -> None:
        self._stop.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5.0)

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                sock, _ = self._listener.accept()
            except OSError:
                return
            self.counters["conns"] += 1
            threading.Thread(target=self._serve_conn, args=(sock,),
                             daemon=True, name="route-conn").start()

    # -- placement ---------------------------------------------------------

    def placement_of(self, tenant: str) -> str:
        """The cluster id owning ``tenant``: a migration override if one
        landed, the hash ring otherwise.  An override naming a cluster
        no longer in the map falls back to the ring (never KeyErrors a
        request)."""
        with self._lock:
            cid = self._overrides.get(tenant)
        if cid is not None and cid in self.clusters:
            return cid
        return self.ring.lookup(tenant)

    def cluster_for(self, tenant: str) -> _Cluster:
        return self.clusters[self.placement_of(tenant)]

    def cluster_by_id(self, cid: str) -> _Cluster | None:
        return self.clusters.get(cid)

    def remap(self, tenant: str, cid: str) -> None:
        """Atomically repoint ``tenant`` at ``cid`` — durable FIRST
        (tmp+fsync+rename of tenant-map.json), then the in-memory swap,
        so a kill -9 between the two re-reads the new placement instead
        of reviving the old one."""
        with self._lock:
            nxt = dict(self._overrides)
            nxt[tenant] = cid
            self._save_overrides(nxt)
            self._overrides = nxt

    def _overrides_path(self) -> str | None:
        if not self.state_dir:
            return None
        return os.path.join(self.state_dir, TENANT_MAP_FILE)

    def _load_overrides(self) -> dict[str, str]:
        path = self._overrides_path()
        if path is None:
            return {}
        try:
            with open(path) as f:
                rec = json.load(f)
            return {str(k): str(v) for k, v in rec.items()} \
                if isinstance(rec, dict) else {}
        except (OSError, ValueError):
            return {}

    def _save_overrides(self, recs: dict[str, str]) -> None:
        path = self._overrides_path()
        if path is None:
            return
        tmp = path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(recs, f, sort_keys=True)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except OSError:
            pass  # non-durable router: the remap still holds in memory

    # -- live migration (ISSUE 17) -----------------------------------------

    def start_migration(self, tenant: str, dest: str):
        """Start (or report) the one in-flight migration for ``tenant``.
        Returns the Migration driver; raises ValueError on an unknown
        destination or a no-op (tenant already lives there)."""
        from .migrate import Migration
        if dest not in self.clusters:
            raise ValueError(f"unknown cluster {dest!r} (have: "
                             f"{'/'.join(sorted(self.clusters))})")
        with self._lock:
            cur = self._migrations.get(tenant)
            if cur is not None and not cur.done.is_set():
                return cur
        if self.placement_of(tenant) == dest:
            raise ValueError(f"tenant {tenant!r} already lives on "
                             f"{dest}")
        mig = Migration(self, tenant, dest)
        with self._lock:
            self._migrations[tenant] = mig
        return mig.start()

    def migration_finished(self, mig) -> None:
        """Driver completion callback (any terminal phase)."""
        with self._lock:
            if mig.phase == "done":
                self.mig_completed += 1
            elif mig.phase == "aborted":
                self.mig_aborted += 1

    def resume_migrations(self) -> list:
        """Restart every persisted, unfinished migration manifest —
        the kill -9'd router picks up where it stopped (every daemon-
        side MIG op is idempotent, so resuming is re-issuing)."""
        from .migrate import Migration, load_manifests
        out = []
        if not self.state_dir:
            return out
        for rec in load_manifests(self.state_dir):
            if rec.get("phase") in ("done", "aborted"):
                continue
            tenant, dest = rec["tenant"], rec.get("dest")
            if dest not in self.clusters:
                continue
            mig = Migration(self, tenant, dest, resume=rec)
            with self._lock:
                self._migrations[tenant] = mig
            out.append(mig.start())
        return out

    # -- one client connection ---------------------------------------------

    def _serve_conn(self, sock: socket.socket) -> None:
        upstreams: dict[tuple[str, int], _Upstream] = {}
        tenant = DEFAULT_TENANT
        try:
            rf = sock.makefile("rb")
            while not self._stop.is_set():
                raw = rf.readline()
                if not raw:
                    return
                try:
                    text = raw.decode("ascii").strip()
                except UnicodeDecodeError:
                    sock.sendall((err_line(
                        "badreq", "non-ascii request line") + "\n")
                        .encode("ascii"))
                    continue
                if not text:
                    continue
                self.counters["requests"] += 1
                # prefix-aware verb peek: DEADLINE=/RID=/unknown tokens
                # may precede the verb (protocol.split_prefix_tokens);
                # a malformed known token forwards as-is and gets the
                # upstream's typed badreq
                toks = text.split(None, 8)
                rid = None
                try:
                    _, rid, vi = split_prefix_tokens(toks)
                    verb = toks[vi].upper() if vi < len(toks) else ""
                except BadRequest:
                    verb, vi = toks[0].upper(), 0
                if verb == "QUIT":
                    sock.sendall(b"OK bye\n")
                    return
                if verb == "TENANT":
                    args = toks[vi + 1:] if vi + 1 <= len(toks) else []
                    tenant, resp = self._handle_tenant(
                        [verb] + args, tenant)
                    sock.sendall((resp + "\n").encode("ascii"))
                    continue
                if verb == "ROUTER":
                    sock.sendall((self._router_stats(tenant) + "\n")
                                 .encode("ascii"))
                    continue
                if verb == "MIGRATE":
                    args = toks[vi + 1:] if vi + 1 <= len(toks) else []
                    sock.sendall((self._handle_migrate(args) + "\n")
                                 .encode("ascii"))
                    continue
                if verb == "METRICS":
                    # the fleet scrape (ISSUE 12): fan-in from every
                    # reachable member, answered by the router itself
                    try:
                        body = self.fleet_metrics()
                    except Exception as exc:
                        sock.sendall((err_line(
                            "internal", f"fleet scrape failed: {exc}")
                            + "\n").encode("ascii"))
                        continue
                    sock.sendall(f"OK bytes={len(body)}\n"
                                 .encode("ascii") + body)
                    continue
                # stamp the trace context (ISSUE 12): a client-sent RID
                # wins; otherwise mint one so the whole fleet's spans
                # for this request share a join key.  Reads are gated on
                # the router's own recorder being live (RID_ENV note):
                # a read rid nobody can record is wire+parse for nothing
                fwd = text
                if rid is None and self.rid_enabled and verb and \
                        (verb not in SPREAD_VERBS or self.rid_always
                         or trace.enabled()):
                    rid = trace.new_rid()
                    fwd = f"RID={rid} {text}"
                with trace.rid_scope(rid):
                    with trace.sampled_span("route.req") as sp:
                        resp, payload = self._forward(fwd, verb, tenant,
                                                      upstreams)
                        sp.annotate(verb=verb, tenant=tenant,
                                    ok=resp[:2] == "OK")
                sock.sendall((resp + "\n").encode(
                    "ascii", errors="replace") + payload)
        except (OSError, ConnectionError):
            pass
        finally:
            for up in upstreams.values():
                try:
                    up.client.close()
                except Exception:
                    pass
            try:
                sock.close()
            except OSError:
                pass

    def _handle_tenant(self, toks, tenant) -> tuple[str, str]:
        args = toks[1:] if len(toks) > 1 else []
        if len(args) > 1:
            return tenant, err_line("badreq",
                                    "TENANT wants at most one name")
        if not args:
            return tenant, ok_kv(tenant=tenant)
        name = args[0]
        return name, ok_kv(tenant=name,
                           cluster=self.placement_of(name))

    def _router_stats(self, tenant: str) -> str:
        rec = dict(self.counters)
        rec["clusters"] = len(self.clusters)
        rec["tenant"] = tenant
        rec["cluster"] = self.placement_of(tenant)
        rec["migrations_completed"] = self.mig_completed
        rec["migrations_aborted"] = self.mig_aborted
        return ok_kv(**rec)

    def _handle_migrate(self, args) -> str:
        """``MIGRATE <tenant> <cluster> [wait=<s>]`` — kick the live
        migration driver.  Async by default (poll ROUTER / METRICS /
        MIG STAT); ``wait=`` blocks up to that many seconds and reports
        the phase it saw."""
        kv = {}
        pos = []
        for a in args:
            k, sep, v = a.partition("=")
            if sep:
                kv[k] = v
            else:
                pos.append(a)
        if len(pos) != 2:
            return err_line("badreq",
                            "MIGRATE wants <tenant> <cluster> "
                            "[wait=<s>]")
        tenant, dest = pos
        try:
            mig = self.start_migration(tenant, dest)
        except ValueError as exc:
            return err_line("badreq", str(exc))
        try:
            wait_s = float(kv.get("wait", "0") or 0)
        except ValueError:
            wait_s = 0.0
        if wait_s > 0:
            mig.done.wait(wait_s)
        rec = {"tenant": tenant, "src": mig.src, "dest": mig.dest,
               "phase": mig.phase}
        if mig.error:
            rec["error"] = mig.error.replace(" ", "_")[:120]
        return ok_kv(**rec)

    # -- the fleet scrape (ISSUE 12) ---------------------------------------

    def fleet_metrics(self) -> bytes:
        """Fan-in ``METRICS`` from every reachable cluster member,
        stamp each sample with ``instance``/``cluster`` labels (tenant
        labels already ride the member series), derive the fleet gauges
        a dashboard wants (max repl lag and epoch skew per cluster,
        tenant residency counts, reachability), and prepend the
        router's own counters + process self-accounting.  One scrape of
        the router IS a scrape of the fleet."""
        t0 = time.monotonic()
        self.counters["scrapes"] += 1
        members: list[tuple[str, tuple[str, int]]] = []
        for cid, cluster in sorted(self.clusters.items()):
            for addr in cluster.nodes():
                members.append((cid, addr))
        bodies: dict[tuple, str | None] = {}
        lock = threading.Lock()

        def scrape(cid, addr):
            body = None
            try:
                with ServeClient(addr[0], addr[1],
                                 timeout_s=self.poll_timeout_s) as c:
                    body = c.metrics()
            except Exception:
                pass
            with lock:
                bodies[(cid, addr)] = body

        threads = [threading.Thread(target=scrape, args=m, daemon=True)
                   for m in members]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=self.poll_timeout_s * 2 + 5)

        per_cluster = {cid: {"ok": 0, "bad": 0, "lags": [], "epochs": []}
                       for cid in self.clusters}
        tenant_res: dict[str, int] = {}
        seen_headers: set = set()
        member_parts: list[str] = []
        for (cid, addr), body in sorted(bodies.items()):
            acc = per_cluster[cid]
            if body is None:
                acc["bad"] += 1
                self.counters["scrape_errors"] += 1
                continue
            acc["ok"] += 1
            for name, labels, val in parse_prometheus(body):
                if name == "sheep_serve_repl_lag_records" \
                        and not labels:
                    acc["lags"].append(val)
                elif name == "sheep_serve_epoch":
                    acc["epochs"].append(val)
                elif name == "sheep_serve_tenant_resident" and val >= 1:
                    tn = labels.get("tenant", "?")
                    tenant_res[tn] = tenant_res.get(tn, 0) + 1
            member_parts.append(relabel(
                body, {"cluster": cid, "instance":
                       f"{addr[0]}:{addr[1]}"}, seen_headers))

        m = self.metrics
        g = m.gauge
        for k, v in sorted(self.counters.items()):
            g(f"sheep_route_{k}", f"router {k} counter").set(v)
        g("sheep_route_clusters",
          "clusters behind this router").set(len(self.clusters))
        reach = g("sheep_fleet_members_reachable",
                  "members that answered this scrape, per cluster")
        unreach = g("sheep_fleet_members_unreachable",
                    "members that did not answer, per cluster")
        lagg = g("sheep_fleet_repl_lag_max_records",
                 "max replication lag across a cluster's members")
        skew = g("sheep_fleet_epoch_skew",
                 "max-min epoch across a cluster's members (nonzero = "
                 "a fenced straggler is still rejoining)")
        for cid, acc in sorted(per_cluster.items()):
            reach.labels(cluster=cid).set(acc["ok"])
            unreach.labels(cluster=cid).set(acc["bad"])
            lagg.labels(cluster=cid).set(max(acc["lags"], default=0))
            ep = acc["epochs"]
            skew.labels(cluster=cid).set(max(ep) - min(ep) if ep else 0)
        tres = g("sheep_fleet_tenant_resident_instances",
                 "instances holding the tenant resident in memory")
        for tn, n in sorted(tenant_res.items()):
            tres.labels(tenant=tn).set(n)
        # live migration telemetry (ISSUE 17)
        with self._lock:
            migs = list(self._migrations.values())
            completed, aborted = self.mig_completed, self.mig_aborted
        inflight = [x for x in migs if not x.done.is_set()]
        g("sheep_migrate_inflight",
          "migrations currently in flight through this router").set(
            len(inflight))
        g("sheep_migrate_completed",
          "migrations that finished the epoch-fenced cutover").set(
            completed)
        g("sheep_migrate_aborted",
          "migrations aborted cleanly back to their source").set(
            aborted)
        dlag = g("sheep_migrate_delta_lag_records",
                 "records the migration target still trails its "
                 "source by (phase 2/3 drain)")
        for x in inflight:
            if x.last_lag is not None:
                dlag.labels(tenant=x.tenant).set(x.last_lag)
        rb = self.rebalancer
        if rb is not None:
            verd = g("sheep_rebalance_verdicts_total",
                     "rebalancer verdicts by action")
            for action, n in sorted(rb.verdict_counts.items()):
                verd.labels(action=action).set(n)
            g("sheep_rebalance_migrations_started",
              "migrations the rebalancer kicked off").set(
                rb.migrations_started)
        set_process_gauges(m, self.started_at)
        g("sheep_fleet_scrape_seconds",
          "wall cost of this fan-in scrape").set(
            round(time.monotonic() - t0, 6))
        h, p = self.address
        own = relabel(m.render(),
                      {"cluster": "router", "instance": f"{h}:{p}"},
                      seen_headers)
        return "".join([own] + member_parts).encode("ascii")

    # -- forwarding --------------------------------------------------------

    def _upstream(self, upstreams, addr, tenant) -> ServeClient:
        up = upstreams.get(addr)
        if up is None:
            up = _Upstream(ServeClient(addr[0], addr[1], timeout_s=30.0))
            upstreams[addr] = up
        if up.tenant != tenant:
            up.client._ok(f"TENANT {tenant}")  # ServeError propagates
            up.tenant = tenant
        return up.client

    def _drop(self, upstreams, addr) -> None:
        up = upstreams.pop(addr, None)
        if up is not None:
            try:
                up.client.close()
            except Exception:
                pass

    def _forward(self, text: str, verb: str, tenant: str,
                 upstreams) -> tuple[str, bytes]:
        """Route one request line; returns (response line, extra payload
        bytes) — the payload is only ever the METRICS scrape body."""
        is_read = verb in SPREAD_VERBS
        self.counters["reads" if is_read else "writes"] += 1
        last_err = "no reachable cluster member"
        for attempt in range(self.retries + 1):
            # re-resolved per attempt: an ``ERR moved`` mid-loop remaps
            # the tenant, and the replay must chase the new home
            cluster = self.cluster_for(tenant)
            if attempt:
                self.counters["retries"] += 1
            if is_read:
                targets = cluster.read_targets()
            else:
                leader = cluster.leader(refresh=attempt > 0)
                targets = [leader] if leader is not None else []
            if not targets:
                time.sleep(0.05 * attempt)
                continue
            for addr in targets if is_read else targets[:1]:
                try:
                    # connect + tenant-select: a failure HERE means the
                    # request was never sent — always safe to retry
                    client = self._upstream(upstreams, addr, tenant)
                except ServeError as exc:
                    last_err = f"{exc.code}: {exc.detail}"
                    self._drop(upstreams, addr)
                    continue
                except (OSError, ConnectionError) as exc:
                    self._drop(upstreams, addr)
                    last_err = f"{addr[0]}:{addr[1]} unreachable ({exc})"
                    if not is_read:
                        cluster.forget_leader()
                    continue
                try:
                    resp = client.request(text)
                except ServeError as exc:
                    last_err = f"{exc.code}: {exc.detail}"
                    self._drop(upstreams, addr)
                    continue
                except (OSError, ConnectionError) as exc:
                    self._drop(upstreams, addr)
                    last_err = f"connection to {addr[0]}:{addr[1]} " \
                               f"died mid-request ({exc})"
                    if verb == "INSERT":
                        # the epoch-safe rule (module docstring): an
                        # un-answered INSERT may be durable on the old
                        # chain — never re-sent to a new epoch by us
                        self.counters["insert_unknown"] += 1
                        cluster.forget_leader()
                        return (err_line(
                            "unavailable",
                            f"insert outcome unknown ({last_err}); "
                            f"not retried across failover - re-send "
                            f"only if idempotent for you"), b"")
                    cluster.forget_leader()
                    continue
                # a complete response line: decide retry vs passthrough
                if resp.startswith("ERR notleader"):
                    self.counters["reroutes"] += 1
                    hint = resp.split()[2] if len(resp.split()) > 2 \
                        else "-"
                    if hint != "-":
                        cluster.set_leader_hint(hint)
                    else:
                        cluster.forget_leader()
                    last_err = "notleader"
                    break  # next attempt re-resolves
                if resp.startswith("ERR moved"):
                    # the cutover fence (ISSUE 17): this tenant lives on
                    # another cluster now.  The fence refused BEFORE
                    # applying, so replaying the request — a write
                    # included — at the new home is a first apply, never
                    # a double one (the notleader retry shape).
                    self.counters["moved_reroutes"] += 1
                    dest = None
                    for tok in resp.split():
                        if tok.startswith("dest="):
                            dest = tok[5:]
                    if dest and dest in self.clusters \
                            and dest != cluster.cid:
                        self.remap(tenant, dest)
                        last_err = f"moved to {dest}"
                        break  # next attempt re-resolves the cluster
                    return resp, b""  # dest unknown: typed passthrough
                if resp.startswith("ERR stale") and is_read:
                    last_err = "stale replica"
                    continue  # typed, unanswered: next replica
                if resp.startswith("ERR diverged") and is_read:
                    # the quarantine refusal (ISSUE 20): typed and
                    # unanswered like stale, but ALSO remembered — the
                    # member refuses every read until its re-sync
                    # completes, so the spread stops offering it reads
                    self.counters["diverged_skips"] += 1
                    cluster.mark_diverged(addr)
                    last_err = "diverged replica (quarantined)"
                    continue
                if resp.startswith(("ERR fenced", "ERR unavailable")):
                    # surface typed (an INSERT here is durable-but-
                    # unacked territory: the client decides), but make
                    # the NEXT request re-resolve
                    cluster.forget_leader()
                    return resp, b""
                return resp, b""
        self.counters["errors"] += 1
        return err_line("unavailable",
                        f"cluster {cluster.cid} did not answer after "
                        f"{self.retries + 1} attempts ({last_err})"), b""
