"""The router-side rebalancer: a fleet that moves its own tenants.

Live migration (serve/migrate.py) gives the fleet a verb; this module
gives it a POLICY (ISSUE 17).  A thread in the router process folds the
fleet scrape — per-tenant request counters, window p99, replication
lag, process RSS — into per-cluster load, prices the busiest tenant's
move with the plan layer's cost model (plan/model.plan_migration), and
drives ``Router.start_migration`` when the numbers say GO.

Deliberately conservative, because a rebalancer that flaps is worse
than none:

  off by default   ``SHEEP_REBALANCE=1`` opts in (cli/route.py starts
                   the thread; nothing else changes)
  hysteresis       the hottest cluster must out-qps the coolest by
                   ``SHEEP_REBALANCE_HYSTERESIS``x before a move is
                   even considered — inside the band, hold
  min traffic      below ``SHEEP_REBALANCE_MIN_QPS`` on the hot
                   cluster the fleet is quiet; moving tenants around
                   an idle fleet is churn
  one at a time    a migration in flight holds every verdict (the
                   driver is one-per-tenant; the POLICY is one total)
  cooldown         ``SHEEP_REBALANCE_COOLDOWN_S`` after a migration
                   lands before the next is considered, so the post-
                   move qps picture settles before it is judged

:func:`decide` is pure — two folded scrapes in, a verdict dict out —
so the hysteresis/cooldown behavior unit-tests without a socket.
Every verdict (hold or migrate, with its reason) is kept on a bounded
ring the router's METRICS and ``sheep top`` surface.
"""

from __future__ import annotations

import os
import threading
import time

#: master switch: the rebalancer thread only starts when this is "1"
REBALANCE_ENV = "SHEEP_REBALANCE"
#: seconds between fleet-scrape verdicts
INTERVAL_ENV = "SHEEP_REBALANCE_INTERVAL_S"
DEFAULT_INTERVAL_S = 5.0
#: quiet period after a migration lands before the next is considered
COOLDOWN_ENV = "SHEEP_REBALANCE_COOLDOWN_S"
DEFAULT_COOLDOWN_S = 30.0
#: hot cluster must out-qps the cool one by this factor to act
HYSTERESIS_ENV = "SHEEP_REBALANCE_HYSTERESIS"
DEFAULT_HYSTERESIS = 1.5
#: below this hot-cluster qps the fleet is considered quiet
MIN_QPS_ENV = "SHEEP_REBALANCE_MIN_QPS"
DEFAULT_MIN_QPS = 5.0

#: verdicts kept for METRICS / `sheep top`
VERDICT_RING = 32


def enabled() -> bool:
    return os.environ.get(REBALANCE_ENV, "") == "1"


def _knob_float(env: str, default: float) -> float:
    try:
        return float(os.environ.get(env, "") or default)
    except ValueError:
        return default


def fold_fleet(samples) -> dict:
    """Fold ``parse_prometheus`` triples from one fleet scrape into the
    rebalancer's working set:

    ``{"tenants": {name: {"requests": cum, "applied": seqno,
    "p99": s, "mig": bool}}, "clusters": {cid: {"rss": bytes,
    "lag": records}}}``

    Requests are CUMULATIVE counters summed across instances; qps
    comes from the delta between two folds (:func:`qps_of`).  A tenant
    mid-migration is flagged so every verdict holds while it moves.
    """
    tenants: dict[str, dict] = {}
    clusters: dict[str, dict] = {}

    def trec(name):
        return tenants.setdefault(name, {"requests": 0.0, "applied": 0,
                                         "p99": 0.0, "mig": False})

    for name, labels, val in samples:
        cid = labels.get("cluster")
        if cid and cid != "router":
            crec = clusters.setdefault(cid, {"rss": 0.0, "lag": 0.0})
            if name == "sheep_process_rss_bytes":
                crec["rss"] += val
            elif name == "sheep_fleet_repl_lag_max_records":
                # emitted by the router with a cluster LABEL, not the
                # member relabel — folded below
                pass
            elif name == "sheep_serve_repl_lag_records" and not (
                    set(labels) - {"cluster", "instance"}):
                crec["lag"] = max(crec["lag"], val)
        if name == "sheep_fleet_repl_lag_max_records":
            lcid = labels.get("cluster")
            if lcid:
                clusters.setdefault(
                    lcid, {"rss": 0.0, "lag": 0.0})["lag"] = max(
                    clusters[lcid]["lag"], val)
        tn = labels.get("tenant")
        if not tn:
            continue
        if name == "sheep_serve_tenant_requests_total":
            trec(tn)["requests"] += val
        elif name == "sheep_serve_tenant_applied_seqno":
            rec = trec(tn)
            rec["applied"] = max(rec["applied"], int(val))
        elif name == "sheep_serve_tenant_window_p99_seconds":
            rec = trec(tn)
            rec["p99"] = max(rec["p99"], val)
        elif name in ("sheep_serve_mig_phase",
                      "sheep_migrate_delta_lag_records") and val >= 1:
            trec(tn)["mig"] = True
    return {"tenants": tenants, "clusters": clusters}


def qps_of(prev: dict, cur: dict, dt_s: float) -> dict[str, float]:
    """Per-tenant qps from two folds' cumulative request counters.
    Counter resets (a restarted member) clamp to 0 instead of going
    negative."""
    if dt_s <= 0:
        return {}
    out = {}
    pt = prev.get("tenants", {})
    for tn, rec in cur.get("tenants", {}).items():
        d = rec["requests"] - pt.get(tn, {}).get("requests", 0.0)
        out[tn] = max(0.0, d) / dt_s
    return out


def decide(prev: dict, cur: dict, dt_s: float, placements: dict,
           *, hysteresis: float, min_qps: float,
           migration_inflight: bool = False,
           cooldown_remaining_s: float = 0.0,
           horizon_s: float = 60.0) -> dict:
    """One pure rebalance verdict.  ``placements`` maps tenant ->
    cluster id (the router's view, overrides included).  Returns
    ``{"action": "hold"|"migrate", "reason": ..., and for migrate:
    "tenant", "src", "dest", "plan": <plan_migration dict>}``."""
    from ..plan.model import plan_migration

    def hold(reason):
        return {"action": "hold", "reason": reason}

    if migration_inflight:
        return hold("a migration is already in flight "
                    "(one at a time)")
    if cooldown_remaining_s > 0:
        return hold(f"cooling down {cooldown_remaining_s:.0f}s after "
                    f"the last migration")
    qps = qps_of(prev, cur, dt_s)
    if any(rec.get("mig") for rec in cur.get("tenants", {}).values()):
        return hold("a tenant is mid-migration on a member")
    cluster_qps: dict[str, float] = {cid: 0.0 for cid in
                                     set(placements.values())}
    by_cluster: dict[str, list] = {}
    for tn, cid in placements.items():
        cluster_qps[cid] = cluster_qps.get(cid, 0.0) + qps.get(tn, 0.0)
        by_cluster.setdefault(cid, []).append(tn)
    if len(cluster_qps) < 2:
        return hold("fewer than two clusters see traffic")
    hot = max(cluster_qps, key=lambda c: cluster_qps[c])
    cool = min(cluster_qps, key=lambda c: cluster_qps[c])
    hot_qps, cool_qps = cluster_qps[hot], cluster_qps[cool]
    if hot_qps < min_qps:
        return hold(f"fleet is quiet (hot cluster {hot} at "
                    f"{hot_qps:.1f} qps < {min_qps:g})")
    if hot_qps < hysteresis * max(cool_qps, 1e-9) or hot == cool:
        return hold(f"inside the hysteresis band ({hot} at "
                    f"{hot_qps:.1f} vs {cool} at {cool_qps:.1f} qps, "
                    f"need {hysteresis:g}x)")
    # price the hot cluster's tenants, busiest first; the first GO wins
    cands = sorted(by_cluster.get(hot, []),
                   key=lambda t: qps.get(t, 0.0), reverse=True)
    for tn in cands:
        tqps = qps.get(tn, 0.0)
        if tqps <= 0:
            break
        rec = cur["tenants"].get(tn, {})
        plan = plan_migration(rec.get("applied", 0), tqps,
                              hot_qps, cool_qps, horizon_s=horizon_s)
        if plan["migrate"] == "go":
            return {"action": "migrate", "tenant": tn, "src": hot,
                    "dest": cool, "plan": plan,
                    "reason": plan["reason"]}
    return hold(f"no tenant on {hot} prices out "
                f"(moving any would not shrink the imbalance)")


class Rebalancer:
    """The thread: scrape -> fold -> decide -> (maybe) migrate."""

    def __init__(self, router, interval_s: float | None = None,
                 cooldown_s: float | None = None,
                 hysteresis: float | None = None,
                 min_qps: float | None = None):
        self.router = router
        self.interval_s = interval_s if interval_s is not None else \
            _knob_float(INTERVAL_ENV, DEFAULT_INTERVAL_S)
        self.cooldown_s = cooldown_s if cooldown_s is not None else \
            _knob_float(COOLDOWN_ENV, DEFAULT_COOLDOWN_S)
        self.hysteresis = hysteresis if hysteresis is not None else \
            _knob_float(HYSTERESIS_ENV, DEFAULT_HYSTERESIS)
        self.min_qps = min_qps if min_qps is not None else \
            _knob_float(MIN_QPS_ENV, DEFAULT_MIN_QPS)
        self.verdicts: list[dict] = []
        self.verdict_counts = {"hold": 0, "migrate": 0}
        self.migrations_started = 0
        self._prev: tuple[float, dict] | None = None
        self._last_mig_t: float | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> "Rebalancer":
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="rebalancer")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def _placements(self) -> dict[str, str]:
        """tenant -> cluster for every tenant the last fold saw."""
        prev = self._prev[1] if self._prev else {}
        return {tn: self.router.placement_of(tn)
                for tn in prev.get("tenants", {})}

    def _record(self, verdict: dict) -> None:
        verdict["at"] = time.time()
        self.verdict_counts[verdict.get("action", "hold")] = \
            self.verdict_counts.get(verdict.get("action", "hold"), 0) + 1
        self.verdicts.append(verdict)
        del self.verdicts[:-VERDICT_RING]

    def tick(self) -> dict | None:
        """One scrape+verdict step (the loop body, callable from tests
        without the thread).  None until two folds exist."""
        from ..obs.metrics import parse_prometheus
        body = self.router.fleet_metrics().decode("ascii", "replace")
        cur = fold_fleet(parse_prometheus(body))
        now = time.monotonic()
        prev = self._prev
        self._prev = (now, cur)
        if prev is None:
            return None
        dt = now - prev[0]
        inflight = any(not m.done.is_set()
                       for m in self.router._migrations.values())
        cool_left = 0.0
        if self._last_mig_t is not None:
            cool_left = max(
                0.0, self.cooldown_s - (now - self._last_mig_t))
        verdict = decide(prev[1], cur, dt, self._placements(),
                         hysteresis=self.hysteresis,
                         min_qps=self.min_qps,
                         migration_inflight=inflight,
                         cooldown_remaining_s=cool_left)
        if verdict["action"] == "migrate":
            try:
                self.router.start_migration(verdict["tenant"],
                                            verdict["dest"])
                self.migrations_started += 1
                self._last_mig_t = time.monotonic()
            except ValueError as exc:
                verdict = {"action": "hold",
                           "reason": f"driver refused: {exc}"}
        self._record(verdict)
        return verdict

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception as exc:  # scrape hiccups never kill policy
                self._record({"action": "hold",
                              "reason": f"tick failed: {exc}"})
