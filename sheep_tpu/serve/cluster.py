"""Cluster membership and epoch-fenced failover for the serve daemon.

A replicated serve cluster is deliberately coordination-service-free: the
static peer set (``SHEEP_SERVE_PEERS``) plus the wire ``STATS`` verb is
the whole membership protocol.  Every node can ask every other node
"what role, what epoch, how far applied?", and from those answers each
transition is a deterministic rule:

  discovery   the current leader is whichever reachable peer reports
              ``role=leader`` with the HIGHEST epoch.  Followers point
              their replication stream at it; there is no registry to
              keep consistent.
  election    when the stream has been silent past the failover
              deadline AND no reachable peer is a live leader, the
              designated successor is the reachable candidate with the
              highest ``(applied_seqno, node_id)`` — the replica that
              lost the least, tie-broken totally.  Only that node
              promotes; everyone else waits for it to show up as
              leader.  Promotion = bump the epoch past every epoch seen
              and seal the boundary durably (ServeCore.advance_epoch)
              BEFORE accepting a single write.
  fencing     epochs only ever move forward.  A fenced ex-leader
              returning from a partition learns of the later epoch on
              its next peer poll (or from a follower's REPL FENCED) and
              demotes instead of accepting writes; its divergent
              unacknowledged tail is rolled back by snapshot re-sync
              when it rejoins as a follower.

Quorum votes (ISSUE 11, closing the PR-7 symmetric-partition hole): a
candidate that wins the deterministic rule must ALSO collect ``REPL
VOTE`` grants from a majority of its reachable peers before promoting.
Each node grants at most one candidate per epoch and only while its own
replication stream is stale (daemon.grant_vote), so two candidates that
share ANY voter can never both promote into the same epoch — a
symmetric partition now produces at most one leader per epoch instead
of two leaders in one.  A candidate whose whole peer set is unreachable
(the 2-node cluster after its leader dies) needs zero votes: that is
the availability the PR-7 design chose, and the epoch fence still
resolves any cross-epoch split deterministically on heal.  Writes need
``repl_acks`` follower acknowledgements to be acked at all, so no
acknowledged insert is ever lost to a split either way.

Peer specs: ``host:port``, or a serve state-dir path (its ``serve.addr``
file is read fresh on every poll — ephemeral ports move across
restarts), or a path to an addr file itself.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field

ROLE_ENV = "SHEEP_SERVE_ROLE"
PEERS_ENV = "SHEEP_SERVE_PEERS"
NODE_ID_ENV = "SHEEP_SERVE_NODE_ID"
REPL_ACKS_ENV = "SHEEP_SERVE_REPL_ACKS"
FAILOVER_ENV = "SHEEP_SERVE_FAILOVER_S"
MAX_LAG_ENV = "SHEEP_SERVE_MAX_LAG"

ROLES = ("leader", "follower")

ADDR_FILE = "serve.addr"


@dataclass
class ClusterConfig:
    """One node's view of the cluster (all overridable per test)."""

    node_id: str = ""
    role: str = "leader"          # standalone daemons are trivially leader
    peers: list = field(default_factory=list)
    #: follower acks required before an insert is acknowledged (0 =
    #: async replication — acked inserts can die with the leader)
    repl_acks: int = 1
    #: replication-stream heartbeat cadence (leader PING when idle)
    hb_s: float = 1.0
    #: stream silence (follower) / peer-poll cadence (leader) past which
    #: failover/fence checks run
    failover_s: float = 5.0
    #: bounded staleness: a follower whose lag exceeds this many records
    #: refuses reads typed (``ERR stale``); None = serve any staleness
    max_lag: int | None = None
    poll_timeout_s: float = 2.0

    def __post_init__(self):
        if self.role not in ROLES:
            raise ValueError(f"serve role {self.role!r} must be one of "
                             f"{'/'.join(ROLES)}")

    @classmethod
    def from_env(cls, **overrides) -> "ClusterConfig":
        kw: dict = {}
        if os.environ.get(ROLE_ENV):
            kw["role"] = os.environ[ROLE_ENV].strip().lower()
        if os.environ.get(PEERS_ENV):
            kw["peers"] = [p.strip() for p in
                           os.environ[PEERS_ENV].split(",") if p.strip()]
        if os.environ.get(NODE_ID_ENV):
            kw["node_id"] = os.environ[NODE_ID_ENV].strip()
        if os.environ.get(REPL_ACKS_ENV):
            kw["repl_acks"] = int(os.environ[REPL_ACKS_ENV])
        if os.environ.get(FAILOVER_ENV):
            kw["failover_s"] = float(os.environ[FAILOVER_ENV])
        if os.environ.get(MAX_LAG_ENV):
            kw["max_lag"] = int(os.environ[MAX_LAG_ENV])
        from .replicate import REPL_HB_ENV
        if os.environ.get(REPL_HB_ENV):
            kw["hb_s"] = float(os.environ[REPL_HB_ENV])
        kw.update(overrides)
        return cls(**kw)

    @property
    def clustered(self) -> bool:
        return bool(self.peers)


def resolve_peer(spec: str) -> tuple[str, int] | None:
    """Peer spec -> (host, port), or None while unresolvable (a state
    dir whose daemon has not published its address yet)."""
    spec = spec.strip()
    path = None
    if os.path.isdir(spec):
        path = os.path.join(spec, ADDR_FILE)
    elif os.sep in spec or os.path.isfile(spec):
        path = spec
    if path is not None:
        try:
            host, port = open(path).read().split()
            return host, int(port)
        except (OSError, ValueError):
            return None
    host, _, port = spec.rpartition(":")
    try:
        return (host or "127.0.0.1"), int(port)
    except ValueError:
        return None


def poll_peer(spec: str, timeout_s: float = 2.0) -> dict | None:
    """One peer's ``STATS`` as a dict, or None when unreachable.  The
    whole membership protocol is this call."""
    from .protocol import ServeClient
    addr = resolve_peer(spec)
    if addr is None:
        return None
    try:
        with ServeClient(addr[0], addr[1], timeout_s=timeout_s) as c:
            st = c.kv("STATS")
            st["_addr"] = f"{addr[0]}:{addr[1]}"
            return st
    except Exception:
        return None


def find_leader(peers, timeout_s: float = 2.0,
                min_epoch: int = -1) -> tuple[str, dict] | None:
    """The reachable peer reporting ``role=leader`` with the highest
    epoch (>= ``min_epoch``), as ``(addr, stats)`` — replication
    discovery and the fence check share this."""
    best = None
    for spec in peers:
        st = poll_peer(spec, timeout_s)
        if st is None or st.get("role") != "leader":
            continue
        epoch = int(st.get("epoch", 0))
        if epoch < min_epoch:
            continue
        if best is None or epoch > int(best[1].get("epoch", 0)):
            best = (st["_addr"], st)
    return best


def request_vote(spec: str, epoch: int, candidate: str, seqno: int,
                 timeout_s: float = 2.0) -> bool:
    """Ask one peer to grant ``candidate`` its vote for ``epoch``.
    Returns True only on an explicit ``grant=1`` — unreachable peers
    and refusals count identically (no grant)."""
    from .protocol import ServeClient, parse_kv_args
    addr = resolve_peer(spec)
    if addr is None:
        return False
    try:
        with ServeClient(addr[0], addr[1], timeout_s=timeout_s) as c:
            resp = c.request(f"REPL VOTE epoch={epoch} "
                             f"candidate={candidate} seqno={seqno}")
            toks = resp.split()
            if not toks or toks[0] != "OK":
                return False
            return parse_kv_args(toks[1:]).get("grant") == "1"
    except Exception:
        return False


def choose_successor(candidates: list[tuple[int, str]]) -> str:
    """The deterministic election rule: highest ``(applied_seqno,
    node_id)`` wins.  ``candidates`` must include the caller; every
    node evaluating the same candidate set picks the same winner."""
    if not candidates:
        raise ValueError("no candidates")
    return max(candidates)[1]


class FailoverWatcher:
    """One daemon's transition engine, polled on a timer thread.

    follower   while the replication stream is fresh: do nothing.  Once
               it has been silent past ``failover_s``: poll the peers;
               if a live leader exists, re-point (discovery handles it);
               otherwise run the election rule over the reachable
               candidates and promote iff self wins.
    leader     every ``failover_s``: poll the peers for a leader with a
               LATER epoch; seeing one means this node's term is over —
               demote (the fence check).  The hub's REPL FENCED callback
               triggers the same demotion without waiting for the poll.
    """

    def __init__(self, daemon, config: ClusterConfig):
        self.daemon = daemon
        self.config = config
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.elections = 0
        self.votes_denied = 0

    def start(self) -> "FailoverWatcher":
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"serve-watch:{self.config.node_id}")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def _run(self) -> None:
        poll = max(0.05, self.config.failover_s / 4)
        while not self._stop.wait(poll):
            try:
                if self.daemon.role == "leader":
                    self._check_fence()
                else:
                    self._check_failover()
            except Exception as exc:  # a watcher crash must not be silent
                self.daemon.config.events.append(
                    ("watcher_error", f"{type(exc).__name__}: {exc}"))

    def _check_fence(self) -> None:
        other = find_leader(self.config.peers,
                            self.config.poll_timeout_s,
                            min_epoch=self.daemon.core.epoch + 1)
        if other is not None:
            self.daemon.demote(other[0], int(other[1].get("epoch", 0)))

    def _check_failover(self) -> None:
        # a quarantined node never stands for election (ISSUE 20): its
        # applied state is KNOWN divergent, so a high applied_seqno is
        # a lie — promoting it would serve the divergence fleet-wide
        if getattr(self.daemon.core, "quarantined", False):
            return
        rep = self.daemon.replicator
        age = rep.stream_age_s() if rep is not None else None
        if age is None:
            # never streamed: count from daemon start, not forever
            age = time.monotonic() - self.daemon.started_at
        if age <= self.config.failover_s:
            return
        stats = [(spec, poll_peer(spec, self.config.poll_timeout_s))
                 for spec in self.config.peers]
        alive = [(spec, st) for spec, st in stats if st is not None]
        top_epoch = self.daemon.core.epoch
        for _, st in alive:
            top_epoch = max(top_epoch, int(st.get("epoch", 0)))
            if st.get("role") == "leader":
                return  # a leader lives; discovery will (re)point at it
        # peers advertising `diverged` in STATS are excluded the same
        # way — every node filters identically, so the deterministic
        # rule still picks one winner from the same candidate set
        candidates = [(int(st.get("applied_seqno", 0)),
                       str(st.get("node", st.get("_addr", ""))))
                      for _, st in alive
                      if not int(st.get("diverged", 0))]
        candidates.append((self.daemon.core.applied_seqno,
                           self.config.node_id))
        self.elections += 1
        if choose_successor(candidates) != self.config.node_id:
            return
        # the quorum vote (module docstring): a majority of the
        # REACHABLE peers must grant this epoch before promotion — an
        # empty reachable set needs no votes (the 2-node availability
        # choice), a shared voter forbids same-epoch dual leaders
        reachable = [spec for spec, st in stats if st is not None]
        need = len(reachable) // 2 + 1 if reachable else 0
        grants = 0
        for spec in reachable:
            if request_vote(spec, top_epoch + 1, self.config.node_id,
                            self.daemon.core.applied_seqno,
                            self.config.poll_timeout_s):
                grants += 1
            if grants >= need:
                break
        if grants < need:
            self.votes_denied += 1
            self.daemon.config.events.append(
                ("election_denied", top_epoch + 1, grants, need))
            return
        self.daemon.promote(top_epoch + 1)
