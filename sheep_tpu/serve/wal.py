"""Write-ahead log for the serve daemon: every accepted insert is durable
before it is acknowledged.

The recovery contract (ISSUE 6) is ARIES-shaped but deliberately tiny: the
serving state is a pure function of (snapshot, ordered insert records), so
the log needs no undo, no pages, no LSN map — just records that are (a)
individually checksummed, (b) strictly ordered, and (c) fsync'd before the
client hears "OK".  Restart = load snapshot + replay the records with
``seqno > snapshot.applied_seqno``; because the incremental insert
transform is deterministic (serve/state.py), the replayed tree is
bit-identical to the pre-crash tree at every insert boundary.

On-disk format (little-endian throughout)::

    header   "SHEEPWAL" | uint32 version | 64-byte ascii input signature
             | uint64 epoch                                  (version 2)
    record   uint64 seqno | uint32 payload_len | uint32 crc32 | payload

``epoch`` (ISSUE 7) stamps the log with the replication term that wrote
it: every leader promotion bumps the epoch and swaps in a fresh log, so
two logs with different epochs must cover DISJOINT seqno ranges — the
fence that makes a rejoining ex-leader's divergent tail detectable
instead of silently merged (``sheep fsck`` refuses cross-epoch seqno
overlap).  Version-1 logs (pre-replication state dirs) read as epoch 0.

``crc32`` (zlib, pinned — the WAL must verify on any host, so the algo is
not environment-gated like sidecars) covers seqno + payload_len + payload.
The signature ties the log to the (n, sequence) identity of the build it
mutates (runtime.snapshot.input_signature): replaying someone else's WAL
into a tree is refused up front, same as checkpoint resume.

A kill mid-append leaves a torn trailing record.  ``read_wal`` surfaces it
per the integrity policy: **strict** refuses the whole log (typed
MalformedArtifact — an operator must decide), **repair** returns the clean
prefix and reports the tear so the owner (ServeCore.open) can truncate it
away; a record that went bad in the MIDDLE of the chain (CRC or sequence
break with clean records after it) is corruption, not a tear, and is
refused in every mode.  Torn-at-every-byte-boundary behavior is property
tested (tests/test_serve.py).

Appends run through the I/O fault layer (io/faultfs.py, site ``wal``) so
ENOSPC/EIO/short/slow fire through the exact path a real failure takes; a
failed append truncates back to the record boundary and re-raises typed
(DiskExhausted/WriteFault) — the log never retains a torn record that was
never acknowledged.
"""

from __future__ import annotations

import os
import re
import struct
import warnings
import zlib

from ..integrity.errors import IntegrityError, MalformedArtifact
from ..integrity.sidecar import resolve_policy
from ..io import faultfs
from ..io.atomic import _typed, atomic_write
from ..obs import trace as _obs
from ..resources.governor import ResourceGovernor

WAL_NAME = "serve.wal"
_MAGIC = b"SHEEPWAL"
_VERSION = 2
_SIG_BYTES = 64  # ascii sha256 hexdigest

_HEADER_V1 = struct.Struct(f"<8sI{_SIG_BYTES}s")
#: v2 appends the replication epoch; new logs always write v2
_HEADER = struct.Struct(f"<8sI{_SIG_BYTES}sQ")
_RECORD = struct.Struct("<QII")  # seqno, payload_len, crc32

#: refuse absurd record claims up front (a corrupt length field must not
#: make the reader allocate gigabytes): one insert batch is bounded by the
#: protocol's line length; 16MB is orders of magnitude above it
MAX_PAYLOAD = 16 << 20


def wal_path(state_dir: str) -> str:
    return os.path.join(state_dir, WAL_NAME)


def archived_wal_name(epoch: int) -> str:
    return f"serve-e{epoch:06d}.wal"


def archived_wal_paths(state_dir: str) -> list[str]:
    """Epoch-archived logs in the dir, oldest epoch first.  A promotion
    (state.ServeCore.advance_epoch) copies the outgoing epoch's log aside
    before sealing, so the seqno hand-off across the promotion boundary
    stays auditable by ``sheep fsck``."""
    import glob
    out = []
    for path in glob.glob(os.path.join(glob.escape(state_dir),
                                       "serve-e*.wal")):
        if re.match(r"^serve-e\d{6}\.wal$", os.path.basename(path)):
            out.append(path)
    return sorted(out)


def _record_crc(seqno: int, payload: bytes) -> int:
    head = struct.pack("<QI", seqno, len(payload))
    return zlib.crc32(payload, zlib.crc32(head)) & 0xFFFFFFFF


def create_wal(path: str, sig: str, epoch: int = 0) -> None:
    """Write a fresh, empty WAL (crash-safely — the old log, if any, stays
    intact until the new one is complete), stamped with the replication
    ``epoch`` that owns it (0 = never promoted / standalone)."""
    sig_b = sig.encode("ascii")
    if len(sig_b) != _SIG_BYTES:
        raise ValueError(f"input signature must be {_SIG_BYTES} ascii "
                         f"chars, got {len(sig_b)}")
    if epoch < 0:
        raise ValueError(f"negative WAL epoch {epoch}")
    with atomic_write(path, "wb", expect_bytes=_HEADER.size) as f:
        f.write(_HEADER.pack(_MAGIC, _VERSION, sig_b, epoch))


def read_wal(path: str, mode: str | None = None):
    """Parse the whole log.  Returns ``(sig, epoch, records, clean_end,
    torn)``: ``records`` is a list of (seqno, payload) in log order,
    ``clean_end`` the byte offset after the last intact record, ``torn``
    whether bytes follow it.  Never mutates the file (fsck uses this too).

    Policy (``mode``: strict/repair/trust, default SHEEP_INTEGRITY):
    strict raises MalformedArtifact on a torn tail; repair/trust warn and
    return the clean prefix.  Mid-chain corruption — a bad CRC or a
    non-monotone seqno with a VALID record after it — raises in every
    mode: that log did not tear, it rotted.
    """
    mode = resolve_policy(mode)
    with open(path, "rb") as f:
        data = f.read()
    if len(data) < _HEADER_V1.size:
        raise MalformedArtifact(
            f"{path}: corrupt WAL — {len(data)} bytes is shorter than the "
            f"{_HEADER_V1.size}-byte header")
    magic, version, sig_b = _HEADER_V1.unpack_from(data, 0)
    if magic != _MAGIC:
        raise MalformedArtifact(
            f"{path}: corrupt WAL — bad magic {magic!r}")
    if version > _VERSION:
        raise MalformedArtifact(
            f"{path}: WAL version {version} > supported {_VERSION}")
    if version >= 2:
        if len(data) < _HEADER.size:
            raise MalformedArtifact(
                f"{path}: corrupt WAL — v2 log of {len(data)} bytes is "
                f"shorter than the {_HEADER.size}-byte epoch header")
        magic, version, sig_b, epoch = _HEADER.unpack_from(data, 0)
        header_size = _HEADER.size
    else:
        epoch = 0  # pre-replication log: never promoted
        header_size = _HEADER_V1.size
    try:
        sig = sig_b.decode("ascii")
    except UnicodeDecodeError:
        raise MalformedArtifact(f"{path}: corrupt WAL — unreadable "
                                f"input signature in header")

    records: list[tuple[int, bytes]] = []
    off = header_size
    bad_at = None  # (offset, reason) of the first unreadable record
    last_seqno = None
    while off < len(data):
        if off + _RECORD.size > len(data):
            bad_at = (off, f"{len(data) - off} trailing bytes are shorter "
                           f"than a record header")
            break
        seqno, length, crc = _RECORD.unpack_from(data, off)
        if length > MAX_PAYLOAD:
            bad_at = (off, f"record claims {length} payload bytes "
                           f"(cap {MAX_PAYLOAD})")
            break
        if off + _RECORD.size + length > len(data):
            bad_at = (off, f"record claims {length} payload bytes but only "
                           f"{len(data) - off - _RECORD.size} follow")
            break
        payload = data[off + _RECORD.size: off + _RECORD.size + length]
        if _record_crc(seqno, payload) != crc:
            bad_at = (off, f"record {seqno} fails its crc32")
            break
        if last_seqno is not None and seqno <= last_seqno:
            # never a tear: both records are intact, the ORDER is lying
            raise MalformedArtifact(
                f"{path}: corrupt WAL — seqno {seqno} after {last_seqno} "
                f"(sequence numbers must be strictly monotone)")
        last_seqno = seqno
        records.append((seqno, payload))
        off += _RECORD.size + length

    if bad_at is None:
        return sig, epoch, records, off, False

    # A bad record is only a TEAR if nothing valid follows it; scan for a
    # clean record past the damage — finding one means mid-chain rot.
    tail_off, reason = bad_at
    scan = tail_off + 1
    while scan + _RECORD.size <= len(data):
        seqno, length, crc = struct.unpack_from("<QII", data, scan)
        if (length <= MAX_PAYLOAD
                and scan + _RECORD.size + length <= len(data)
                and _record_crc(
                    seqno,
                    data[scan + _RECORD.size: scan + _RECORD.size + length]
                ) == crc):
            raise MalformedArtifact(
                f"{path}: corrupt WAL — record at offset {tail_off} is "
                f"damaged ({reason}) but an intact record follows at "
                f"{scan}: mid-chain corruption, not a torn tail")
        scan += 1

    msg = (f"{path}: torn WAL — {reason} at offset {tail_off} "
           f"({len(records)} intact record(s) precede it)")
    if mode == "strict":
        raise MalformedArtifact(
            msg + "; refusing in strict mode (repair mode truncates the "
                  "torn tail)")
    warnings.warn(msg + "; salvaging the clean prefix")
    return sig, epoch, records, tail_off, True


def repair_wal(path: str) -> int:
    """Truncate a torn tail off the log (the repair-mode recovery step,
    ServeCore.open).  Returns the number of bytes removed (0 when the log
    was already clean).  Mid-chain corruption still raises — truncation
    can only ever amputate a tear, never resurrect rot."""
    _, _, _, clean_end, torn = read_wal(path, "repair")
    if not torn:
        return 0
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(clean_end)
        f.flush()
        os.fsync(f.fileno())
    return size - clean_end


class WalAppender:
    """Append-side handle: owns the open fd, the next sequence number, and
    the durability discipline (write -> flush -> fsync -> only then return).

    The constructor verifies the existing log end-to-end (``read_wal``
    strict — an appender must never extend a log it cannot vouch for) and
    positions at the clean end.
    """

    def __init__(self, path: str, expect_sig: str | None = None,
                 governor: ResourceGovernor | None = None):
        sig, epoch, records, clean_end, _ = read_wal(path, "strict")
        if expect_sig is not None and sig != expect_sig:
            raise IntegrityError(
                f"{path}: WAL belongs to a different build input "
                f"(log sig {sig[:12]}..., expected {expect_sig[:12]}...) — "
                f"refusing to append")
        self.path = path
        self.sig = sig
        self.epoch = epoch
        self.next_seqno = (records[-1][0] + 1) if records else 1
        self.governor = governor if governor is not None \
            else ResourceGovernor.from_env()
        self._f = open(path, "r+b")
        self._f.seek(clean_end)
        self._unsynced = False

    def append(self, payload: bytes, sync: bool = True) -> int:
        """Durably append one record; returns its seqno.  The record is
        on disk (fsync'd) when this returns — the caller may acknowledge.
        On ANY write failure the log is truncated back to the record
        boundary and the error re-raises typed (DiskExhausted/WriteFault
        for ENOSPC/EIO, real or injected): a failed append leaves no
        trace, so it can be retried or refused without a repair pass."""
        return self.append_at(self.next_seqno, payload, sync=sync)

    def append_at(self, seqno: int, payload: bytes,
                  sync: bool = True) -> int:
        """Append one record under a CALLER-chosen seqno (the follower
        apply path, serve/replicate.py: a replica logs records under the
        leader's numbering so the two logs stay comparable).  ``seqno``
        must keep the chain strictly monotone; same durability contract
        as :meth:`append`.

        ``sync=False`` defers the fsync (write+flush only): the batched
        follower apply appends a whole APPEND burst and pays ONE
        :meth:`sync` for the lot — the caller MUST NOT acknowledge any
        deferred record before that sync returns.  A crash in the window
        loses only unacknowledged records, and the torn-tail repair
        truncates any partially-flushed one."""
        if len(payload) > MAX_PAYLOAD:
            raise ValueError(f"WAL payload of {len(payload)} bytes exceeds "
                             f"the {MAX_PAYLOAD} cap")
        if seqno < self.next_seqno:
            raise ValueError(
                f"{self.path}: append_at seqno {seqno} would break the "
                f"strictly-monotone chain (next is {self.next_seqno})")
        rec = _RECORD.pack(seqno, len(payload),
                           _record_crc(seqno, payload)) + payload
        start = self._f.tell()
        # cheap preflight: an append that cannot fit should refuse before
        # bytes land, same contract as the atomic writers (io/atomic.py)
        self.governor.preflight_write(self.path, len(rec))
        w = faultfs.wrap(self._f, faultfs.arm(self.path), text=False)
        try:
            w.write(rec)
            self._f.flush()
            if sync:
                # flight-recorder span (obs/trace.py): WAL fsyncs are
                # the serve write path's dominant latency term
                with _obs.span("wal.fsync", seqno=seqno):
                    os.fsync(self._f.fileno())
        except OSError as exc:
            try:
                self._f.truncate(start)
                self._f.seek(start)
                self._f.flush()
                os.fsync(self._f.fileno())
            except OSError:
                pass  # the truncate is best-effort; recovery re-truncates
            typed = _typed(exc, self.path)
            if typed is not exc:
                raise typed from exc
            raise
        if not sync:
            self._unsynced = True
        self.next_seqno = seqno + 1
        return seqno

    def sync(self, **attrs) -> None:
        """fsync any deferred appends (the burst seal).  No-op when
        nothing is pending.  On failure the error re-raises typed and the
        log is NOT truncated: deferred records are already applied by the
        caller and a truncation here could leave a seqno gap on disk —
        the bytes stay buffered for a later retry, and a crash before one
        lands is covered by the torn-tail repair (none of the deferred
        records were acknowledged).

        ``attrs`` land on the ``wal.fsync`` span: the group-commit
        coordinator (serve/state.py) attributes the one shared fsync to
        every rid it seals (one span, many rids)."""
        if not self._unsynced:
            return
        try:
            self._f.flush()
            with _obs.span("wal.fsync", burst=True, **attrs):
                os.fsync(self._f.fileno())
        except OSError as exc:
            typed = _typed(exc, self.path)
            if typed is not exc:
                raise typed from exc
            raise
        self._unsynced = False

    def close(self) -> None:
        try:
            self._f.close()
        except OSError:
            pass

    def __enter__(self) -> "WalAppender":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
