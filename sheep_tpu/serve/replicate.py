"""WAL shipping: the leader streams acked records to followers.

The PR-6 WAL is already an ordered, checksummed, sig-fenced record
stream; replication (ISSUE 7) ships exactly those records over the line
protocol and replays them through the exact insert path the leader ran —
so a follower is bit-identical by construction, the same way WAL replay
after a crash is.  The pieces:

  frame codec     one APPEND frame per WAL record — ascii line, base64
                  payload, crc32 over the RAW payload bytes (a frame
                  torn mid-line never parses; a frame corrupted in
                  flight fails its crc; both trigger re-sync, never a
                  partial apply)
  ReplApplier     the follower-side frame consumer.  Socket-free on
                  purpose: the torn-stream property test feeds it byte
                  prefixes directly (tests/test_replicate.py), the same
                  discipline as the WAL torn-tail sweep.
  ReplicationHub  the leader side: one sender thread per attached
                  follower, woken by ServeCore.on_append, double-
                  buffered in the Pipelined-Workflow sense — the leader
                  keeps acking local WAL appends while senders drain the
                  tail to followers concurrently.  Cumulative ACKs feed
                  the per-follower lag report and the insert quorum wait.
  Replicator      the follower's connection owner: discover the leader,
                  HELLO, stream (or snapshot-bootstrap when the leader's
                  WAL moved past us), reconnect on any failure.

Delivery contract: frames can be lost, duplicated, delayed, or the
connection cut at ANY byte (SHEEP_SERVE_NETFAULT_PLAN rehearses each) —
the seqno chain makes every case safe: duplicates drop idempotently,
gaps NACK a re-stream, and a follower only ever ACKs what is durable in
its OWN WAL.
"""

from __future__ import annotations

import base64
import binascii
import os
import socket
import threading
import time
import zlib

from ..integrity.errors import IntegrityError
from ..obs import trace
from . import netfaults
from .protocol import BadRequest, parse_kv_args
from .state import ReplicationGap, ServeCore, load_serve_snapshot
from .wal import MAX_PAYLOAD

#: replication stream heartbeat cadence (leader PING when idle) and the
#: socket read timeout followers derive from it
REPL_HB_ENV = "SHEEP_SERVE_REPL_HB_S"
DEFAULT_HB_S = 1.0


class ReplProtocolError(RuntimeError):
    """A replication frame this node cannot honor (maps to badrepl)."""


class ResyncRequired(ReplProtocolError):
    """The leader's stream moved to a sequence generation this follower
    does not have (a RESEQ frame, or an APPEND carrying a foreign
    ``gen=``): the stream cannot continue record-by-record — the
    follower must re-HELLO and adopt the leader's re-sequenced snapshot
    as one unit (ISSUE 18).  Subclasses ReplProtocolError so every
    existing reconnect path already handles it."""


class Diverged(ReplProtocolError):
    """A VERIFY frame proved this follower's applied state differs from
    the leader's at the same seqno (ISSUE 20): silently corrupted state
    the seqno chain can never catch.  The applier has already entered
    the durable quarantine (serve/scrub.py) before raising; subclassing
    ReplProtocolError tears the stream through the existing reconnect
    path, where the Replicator's quarantine re-sync takes over."""


# -- frame codec ------------------------------------------------------------


def payload_crc(payload: bytes) -> int:
    return zlib.crc32(payload) & 0xFFFFFFFF


def encode_append(epoch: int, seqno: int, payload: bytes,
                  rid: str | None = None, gen: int = 0) -> str:
    """One WAL record -> one APPEND frame line (no trailing newline).
    ``rid`` (ISSUE 12) forwards the originating request's trace-context
    id so the follower's WAL fsync is attributable to it; the token is
    omitted when absent, and old daemons ignore it either way (kv-token
    grammar — unknown keys pass through parse_kv_args untouched).
    ``gen`` (ISSUE 18) stamps the record with the leader's sequence
    generation; omitted at generation 0 so a never-re-sequenced stream
    stays byte-identical to PR 7.  A follower on a different generation
    trips :class:`ResyncRequired` — this is the belt under the RESEQ
    frame's suspenders: even if the announce is lost on the wire, the
    very next record forces the re-sync."""
    data = base64.b64encode(payload).decode("ascii")
    head = f"REPL APPEND epoch={epoch} seqno={seqno} " \
           f"crc={payload_crc(payload)}"
    if gen:
        head += f" gen={gen}"
    if rid is not None:
        head += f" rid={rid}"
    return f"{head} data={data}"


def encode_reseq(epoch: int, seqno: int, gen: int, sig: str) -> str:
    """The re-sequence announce (ISSUE 18): "everything after ``seqno``
    is generation ``gen`` under input signature ``sig``" — a sequenced
    barrier in the stream, never a partial apply.  A follower that is
    not already at ``gen`` must adopt the leader's snapshot."""
    return f"REPL RESEQ epoch={epoch} seqno={seqno} gen={gen} sig={sig}"


def encode_ping(epoch: int, seqno: int) -> str:
    return f"REPL PING epoch={epoch} seqno={seqno}"


def encode_verify(epoch: int, seqno: int, crc: int) -> str:
    """The anti-entropy checkpoint (ISSUE 20): "my state_crc at applied
    seqno ``seqno`` was ``crc``".  Stamped in-stream right after the
    APPEND it names, so a follower at the same position compares
    directly.  Only sent on streams whose HELLO advertised ``verify=1``
    — an old follower never sees the frame (forward compat by
    capability, not by tolerance)."""
    return f"REPL VERIFY epoch={epoch} seqno={seqno} crc={crc}"


def encode_hello(node: str, epoch: int, seqno: int, sig: str,
                 tenant: str | None = None, mig: bool = False,
                 verify: bool = False) -> str:
    """The stream handshake; ``tenant`` names a non-default tenant's
    stream (ISSUE 11) and is omitted otherwise so the single-tenant
    handshake stays byte-identical to PR 7.  ``mig=1`` (ISSUE 17) marks
    a MIGRATION delta stream: the leader files its APPENDs under the
    ``mdelta`` netfault site instead of ``repl`` so the migration wire
    is chaos-sweepable independently of ordinary replication.
    ``verify=1`` (ISSUE 20) advertises that this follower understands
    VERIFY anti-entropy frames; a leader that predates them ignores the
    unknown token (kv grammar), a new leader only stamps VERIFY on
    streams that asked — either mixed-version pairing degrades to plain
    PR-7 replication, never to a NACK storm."""
    line = f"REPL HELLO node={node} epoch={epoch} seqno={seqno} sig={sig}"
    if tenant is not None and tenant != "default":
        line += f" tenant={tenant}"
    if mig:
        line += " mig=1"
    if verify:
        line += " verify=1"
    return line


def encode_ack(seqno: int) -> str:
    """Cumulative: everything <= seqno is durable + applied here."""
    return f"REPL ACK seqno={seqno}"


def encode_nack(expect: int) -> str:
    return f"REPL NACK expect={expect}"


def encode_fenced(epoch: int) -> str:
    return f"REPL FENCED epoch={epoch}"


class ReplFrame:
    __slots__ = ("kind", "kv", "payload")

    def __init__(self, kind: str, kv: dict, payload: bytes | None = None):
        self.kind = kind
        self.kv = kv
        self.payload = payload

    def seqno(self) -> int:
        return int(self.kv["seqno"])

    def epoch(self) -> int:
        return int(self.kv["epoch"])


def parse_frame(line: str) -> ReplFrame:
    """Parse one ``REPL ...`` line into a typed frame; raises
    :class:`ReplProtocolError` on anything malformed (bad base64, crc
    mismatch, missing fields) — the caller re-syncs, it never guesses."""
    toks = line.split()
    if len(toks) < 2 or toks[0].upper() != "REPL":
        raise ReplProtocolError(f"not a replication frame: {line!r}")
    kind = toks[1].upper()
    try:
        kv = parse_kv_args(toks[2:])
    except BadRequest as exc:
        raise ReplProtocolError(f"bad {kind} frame: {exc}")
    payload = None
    if kind == "APPEND":
        for field in ("epoch", "seqno", "crc", "data"):
            if field not in kv:
                raise ReplProtocolError(f"APPEND frame missing {field}=")
        try:
            payload = base64.b64decode(kv["data"].encode("ascii"),
                                       validate=True)
        except (binascii.Error, ValueError) as exc:
            raise ReplProtocolError(f"APPEND frame payload is not valid "
                                    f"base64 ({exc})")
        if len(payload) > MAX_PAYLOAD:
            raise ReplProtocolError(
                f"APPEND frame claims {len(payload)} payload bytes "
                f"(cap {MAX_PAYLOAD})")
        try:
            want = int(kv["crc"])
        except ValueError:
            raise ReplProtocolError(f"APPEND frame crc {kv['crc']!r} is "
                                    f"not an integer")
        if payload_crc(payload) != want:
            raise ReplProtocolError(
                f"APPEND frame for seqno {kv.get('seqno')} fails its "
                f"crc32 — corrupted in flight")
    elif kind == "PING":
        for field in ("epoch", "seqno"):
            if field not in kv:
                raise ReplProtocolError(f"PING frame missing {field}=")
    elif kind == "ACK":
        if "seqno" not in kv:
            raise ReplProtocolError("ACK frame missing seqno=")
    elif kind == "NACK":
        if "expect" not in kv:
            raise ReplProtocolError("NACK frame missing expect=")
    elif kind == "RESEQ":
        for field in ("epoch", "seqno", "gen", "sig"):
            if field not in kv:
                raise ReplProtocolError(f"RESEQ frame missing {field}=")
    elif kind == "VERIFY":
        for field in ("epoch", "seqno", "crc"):
            if field not in kv:
                raise ReplProtocolError(f"VERIFY frame missing {field}=")
        try:
            if int(kv["crc"]) < 0:
                raise ValueError
        except ValueError:
            raise ReplProtocolError(
                f"VERIFY frame crc={kv['crc']!r} is not a non-negative "
                f"integer")
    elif kind in ("HELLO", "FENCED", "SNAPSHOT"):
        pass
    else:
        raise ReplProtocolError(f"unknown replication frame {kind!r}")
    for field in ("epoch", "seqno", "expect", "gen"):
        if field in kv:
            try:
                if int(kv[field]) < 0:
                    raise ValueError
            except ValueError:
                raise ReplProtocolError(
                    f"{kind} frame {field}={kv[field]!r} is not a "
                    f"non-negative integer")
    return ReplFrame(kind, kv, payload)


# -- follower side ----------------------------------------------------------


class ReplApplier:
    """Consume the leader's byte stream and apply complete, crc-valid
    frames to a follower core — nothing else, ever.

    Socket-free: ``feed`` takes raw bytes (any split), buffers the
    incomplete tail, and hands complete frames to the core; outbound
    ACK/NACK/FENCED lines go through the injected ``send`` callable.  A
    stream cut at ANY byte boundary leaves at most an incomplete line in
    the buffer — no partial record can reach the tree (property-swept in
    tests/test_replicate.py, mirroring the PR-6 torn-WAL sweep).
    """

    def __init__(self, core: ServeCore, send, on_epoch=None,
                 on_diverged=None):
        self.core = core
        self._send = send
        #: adopt a later leader epoch (default: seal the boundary
        #: locally via core.advance_epoch)
        self._on_epoch = on_epoch or core.advance_epoch
        #: divergence hook (ISSUE 20): called with (seqno, want_crc,
        #: got_crc) AFTER the durable quarantine is entered, BEFORE the
        #: stream tears — the daemon bumps counters/events off it
        self.on_diverged = on_diverged
        self._buf = bytearray()
        self.leader_seqno = core.applied_seqno
        self.last_frame_t: float | None = None
        self.applied = 0
        self.dups = 0
        self.gaps = 0
        self.frame_errors = 0
        self.verifies = 0   # VERIFY checkpoints compared (ISSUE 20)
        self.diverged = 0   # ... of which mismatched -> quarantine
        self.resyncs_required = 0  # generation breaks (ISSUE 18)
        self.bursts = 0  # sealed APPEND bursts (one fsync + one ACK each)
        self._unsynced = False  # applied-but-unsynced records in the WAL
        self._ack_due = False   # an APPEND landed since the last ACK
        self._burst_rid: str | None = None  # newest rid in the open burst

    @property
    def lag(self) -> int:
        return max(0, self.leader_seqno - self.core.applied_seqno)

    def feed(self, data: bytes) -> None:
        """Buffer ``data`` and handle every COMPLETE line in it.

        APPEND frames that arrive together are applied as ONE durability
        burst (batched follower acks): each record appends to the local
        WAL with its fsync deferred, the burst seals with a SINGLE fsync,
        and one cumulative ACK answers the lot — the per-record fsync was
        the throughput cap on replicated inserts.  The ack invariant is
        unchanged: nothing is ACKed before it is durable in the local WAL
        (the seal's fsync strictly precedes the ACK), so a crash
        mid-burst loses only never-acknowledged records and recovery
        lands on a valid earlier record boundary.
        """
        self._buf.extend(data)
        lines = []
        while True:
            nl = self._buf.find(b"\n")
            if nl < 0:
                break
            lines.append(bytes(self._buf[:nl]))
            del self._buf[: nl + 1]
        for i, raw in enumerate(lines):
            try:
                text = raw.decode("ascii").strip()
            except UnicodeDecodeError:
                self._seal_burst()
                self.frame_errors += 1
                self._send(encode_nack(self.core.applied_seqno + 1))
                continue
            if text:
                self.handle_line(text, defer_ack=i + 1 < len(lines))
        self._seal_burst()

    def _seal_burst(self) -> None:
        """fsync the burst's deferred WAL tail, then send ONE cumulative
        ACK.  No-op when nothing is pending.  A failed fsync propagates
        with nothing acked — the stream dies and the reconnect re-syncs
        from the durable position.  The seal's ``wal.fsync`` span
        carries the burst's NEWEST rid (a one-record burst — the common
        quorum-acked insert — is exactly attributed; multi-rid bursts
        attribute the seal to their last request, with every per-record
        rid still on the records' own apply spans)."""
        if self._unsynced:
            with trace.rid_scope(self._burst_rid):
                self.core.wal_sync()  # may raise: nothing gets acked
            self._unsynced = False
            self.bursts += 1
        self._burst_rid = None
        if self._ack_due:
            self._ack_due = False
            self._send(encode_ack(self.core.applied_seqno))

    def handle_line(self, text: str, defer_ack: bool = False) -> None:
        """Handle one frame line.  ``defer_ack`` marks a mid-burst APPEND
        (more complete lines are already buffered): its fsync+ACK are
        deferred to the burst seal.  Every other frame kind seals any
        open burst first, so an ACK for a PING can never cover an
        unsynced record."""
        self.last_frame_t = time.monotonic()
        try:
            frame = parse_frame(text)
        except ReplProtocolError:
            # a frame that parses wrong is indistinguishable from lost
            # bytes: ask for a re-stream from our applied position
            self._seal_burst()
            self.frame_errors += 1
            self._send(encode_nack(self.core.applied_seqno + 1))
            return
        if frame.kind not in ("APPEND", "PING", "RESEQ", "VERIFY"):
            return  # HELLO responses etc. are the Replicator's business
        epoch = frame.epoch()
        if epoch < self.core.epoch:
            # a fenced ex-leader is still streaming at us: tell it its
            # term is over instead of applying history that lost
            self._seal_burst()
            self._send(encode_fenced(self.core.epoch))
            return
        if epoch > self.core.epoch:
            self._seal_burst()  # the old epoch's tail seals under it
            self._on_epoch(epoch)
        self.leader_seqno = max(self.leader_seqno, frame.seqno())
        if frame.kind == "RESEQ":
            # the swap arrives as a sequenced unit: either we are
            # already on the announced generation (we adopted it via an
            # earlier snapshot re-sync) or the stream cannot continue —
            # a record-by-record replay across a re-sequence would be
            # exactly the half-swapped tree this frame exists to forbid
            self._seal_burst()
            gen = int(frame.kv["gen"])
            if self.core.seq_gen >= gen:
                self._send(encode_ack(self.core.applied_seqno))
                return
            self.resyncs_required += 1
            raise ResyncRequired(
                f"leader re-sequenced to generation {gen} (sig "
                f"{frame.kv['sig'][:12]}...); this follower is at "
                f"{self.core.seq_gen} — snapshot adoption required")
        if frame.kind == "VERIFY":
            # anti-entropy checkpoint (ISSUE 20): the leader's state_crc
            # at exactly this applied seqno.  Comparable only when we
            # are AT that seqno — after a NACK rewind the leader
            # re-streams records we already hold, and the re-sent VERIFY
            # lands while applied_seqno is ahead; skip it, the next
            # in-position point compares.  The burst seals first so the
            # crc names a durable state.
            self._seal_burst()
            if self.core.applied_seqno != frame.seqno():
                return
            want = int(frame.kv["crc"])
            got = self.core.state_crc()
            self.verifies += 1
            if got == want:
                self._send(encode_ack(self.core.applied_seqno))
                return
            self.diverged += 1
            from . import scrub
            if self.core.state_dir:
                scrub.enter_quarantine(
                    self.core.state_dir, reason="stream-verify",
                    seqno=frame.seqno(), epoch=frame.epoch(),
                    expect_crc=want, got_crc=got)
            self.core.quarantined = True
            self.core._fire("quar-enter")
            if self.on_diverged is not None:
                self.on_diverged(frame.seqno(), want, got)
            raise Diverged(
                f"state_crc {got:#010x} != leader's {want:#010x} at "
                f"seqno {frame.seqno()} — quarantined; snapshot re-sync "
                f"required")
        if frame.kind == "APPEND":
            gen = int(frame.kv.get("gen", 0))
            if gen != self.core.seq_gen:
                # the RESEQ announce was lost (netfault drop / attach
                # race): the record's generation stamp is the backstop
                self._seal_burst()
                self.resyncs_required += 1
                raise ResyncRequired(
                    f"APPEND seqno {frame.seqno()} carries generation "
                    f"{gen}; this follower is at {self.core.seq_gen} — "
                    f"snapshot adoption required")
            rid = frame.kv.get("rid")
            try:
                # rid scope (ISSUE 12): the apply's WAL append — and, on
                # the sync=True path, its fsync — record under the
                # originating request's id
                with trace.rid_scope(rid):
                    out = self.core.apply_replicated(
                        frame.seqno(), frame.payload, sync=False, rid=rid)
            except ReplicationGap as gap:
                self._seal_burst()
                self.gaps += 1
                self._send(encode_nack(gap.expected))
                return
            if out == "dup":
                self.dups += 1
            else:
                self.applied += 1
                self._unsynced = True
                if rid is not None:
                    self._burst_rid = rid
            self._ack_due = True
            if not defer_ack:
                self._seal_burst()
        else:  # PING carries the leader's latest seqno: gap detector
            self._seal_burst()
            if self.leader_seqno > self.core.applied_seqno:
                self.gaps += 1
                self._send(encode_nack(self.core.applied_seqno + 1))
            else:
                self._send(encode_ack(self.core.applied_seqno))


# -- leader side ------------------------------------------------------------


class _FollowerState:
    __slots__ = ("conn", "node", "acked", "next_send", "last_ack_t",
                 "attached_at", "alive", "thread", "site", "verify")

    def __init__(self, conn, node: str, next_send: int,
                 site: str = "repl", verify: bool = False):
        self.conn = conn
        self.node = node
        self.acked = 0
        self.next_send = next_send
        self.last_ack_t: float | None = None
        self.attached_at = time.monotonic()
        self.alive = True
        self.thread: threading.Thread | None = None
        self.site = site  # netfault site for APPENDs (mdelta: migration)
        self.verify = verify  # HELLO advertised verify=1 (ISSUE 20)


class ReplicationHub:
    """The leader's fan-out: per-follower sender threads draining the
    WAL tail, cumulative-ACK bookkeeping, and the quorum wait an insert
    blocks on before it is acknowledged to the client.

    Transport-agnostic: the daemon injects ``send(conn, data: bytes) ->
    bool`` and ``close(conn)``; the hub never touches a socket API, so
    property tests drive it with in-memory pipes.
    """

    def __init__(self, core: ServeCore, send, close,
                 hb_s: float = DEFAULT_HB_S, on_fenced=None):
        self.core = core
        self._send = send
        self._close = close
        self.hb_s = hb_s
        self.on_fenced = on_fenced
        self._cv = threading.Condition()
        self._followers: dict[int, _FollowerState] = {}
        self._stopped = False
        core.on_append = self.notify

    # -- membership --------------------------------------------------------

    def attach(self, conn, node: str, from_seqno: int,
               site: str = "repl", verify: bool = False) -> None:
        """Register one follower stream starting after ``from_seqno``
        and spawn its sender.  The caller (daemon) already decided
        stream-vs-snapshot; a sender that later finds the WAL moved past
        its position closes the connection so the follower re-HELLOs.
        ``site`` names the netfault site its APPENDs arm ("mdelta" for a
        migration delta stream, ISSUE 17).  ``verify`` marks a stream
        whose HELLO advertised VERIFY capability (ISSUE 20): its sender
        stamps anti-entropy checkpoints; the caller is responsible for
        core.enable_verify so the capture ring is live."""
        fs = _FollowerState(conn, node, from_seqno + 1, site=site,
                            verify=verify)
        fs.acked = from_seqno
        with self._cv:
            self._followers[id(conn)] = fs
            self._cv.notify_all()
        t = threading.Thread(target=self._sender, args=(fs,), daemon=True,
                             name=f"repl-send:{node}")
        fs.thread = t
        t.start()

    def detach(self, conn) -> None:
        with self._cv:
            fs = self._followers.pop(id(conn), None)
            if fs is not None:
                fs.alive = False
            self._cv.notify_all()

    def stop(self) -> None:
        with self._cv:
            self._stopped = True
            for fs in self._followers.values():
                fs.alive = False
            self._followers.clear()
            self._cv.notify_all()

    def disconnect_all(self) -> None:
        """Drop every follower stream but stay usable (a demoted leader
        cuts its followers loose so they rediscover the real one; a
        re-promotion attaches fresh streams)."""
        with self._cv:
            dropped = list(self._followers.values())
            for fs in dropped:
                fs.alive = False
            self._followers.clear()
            self._cv.notify_all()
        for fs in dropped:
            self._close(fs.conn)

    def notify(self) -> None:
        """ServeCore.on_append hook: a record landed — wake senders and
        quorum waiters.  Runs under the core lock; must never block."""
        with self._cv:
            self._cv.notify_all()

    # -- inbound (follower -> leader lines on a stream conn) ---------------

    def on_line(self, conn, text: str) -> None:
        try:
            frame = parse_frame(text)
        except ReplProtocolError:
            return  # a garbled ack is only a missed wakeup, never state
        with self._cv:
            fs = self._followers.get(id(conn))
            if fs is None:
                return
            if frame.kind == "ACK":
                fs.acked = max(fs.acked, frame.seqno())
                fs.last_ack_t = time.monotonic()
                self._cv.notify_all()
            elif frame.kind == "NACK":
                expect = int(frame.kv["expect"])
                fs.next_send = min(fs.next_send, expect)
                self._cv.notify_all()
            elif frame.kind == "FENCED":
                fenced_by = int(frame.kv.get("epoch", 0))
                fs.alive = False
                self._cv.notify_all()
                if self.on_fenced is not None:
                    self.on_fenced(fenced_by)

    # -- outbound ----------------------------------------------------------

    def _transmit(self, fs: _FollowerState, line: str, site: str) -> bool:
        """One frame through the netfault plan to one follower.  Returns
        False when the connection is gone (caller detaches)."""
        kind = netfaults.arm(site)
        if kind == "drop":
            return True  # the wire ate it; the seqno chain will notice
        if kind == "partition":
            self._close(fs.conn)
            return False
        if kind == "slow":
            time.sleep(netfaults.SLOW_S)
        data = (line + "\n").encode("ascii")
        if not self._send(fs.conn, data):
            return False
        if kind == "dup":
            self._send(fs.conn, data)
        return True

    def _sender(self, fs: _FollowerState) -> None:
        """One follower's drain loop: ship the backlog, then block on
        the append condition; PING with the latest seqno when idle so
        the follower can detect gaps and the watcher can see liveness."""
        last_sent_t = 0.0
        while fs.alive and not self._stopped:
            recs = self.core.records_from(fs.next_send - 1)
            if recs is None:
                # the WAL was sealed past this follower: it needs a
                # snapshot bootstrap, which needs a fresh HELLO
                self._close(fs.conn)
                self.detach(fs.conn)
                return
            sent_any = False
            for seqno, payload in recs:
                if not fs.alive or self._stopped:
                    return
                line = encode_append(self.core.epoch, seqno, payload,
                                     rid=self.core.rid_for(seqno),
                                     gen=self.core.seq_gen)
                if not self._transmit(fs, line, fs.site):
                    self.detach(fs.conn)
                    return
                if fs.verify:
                    # stamp the anti-entropy checkpoint right AFTER the
                    # APPEND it names (ISSUE 20): the follower compares
                    # at exactly this applied position.  verify_crc is
                    # only non-None at captured verify points, so the
                    # common record ships nothing extra.
                    vcrc = self.core.verify_crc(seqno)
                    if vcrc is not None:
                        vline = encode_verify(self.core.epoch, seqno,
                                              vcrc)
                        if not self._transmit(fs, vline, fs.site):
                            self.detach(fs.conn)
                            return
                fs.next_send = seqno + 1
                sent_any = True
            if sent_any:
                last_sent_t = time.monotonic()
                continue  # more may have landed while we were sending
            # durable_seqno, not applied: records_from withholds the
            # group-commit window's unsynced tail, so waking on applied
            # would busy-spin until the shared fsync lands — and a PING
            # advertising an unsynced seqno would NACK-loop the follower
            # asking for records the leader will not ship yet
            with self._cv:
                if fs.next_send <= self.core.durable_seqno:
                    continue  # a NACK rewound us while unlocked
                self._cv.wait(self.hb_s)
            if not fs.alive or self._stopped:
                return
            if (time.monotonic() - last_sent_t >= self.hb_s
                    and fs.next_send > self.core.durable_seqno):
                line = encode_ping(self.core.epoch,
                                   self.core.durable_seqno)
                if not self._transmit(fs, line, "hb"):
                    self.detach(fs.conn)
                    return
                last_sent_t = time.monotonic()

    def announce_reseq(self) -> int:
        """Broadcast the leader's re-sequence to every attached follower
        as one RESEQ frame (netfault site "reseq" — the chaos sweep's
        arm on the replicated swap).  Best-effort by design: a follower
        that misses the frame trips the ``gen=`` stamp on the next
        APPEND, and one that was attached to the pre-reseq WAL hits the
        sealed-WAL snapshot path on its next drain — every road leads to
        snapshot adoption.  Returns the number of followers reached."""
        line = encode_reseq(self.core.epoch, self.core.applied_seqno,
                            self.core.seq_gen, self.core.sig)
        with self._cv:
            targets = list(self._followers.values())
        reached = 0
        for fs in targets:
            if not fs.alive:
                continue
            if self._transmit(fs, line, "reseq"):
                reached += 1
            else:
                self.detach(fs.conn)
        return reached

    # -- queries -----------------------------------------------------------

    def wait_acks(self, seqno: int, need: int, timeout_s: float) -> bool:
        """Block until ``need`` followers have cumulatively acked
        ``seqno`` (their copy is durable + applied), or the deadline
        passes.  The replication quorum an insert rides on."""
        if need <= 0:
            return True
        deadline = time.monotonic() + timeout_s
        with self._cv:
            while True:
                acked = sum(1 for fs in self._followers.values()
                            if fs.acked >= seqno)
                if acked >= need:
                    return True
                left = deadline - time.monotonic()
                if left <= 0 or self._stopped:
                    return False
                self._cv.wait(min(left, 0.1))

    def follower_count(self) -> int:
        with self._cv:
            return len(self._followers)

    def lag_report(self) -> dict:
        """node -> {acked, lag, ack_age_s} for STATS and the status
        file; lag is in records against the leader's applied seqno."""
        now = time.monotonic()
        applied = self.core.applied_seqno
        with self._cv:
            return {
                fs.node: {
                    "acked": fs.acked,
                    "lag": max(0, applied - fs.acked),
                    "ack_age_s": (round(now - fs.last_ack_t, 3)
                                  if fs.last_ack_t is not None else None),
                }
                for fs in self._followers.values()
            }


# -- snapshot bootstrap (client side) ---------------------------------------


def recv_exact(rf, n: int) -> bytes:
    out = bytearray()
    while len(out) < n:
        chunk = rf.read(n - len(out))
        if not chunk:
            raise ConnectionError(
                f"replication peer closed mid-snapshot "
                f"({len(out)}/{n} bytes)")
        out.extend(chunk)
    return bytes(out)


def parse_snapshot_header(line: str) -> dict:
    toks = line.split()
    if not toks or toks[0] != "OK":
        raise ReplProtocolError(f"snapshot fetch refused: {line!r}")
    kv = parse_kv_args(toks[1:])
    for field in ("bytes", "seqno", "epoch", "crc"):
        if field not in kv:
            raise ReplProtocolError(
                f"snapshot header missing {field}=: {line!r}")
    return kv


def fetch_snapshot(host: str, port: int, timeout_s: float = 60.0,
                   tenant: str | None = None):
    """Bootstrap fetch: ``REPL SNAPSHOT`` against a leader (for
    ``tenant``'s state dir when named).  Returns ``(blob, seqno, epoch,
    sig)`` with the crc already verified."""
    line = b"REPL SNAPSHOT\n" if tenant in (None, "default") \
        else f"REPL SNAPSHOT tenant={tenant}\n".encode("ascii")
    with socket.create_connection((host, port), timeout=timeout_s) as s:
        rf = s.makefile("rb")
        s.sendall(line)
        line = rf.readline().decode("ascii").strip()
        kv = parse_snapshot_header(line)
        blob = recv_exact(rf, int(kv["bytes"]))
    if payload_crc(blob) != int(kv["crc"]):
        raise IntegrityError(
            "replication snapshot failed its crc32 in flight")
    return blob, int(kv["seqno"]), int(kv["epoch"]), kv.get("sig", "")


def bootstrap_state_dir(state_dir: str, host: str, port: int,
                        timeout_s: float = 60.0,
                        tenant: str | None = None) -> int:
    """First start of a follower with an EMPTY state dir: fetch the
    leader's snapshot, seal it locally (sidecar resealed — the blob was
    crc-verified in flight), lay down a fresh WAL at the leader's epoch.
    Returns the snapshot's applied seqno; the caller then enters through
    ServeCore.open — the exact restart path, same as bootstrap."""
    from ..integrity.sidecar import write_sidecar
    from .state import snap_name
    from .wal import create_wal, wal_path
    blob, seqno, epoch, sig = fetch_snapshot(host, port, timeout_s,
                                             tenant=tenant)
    os.makedirs(state_dir, exist_ok=True)
    path = os.path.join(state_dir, snap_name(seqno))
    tmp = path + ".fetch"
    with open(tmp, "wb") as f:
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    snap = load_serve_snapshot(tmp, integrity="trust")
    snap.validate()
    if sig and snap.sig != sig:
        raise IntegrityError(
            f"replication snapshot sig {snap.sig[:12]}... does not match "
            f"the advertised {sig[:12]}...")
    os.replace(tmp, path)
    write_sidecar(path)
    create_wal(wal_path(state_dir), snap.sig, epoch=epoch)
    return seqno


# -- the follower's connection owner ----------------------------------------


class Replicator:
    """Own the follower->leader connection for one daemon: discover the
    leader, HELLO, then pump bytes into a :class:`ReplApplier` until the
    stream dies — and reconnect.  ``discover`` is injected
    (serve/cluster.py): it returns the current leader's (host, port) or
    None, so failover re-pointing is just discovery returning a new
    address."""

    def __init__(self, core: ServeCore, node_id: str, discover,
                 hb_s: float = DEFAULT_HB_S, retry_s: float = 0.2,
                 events: list | None = None, tenant: str | None = None,
                 mig: bool = False, verify: bool = True,
                 on_diverged=None):
        self.core = core
        self.node_id = node_id
        self.discover = discover
        self.tenant = tenant  # None/"default": the PR-7 handshake bytes
        self.mig = mig        # migration delta stream (mdelta site)
        #: advertise VERIFY capability in HELLO (ISSUE 20; migration
        #: delta streams replay into a build-side core mid-cutover, so
        #: they stay on the plain PR-7 handshake)
        self.verify = verify and not mig
        self.on_diverged = on_diverged
        self.hb_s = hb_s
        self.retry_s = retry_s
        self.events = events if events is not None else []
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.applier: ReplApplier | None = None
        self.connected_to: tuple[str, int] | None = None
        self.last_frame_t: float | None = None
        self.resyncs = 0
        self.quarantine_heals = 0

    @property
    def lag(self) -> int:
        a = self.applier
        return a.lag if a is not None else 0

    @property
    def leader_seqno(self) -> int:
        a = self.applier
        return a.leader_seqno if a is not None else self.core.applied_seqno

    def start(self) -> "Replicator":
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"replicator:{self.node_id}")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def stream_age_s(self) -> float | None:
        """Seconds since the last frame arrived (None = never streamed)
        — the staleness signal the failover watcher deadlines."""
        t = self.last_frame_t
        return None if t is None else max(0.0, time.monotonic() - t)

    def _run(self) -> None:
        while not self._stop.is_set():
            target = self.discover()
            if target is None:
                self._stop.wait(self.retry_s)
                continue
            try:
                self._stream_once(target)
            except (OSError, ConnectionError, ReplProtocolError,
                    IntegrityError) as exc:
                self.events.append(("repl_error", str(exc)))
                self._stop.wait(self.retry_s)
            finally:
                self.connected_to = None

    def _stream_once(self, target: tuple[str, int]) -> None:
        host, port = target
        from . import scrub
        if self.core.quarantined or (
                self.core.state_dir
                and scrub.read_quarantine(self.core.state_dir)
                is not None):
            # quarantine healing takes priority over streaming: the
            # durable marker survives any kill, so every restart lands
            # back here until the re-sync completes and clears it
            self._heal_quarantine(target)
            return  # reconnect streams normally from the adopted boundary
        with socket.create_connection((host, port),
                                      timeout=max(1.0, 3 * self.hb_s)) \
                as sock:
            rf = sock.makefile("rb")
            hello = encode_hello(self.node_id, self.core.epoch,
                                 self.core.applied_seqno, self.core.sig,
                                 tenant=self.tenant, mig=self.mig,
                                 verify=self.verify)
            sock.sendall((hello + "\n").encode("ascii"))
            line = rf.readline().decode("ascii").strip()
            toks = line.split()
            if not toks or toks[0] != "OK":
                if " badrepl " in f" {line} ":
                    # our sig is unknown to the leader's chain: we
                    # applied a re-sequence generation the cluster lost
                    # (the old leader died before its swap quorum-acked,
                    # ISSUE 19).  Without an exit this retries forever;
                    # with one, the orphan rolls back to the surviving
                    # leader's generation — sound, because it is in our
                    # own manifest chain and nothing acked lives only in
                    # the orphaned gen.
                    if self._adopt_across_badrepl(host, port):
                        return  # reconnect under the adopted identity
                raise ReplProtocolError(f"HELLO refused: {line!r}")
            kv = parse_kv_args(toks[1:])
            if kv.get("mode") == "snapshot":
                self.resyncs += 1
                self.events.append(("repl_resync", int(kv["seqno"])))
                blob = recv_exact(rf, int(kv["bytes"]))
                if payload_crc(blob) != int(kv["crc"]):
                    raise IntegrityError("replication snapshot failed "
                                         "its crc32 in flight")
                tmp = os.path.join(self.core.state_dir, "resync.fetch")
                with open(tmp, "wb") as f:
                    f.write(blob)
                try:
                    snap = load_serve_snapshot(tmp, integrity="trust")
                    if (snap.sig != self.core.sig
                            and snap.seq_gen > self.core.seq_gen):
                        # the leader re-sequenced (ISSUE 18): adopt the
                        # new generation as one unit, sanctioned by a
                        # durable adopt manifest FIRST so a kill inside
                        # reset_from_snapshot (old WAL beside a new-sig
                        # snapshot) heals on restart instead of refusing
                        from . import reseq as reseq_mod
                        reseq_mod.write_adoption(
                            self.core.state_dir, self.core.sig,
                            self.core.seq_gen, snap.sig, snap.seq_gen)
                        self.core.reset_from_snapshot(
                            snap, allow_sig_change=True)
                        reseq_mod.finish_adoption(
                            self.core.state_dir, snap.sig, snap.seq_gen)
                        self.events.append(("repl_reseq_adopt",
                                            snap.seq_gen))
                    else:
                        self.core.reset_from_snapshot(snap)
                finally:
                    try:
                        os.unlink(tmp)
                    except OSError:
                        pass
            elif kv.get("mode") != "stream":
                raise ReplProtocolError(f"unknown HELLO mode: {line!r}")
            self.connected_to = target
            self.events.append(("repl_connected", f"{host}:{port}"))

            def send_up(text: str) -> None:
                sock.sendall((text + "\n").encode("ascii"))

            applier = ReplApplier(self.core, send_up,
                                  on_diverged=self.on_diverged)
            self.applier = applier
            sock.settimeout(max(0.2, 3 * self.hb_s))
            while not self._stop.is_set():
                try:
                    data = sock.recv(1 << 16)
                except socket.timeout:
                    continue  # staleness is the watcher's deadline call
                if not data:
                    return  # leader went away: rediscover + reconnect
                applier.feed(data)
                self.last_frame_t = time.monotonic()

    def _heal_quarantine(self, target: tuple[str, int]) -> None:
        """The quarantine re-sync (ISSUE 20): this replica's state
        proved divergent, so stream resumption is forbidden — fetch the
        leader's snapshot and adopt it WHOLE, rolling back any divergent
        tail (the leader re-streams every acked record past the
        snapshot boundary on reconnect).  Phase machine, each phase
        durable in the quarantine marker BEFORE its work starts::

            diverged -> resync -> verify -> (cleared)

        kill -9 anywhere re-enters here on restart (the marker survives;
        reads stay refused throughout) and every step is idempotent — a
        re-fetch is just a fresh snapshot.  The ``quar-resync`` /
        ``quar-verify`` / ``quar-clear`` serve-fault sites make each
        boundary deterministically killable."""
        from . import scrub
        core = self.core
        host, port = target
        core.quarantined = True  # restart path: marker seen before flag
        scrub.mark_phase(core.state_dir, scrub.PHASE_RESYNC)
        core._fire("quar-resync")
        blob, seqno, epoch, sig = fetch_snapshot(
            host, port, timeout_s=max(5.0, 10 * self.hb_s),
            tenant=self.tenant)
        tmp = os.path.join(core.state_dir, "resync.fetch")
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        try:
            snap = load_serve_snapshot(tmp, integrity="trust")
            if snap.sig != core.sig and snap.seq_gen > core.seq_gen:
                # the leader ALSO re-sequenced while we were dark:
                # sanction the generation adoption exactly like the
                # ordinary snapshot-mode path
                from . import reseq as reseq_mod
                reseq_mod.write_adoption(
                    core.state_dir, core.sig, core.seq_gen,
                    snap.sig, snap.seq_gen)
                core.reset_from_snapshot(snap, allow_sig_change=True,
                                         allow_rollback=True)
                reseq_mod.finish_adoption(core.state_dir, snap.sig,
                                          snap.seq_gen)
            else:
                core.reset_from_snapshot(snap, allow_rollback=True)
        finally:
            try:
                os.unlink(tmp)
            except OSError:
                pass
        # the adopted state IS the leader's sealed snapshot (crc-checked
        # in flight, resealed locally): record the crc we rejoined at —
        # the next in-stream VERIFY point is the live rejoin proof
        scrub.mark_phase(core.state_dir, scrub.PHASE_VERIFY,
                         crc=core.state_crc(), seqno=core.applied_seqno)
        core._fire("quar-verify")
        scrub.clear_quarantine(core.state_dir)
        core.quarantined = False
        core._fire("quar-clear")
        self.resyncs += 1
        self.quarantine_heals += 1
        self.events.append(("quarantine_healed", core.applied_seqno,
                            core.state_crc()))

    def _adopt_across_badrepl(self, host: str, port: int) -> bool:
        """The snapshot-adoption exit for a ``badrepl`` refusal: this
        replica serves a sequence generation the surviving leader's
        chain has never seen (it applied a RESEQ swap whose leader died
        before the quorum ack, PR 18's orphan).  Fetch the leader's
        snapshot and, ONLY when its sig is in our own manifest chain
        (i.e. we are rolling back along our own history, not adopting a
        foreign build input), adopt it under a durable adoption manifest
        — the exact discipline of the forward gen-mismatch path, with
        the rollback sanctioned the same way.  Returns True when the
        core now serves the leader's generation."""
        from . import reseq as reseq_mod
        core = self.core
        if not core.state_dir:
            return False
        try:
            blob, seqno, epoch, sig = fetch_snapshot(
                host, port, timeout_s=max(5.0, 10 * self.hb_s),
                tenant=self.tenant)
        except (OSError, ConnectionError, ReplProtocolError,
                IntegrityError) as exc:
            self.events.append(("repl_error", f"badrepl fetch: {exc}"))
            return False
        if not sig or sig == core.sig:
            return False
        if not reseq_mod.chain_has_sig(core.state_dir, sig):
            # genuinely a different build input: keep refusing loudly
            self.events.append(("repl_error",
                                f"badrepl sig {sig[:12]}... not in the "
                                f"local chain — not adopting"))
            return False
        tmp = os.path.join(core.state_dir, "resync.fetch")
        with open(tmp, "wb") as f:
            f.write(blob)
        try:
            snap = load_serve_snapshot(tmp, integrity="trust")
            if snap.sig != sig:
                raise IntegrityError(
                    f"replication snapshot sig {snap.sig[:12]}... does "
                    f"not match the advertised {sig[:12]}...")
            reseq_mod.write_adoption(core.state_dir, core.sig,
                                     core.seq_gen, snap.sig, snap.seq_gen)
            core.reset_from_snapshot(snap, allow_sig_change=True,
                                     allow_gen_rollback=True)
            reseq_mod.finish_adoption(core.state_dir, snap.sig,
                                      snap.seq_gen)
        finally:
            try:
                os.unlink(tmp)
            except OSError:
                pass
        self.resyncs += 1
        self.events.append(("repl_reseq_rollback", snap.seq_gen))
        return True
