"""Crash-safe incremental re-sequencing (ISSUE 18): the background job
that rebuilds sequence + tree + partition from the durable edge set when
sequence drift says the bootstrap-fixed order is now lying.

Repartition (serve/state.py) re-bins the EXISTING tree, so it can never
recover quality lost to inserts that landed outside the sequence —
pst-only vertices are invisible to the partitioner, and ECV(down) decays
monotonically under sustained insert load.  The fix is the paper's own
lever: re-run the degree order.  The serve tier already maintains the
degree histogram incrementally (two +1s per insert, parity-asserted
against a full recount), so pass 1 of the rebuild is a host counting
sort; pass 2 is the EXISTING streamed fold (ops/extmem.py) over the
``.dat`` records plus the WAL'd inserts as its tail block — the durable
edge source is exactly ``.dat + log``, so a rebuild is "the offline
build over what the state dir already persists".

**Phases, each durable before it runs** (the manifest is written
tmp+fsync+rename, the migration-manifest discipline)::

    hist   counting-sort sequence rebuild over the cut's histogram
    fold   streamed fold (extmem checkpoints at block boundaries)
    swap   pending tree artifact durable -> ticket-guarded atomic swap
           (later-started wins; queries serve stale-but-consistent)
    done   sealed snapshot under the NEW input signature; (gen, sig)
           appended to the manifest chain

kill -9 at any boundary resumes (or aborts) off the manifest: (durable
edges, cut) fully determine the rebuilt state, so a resumed rebuild is
bit-identical to an uninterrupted one.  The crash window between the
new-generation snapshot seal and the WAL swap leaves an old-sig log
beside a new-sig snapshot; ``ServeCore.open`` heals it ONLY when this
manifest sanctions the old->new transition and no log record lies past
the snapshot boundary — anything else is the torn mid-swap state
``sheep fsck`` refuses.

Replication: the swap is announced as a sequenced ``REPL RESEQ`` frame
and every later APPEND carries ``gen=``; a follower that missed the
frame trips the generation mismatch, re-handshakes, and adopts the
leader's new-generation snapshot (serve/replicate.py) — a mid-reseq
failover therefore serves either the old or the new generation, never a
half-swapped tree.  The adopting follower writes an ``adopt`` manifest
first, sanctioning its own crash windows.
"""

from __future__ import annotations

import json
import os
import warnings

import numpy as np

from ..core.sequence import (degree_sequence_from_degrees,
                             host_degree_histogram)
from ..integrity.errors import IntegrityError, MalformedArtifact
from ..obs import trace as _obs
from ..runtime.snapshot import input_signature

MANIFEST_NAME = "reseq.json"
PENDING_NAME = "reseq-pending.npz"
CKPT_DIR = "reseq-ckpt"
MANIFEST_VERSION = 1
#: terminal phases; anything else is an in-flight rebuild
DONE_PHASES = ("done", "aborted")
#: completed (gen, sig) links the manifest chain retains
CHAIN_KEEP = 8


def manifest_path(state_dir: str) -> str:
    return os.path.join(state_dir, MANIFEST_NAME)


def pending_path(state_dir: str) -> str:
    return os.path.join(state_dir, PENDING_NAME)


def ckpt_dir(state_dir: str) -> str:
    return os.path.join(state_dir, CKPT_DIR)


def load_manifest(state_dir: str) -> dict | None:
    """The state dir's reseq manifest, or None when it never re-sequenced.
    An unparseable manifest raises (fsck's cue) — a torn write is
    impossible by the tmp+rename landing, so garbage means tampering or
    disk corruption, never a crash."""
    path = manifest_path(state_dir)
    if not os.path.exists(path):
        return None
    try:
        with open(path, "r", encoding="utf-8") as f:
            man = json.load(f)
    except (OSError, ValueError) as exc:
        raise MalformedArtifact(f"{path}: unreadable reseq manifest "
                                f"({exc})")
    if not isinstance(man, dict) or "phase" not in man:
        raise MalformedArtifact(f"{path}: reseq manifest missing a phase")
    return man


def save_manifest(state_dir: str, man: dict) -> None:
    """Durable manifest landing: tmp + fsync + atomic rename (the
    migration-manifest discipline) — a crash leaves either the old
    manifest or the new one, never a tear."""
    path = manifest_path(state_dir)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(man, f, indent=1, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    try:
        dfd = os.open(state_dir, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        pass


def active(state_dir: str) -> bool:
    """Is a re-sequence in flight in this state dir?  (Tenant eviction
    refuses while one is: sealing a mid-rebuild tenant out of memory
    would orphan the job.)"""
    try:
        man = load_manifest(state_dir)
    except IntegrityError:
        return True  # fsck's problem; do not evict over it
    return man is not None and man.get("phase") not in DONE_PHASES


def _sig_order(man: dict) -> list[str]:
    """Every signature the manifest vouches for, oldest first: the
    completed chain plus (once the swap phase is durable) the in-flight
    old->new link.  The in-flight link DEFINES the direction: a
    sanctioned gen ROLLBACK (the badrepl orphan adopting the surviving
    leader's older generation, ISSUE 19) re-orders new_sig after
    old_sig even when both already sit in the chain the other way
    around — so every crash window of the rollback heals through the
    same old->new gate as a forward adoption."""
    order = [c.get("sig") for c in man.get("chain", [])
             if isinstance(c, dict) and c.get("sig")]
    if man.get("phase") in ("swap", "adopt", "done"):
        old, new = man.get("old_sig"), man.get("new_sig")
        if old and old not in order:
            order.append(old)
        if new:
            if new in order:
                order.remove(new)
            order.append(new)
    return order


def sanctions_sig_change(state_dir: str, from_sig: str,
                         to_sig: str) -> bool:
    """Does the manifest sanction a WAL(from_sig) beside a
    snapshot(to_sig)?  True only for a planned sequence-generation step
    (from strictly older in the chain than to) — the gate
    ``ServeCore.open`` and ``sheep fsck`` apply before healing a sig
    mismatch instead of refusing it."""
    try:
        man = load_manifest(state_dir)
    except IntegrityError:
        return False
    if man is None:
        return False
    order = _sig_order(man)
    if from_sig in order and to_sig in order:
        return order.index(from_sig) < order.index(to_sig)
    return False


def chain_has_sig(state_dir: str, sig: str) -> bool:
    """Is ``sig`` a (possibly older) generation this state dir has ever
    served?  The replication HELLO uses it to tell a follower one
    generation behind (answer: snapshot bootstrap) from a foreign build
    input (answer: badrepl)."""
    try:
        man = load_manifest(state_dir)
    except IntegrityError:
        return False
    if man is None:
        return False
    order = _sig_order(man)
    for s in (man.get("old_sig"), man.get("new_sig")):
        if s and s not in order:
            order.append(s)
    return sig in order


def _append_chain(man: dict, gen: int, sig: str) -> None:
    chain = [c for c in man.get("chain", [])
             if isinstance(c, dict) and c.get("sig") != sig]
    chain.append({"gen": int(gen), "sig": sig})
    man["chain"] = chain[-CHAIN_KEEP:]


# -- the pending artifact ---------------------------------------------------


def _save_pending(state_dir: str, seq, parent, pst, cut: int,
                  gen: int, sig: str) -> None:
    """Land the rebuilt tree durably BEFORE the swap phase: the extmem
    checkpoints are cleared when the fold completes, so without this
    artifact a kill between fold-complete and swap would have nothing to
    resume from."""
    import zlib
    seq = np.ascontiguousarray(seq, dtype=np.uint32)
    parent = np.ascontiguousarray(parent, dtype=np.uint32)
    pst = np.ascontiguousarray(pst, dtype=np.uint32)
    crc = 0
    for arr in (seq, parent, pst):
        crc = zlib.crc32(arr.tobytes(), crc)
    path = pending_path(state_dir)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, seq=seq, parent=parent, pst=pst,
                 cut=np.int64(cut), gen=np.int64(gen), sig=np.str_(sig),
                 crc=np.int64(crc & 0xFFFFFFFF))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _load_pending(state_dir: str) -> dict:
    """Reload the pending tree; crc-verified, so a torn or tampered
    artifact raises (the resume path then refolds instead)."""
    import zlib
    path = pending_path(state_dir)
    try:
        with np.load(path) as z:
            pend = {"seq": z["seq"].copy(), "parent": z["parent"].copy(),
                    "pst": z["pst"].copy(), "cut": int(z["cut"]),
                    "gen": int(z["gen"]), "sig": str(z["sig"]),
                    "crc": int(z["crc"])}
    except Exception as exc:
        raise MalformedArtifact(f"{path}: unreadable reseq pending "
                                f"artifact ({type(exc).__name__}: {exc})")
    crc = 0
    for key in ("seq", "parent", "pst"):
        crc = zlib.crc32(np.ascontiguousarray(pend[key]).tobytes(), crc)
    if (crc & 0xFFFFFFFF) != pend["crc"]:
        raise IntegrityError(f"{path}: reseq pending artifact failed its "
                             f"crc — refusing the swap")
    return pend


def _cleanup(state_dir: str) -> None:
    try:
        os.unlink(pending_path(state_dir))
    except OSError:
        pass
    cdir = ckpt_dir(state_dir)
    if os.path.isdir(cdir):
        import shutil
        shutil.rmtree(cdir, ignore_errors=True)


# -- follower adoption sanctions --------------------------------------------


def write_adoption(state_dir: str, old_sig: str, old_gen: int,
                   new_sig: str, new_gen: int) -> None:
    """A follower about to adopt a re-sequenced leader snapshot writes
    this FIRST: it sanctions the sig change through every crash window
    of :meth:`ServeCore.reset_from_snapshot`."""
    try:
        man = load_manifest(state_dir)
    except IntegrityError:
        man = None
    if man is None:
        man = {"version": MANIFEST_VERSION, "chain": []}
    if not man.get("chain"):
        man["chain"] = [{"gen": int(old_gen), "sig": old_sig}]
    man.update(phase="adopt", old_sig=old_sig, new_sig=new_sig,
               old_gen=int(old_gen), new_gen=int(new_gen))
    save_manifest(state_dir, man)


def finish_adoption(state_dir: str, new_sig: str, new_gen: int) -> None:
    try:
        man = load_manifest(state_dir)
    except IntegrityError:
        return
    if man is None:
        return
    _append_chain(man, new_gen, new_sig)
    man["phase"] = "done"
    save_manifest(state_dir, man)


# -- the driver -------------------------------------------------------------


def _price(records: int, inserted: int, seq_drift: int) -> dict:
    from ..plan.model import plan_reseq
    from ..plan.priors import PriorStore
    return plan_reseq(records, inserted, seq_drift,
                      priors=PriorStore.from_env())


def run_reseq(core, force: bool = False, hub=None,
              events: list | None = None) -> dict:
    """One re-sequence attempt, start to finish: price it, make each
    phase durable, rebuild, swap, seal, announce.  Raises ServeKilled /
    exits at the injected ``reseq-*`` fault sites (serve/faults.py) —
    the kill-at-every-boundary sweep drives exactly this function."""
    events = events if events is not None else []
    info = core.reseq_begin()
    if info["graph_path"] is None:
        # no durable .dat: the WAL'd inserts alone cannot reproduce a
        # tree bootstrapped from -T/-s artifacts — refuse, don't destroy
        return {"skipped": 1, "reason": "no-durable-graph"}
    # the tentpole's parity gate: the incremental histogram must equal a
    # full recount before a rebuild is allowed to trust it
    if not core.degree_parity():
        raise IntegrityError(
            "incremental degree histogram diverged from the full "
            "recount — refusing to re-sequence off corrupt counters")
    plan = _price(len(core.edges_tail) if core.edges_tail is not None
                  else 0, info["cut"], info["seq_drift"])
    if not force and plan.get("decision") == "stay":
        events.append(("reseq-stay", plan.get("provenance")))
        return {"skipped": 1, "reason": "priced-stay", "plan": plan}

    state_dir = core.state_dir
    try:
        prev = load_manifest(state_dir)
    except IntegrityError:
        prev = None
    man = {"version": MANIFEST_VERSION, "phase": "hist",
           "cut": int(info["cut"]), "block": 0,
           "old_sig": info["old_sig"], "new_sig": "",
           "old_gen": int(info["seq_gen"]),
           "new_gen": int(info["seq_gen"]) + 1,
           "applied_seqno": int(info["applied_seqno"]),
           "plan": plan,
           "chain": (prev.get("chain") if prev else None)
           or [{"gen": int(info["seq_gen"]), "sig": info["old_sig"]}]}
    save_manifest(state_dir, man)
    return _drive(core, man, info["ticket"], hub=hub, events=events)


def resume_reseq(core, hub=None, events: list | None = None
                 ) -> dict | None:
    """Pick an interrupted re-sequence back up after a restart (daemon
    startup / the kill-sweep harness).  Resumes when the durable inputs
    still determine the rebuild; aborts cleanly (phase ``aborted``, old
    generation keeps serving) when they no longer do.  None = nothing
    pending."""
    events = events if events is not None else []
    state_dir = core.state_dir
    try:
        man = load_manifest(state_dir)
    except IntegrityError as exc:
        warnings.warn(f"serve: {exc}; ignoring the reseq manifest")
        return None
    if man is None or man.get("phase") in DONE_PHASES:
        return None
    if man.get("phase") == "adopt":
        # an interrupted follower adoption: either the snapshot landed
        # (we opened on the new generation) or it never did
        if core.seq_gen >= man.get("new_gen", 0):
            finish_adoption(state_dir, man.get("new_sig", ""),
                            man.get("new_gen", 0))
            return {"resumed": "adopt-finalize"}
        man["phase"] = "aborted"
        save_manifest(state_dir, man)
        return {"aborted": 1, "reason": "adoption-never-landed"}
    if core.seq_gen >= man.get("new_gen", 0):
        # the swap sealed before the crash; only the bookkeeping is left
        _append_chain(man, core.seq_gen, core.sig)
        man["phase"] = "done"
        save_manifest(state_dir, man)
        _cleanup(state_dir)
        return {"resumed": "finalize", "seq_gen": core.seq_gen}
    if (core.graph_path is None
            or man.get("cut", 0) > len(core.ins_tail)):
        man["phase"] = "aborted"
        save_manifest(state_dir, man)
        _cleanup(state_dir)
        warnings.warn("serve: aborted an unresumable re-sequence (durable "
                      "edge source changed under the manifest)")
        return {"aborted": 1, "reason": "unresumable"}
    ticket = core.reseq_begin()["ticket"]
    if man.get("phase") == "swap":
        try:
            return _swap_from_pending(core, man, ticket, hub=hub,
                                      events=events)
        except (IntegrityError, OSError) as exc:
            # pending artifact torn: fall back to refolding — (edges,
            # cut) still determine the same tree bit for bit
            events.append(("reseq-repend", str(exc)))
    return _drive(core, man, ticket, hub=hub, events=events)


def _drive(core, man: dict, ticket: int, hub=None,
           events: list | None = None) -> dict:
    """Phases hist -> fold -> swap -> done for one attempt (fresh or
    resumed: the manifest's cut pins the edge set either way)."""
    state_dir = core.state_dir
    cut = int(man["cut"])
    core._fire("reseq-hist")

    # -- hist: counting-sort sequence rebuild over the cut's histogram
    ins_t, ins_h = core.ins_slice(cut)
    if core.edges_tail is None:
        raise IntegrityError("re-sequence needs the graph edges resident "
                             "(the .dat is the durable edge source)")
    tail = np.concatenate([core.edges_tail, ins_t])
    head = np.concatenate([core.edges_head, ins_h])
    n = (int(max(tail.max(initial=0), head.max(initial=0))) + 1
         if len(tail) else 0)
    deg_at_cut = host_degree_histogram(tail, head, n)
    new_seq = degree_sequence_from_degrees(deg_at_cut)
    new_sig = input_signature(len(new_seq), new_seq)
    if man.get("new_sig") and man["new_sig"] != new_sig:
        man["phase"] = "aborted"
        save_manifest(state_dir, man)
        _cleanup(state_dir)
        raise IntegrityError(
            f"resumed re-sequence disagrees with its manifest (sig "
            f"{new_sig[:12]}... != pinned {man['new_sig'][:12]}...) — "
            f"the durable edge set changed; aborted")
    block = int(man.get("block") or 0) or core.governor.ext_fitted_block()
    man.update(phase="fold", new_sig=new_sig, block=block)
    save_manifest(state_dir, man)
    core._fire("reseq-fold")

    # -- fold: the streamed build over .dat + WAL'd inserts.  Checkpoints
    # land in the state dir; resume=True picks them up after a kill.
    # The span carries the fold's blob size so the PriorStore can
    # harvest MEASURED fold throughput for plan_reseq (the same loop
    # that teaches plan_build its rung seconds).
    graph_path = core.graph_path
    blob_bytes = (len(tail) + len(head)) * tail.itemsize \
        + len(new_seq) * new_seq.itemsize
    with _obs.span("reseq.fold", bytes=int(blob_bytes),
                   records=int(len(tail)), gen=int(man["new_gen"])):
        if graph_path and graph_path.endswith(".dat"):
            from ..ops.extmem import build_forest_extmem
            _, forest = build_forest_extmem(
                graph_path, block_edges=block, seq=new_seq,
                checkpoint_dir=ckpt_dir(state_dir), resume=True,
                governor=core.governor, events=events,
                tail_edges=(ins_t, ins_h))
        else:
            from ..core.forest import build_forest
            forest = build_forest(tail, head, new_seq,
                                  max_vid=max(n - 1, 0))
    parent, pst = forest.parent, forest.pst_weight

    # -- pending artifact durable, THEN the swap phase: the extmem
    # checkpoints are cleared on fold completion, so this artifact is
    # what a kill between here and the seal resumes from
    _save_pending(state_dir, new_seq, parent, pst, cut,
                  man["new_gen"], new_sig)
    man["phase"] = "swap"
    save_manifest(state_dir, man)
    return _swap_from_pending(core, man, ticket, hub=hub, events=events)


def _swap_from_pending(core, man: dict, ticket: int, hub=None,
                       events: list | None = None) -> dict:
    """Phase swap: partition the pending tree, swap it in under the
    ticket guard, seal the new generation durable, finish the manifest,
    announce to followers."""
    from ..core.forest import Forest
    from ..partition.tree_partition import (TreePartitionOptions,
                                            partition_forest)
    state_dir = core.state_dir
    pend = _load_pending(state_dir)
    if pend["sig"] != man.get("new_sig") or pend["gen"] != man["new_gen"]:
        raise IntegrityError(
            f"{pending_path(state_dir)}: pending artifact belongs to a "
            f"different rebuild (gen {pend['gen']}, sig "
            f"{pend['sig'][:12]}...) — refusing the swap")
    core._fire("reseq-swap")
    jparts = partition_forest(
        Forest(pend["parent"], pend["pst"]), core.num_parts,
        TreePartitionOptions(balance_factor=core.balance))
    res = core.reseq_swap(ticket, pend["cut"], pend["seq"],
                          pend["parent"], pend["pst"], jparts,
                          pend["sig"], pend["gen"])
    if res.get("stale"):
        return res  # a later-started rebuild already swapped; its
        # manifest supersedes this attempt's bookkeeping
    core._fire("reseq-seal")
    sealed = core.maybe_seal()
    _append_chain(man, pend["gen"], pend["sig"])
    man["phase"] = "done"
    save_manifest(state_dir, man)
    _cleanup(state_dir)
    if events is not None:
        events.append(("reseq-swap", pend["gen"], len(pend["seq"])))
    if hub is not None:
        try:
            hub.announce_reseq()
        except Exception as exc:  # announce is best-effort: gen= on
            # every later APPEND is the reliable resync trigger
            warnings.warn(f"serve: RESEQ announce failed ({exc})")
    res["sealed"] = 1 if sealed else 0
    return res
