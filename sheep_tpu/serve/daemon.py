"""The long-lived partition service: sockets around a ServeCore.

Thread shapes: one accept loop, one handler thread per connection (each
connection serializes its own requests — the batching unit is the line),
one optional background repartition thread, and the supervisor-machinery
heartbeat (supervisor/heartbeat.HeartbeatWriter beating
``<state-dir>/serve.hb``) so the same ``is_stale`` deadline the
tournament supervisor applies to workers answers "is the daemon alive?"
for outside monitors — including `sheep supervise --status --json`
consumers watching a shared state tree.

Request lifecycle (the order is the contract)::

    read line -> parse -> admission slot -> fault hooks (serve/faults:
    req/query/insert sites) -> deadline check -> dispatch -> respond

Admission holds its slot across the fault hooks on purpose: an injected
``slow``/``hang`` occupies capacity exactly like a real slow client, so
the shedding paths are exercised by the same plan grammar that kills the
process.  The deadline check runs AFTER the hooks — a handler that lost
its budget answers ``ERR timeout``, it does not answer late.

Every insert is durable (WAL fsync) before its ``OK`` leaves the process;
a kill -9 anywhere in the lifecycle loses at most inserts that were never
acknowledged — the restart contract tests/test_serve.py and the tier-1
smoke enforce.
"""

from __future__ import annotations

import os
import socket
import sys
import threading
import time
from dataclasses import dataclass, field

from ..resources.errors import ResourceError
from ..supervisor.heartbeat import HeartbeatWriter, maybe_start_from_env
from . import faults as serve_faults
from .admission import AdmissionController, AdmissionRefused
from .protocol import (MAX_LINE, BadRequest, err_line, ok_kv, ok_line,
                       parse_request, parse_vids)
from .state import ServeCore

ADDR_FILE = "serve.addr"
HEARTBEAT_FILE = "serve.hb"

DEADLINE_ENV = "SHEEP_SERVE_DEADLINE_S"
MAX_INFLIGHT_ENV = "SHEEP_SERVE_MAX_INFLIGHT"
SNAP_EVERY_ENV = "SHEEP_SERVE_SNAP_EVERY"
DRIFT_ENV = "SHEEP_SERVE_DRIFT"
DRIFT_MIN_ENV = "SHEEP_SERVE_DRIFT_MIN"


@dataclass
class ServeConfig:
    host: str = "127.0.0.1"
    port: int = 0            # 0: ephemeral, discover via serve.addr
    deadline_s: float = 30.0
    max_inflight: int = 64
    snap_every: int = 256
    drift_frac: float = 0.1
    drift_min_cut: int = 64
    read_only: bool = False
    #: ceiling on how long an injected hang may stall a handler
    hang_cap_s: float = 2.0
    events: list = field(default_factory=list)

    @classmethod
    def from_env(cls, **overrides) -> "ServeConfig":
        kw: dict = {}
        if os.environ.get(DEADLINE_ENV):
            kw["deadline_s"] = float(os.environ[DEADLINE_ENV])
        if os.environ.get(MAX_INFLIGHT_ENV):
            kw["max_inflight"] = int(os.environ[MAX_INFLIGHT_ENV])
        if os.environ.get(SNAP_EVERY_ENV):
            kw["snap_every"] = int(os.environ[SNAP_EVERY_ENV])
        if os.environ.get(DRIFT_ENV):
            kw["drift_frac"] = float(os.environ[DRIFT_ENV])
        if os.environ.get(DRIFT_MIN_ENV):
            kw["drift_min_cut"] = int(os.environ[DRIFT_MIN_ENV])
        kw.update(overrides)
        return cls(**kw)


class ServeDaemon:
    """Sockets + admission + deadlines + fault hooks around one core."""

    def __init__(self, core: ServeCore, config: ServeConfig | None = None):
        self.core = core
        self.config = config or ServeConfig.from_env()
        self.admission = AdmissionController(
            max_inflight=self.config.max_inflight,
            governor=core.governor,
            read_only=self.config.read_only)
        self._listener: socket.socket | None = None
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        self._hb: HeartbeatWriter | None = None
        self._env_hb = None
        self._repartitioning = threading.Lock()
        self.started_at = time.time()
        self.counters = {"requests": 0, "queries": 0, "inserts": 0,
                         "shed": 0, "timeouts": 0, "readonly": 0,
                         "errors": 0, "faults": 0}

    # -- lifecycle ---------------------------------------------------------

    @property
    def address(self) -> tuple[str, int]:
        assert self._listener is not None, "daemon not started"
        return self._listener.getsockname()[:2]

    def start(self) -> "ServeDaemon":
        """Bind, publish the address, start beating, spawn the accept
        loop.  Returns self so tests can ``daemon = ServeDaemon(...)
        .start()``."""
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((self.config.host, self.config.port))
        self._listener.listen(128)
        self._listener.settimeout(0.2)
        host, port = self.address
        # address discovery for scripts: plain tiny file, rewritten on
        # every start (ephemeral ports move across restarts)
        with open(os.path.join(self.core.state_dir, ADDR_FILE), "w") as f:
            f.write(f"{host} {port}\n")
        self._hb = HeartbeatWriter(
            os.path.join(self.core.state_dir, HEARTBEAT_FILE)).start()
        self._env_hb = maybe_start_from_env()  # supervisor-launched case
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name="serve-accept")
        t.start()
        self._threads.append(t)
        return self

    def run_forever(self) -> None:
        """Block until :meth:`shutdown` (the CLI foreground mode)."""
        while not self._stop.wait(0.5):
            pass

    def shutdown(self) -> None:
        self._stop.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
        if self._hb is not None:
            self._hb.stop()
        if self._env_hb is not None:
            self._env_hb.stop()
        self.core.close()

    # -- connection handling -----------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return  # listener closed: shutting down
            with self._conns_lock:
                self._conns.add(conn)
            t = threading.Thread(target=self._handle_conn, args=(conn,),
                                 daemon=True, name="serve-conn")
            t.start()

    def _handle_conn(self, conn: socket.socket) -> None:
        conn.settimeout(None)
        try:
            rf = conn.makefile("rb")
            while not self._stop.is_set():
                line = rf.readline(MAX_LINE + 1)
                if not line:
                    return  # client went away
                if len(line) > MAX_LINE:
                    self._send(conn, err_line(
                        "badreq", f"request line exceeds {MAX_LINE} bytes"))
                    return
                try:
                    text = line.decode("ascii").strip()
                except UnicodeDecodeError:
                    self._send(conn, err_line("badreq",
                                              "non-ascii request line"))
                    continue
                if not text:
                    continue
                resp, close = self._handle_request(text)
                if not self._send(conn, resp) or close:
                    return
        finally:
            with self._conns_lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _send(self, conn: socket.socket, resp: str) -> bool:
        try:
            # replace, never raise: a non-ascii character smuggled into an
            # error message must not kill the connection handler
            conn.sendall(resp.encode("ascii", "replace") + b"\n")
            return True
        except OSError:
            return False

    # -- request lifecycle ---------------------------------------------------

    def _handle_request(self, text: str) -> tuple[str, bool]:
        """One request -> (response line, close-connection?)."""
        self.counters["requests"] += 1
        t0 = time.monotonic()
        try:
            req = parse_request(text)
        except BadRequest as exc:
            self.counters["errors"] += 1
            return err_line("badreq", str(exc)), False
        budget = req.deadline_s if req.deadline_s is not None \
            else self.config.deadline_s
        deadline = t0 + budget
        kind = req.kind
        self.counters["inserts" if kind == "insert" else "queries"] += 1
        try:
            with self.admission.admit(kind):
                # fault hooks run INSIDE admission: an injected hang/slow
                # occupies a slot exactly like a real slow client
                hang = max(0.0, min(deadline - time.monotonic() + 0.05,
                                    self.config.hang_cap_s))
                if serve_faults.fire("req", hang_s=hang):
                    self.counters["faults"] += 1
                if serve_faults.fire(kind, hang_s=hang):
                    self.counters["faults"] += 1
                if time.monotonic() > deadline:
                    self.counters["timeouts"] += 1
                    return err_line(
                        "timeout",
                        f"request exceeded its {budget:g}s deadline "
                        f"before dispatch"), False
                return self._dispatch(req, deadline)
        except BadRequest as exc:
            # argument-level parse errors surface from dispatch
            self.counters["errors"] += 1
            return err_line("badreq", str(exc)), False
        except AdmissionRefused as exc:
            if exc.code == "readonly":
                self.counters["readonly"] += 1
            else:
                self.counters["shed"] += 1
            return err_line(exc.code, str(exc)), False
        except ResourceError as exc:
            # WAL append / snapshot refused by the environment: typed,
            # nothing acknowledged, daemon keeps serving
            self.counters["errors"] += 1
            return err_line("unavailable", str(exc)), False
        except serve_faults.ServeKilled:
            raise
        except Exception as exc:  # the one place "internal" is honest
            self.counters["errors"] += 1
            print(f"serve: internal error on {text!r}: "
                  f"{type(exc).__name__}: {exc}", file=sys.stderr,
                  flush=True)
            return err_line("internal", f"{type(exc).__name__}: {exc}"), \
                False

    def _dispatch(self, req, deadline: float) -> tuple[str, bool]:
        core = self.core
        verb = req.verb
        if verb == "PING":
            return ok_line("pong"), False
        if verb == "QUIT":
            return ok_line("bye"), True
        if verb == "PART":
            vids = parse_vids(req.args)
            return ok_line(*[core.part(v) for v in vids]), False
        if verb == "PARENT":
            if len(req.args) != 1:
                raise BadRequest("PARENT wants exactly one vertex")
            (vid,) = parse_vids(req.args)
            p = core.parent_vid(vid)
            return ok_line("absent" if p is None else p), False
        if verb == "SUBTREE":
            if len(req.args) != 1:
                raise BadRequest("SUBTREE wants exactly one vertex")
            (vid,) = parse_vids(req.args)
            st = core.subtree(vid)
            if st is None:
                return err_line("notfound",
                                f"vertex {vid} is not in the sequence"), \
                    False
            return ok_kv(size=st[0], pst=st[1]), False
        if verb == "ECV":
            try:
                return ok_kv(**core.ecv()), False
            except RuntimeError as exc:
                return err_line("unavailable", str(exc)), False
        if verb == "STATS":
            rec = core.stats()
            rec.update(self.counters)
            rec["inflight"] = self.admission.inflight
            rec["uptime_s"] = round(time.time() - self.started_at, 3)
            rec["read_only"] = int(self.admission.read_only
                                   or core.governor.mem_pressure())
            return ok_kv(**rec), False
        if verb == "INSERT":
            vids = parse_vids(req.args, want_pairs=True)
            pairs = [(vids[i], vids[i + 1])
                     for i in range(0, len(vids), 2)]
            import numpy as np
            seqno = core.insert(np.asarray(pairs, dtype=np.uint32))
            if time.monotonic() > deadline:
                # the insert IS durable and applied; saying "timeout"
                # now would teach the client to retry a success.  Honest
                # answer: OK, late — the deadline bounded the wait for
                # admission+WAL, which it made.
                pass
            self._maybe_background_repartition()
            return ok_kv(seq=seqno, applied=len(pairs)), False
        if verb == "SNAPSHOT":
            path = core.seal_snapshot()
            return ok_kv(snap=os.path.basename(path)), False
        if verb == "REPARTITION":
            return ok_kv(**core.repartition()), False
        raise BadRequest(f"unhandled verb {verb!r}")  # unreachable

    def _maybe_background_repartition(self) -> None:
        """Kick the drift-triggered repartition exactly once at a time;
        queries serve the stale partition until the swap (state.py)."""
        if not self.core.drift_exceeded():
            return
        if not self._repartitioning.acquire(blocking=False):
            return  # one already running

        def work():
            try:
                self.core.repartition()
                self.config.events.append(("repartition",
                                           self.core.repartitions))
            finally:
                self._repartitioning.release()

        t = threading.Thread(target=work, daemon=True,
                             name="serve-repartition")
        t.start()
        self._threads.append(t)
