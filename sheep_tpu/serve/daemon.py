"""The long-lived partition service: sockets around a ServeCore.

Thread shapes (ISSUE 7 replaced the PR-6 thread-per-connection model):
ONE ``selectors``-based I/O loop owns every socket — accept, non-blocking
reads, buffered non-blocking writes — and hands complete request lines to
a bounded worker pool.  One slow client can no longer stall anything: a
reader that sends bytes slowly only delays its own lines, a client that
stops draining responses fills its own bounded output buffer and is
disconnected, and replication peers are just more registered sockets on
the same loop.  Each connection still serializes its OWN requests (the
batching unit is the line; responses never reorder), but connections are
fully independent.

Request lifecycle (the order is the contract)::

    io loop: read line -> queue on the connection
    worker:  parse -> admission slot -> fault hooks (serve/faults:
             req/query/insert sites) -> deadline check -> dispatch
    io loop: flush the response

Admission holds its slot across the fault hooks on purpose: an injected
``slow``/``hang`` occupies capacity exactly like a real slow client, so
the shedding paths are exercised by the same plan grammar that kills the
process.  The deadline check runs AFTER the hooks — a handler that lost
its budget answers ``ERR timeout``, it does not answer late.

Replication (serve/replicate.py) rides the same loop: a follower's
``REPL HELLO`` converts its connection into a push stream owned by the
:class:`ReplicationHub`; inbound ACK/NACK/FENCED lines route straight to
the hub without touching admission.  Roles: a ``leader`` accepts writes
(each insert waits for ``repl_acks`` follower acknowledgements before
its OK when the cluster is configured — an acknowledged insert is on at
least that many replicas, which is what makes failover lossless); a
``follower`` serves reads with a bounded-staleness guarantee and
redirects writes with ``ERR notleader <addr>``.  Failover transitions
(serve/cluster.py) are epoch-fenced: promotion seals the boundary
durably before the first write, and a fenced ex-leader demotes instead
of split-braining.

Every insert is durable (WAL fsync) before its ``OK`` leaves the process;
a kill -9 anywhere in the lifecycle loses at most inserts that were never
acknowledged — the restart contract tests/test_serve.py and the tier-1
smoke enforce, now cluster-wide (tests/test_replicate.py).

Multi-tenancy (ISSUE 11, serve/tenants.py): the daemon hosts N tenant
cores behind this one loop.  A connection's ``TENANT <name>`` selector
re-points its verbs; every tenant gets its own admission slots and (on
a clustered daemon) its own replication hub/stream, and the hot read
verbs answer as single numpy gathers over the selected tenant's
arrays.  Election is quorum-voted (``REPL VOTE``, :meth:`ServeDaemon.
grant_vote`): one grant per epoch per voter, majority of reachable
peers to promote.
"""

from __future__ import annotations

import json
import os
import selectors
import socket
import sys
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field

from ..obs.metrics import Registry
from ..resources.errors import ResourceError
from ..supervisor.heartbeat import HeartbeatWriter, maybe_start_from_env
from . import faults as serve_faults
from .admission import AdmissionController, AdmissionRefused
from .cluster import ClusterConfig, FailoverWatcher, find_leader
from ..obs import trace
from .protocol import (MAX_LINE, BadRequest, err_line, ok_kv, ok_line,
                       parse_kv_args, parse_request, parse_vids,
                       parse_vids_batch)
from .replicate import ReplicationHub, Replicator, payload_crc
from .scrub import ALLOW_CORRUPT_ENV
from .state import PARENT_ABSENT, PARENT_ROOT, ServeCore
from .tenants import DEFAULT_TENANT, Tenant, TenantManager, UnknownTenant

ADDR_FILE = "serve.addr"
HEARTBEAT_FILE = "serve.hb"
STATUS_FILE = "serve.status.json"

DEADLINE_ENV = "SHEEP_SERVE_DEADLINE_S"
MAX_INFLIGHT_ENV = "SHEEP_SERVE_MAX_INFLIGHT"
SNAP_EVERY_ENV = "SHEEP_SERVE_SNAP_EVERY"
DRIFT_ENV = "SHEEP_SERVE_DRIFT"
DRIFT_MIN_ENV = "SHEEP_SERVE_DRIFT_MIN"
#: the re-sequence family (ISSUE 18): master switch, sequence-drift
#: fraction, minimum inserts before the detector may fire, and the
#: degree-rank movement that counts an in-sequence insert as drifted
RESEQ_ENV = "SHEEP_RESEQ"
RESEQ_DRIFT_ENV = "SHEEP_RESEQ_DRIFT"
RESEQ_DRIFT_MIN_ENV = "SHEEP_RESEQ_DRIFT_MIN"
RESEQ_RANK_ENV = "SHEEP_RESEQ_RANK"
#: the leader group-commit window (ISSUE 19): the shared fsync is cut
#: when the group reaches MAX records or DELAY_S elapses with company; a
#: lone insert never waits (idle latency unchanged).  DELAY_S=0 keeps
#: pure piggybacking: whatever appended during the previous fsync forms
#: the next group.
GROUP_COMMIT_MAX_ENV = "SHEEP_SERVE_GROUP_COMMIT_MAX"
GROUP_COMMIT_DELAY_ENV = "SHEEP_SERVE_GROUP_COMMIT_DELAY_S"

#: a connection whose un-flushed responses exceed this is a slow
#: consumer and is closed (replication peers get snapshot-sized room)
OUTBUF_CAP = 8 << 20
#: per-connection queued-line backpressure: past this many undrained
#: requests the loop stops READING that connection until it catches up
PENDING_CAP = 256


@dataclass
class ServeConfig:
    host: str = "127.0.0.1"
    port: int = 0            # 0: ephemeral, discover via serve.addr
    deadline_s: float = 30.0
    max_inflight: int = 64
    snap_every: int = 256
    drift_frac: float = 0.1
    drift_min_cut: int = 64
    #: re-sequencing (ISSUE 18): the detector thresholds travel to the
    #: core (cli/serve.py's core_kw); ``reseq`` gates the background job
    reseq: bool = True
    reseq_frac: float = 0.25
    reseq_min: int = 256
    reseq_rank: int = 8
    #: leader group commit (ISSUE 19): records per shared fsync cap and
    #: the adaptive window a non-lone leader may stretch to fill it
    group_commit_max: int = 256
    group_commit_delay_s: float = 0.002
    #: anti-entropy (ISSUE 20): background artifact-scrub period in
    #: seconds (0 = off; the SCRUB verb still runs one inline)
    scrub_interval_s: float = 0.0
    read_only: bool = False
    #: ceiling on how long an injected hang may stall a handler
    hang_cap_s: float = 2.0
    events: list = field(default_factory=list)

    @classmethod
    def from_env(cls, **overrides) -> "ServeConfig":
        kw: dict = {}
        if os.environ.get(DEADLINE_ENV):
            kw["deadline_s"] = float(os.environ[DEADLINE_ENV])
        if os.environ.get(MAX_INFLIGHT_ENV):
            kw["max_inflight"] = int(os.environ[MAX_INFLIGHT_ENV])
        if os.environ.get(SNAP_EVERY_ENV):
            kw["snap_every"] = int(os.environ[SNAP_EVERY_ENV])
        if os.environ.get(DRIFT_ENV):
            kw["drift_frac"] = float(os.environ[DRIFT_ENV])
        if os.environ.get(DRIFT_MIN_ENV):
            kw["drift_min_cut"] = int(os.environ[DRIFT_MIN_ENV])
        if os.environ.get(RESEQ_ENV):
            kw["reseq"] = os.environ[RESEQ_ENV] not in ("0", "no", "off")
        if os.environ.get(RESEQ_DRIFT_ENV):
            kw["reseq_frac"] = float(os.environ[RESEQ_DRIFT_ENV])
        if os.environ.get(RESEQ_DRIFT_MIN_ENV):
            kw["reseq_min"] = int(os.environ[RESEQ_DRIFT_MIN_ENV])
        if os.environ.get(RESEQ_RANK_ENV):
            kw["reseq_rank"] = int(os.environ[RESEQ_RANK_ENV])
        if os.environ.get(GROUP_COMMIT_MAX_ENV):
            kw["group_commit_max"] = int(os.environ[GROUP_COMMIT_MAX_ENV])
        if os.environ.get(GROUP_COMMIT_DELAY_ENV):
            kw["group_commit_delay_s"] = float(
                os.environ[GROUP_COMMIT_DELAY_ENV])
        from .scrub import scrub_interval_s
        kw["scrub_interval_s"] = scrub_interval_s()
        kw.update(overrides)
        return cls(**kw)


class _Conn:
    """One client on the I/O loop.  All mutable fields are guarded by
    the daemon's ``_io_lock`` except ``inbuf``, which only the loop
    thread touches."""

    __slots__ = ("sock", "inbuf", "outbuf", "pending", "busy", "repl",
                 "paused", "close_after_flush", "abort", "closed",
                 "outbuf_cap", "tenant", "hub")

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.inbuf = bytearray()
        self.outbuf = bytearray()
        self.pending: deque = deque()
        self.busy = False          # a worker owns this conn's queue
        self.repl = False          # converted to a replication stream
        self.paused = False        # read interest dropped (backpressure)
        self.close_after_flush = False
        self.abort = False         # close NOW, drop unflushed bytes
        self.closed = False
        self.outbuf_cap = OUTBUF_CAP
        self.tenant = DEFAULT_TENANT  # connection-scoped TENANT selector
        self.hub = None            # the hub owning a repl stream conn


class ServeDaemon:
    """Selectors loop + worker pool + admission + deadlines + fault
    hooks + replication roles around one core."""

    def __init__(self, core: ServeCore, config: ServeConfig | None = None,
                 cluster: ClusterConfig | None = None,
                 tenants: TenantManager | None = None):
        self.core = core
        self.config = config or ServeConfig.from_env()
        self.cluster = cluster or ClusterConfig.from_env()
        self.role = self.cluster.role
        self.node_id = self.cluster.node_id  # finalized at bind
        # the tenant table (ISSUE 11): the default tenant IS this core;
        # a bare ServeDaemon(core) hosts exactly one tenant and speaks
        # the PR-7 grammar byte for byte
        self.tenants = tenants if tenants is not None \
            else TenantManager(core)
        self.admission = AdmissionController(
            max_inflight=self.config.max_inflight,
            governor=core.governor,
            read_only=self.config.read_only)
        # per-tenant admission: each tenant gets its own slot pool so a
        # hot tenant's burst sheds ITS load, not its neighbors'
        for name in self.tenants.names():
            t = self.tenants.get(name)
            if name == DEFAULT_TENANT:
                t.admission = self.admission
            elif t.admission is None:
                t.admission = AdmissionController(
                    max_inflight=self.config.max_inflight,
                    governor=core.governor,
                    read_only=self.config.read_only)
        self._listener: socket.socket | None = None
        self._sel: selectors.DefaultSelector | None = None
        self._wake_r: socket.socket | None = None
        self._wake_w: socket.socket | None = None
        self._io_thread: threading.Thread | None = None
        self._pool: ThreadPoolExecutor | None = None
        self._stop = threading.Event()
        self._io_lock = threading.Lock()
        self._conns: dict[int, _Conn] = {}
        self._dirty: set[int] = set()
        self._hb: HeartbeatWriter | None = None
        self._env_hb = None
        self._repartitioning = threading.Lock()
        self._resequencing = threading.Lock()
        self._role_lock = threading.RLock()
        self.started_at = time.monotonic()
        self._status_written = 0.0
        self.counters = {"requests": 0, "queries": 0, "inserts": 0,
                         "shed": 0, "timeouts": 0, "readonly": 0,
                         "errors": 0, "faults": 0, "notleader": 0,
                         "stale": 0, "repl_quorum_fails": 0, "moved": 0,
                         "diverged_reads": 0}
        # anti-entropy accounting (ISSUE 20): daemon-lifetime scrub
        # totals, exported via STATS + the sheep_scrub_* gauges
        self._scrubbing = threading.Lock()
        self._last_scrub = time.monotonic()
        self.scrub_totals = {"runs": 0, "checked": 0, "failed": 0,
                             "quarantined": 0, "repaired": 0,
                             "unrepaired": 0}
        # flight-recorder metrics (ISSUE 10): per-daemon registry so
        # in-process test clusters never share counters; exported raw
        # over the METRICS verb and summarized into STATS (per-verb
        # counts + p50/p99 come from THIS registry, one code path)
        self.metrics = Registry()
        self._m_requests = self.metrics.counter(
            "sheep_serve_requests_total", "requests by verb")
        self._m_latency = self.metrics.histogram(
            "sheep_serve_request_seconds", "request latency by verb")
        self._m_errors = self.metrics.counter(
            "sheep_serve_errors_total", "typed ERR responses by code")
        # per-tenant request accounting rides its OWN series so the
        # PR-10 unlabeled series (and everything scraping it) is
        # untouched by multi-tenancy
        self._m_tenant_requests = self.metrics.counter(
            "sheep_serve_tenant_requests_total",
            "requests by tenant and verb")
        # per-tenant latency (ISSUE 12): `sheep top` renders a current
        # per-tenant p99 from this histogram's sliding window; the
        # per-verb series above stays the lifetime view scrapers built on
        self._m_tenant_latency = self.metrics.histogram(
            "sheep_serve_tenant_request_seconds",
            "request latency by tenant")
        self.hub = ReplicationHub(core, send=self._send_async,
                                  close=self._abort_async,
                                  hb_s=self.cluster.hb_s,
                                  on_fenced=self._on_fenced)
        self.tenants.get(DEFAULT_TENANT).hub = self.hub
        self.watcher: FailoverWatcher | None = None
        #: quorum-vote state (ISSUE 11): the newest (epoch, candidate)
        #: this node granted — one vote per epoch is what makes two
        #: same-epoch leaders impossible (serve/cluster.py)
        self._vote: tuple[int, str] | None = None
        self.votes_granted = 0
        self.votes_refused = 0

    # -- lifecycle ---------------------------------------------------------

    @property
    def address(self) -> tuple[str, int]:
        assert self._listener is not None, "daemon not started"
        return self._listener.getsockname()[:2]

    def start(self) -> "ServeDaemon":
        """Bind, publish the address, start beating, spawn the I/O loop
        and worker pool, join the cluster.  Returns self so tests can
        ``daemon = ServeDaemon(...).start()``."""
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((self.config.host, self.config.port))
        self._listener.listen(128)
        self._listener.setblocking(False)
        host, port = self.address
        if not self.node_id:
            self.node_id = f"{host}:{port}"
        # address discovery for scripts: plain tiny file, rewritten on
        # every start (ephemeral ports move across restarts)
        with open(os.path.join(self.core.state_dir, ADDR_FILE), "w") as f:
            f.write(f"{host} {port}\n")
        self._hb = HeartbeatWriter(
            os.path.join(self.core.state_dir, HEARTBEAT_FILE)).start()
        self._env_hb = maybe_start_from_env()  # supervisor-launched case

        self._sel = selectors.DefaultSelector()
        self._sel.register(self._listener, selectors.EVENT_READ, "accept")
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._wake_w.setblocking(False)
        self._sel.register(self._wake_r, selectors.EVENT_READ, "wakeup")
        # spare threads past the slot budget so a request that will be
        # REFUSED by admission always finds a thread to refuse it on —
        # that is what keeps "ERR overload" prompt while hang-faulted
        # requests squat on their slots
        self._pool = ThreadPoolExecutor(
            max_workers=self.config.max_inflight + 8,
            thread_name_prefix="serve-worker")
        self._io_thread = threading.Thread(target=self._io_loop,
                                           daemon=True, name="serve-io")
        self._io_thread.start()

        # every hosted tenant opens (or first-touch bootstraps) before
        # the cluster join: followers HELLO per tenant immediately, and
        # a leader must be able to answer those HELLOs
        self.tenants.open_all()

        if self.cluster.clustered:
            if self.role == "leader":
                # a returning ex-leader must discover its fencing BEFORE
                # accepting a single write (split-brain rejoin guard)
                other = find_leader(self.cluster.peers,
                                    self.cluster.poll_timeout_s,
                                    min_epoch=self.core.epoch + 1)
                if other is not None:
                    self.role = "follower"
                    self.config.events.append(
                        ("fenced_at_start",
                         int(other[1].get("epoch", 0))))
            if self.role == "follower":
                self._start_replicators()
            self.watcher = FailoverWatcher(self, self.cluster).start()
        # a kill -9 mid-quarantine left a durable marker (ISSUE 20):
        # restart into the quarantine — reads stay refused, and the
        # follower stream heals off the marker's recorded phase
        self._sweep_quarantine()
        # a kill -9 mid-re-sequence left a durable manifest: resume (or
        # cleanly abort) it now, in the background (ISSUE 18)
        self._resume_pending_reseqs()
        self._write_status(force=True)
        return self

    def run_forever(self) -> None:
        """Block until :meth:`shutdown` (the CLI foreground mode)."""
        while not self._stop.wait(0.5):
            pass

    def shutdown(self) -> None:
        self._stop.set()
        self._wake()
        if self.watcher is not None:
            self.watcher.stop()
        for t in self._tenant_entries():
            if t.replicator is not None:
                t.replicator.stop()
                t.replicator = None
            if t.mig is not None:
                rep = t.mig.get("replicator")
                if rep is not None:
                    rep.stop()
                t.mig = None
            if t.hub is not None:
                t.hub.stop()
        if self._io_thread is not None:
            self._io_thread.join(timeout=5.0)
        if self._pool is not None:
            self._pool.shutdown(wait=False)
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        if self._hb is not None:
            self._hb.stop()
        if self._env_hb is not None:
            self._env_hb.stop()
        self._write_status(force=True)
        self.tenants.close_all()

    # -- cluster role transitions ------------------------------------------

    def _tenant_entries(self) -> list[Tenant]:
        return [self.tenants.get(n) for n in self.tenants.names()]

    @property
    def replicator(self) -> Replicator | None:
        """The DEFAULT tenant's replication stream — the one the
        cluster's liveness/staleness machinery keys on (named tenants
        ride their own streams to the same leader)."""
        return self.tenants.get(DEFAULT_TENANT).replicator

    def _hub_for(self, t: Tenant) -> ReplicationHub:
        """The tenant's leader-side hub, rebuilt if an evict/restore
        cycle replaced the core object underneath it (only possible
        with zero attached followers — tenants.Tenant.evictable)."""
        core = self.tenants.core_of(t.name)
        hub = t.hub
        if hub is None or hub.core is not core:
            if hub is not None:
                hub.stop()
            hub = ReplicationHub(core, send=self._send_async,
                                 close=self._abort_async,
                                 hb_s=self.cluster.hb_s,
                                 on_fenced=self._on_fenced)
            t.hub = hub
            if t.name == DEFAULT_TENANT:
                self.hub = hub
        return hub

    def _start_replicators(self) -> None:
        """One follower stream per hosted tenant, all discovering the
        same leader (the cluster is one unit; tenants are state dirs)."""
        for t in self._tenant_entries():
            if t.replicator is not None or t.mig is not None:
                continue
            try:
                core = self.tenants.core_of(t.name)
            except FileNotFoundError:
                # adopted-but-empty (kill -9 before the migration's
                # snapshot landed): the resumed migration re-bootstraps
                continue
            t.replicator = Replicator(
                core, self.node_id,
                self._discover_leader, hb_s=self.cluster.hb_s,
                events=self.config.events, tenant=t.name).start()

    def _discover_leader(self) -> tuple[str, int] | None:
        """Replication discovery: the reachable peer that is leader at
        our epoch or later (a stale-epoch claimant is ignored)."""
        found = find_leader(self.cluster.peers,
                            self.cluster.poll_timeout_s,
                            min_epoch=self.core.epoch)
        if found is None:
            return None
        host, _, port = found[0].rpartition(":")
        return host, int(port)

    def leader_addr(self) -> str:
        """Where writes should go, as ``host:port`` (``-`` unknown)."""
        if self.role == "leader":
            h, p = self.address
            return f"{h}:{p}"
        rep = self.replicator
        if rep is not None and rep.connected_to is not None:
            return f"{rep.connected_to[0]}:{rep.connected_to[1]}"
        return "-"

    def promote(self, new_epoch: int) -> None:
        """Epoch-fenced promotion (the election winner's side): stop
        following, seal the boundary DURABLY — on EVERY hosted tenant,
        evicted ones restored first, so the whole daemon changes term as
        one unit — only then start taking writes.  A failed seal on the
        default tenant leaves this node a follower."""
        with self._role_lock:
            if self.role == "leader" or self._stop.is_set():
                return
            for t in self._tenant_entries():
                if t.replicator is not None:
                    t.replicator.stop()
                    t.replicator = None
            # the default tenant's seal is the promotion gate; named
            # tenants follow (their cores adopt the same epoch — a
            # failed named seal is retried by the applier's epoch fence
            # when that tenant next streams)
            self.core.advance_epoch(new_epoch)
            for t in self._tenant_entries():
                if t.name == DEFAULT_TENANT or t.mig is not None:
                    continue
                try:
                    core = self.tenants.core_of(t.name)
                except FileNotFoundError:
                    continue  # adopted-but-empty (mid-migration adopt)
                if core.epoch < new_epoch:
                    try:
                        core.advance_epoch(new_epoch)
                    except OSError as exc:
                        self.config.events.append(
                            ("tenant_epoch_seal_failed",
                             f"{t.name}: {exc}"))
            self.role = "leader"
            self.config.events.append(("promote", new_epoch))
            self._write_status(force=True)

    def demote(self, leader_addr: str | None, fenced_by: int) -> None:
        """The fence: a later epoch exists, this node's term is over.
        Drop the follower streams (they must rediscover the real
        leader) and rejoin as a follower; any divergent unacknowledged
        tail is rolled back by snapshot re-sync on reconnect."""
        with self._role_lock:
            if self.role == "follower" or self._stop.is_set():
                return
            self.role = "follower"
            for t in self._tenant_entries():
                if t.hub is not None:
                    t.hub.disconnect_all()
            self.config.events.append(("demote", fenced_by))
            self._start_replicators()
            self._write_status(force=True)

    def _on_fenced(self, fenced_by: int) -> None:
        """Hub callback: a follower answered REPL FENCED — a later
        epoch exists even if no peer poll has seen it yet."""
        self.demote(None, fenced_by)

    def grant_vote(self, epoch: int, candidate: str, seqno: int) -> bool:
        """The voter's half of the quorum-vote election (ISSUE 11,
        closing the PR-7 symmetric-partition hole): grant at most ONE
        candidate per epoch, and only when this node has itself lost
        its leader — so two candidates that share any voter can never
        both promote into the same epoch.  Refusals:

          - I am a live leader (the candidate should fence on me), or
            the proposed epoch does not advance mine;
          - my replication stream is FRESH (a leader is alive from
            where I stand; the candidate is partitioned, not bereaved);
          - the candidate has not applied everything I have (electing
            it would lose acknowledged inserts);
          - I already voted for a different candidate at this or a
            later epoch.
        """
        with self._role_lock:
            ok = True
            if self.role == "leader" or epoch <= self.core.epoch:
                ok = False
            elif seqno < self.core.applied_seqno:
                ok = False
            else:
                rep = self.replicator
                age = rep.stream_age_s() if rep is not None else None
                if age is not None and age <= self.cluster.failover_s:
                    ok = False
                elif self._vote is not None:
                    ve, vc = self._vote
                    if ve > epoch or (ve == epoch and vc != candidate):
                        ok = False
            if ok:
                self._vote = (epoch, candidate)
                self.votes_granted += 1
                self.config.events.append(
                    ("vote_granted", epoch, candidate))
            else:
                self.votes_refused += 1
            return ok

    # -- the I/O loop ------------------------------------------------------

    def _wake(self) -> None:
        try:
            self._wake_w.send(b"x")
        except (OSError, AttributeError):
            pass

    def _send_async(self, conn: _Conn, data: bytes) -> bool:
        """Queue bytes for one connection (any thread).  False = the
        connection is gone or over its buffer cap (slow consumer)."""
        with self._io_lock:
            if conn.closed or conn.abort:
                return False
            if len(conn.outbuf) + len(data) > conn.outbuf_cap:
                conn.abort = True  # slow consumer: cut it loose
                self._dirty.add(id(conn))
                self._wake()
                return False
            conn.outbuf.extend(data)
            self._dirty.add(id(conn))
        self._wake()
        return True

    def _abort_async(self, conn: _Conn) -> None:
        with self._io_lock:
            conn.abort = True
            self._dirty.add(id(conn))
        self._wake()

    def _io_loop(self) -> None:
        sel = self._sel
        while not self._stop.is_set():
            try:
                events = sel.select(0.2)
            except OSError:
                break
            for key, mask in events:
                if key.data == "accept":
                    self._accept()
                elif key.data == "wakeup":
                    try:
                        while self._wake_r.recv(4096):
                            pass
                    except OSError:
                        pass
                else:
                    conn: _Conn = key.data
                    if mask & selectors.EVENT_READ:
                        self._on_readable(conn)
                    if mask & selectors.EVENT_WRITE and not conn.closed:
                        self._on_writable(conn)
            self._apply_dirty()
            self._write_status()
            self._maybe_background_scrub()
        # shutdown: close everything the loop owns
        for conn in list(self._conns.values()):
            self._close_conn(conn)
        try:
            sel.close()
        except OSError:
            pass
        for s in (self._wake_r, self._wake_w):
            try:
                s.close()
            except OSError:
                pass

    def _accept(self) -> None:
        while True:
            try:
                sock, _ = self._listener.accept()
            except (BlockingIOError, socket.timeout):
                return
            except OSError:
                return
            sock.setblocking(False)
            conn = _Conn(sock)
            self._conns[id(conn)] = conn
            self._sel.register(sock, selectors.EVENT_READ, conn)

    def _interest(self, conn: _Conn) -> int:
        ev = 0
        if not conn.paused and not conn.close_after_flush:
            ev |= selectors.EVENT_READ
        if conn.outbuf:
            ev |= selectors.EVENT_WRITE
        return ev

    def _apply_dirty(self) -> None:
        """Fold worker-thread state changes (queued bytes, aborts,
        pauses) into selector interests — only the loop thread touches
        the selector."""
        with self._io_lock:
            dirty = [self._conns.get(cid) for cid in self._dirty]
            self._dirty.clear()
        for conn in dirty:
            if conn is None or conn.closed:
                continue
            if conn.abort:
                self._close_conn(conn)
                continue
            ev = self._interest(conn)
            try:
                if ev:
                    self._sel.modify(conn.sock, ev, conn)
                else:
                    self._sel.unregister(conn.sock)
            except (KeyError, ValueError, OSError):
                pass

    def _close_conn(self, conn: _Conn) -> None:
        with self._io_lock:
            if conn.closed:
                return
            conn.closed = True
            self._conns.pop(id(conn), None)
        if conn.repl:
            (conn.hub or self.hub).detach(conn)
        try:
            self._sel.unregister(conn.sock)
        except (KeyError, ValueError, OSError):
            pass
        try:
            conn.sock.close()
        except OSError:
            pass

    def _on_readable(self, conn: _Conn) -> None:
        try:
            data = conn.sock.recv(1 << 16)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._close_conn(conn)
            return
        if not data:
            # client went away; flush what it is owed, then close
            if conn.outbuf:
                conn.close_after_flush = True
                self._update_interest(conn)
            else:
                self._close_conn(conn)
            return
        conn.inbuf.extend(data)
        if len(conn.inbuf) > MAX_LINE and b"\n" not in conn.inbuf:
            self._send_async(conn, (err_line(
                "badreq", f"request line exceeds {MAX_LINE} bytes")
                + "\n").encode("ascii"))
            conn.close_after_flush = True
            conn.inbuf.clear()
            self._update_interest(conn)
            return
        submit = False
        while True:
            nl = conn.inbuf.find(b"\n")
            if nl < 0:
                break
            raw = bytes(conn.inbuf[:nl])
            del conn.inbuf[: nl + 1]
            if len(raw) > MAX_LINE:
                self._send_async(conn, (err_line(
                    "badreq", f"request line exceeds {MAX_LINE} bytes")
                    + "\n").encode("ascii"))
                conn.close_after_flush = True
                break
            if conn.repl:
                # stream connection: ACK/NACK/FENCED go straight to the
                # hub that owns this stream (one hub per tenant) —
                # never through admission, never to the pool
                try:
                    (conn.hub or self.hub).on_line(
                        conn, raw.decode("ascii").strip())
                except UnicodeDecodeError:
                    pass
                continue
            with self._io_lock:
                conn.pending.append(raw)
                if not conn.busy:
                    conn.busy = True
                    submit = True
                if len(conn.pending) > PENDING_CAP:
                    conn.paused = True  # backpressure: stop reading
        self._update_interest(conn)
        if submit:
            self._pool.submit(self._drain, conn)

    def _update_interest(self, conn: _Conn) -> None:
        if conn.closed:
            return
        ev = self._interest(conn)
        try:
            if ev:
                self._sel.modify(conn.sock, ev, conn)
            else:
                self._sel.unregister(conn.sock)
        except (KeyError, ValueError, OSError):
            pass

    def _on_writable(self, conn: _Conn) -> None:
        with self._io_lock:
            buf = bytes(conn.outbuf)
        if buf:
            try:
                sent = conn.sock.send(buf)
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                self._close_conn(conn)
                return
            with self._io_lock:
                del conn.outbuf[:sent]
                drained = not conn.outbuf
        else:
            drained = True
        if drained and conn.close_after_flush:
            self._close_conn(conn)
        else:
            self._update_interest(conn)

    # -- worker side -------------------------------------------------------

    def _drain(self, conn: _Conn) -> None:
        """Serialize one connection's queued lines (a pool worker owns
        the queue until it runs dry — responses never reorder within a
        connection, and other connections drain on other workers)."""
        while True:
            with self._io_lock:
                if conn.closed or conn.abort or not conn.pending:
                    conn.busy = False
                    return
                raw = conn.pending.popleft()
                if conn.paused and len(conn.pending) <= PENDING_CAP // 2:
                    conn.paused = False
                    self._dirty.add(id(conn))
                    self._wake()
            try:
                text = raw.decode("ascii").strip()
            except UnicodeDecodeError:
                self._send_async(conn, (err_line(
                    "badreq", "non-ascii request line") + "\n")
                    .encode("ascii"))
                continue
            if not text:
                continue
            if text[:5].upper() in ("REPL ", "REPL"):
                if self._handle_repl(conn, text):
                    # the connection now belongs to the hub
                    with self._io_lock:
                        conn.busy = False
                    return
                continue
            resp, close = self._handle_request(text, conn)
            alive = self._send_async(conn, (resp + "\n").encode("ascii"))
            if close:
                with self._io_lock:
                    conn.close_after_flush = True
                    self._dirty.add(id(conn))
                self._wake()
            if not alive or close:
                with self._io_lock:
                    conn.busy = False
                return

    # -- replication handshakes --------------------------------------------

    def _handle_repl(self, conn: _Conn, text: str) -> bool:
        """One ``REPL ...`` line on a line-mode connection.  Returns
        True when the connection was converted to a stream (the caller
        stops draining it)."""
        toks = text.split()
        sub = toks[1].upper() if len(toks) > 1 else ""
        try:
            if sub == "HELLO":
                return self._repl_hello(conn, toks[2:])
            if sub == "SNAPSHOT":
                self._repl_snapshot(conn, toks[2:])
                return False
            if sub == "VOTE":
                self._repl_vote(conn, toks[2:])
                return False
            self._send_async(conn, (err_line(
                "badrepl", f"unknown replication request {sub!r}")
                + "\n").encode("ascii"))
        except (BadRequest, ValueError) as exc:
            self._send_async(conn, (err_line("badrepl", str(exc)) + "\n")
                             .encode("ascii"))
        except ResourceError as exc:
            self._send_async(conn, (err_line("unavailable", str(exc))
                                    + "\n").encode("ascii"))
        return False

    def _repl_hello(self, conn: _Conn, args: list[str]) -> bool:
        kv = parse_kv_args(args)
        node = kv.get("node", "?")
        epoch = int(kv.get("epoch", 0))
        seqno = int(kv.get("seqno", 0))
        sig = kv.get("sig", "-")
        tname = kv.get("tenant", DEFAULT_TENANT)
        if self.role != "leader":
            self.counters["notleader"] += 1
            self._send_async(conn, (err_line(
                "notleader", self.leader_addr()) + "\n").encode("ascii"))
            return False
        try:
            tenant = self.tenants.get(tname)
        except UnknownTenant as exc:
            self._send_async(conn, (err_line("badrepl", exc.message)
                                    + "\n").encode("ascii"))
            return False
        hub = self._hub_for(tenant)
        core = hub.core
        reseq_behind = False
        if sig != "-" and sig != core.sig:
            # a sig the reseq manifest chains is a follower one or more
            # sequence generations BEHIND us (ISSUE 18) — it adopts our
            # snapshot; a sig the chain has never seen is a foreign
            # build input and is refused exactly as before
            from .reseq import chain_has_sig
            if core.state_dir and chain_has_sig(core.state_dir, sig):
                reseq_behind = True
            else:
                self._send_async(conn, (err_line(
                    "badrepl", f"replica belongs to a different build "
                    f"input (sig {sig[:12]}..., ours {core.sig[:12]}...)")
                    + "\n").encode("ascii"))
                return False
        if epoch > core.epoch:
            # the caller lives in a later term than we do: we are the
            # stale one.  Refuse typed and let the fence check demote us.
            self._send_async(conn, (err_line(
                "fenced", f"epoch={core.epoch}") + "\n").encode("ascii"))
            self.config.events.append(("fence_hint", epoch))
            return False
        # stream iff the replica's position is inside our retention
        # window AND (same epoch, or at/before the promotion boundary —
        # past it an old-epoch replica may carry a divergent tail)
        can_stream = (not reseq_behind
                      and core.records_from(seqno) is not None
                      and seqno <= core.applied_seqno
                      and (epoch == core.epoch
                           or seqno <= core.epoch_base))
        if can_stream:
            self._send_async(conn, (ok_kv(
                mode="stream", epoch=core.epoch,
                seqno=core.applied_seqno) + "\n").encode("ascii"))
            from_seqno = seqno
        else:
            blob, snap_seqno, snap_epoch = core.snapshot_bytes()
            with self._io_lock:
                conn.outbuf_cap = max(conn.outbuf_cap,
                                      len(blob) + OUTBUF_CAP)
            header = ok_kv(mode="snapshot", bytes=len(blob),
                           seqno=snap_seqno, epoch=snap_epoch,
                           crc=payload_crc(blob)) + "\n"
            if not self._send_async(conn,
                                    header.encode("ascii") + blob):
                return False
            from_seqno = snap_seqno
        with self._io_lock:
            conn.repl = True
            conn.hub = hub
            # re-queue any lines the client pipelined behind HELLO so
            # the hub sees them (normally none)
            leftover = list(conn.pending)
            conn.pending.clear()
        for raw in leftover:
            try:
                hub.on_line(conn, raw.decode("ascii").strip())
            except UnicodeDecodeError:
                pass
        # a migration delta stream (ISSUE 17) files its APPENDs under
        # the mdelta netfault site so the migration wire sweeps
        # independently of ordinary replication
        site = "mdelta" if kv.get("mig") else "repl"
        # anti-entropy capability (ISSUE 20): only a follower that said
        # verify=1 gets VERIFY frames — an old follower's parser never
        # sees a kind it cannot name, and the leader only pays the
        # state_crc capture when at least one verifying follower exists
        verify = bool(kv.get("verify")) and not kv.get("mig")
        if verify:
            from .scrub import verify_cadence
            core.enable_verify(verify_cadence())
        hub.attach(conn, node, from_seqno, site=site, verify=verify)
        self.config.events.append(("repl_attach", f"{node}:{tname}"
                                   if tname != DEFAULT_TENANT else node))
        return True

    def _repl_snapshot(self, conn: _Conn, args: list[str]) -> None:
        """Bootstrap fetch: one snapshot blob, connection stays
        line-mode (the follower opens its stream separately)."""
        kv = parse_kv_args(args)
        tname = kv.get("tenant", DEFAULT_TENANT)
        try:
            core = self.tenants.core_of(tname)
        except UnknownTenant as exc:
            self._send_async(conn, (err_line("badrepl", exc.message)
                                    + "\n").encode("ascii"))
            return
        blob, seqno, epoch = core.snapshot_bytes()
        with self._io_lock:
            conn.outbuf_cap = max(conn.outbuf_cap, len(blob) + OUTBUF_CAP)
        header = ok_kv(bytes=len(blob), seqno=seqno, epoch=epoch,
                       crc=payload_crc(blob), sig=core.sig) + "\n"
        self._send_async(conn, header.encode("ascii") + blob)

    def _repl_vote(self, conn: _Conn, args: list[str]) -> None:
        """``REPL VOTE epoch=E candidate=C seqno=S`` — the election
        quorum's ballot (serve/cluster.py requests these before a
        candidate may promote).  Line-mode, never converts the
        connection."""
        kv = parse_kv_args(args)
        try:
            epoch = int(kv["epoch"])
            seqno = int(kv["seqno"])
            candidate = kv["candidate"]
        except (KeyError, ValueError):
            raise BadRequest(
                "VOTE wants epoch=<int> candidate=<id> seqno=<int>")
        granted = self.grant_vote(epoch, candidate, seqno)
        self._send_async(conn, (ok_kv(
            grant=int(granted), epoch=self.core.epoch,
            node=self.node_id) + "\n").encode("ascii"))

    # -- request lifecycle -------------------------------------------------

    def _handle_request(self, text: str,
                        conn: _Conn | None = None) -> tuple[str, bool]:
        """One request -> (response, close?), with the registry fed:
        per-verb request counter + latency histogram (observed whatever
        the outcome — a shed or timed-out request is latency a client
        saw), ERR counter by code, and the per-tenant series.  A
        sampled ``serve.req`` span (SHEEP_TRACE_SAMPLE, obs/trace.py)
        wraps the whole thing so traces exist under load inside the
        <2% overhead budget; the request's RID= token (ISSUE 12) scopes
        the whole handling, so the span AND every downstream span it
        opens (WAL fsync, snapshot seal) carry the rid — including when
        the sampler skips the serve.req span itself."""
        t0 = time.monotonic()
        tname = conn.tenant if conn is not None else DEFAULT_TENANT
        try:
            req = parse_request(text)
        except BadRequest as exc:
            self.counters["requests"] += 1
            self.counters["errors"] += 1
            resp, close = err_line("badreq", str(exc)), False
            verb = "BAD"  # unparseable lines don't mint verb series
        else:
            verb = req.verb
            with trace.rid_scope(req.rid):
                with trace.sampled_span("serve.req") as sp:
                    resp, close = self._handle_one(req, conn, t0)
                    if resp.startswith("ERR badreq"):
                        verb = "BAD"  # bad requests don't mint series
                    sp.annotate(verb=verb, tenant=tname,
                                ok=resp[:2] == "OK")
        self._m_requests.labels(verb=verb).inc()
        dur = time.monotonic() - t0
        self._m_latency.labels(verb=verb).observe(dur)
        self._m_tenant_requests.labels(tenant=tname, verb=verb).inc()
        self._m_tenant_latency.labels(tenant=tname).observe(dur)
        if resp.startswith("ERR "):
            code = resp.split(None, 2)[1]
            self._m_errors.labels(code=code).inc()
        return resp, close

    def _handle_one(self, req, conn: _Conn | None = None,
                    t0: float | None = None) -> tuple[str, bool]:
        """One parsed request -> (response line, close-connection?)."""
        self.counters["requests"] += 1
        if t0 is None:
            t0 = time.monotonic()
        budget = req.deadline_s if req.deadline_s is not None \
            else self.config.deadline_s
        deadline = t0 + budget
        kind = req.kind
        self.counters["inserts" if kind == "insert" else "queries"] += 1
        if req.verb == "TENANT":
            # the connection-scoped selector: touches no tenant state,
            # so it never holds (or is refused) an admission slot
            return self._handle_tenant(req, conn)
        try:
            tenant = self.tenants.get(
                conn.tenant if conn is not None else DEFAULT_TENANT)
        except UnknownTenant as exc:
            self.counters["errors"] += 1
            return err_line("notfound", exc.message), False
        try:
            with (tenant.admission or self.admission).admit(kind):
                # fault hooks run INSIDE admission: an injected hang/slow
                # occupies a slot exactly like a real slow client
                hang = max(0.0, min(deadline - time.monotonic() + 0.05,
                                    self.config.hang_cap_s))
                if serve_faults.fire("req", hang_s=hang):
                    self.counters["faults"] += 1
                if serve_faults.fire(kind, hang_s=hang):
                    self.counters["faults"] += 1
                if time.monotonic() > deadline:
                    self.counters["timeouts"] += 1
                    return err_line(
                        "timeout",
                        f"request exceeded its {budget:g}s deadline "
                        f"before dispatch"), False
                return self._dispatch(req, deadline, tenant)
        except BadRequest as exc:
            # argument-level parse errors surface from dispatch
            self.counters["errors"] += 1
            return err_line("badreq", str(exc)), False
        except AdmissionRefused as exc:
            if exc.code == "readonly":
                self.counters["readonly"] += 1
            else:
                self.counters["shed"] += 1
            return err_line(exc.code, str(exc)), False
        except ResourceError as exc:
            # WAL append / snapshot refused by the environment: typed,
            # nothing acknowledged, daemon keeps serving
            self.counters["errors"] += 1
            return err_line("unavailable", str(exc)), False
        except serve_faults.ServeKilled:
            raise
        except Exception as exc:  # the one place "internal" is honest
            self.counters["errors"] += 1
            print(f"serve: internal error on {req.verb} "
                  f"{' '.join(req.args[:8])!r}: "
                  f"{type(exc).__name__}: {exc}", file=sys.stderr,
                  flush=True)
            return err_line("internal", f"{type(exc).__name__}: {exc}"), \
                False

    def _handle_tenant(self, req, conn: _Conn | None) -> tuple[str, bool]:
        """``TENANT`` -> current selection; ``TENANT <name>`` re-points
        THIS connection at another hosted tenant (the default grammar
        is untouched for connections that never select)."""
        cur = conn.tenant if conn is not None else DEFAULT_TENANT
        if not req.args:
            return ok_kv(tenant=cur), False
        if len(req.args) != 1:
            raise BadRequest("TENANT wants at most one tenant name")
        name = req.args[0]
        try:
            self.tenants.get(name)
        except UnknownTenant as exc:
            self.counters["errors"] += 1
            return err_line("notfound", exc.message), False
        if conn is not None:
            conn.tenant = name
        return ok_kv(tenant=name), False

    def _check_staleness(self, tenant: Tenant) -> str | None:
        """Follower bounded-staleness guarantee: None = fresh enough to
        answer, else the typed refusal line."""
        if self.role != "follower" or self.cluster.max_lag is None:
            return None
        rep = tenant.replicator
        lag = rep.lag if rep is not None else 0
        if rep is None or rep.connected_to is None:
            lag = max(lag, 1)  # disconnected: staleness is unbounded
        if lag > self.cluster.max_lag:
            self.counters["stale"] += 1
            return err_line(
                "stale", f"lag={lag} exceeds the {self.cluster.max_lag}-"
                f"record staleness bound; retry or read the leader")
        return None

    def _dispatch(self, req, deadline: float,
                  tenant: Tenant) -> tuple[str, bool]:
        verb = req.verb
        # verbs that never touch tenant state run BEFORE the core
        # resolves — a PING or an EVICT must not thaw a cold tenant
        if verb == "PING":
            return ok_line("pong"), False
        if verb == "QUIT":
            return ok_line("bye"), True
        if verb == "EVICT":
            return self._handle_evict(req), False
        if verb == "MIG":
            # migration plumbing names its tenant in args and must work
            # on fenced tenants (STAT/UNSEAL are how a fence is
            # inspected and lifted), so it runs before the moved check
            return self._handle_mig(req), False
        if tenant.moved_dest is not None:
            # the cutover fence (ISSUE 17): this tenant lives elsewhere
            # now.  A typed refusal naming the new home — NEVER a
            # silent drop, and never a write applied here — is what
            # lets the router re-resolve and replay in-flight requests
            # epoch-safely with zero acked-insert loss.
            self.counters["moved"] += 1
            return err_line("moved", f"dest={tenant.moved_dest}"), False
        core = self.tenants.core_of(tenant.name)
        if verb in ("PART", "PARENT", "SUBTREE", "ECV"):
            # the quarantine read gate (ISSUE 20): a replica whose state
            # diverged from the leader's refuses every read with a typed
            # error until the re-sync proves it crc-equal again — a
            # wrong answer served fast is still a wrong answer
            if getattr(core, "quarantined", False):
                self.counters["diverged_reads"] += 1
                return err_line(
                    "diverged",
                    "replica state diverged from the leader "
                    "(quarantined); re-sync in progress - read another "
                    "replica or the leader"), False
            stale = self._check_staleness(tenant)
            if stale is not None:
                return stale, False
        # the vectorized hot verbs (ISSUE 11): one numpy parse + one
        # gather + one join per batch, byte-identical to the scalar loop
        if verb == "PART":
            vids = parse_vids_batch(req.args)
            return "OK " + core.part_tokens(vids), False
        if verb == "PARENT":
            vids = parse_vids_batch(req.args)
            res = core.parent_batch(vids).tolist()
            return "OK " + " ".join(
                "absent" if r == PARENT_ABSENT
                else "root" if r == PARENT_ROOT else str(r)
                for r in res), False
        if verb == "SUBTREE":
            vids = parse_vids_batch(req.args)
            if len(vids) == 1:
                # the PR-6 single-vid grammar, byte for byte (kv form,
                # typed notfound); batches answer positionally instead
                st = core.subtree(int(vids[0]))
                if st is None:
                    return err_line(
                        "notfound",
                        f"vertex {int(vids[0])} is not in the "
                        f"sequence"), False
                return ok_kv(size=st[0], pst=st[1]), False
            sizes, psts = core.subtree_batch(vids)
            return "OK " + " ".join(
                "absent" if s < 0 else f"{s}:{w}"
                for s, w in zip(sizes.tolist(), psts.tolist())), False
        if verb == "ECV":
            try:
                return ok_kv(**core.ecv()), False
            except RuntimeError as exc:
                return err_line("unavailable", str(exc)), False
        if verb == "STATS":
            return self._stats_line(tenant), False
        if verb == "METRICS":
            return self._metrics_response(), False
        if verb == "CRC":
            # the anti-entropy comparison point (ISSUE 20): state_crc at
            # the applied seqno — O(state) per call, deliberately its
            # own verb so STATS polling never pays it
            return ok_kv(crc=core.state_crc(),
                         seqno=core.applied_seqno,
                         epoch=core.epoch), False
        if verb == "INSERT":
            if self.role != "leader":
                self.counters["notleader"] += 1
                return err_line("notleader", self.leader_addr()), False
            if tenant.mig is not None:
                # a tenant still migrating IN holds the source's epoch;
                # accepting a write here before the cutover's epoch
                # advance would dual-own the tenant in the same epoch
                return err_line(
                    "unavailable",
                    f"tenant {tenant.name} is migrating in "
                    f"(phase={tenant.mig.get('phase', '?')}); writes "
                    f"open after the epoch-fenced cutover"), False
            vids = parse_vids(req.args, want_pairs=True)
            pairs = [(vids[i], vids[i + 1])
                     for i in range(0, len(vids), 2)]
            import numpy as np
            seqno = core.insert(np.asarray(pairs, dtype=np.uint32),
                                rid=req.rid)
            if self.cluster.clustered and self.cluster.repl_acks > 0:
                # the replication quorum: the OK means this insert is
                # durable on repl_acks followers too, so failover to the
                # best-caught-up replica cannot lose it
                left = max(0.05, deadline - time.monotonic())
                hub = self._hub_for(tenant)
                if not hub.wait_acks(seqno, self.cluster.repl_acks,
                                     left):
                    self.counters["repl_quorum_fails"] += 1
                    return err_line(
                        "unavailable",
                        f"replication quorum not reached (need "
                        f"{self.cluster.repl_acks} follower ack(s) for "
                        f"seqno {seqno}); the insert is durable locally "
                        f"and will replicate, but is NOT acknowledged"), \
                        False
            self._maybe_background_repartition(core)
            self._maybe_background_reseq(core, self._hub_for(tenant))
            self.tenants.maybe_evict_cold()
            return ok_kv(seq=seqno, applied=len(pairs)), False
        if verb == "SNAPSHOT":
            path = core.seal_snapshot()
            return ok_kv(snap=os.path.basename(path)), False
        if verb == "REPARTITION":
            if self.role != "leader":
                self.counters["notleader"] += 1
                return err_line("notleader", self.leader_addr()), False
            return ok_kv(**core.repartition()), False
        if verb == "RESEQ":
            # the operator's forced re-sequence (ISSUE 18): runs the
            # full crash-safe job inline — pricing skipped (force), swap
            # announced to followers.  One at a time daemon-wide, same
            # rationing as REPARTITION's background trigger.
            if self.role != "leader":
                self.counters["notleader"] += 1
                return err_line("notleader", self.leader_addr()), False
            if not self._resequencing.acquire(blocking=False):
                return err_line("unavailable",
                                "a re-sequence is already running"), False
            try:
                from .reseq import run_reseq
                res = run_reseq(core, force=True,
                                hub=self._hub_for(tenant),
                                events=self.config.events)
            finally:
                self._resequencing.release()
            res.pop("plan", None)  # kv lines carry scalars only
            return ok_kv(**res), False
        if verb == "SCRUB":
            # the operator's forced anti-entropy pass (ISSUE 20): one
            # inline scrub over this tenant's sealed artifacts — pricing
            # skipped (force), one at a time daemon-wide like RESEQ
            if not core.state_dir:
                return err_line("unavailable",
                                "tenant has no state dir to scrub"), False
            if not self._scrubbing.acquire(blocking=False):
                return err_line("unavailable",
                                "a scrub is already running"), False
            try:
                counts = self._scrub_tenant(tenant, core)
            finally:
                self._scrubbing.release()
            counts.pop("events", None)  # kv lines carry scalars only
            return ok_kv(**counts), False
        if verb == "CORRUPT":
            # the bench/test divergence injector (ISSUE 20): flip one
            # byte of LIVE applied state.  Refused unless the operator
            # opted the daemon in — a production daemon cannot be asked
            # to corrupt itself over the wire
            if os.environ.get(ALLOW_CORRUPT_ENV, "") != "1":
                return err_line(
                    "unavailable",
                    f"CORRUPT is a rehearsal verb; set "
                    f"{ALLOW_CORRUPT_ENV}=1 to enable it"), False
            try:
                crc = core.corrupt_one_byte()
            except RuntimeError as exc:
                return err_line("unavailable", str(exc)), False
            return ok_kv(crc=crc, seqno=core.applied_seqno), False
        raise BadRequest(f"unhandled verb {verb!r}")  # unreachable

    def _handle_mig(self, req) -> str:
        """``MIG <op> <tenant> [k=v...]`` — the daemon-side migration
        surface (ISSUE 17, serve/migrate.py drives it from the router):
        ADOPT/CUT/DROP run on the target leader, SEAL/UNSEAL on the
        source leader, STAT anywhere.  Every op is idempotent — the
        driver retries through netfaults and kill -9 resumes."""
        from . import migrate
        if len(req.args) < 2:
            raise BadRequest("MIG wants <op> <tenant> [k=v...]")
        op = req.args[0].upper()
        name = req.args[1]
        kv = parse_kv_args(req.args[2:])
        if op not in ("ADOPT", "SEAL", "UNSEAL", "CUT", "DROP", "STAT"):
            raise BadRequest(f"unknown MIG op {op!r}")
        if op != "STAT" and self.role != "leader":
            self.counters["notleader"] += 1
            return err_line("notleader", self.leader_addr())
        try:
            if op == "ADOPT":
                try:
                    host = kv["host"]
                    port = int(kv["port"])
                except (KeyError, ValueError):
                    raise BadRequest(
                        "MIG ADOPT wants host=<h> port=<p>")
                return ok_kv(**migrate.target_adopt(self, name, host,
                                                    port))
            if op == "SEAL":
                dest = kv.get("dest")
                if not dest:
                    raise BadRequest("MIG SEAL wants dest=<cluster>")
                return ok_kv(**migrate.source_seal(self, name, dest))
            if op == "UNSEAL":
                return ok_kv(**migrate.source_unseal(self, name))
            if op == "CUT":
                try:
                    epoch = int(kv["epoch"])
                    expect = int(kv["expect"])
                except (KeyError, ValueError):
                    raise BadRequest(
                        "MIG CUT wants epoch=<int> expect=<seqno>")
                return ok_kv(**migrate.target_cut(self, name, epoch,
                                                  expect))
            if op == "DROP":
                return ok_kv(**migrate.target_drop(self, name))
            return ok_kv(**migrate.mig_stat(self, name))
        except UnknownTenant as exc:
            return err_line("notfound", exc.message)
        except migrate.MigrationError as exc:
            return err_line("unavailable", str(exc))

    def _handle_evict(self, req) -> str:
        """``EVICT <tenant>``: seal the tenant to a snapshot generation
        and drop it from memory (the deterministic face of the
        governor's pressure-driven eviction — tests and operators name
        the victim instead of waiting for the budget)."""
        if len(req.args) != 1:
            raise BadRequest("EVICT wants exactly one tenant name")
        name = req.args[0]
        try:
            t = self.tenants.get(name)
        except UnknownTenant as exc:
            return err_line("notfound", exc.message)
        if name == DEFAULT_TENANT:
            return err_line("badreq",
                            "the default tenant cannot be evicted")
        if not t.resident:
            return ok_kv(tenant=name, resident=0)  # already cold
        try:
            if not self.tenants.evict(name):
                return err_line(
                    "unavailable",
                    f"tenant {name} has replication streams attached; "
                    f"evicting it would strand them")
        except OSError as exc:
            return err_line("unavailable",
                            f"eviction seal failed ({exc}); tenant "
                            f"{name} stays resident")
        return ok_kv(tenant=name, resident=0)

    def _render_metrics(self) -> str:
        """The Prometheus scrape body: refresh the gauges from live
        state, then render the whole registry (obs/metrics.py)."""
        m = self.metrics
        core = self.core
        m.gauge("sheep_serve_applied_seqno",
                "highest WAL seqno applied").set(core.applied_seqno)
        m.gauge("sheep_serve_epoch",
                "replication epoch (term)").set(core.epoch)
        m.gauge("sheep_serve_inflight",
                "requests holding admission slots").set(
            self.admission.inflight)
        m.gauge("sheep_serve_uptime_seconds", "daemon uptime").set(
            round(time.monotonic() - self.started_at, 3))
        lag = m.gauge("sheep_serve_repl_lag_records",
                      "replication lag: max follower lag on a leader, "
                      "own lag on a follower")
        if self.role == "leader":
            lags = self.hub.lag_report()
            lag.set(max((f["lag"] for f in lags.values()), default=0))
            fol = m.gauge("sheep_serve_follower_lag_records",
                          "per-follower replication lag")
            for node, f in sorted(lags.items()):
                fol.labels(node=node).set(f["lag"])
        else:
            rep = self.replicator
            lag.set(rep.lag if rep is not None else 0)
        # per-tenant labels (ISSUE 11): residency, applied seqno, and
        # evict/restore counters per hosted tenant
        res = m.gauge("sheep_serve_tenant_resident",
                      "1 = tenant state is in memory, 0 = evicted to "
                      "its sealed snapshot")
        app = m.gauge("sheep_serve_tenant_applied_seqno",
                      "highest WAL seqno applied, per tenant")
        evg = m.gauge("sheep_serve_tenant_evictions_total",
                      "cold evictions per tenant")
        rsg = m.gauge("sheep_serve_tenant_restores_total",
                      "lazy restores per tenant")
        # migration visibility (ISSUE 17): phase as a labeled presence
        # gauge (snap/delta on a target adopting in, moved on a fenced
        # source) and the target's delta lag in records — `sheep top`
        # and the router's rebalancer read these off the fleet scrape
        mphase = m.gauge("sheep_serve_mig_phase",
                         "1 = tenant is in this migration phase here")
        mlag = m.gauge("sheep_serve_mig_delta_lag_records",
                       "migration delta-stream lag on the target")
        # sequence-drift visibility (ISSUE 18): the quality-decay gauges
        # an operator watches to see the re-sequence detector approach
        # its threshold, plus the generation the tenant serves
        sdrift = m.gauge("sheep_serve_seq_drift",
                         "inserts the current sequence mis-handles "
                         "(out-of-sequence or rank-moved) since the "
                         "last re-sequence cut")
        rsq = m.gauge("sheep_serve_reseqs_total",
                      "completed re-sequence swaps per tenant")
        sgen = m.gauge("sheep_serve_seq_gen",
                       "sequence generation currently served")
        # group-commit + seqlock visibility (ISSUE 19): the write-path
        # amortization (fsyncs vs records, recent group size quantiles)
        # and how often lock-free reads had to retry or take the lock —
        # `sheep top` derives fsyncs/s and grouping from these
        gcf = m.gauge("sheep_serve_group_commit_fsyncs_total",
                      "shared group-commit fsyncs on the leader write "
                      "path")
        gcr = m.gauge("sheep_serve_group_commit_records_total",
                      "insert records covered by group-commit fsyncs")
        gc50 = m.gauge("sheep_serve_group_commit_size_p50",
                       "p50 records per shared fsync (last 512 groups)")
        gc99 = m.gauge("sheep_serve_group_commit_size_p99",
                       "p99 records per shared fsync (last 512 groups)")
        slr = m.gauge("sheep_serve_read_seqlock_retries_total",
                      "lock-free read attempts discarded by a racing "
                      "write")
        slf = m.gauge("sheep_serve_read_seqlock_fallbacks_total",
                      "lock-free reads that fell back to the state lock")
        # anti-entropy visibility (ISSUE 20): per-tenant quarantine
        # state plus daemon-lifetime scrub totals — `sheep top`'s
        # DIVERGED/SCRUB columns and the router's health view read these
        dvg = m.gauge("sheep_diverged",
                      "1 = tenant state diverged from the leader "
                      "(quarantined; reads refused until re-sync)")
        m.gauge("sheep_scrub_runs_total",
                "completed anti-entropy scrub passes").set(
            self.scrub_totals["runs"])
        m.gauge("sheep_scrub_checked_total",
                "sealed artifacts re-verified by the scrubber").set(
            self.scrub_totals["checked"])
        m.gauge("sheep_scrub_quarantined_total",
                "artifacts renamed *.quarantined by the scrubber").set(
            self.scrub_totals["quarantined"])
        m.gauge("sheep_scrub_repaired_total",
                "quarantined artifacts repaired back under their real "
                "name").set(self.scrub_totals["repaired"])
        m.gauge("sheep_scrub_unrepaired_total",
                "quarantined artifacts with no surviving repair input"
                ).set(self.scrub_totals["unrepaired"])
        for name in self.tenants.names():
            t = self.tenants.get(name)
            res.labels(tenant=name).set(int(t.resident))
            if t.core is not None:
                app.labels(tenant=name).set(t.core.applied_seqno)
                sdrift.labels(tenant=name).set(t.core.seq_drift)
                rsq.labels(tenant=name).set(t.core.reseqs)
                sgen.labels(tenant=name).set(t.core.seq_gen)
                gcf.labels(tenant=name).set(t.core.gc_fsyncs)
                gcr.labels(tenant=name).set(t.core.gc_records)
                gc50.labels(tenant=name).set(
                    t.core._gc_size_quantile(0.5))
                gc99.labels(tenant=name).set(
                    t.core._gc_size_quantile(0.99))
                slr.labels(tenant=name).set(t.core.seqlock_retries)
                slf.labels(tenant=name).set(t.core.seqlock_fallbacks)
                dvg.labels(tenant=name).set(
                    int(getattr(t.core, "quarantined", False)))
            evg.labels(tenant=name).set(t.evictions)
            rsg.labels(tenant=name).set(t.restores)
            if t.mig is not None:
                mphase.labels(tenant=name,
                              phase=t.mig.get("phase", "?")).set(1)
                rep = t.mig.get("replicator")
                mlag.labels(tenant=name).set(
                    rep.lag if rep is not None else 0)
            elif t.moved_dest is not None:
                mphase.labels(tenant=name, phase="moved").set(1)
        # sliding-window latency gauges (ISSUE 12): what `sheep top`
        # renders as CURRENT p50/p99 — the lifetime histogram series
        # above are untouched for scrapers that integrate them
        w50 = m.gauge("sheep_serve_window_p50_seconds",
                      "sliding-window (~30s) p50 request latency by verb")
        w99 = m.gauge("sheep_serve_window_p99_seconds",
                      "sliding-window (~30s) p99 request latency by verb")
        for key, child in sorted(self._m_latency.children().items()):
            if not child.window_count():
                continue
            verb = dict(key).get("verb", "?")
            w50.labels(verb=verb).set(
                round(child.window_quantile(0.5), 6))
            w99.labels(verb=verb).set(
                round(child.window_quantile(0.99), 6))
        tw99 = m.gauge("sheep_serve_tenant_window_p99_seconds",
                       "sliding-window (~30s) p99 request latency by "
                       "tenant")
        for key, child in sorted(self._m_tenant_latency
                                 .children().items()):
            if not child.window_count():
                continue
            tw99.labels(tenant=dict(key).get("tenant", "?")).set(
                round(child.window_quantile(0.99), 6))
        # standard process self-accounting, refreshed on scrape (ISSUE
        # 12 satellite: the accounting servebench used to capture from
        # outside now rides every METRICS payload)
        from ..obs.metrics import set_process_gauges
        set_process_gauges(m, self.started_at)
        return m.render()

    def _metrics_response(self) -> str:
        """``METRICS`` -> ``OK bytes=<n>`` followed by the n-byte scrape
        body (the snapshot-transfer shape: the one-line protocol stays
        one HEADER line, the payload is length-prefixed raw bytes).  The
        count includes the body's final newline, which the connection
        writer appends to every response."""
        body = self._render_metrics()  # always newline-terminated
        return f"OK bytes={len(body)}\n" + body[:-1]

    def _stats_line(self, tenant: Tenant | None = None) -> str:
        if tenant is None:
            tenant = self.tenants.get(DEFAULT_TENANT)
        core = self.tenants.core_of(tenant.name)
        rec = core.stats()
        rec.update(self.counters)
        adm = tenant.admission or self.admission
        rec["inflight"] = adm.inflight
        rec["uptime_s"] = round(time.monotonic() - self.started_at, 3)
        rec["read_only"] = int(adm.read_only
                               or core.governor.mem_pressure())
        rec["role"] = self.role
        rec["node"] = self.node_id
        rec["leader"] = self.leader_addr()
        # anti-entropy health (ISSUE 20): the router's read spread and
        # the election candidate filter both key on `diverged`
        rec["diverged"] = int(getattr(core, "quarantined", False))
        rec["scrub_runs"] = self.scrub_totals["runs"]
        rec["scrub_quarantined"] = self.scrub_totals["quarantined"]
        rec["scrub_repaired"] = self.scrub_totals["repaired"]
        rep = tenant.replicator
        if rep is not None and rep.quarantine_heals:
            rec["quarantine_heals"] = rep.quarantine_heals
        if self.role == "leader":
            hub = tenant.hub if tenant.hub is not None else self.hub
            lags = hub.lag_report()
            rec["followers"] = len(lags)
            rec["repl_lag"] = max((f["lag"] for f in lags.values()),
                                  default=0)
            for node, f in sorted(lags.items()):
                rec[f"lag_{node}"] = f["lag"]
        else:
            rep = tenant.replicator
            rec["followers"] = 0
            rec["repl_lag"] = rep.lag if rep is not None else 0
            rec["leader_seqno"] = (rep.leader_seqno if rep is not None
                                   else core.applied_seqno)
        if len(self.tenants) > 1:
            rec["tenant"] = tenant.name
            rec["tenants"] = len(self.tenants)
            rec["tenants_resident"] = len(self.tenants.resident_names())
        if tenant.moved_dest is not None:
            rec["moved_dest"] = tenant.moved_dest
        if tenant.mig is not None:
            rec["mig_phase"] = tenant.mig.get("phase", "?")
            rep = tenant.mig.get("replicator")
            rec["mig_lag"] = rep.lag if rep is not None else 0
        # daemon-wide migration summary (ISSUE 17): visible from ANY
        # connection (supervise --status asks without a tenant select),
        # not just one mid-migration tenant's
        moving = []
        for name in self.tenants.names():
            t = self.tenants.get(name)
            if t.mig is not None:
                moving.append(f"{name}:{t.mig.get('phase', '?')}")
            elif t.moved_dest is not None:
                moving.append(f"{name}:moved->{t.moved_dest}")
        if moving:
            rec["migrating"] = ",".join(sorted(moving))
        # per-verb counts + latency quantiles, derived from the SAME
        # histogram registry the METRICS scrape exports (ISSUE 10) —
        # the wire summary and the scrape cannot disagree
        for key, child in sorted(self._m_requests.children().items()):
            verb = dict(key).get("verb", "?").lower()
            rec[f"req_{verb}"] = int(child.value)
        for key, child in sorted(self._m_latency.children().items()):
            if not child.count:
                continue
            verb = dict(key).get("verb", "?").lower()
            rec[f"p50_{verb}_ms"] = round(child.quantile(0.5) * 1000, 3)
            rec[f"p99_{verb}_ms"] = round(child.quantile(0.99) * 1000, 3)
            # the sliding-window view (ISSUE 12): current latency for
            # `sheep top`; the lifetime p50_/p99_ keys above are
            # unchanged for existing scrapers
            if child.window_count():
                rec[f"w50_{verb}_ms"] = round(
                    child.window_quantile(0.5) * 1000, 3)
                rec[f"w99_{verb}_ms"] = round(
                    child.window_quantile(0.99) * 1000, 3)
        return ok_kv(**rec)

    # -- status file (the dead-daemon face of STATS) -----------------------

    def status_dict(self) -> dict:
        """Machine-readable role/epoch/lag snapshot — what STATS says on
        the wire, persisted for monitors that outlive the process
        (supervisor/status.py renders it when the daemon is down)."""
        core = self.core
        out = {
            "t": time.time(),
            "role": self.role,
            "node": self.node_id,
            "epoch": core.epoch,
            "applied_seqno": core.applied_seqno,
            "leader": self.leader_addr(),
            "peers": list(self.cluster.peers),
            "diverged": int(getattr(core, "quarantined", False)),
            "scrub_runs": self.scrub_totals["runs"],
            "scrub_repaired": self.scrub_totals["repaired"],
        }
        if self.role == "leader":
            out["followers"] = self.hub.lag_report()
        else:
            rep = self.replicator
            out["repl_lag"] = rep.lag if rep is not None else 0
            out["stream_age_s"] = (rep.stream_age_s()
                                   if rep is not None else None)
        if len(self.tenants) > 1:
            out["tenants"] = {}
            for name in self.tenants.names():
                t = self.tenants.get(name)
                rec = {"resident": int(t.resident),
                       "evictions": t.evictions,
                       "restores": t.restores}
                if t.moved_dest is not None:
                    rec["moved_dest"] = t.moved_dest
                if t.mig is not None:
                    rec["mig_phase"] = t.mig.get("phase", "?")
                    rep = t.mig.get("replicator")
                    rec["mig_lag"] = rep.lag if rep is not None else 0
                out["tenants"][name] = rec
        return out

    def _write_status(self, force: bool = False) -> None:
        now = time.monotonic()
        if not force and now - self._status_written < 1.0:
            return
        self._status_written = now
        path = os.path.join(self.core.state_dir, STATUS_FILE)
        try:
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(self.status_dict(), f)
            os.replace(tmp, path)
        except OSError:
            pass  # status is advisory; never let it hurt serving

    def _maybe_background_repartition(self, core: ServeCore) -> None:
        """Kick the drift-triggered repartition exactly once at a time;
        queries serve the stale partition until the swap (state.py).
        One at a time is daemon-wide, not per tenant — the partitioner
        is the expensive thing being rationed, tenants just take
        turns."""
        if not core.drift_exceeded():
            return
        if not self._repartitioning.acquire(blocking=False):
            return  # one already running

        def work():
            try:
                core.repartition()
                self.config.events.append(("repartition",
                                           core.repartitions))
            finally:
                self._repartitioning.release()

        t = threading.Thread(target=work, daemon=True,
                             name="serve-repartition")
        t.start()

    def _maybe_background_reseq(self, core: ServeCore, hub) -> None:
        """Kick the sequence-drift-triggered re-sequence (ISSUE 18)
        exactly once at a time, daemon-wide — the streamed fold is the
        expensive thing being rationed.  Queries serve the stale (but
        consistent) generation until the ticket-guarded swap; the run
        itself is priced by plan_reseq and may still decline."""
        if not self.config.reseq or self.role != "leader":
            return
        if not core.seq_drift_exceeded():
            return
        if not self._resequencing.acquire(blocking=False):
            return  # one already running

        def work():
            try:
                from .reseq import run_reseq
                res = run_reseq(core, hub=hub,
                                events=self.config.events)
                self.config.events.append(
                    ("reseq", core.reseqs, res.get("reason", "")))
            except Exception as exc:
                # the old generation keeps serving; the detector will
                # re-fire and retry off the durable manifest
                self.config.events.append(("reseq_error", str(exc)))
            finally:
                self._resequencing.release()

        t = threading.Thread(target=work, daemon=True,
                             name="serve-reseq")
        t.start()

    def _resume_pending_reseqs(self) -> None:
        """Startup sweep (leader only): any tenant whose state dir holds
        an in-flight reseq manifest — a kill -9 mid-rebuild — resumes
        (or cleanly aborts) it in the background, off the manifest's
        durable phase."""
        if not self.config.reseq or self.role != "leader":
            return
        from .reseq import active, resume_reseq
        pending = []
        for name in self.tenants.names():
            t = self.tenants.get(name)
            if t.core is not None and t.core.state_dir \
                    and active(t.core.state_dir):
                pending.append(t)
        if not pending:
            return
        if not self._resequencing.acquire(blocking=False):
            return

        def work():
            try:
                for t in pending:
                    try:
                        res = resume_reseq(t.core, hub=self._hub_for(t),
                                           events=self.config.events)
                        if res is not None:
                            self.config.events.append(
                                ("reseq_resume", t.name, res))
                    except Exception as exc:
                        self.config.events.append(
                            ("reseq_error", f"{t.name}: {exc}"))
            finally:
                self._resequencing.release()

        threading.Thread(target=work, daemon=True,
                         name="serve-reseq-resume").start()

    # -- anti-entropy (ISSUE 20) -------------------------------------------

    def _sweep_quarantine(self) -> None:
        """Startup sweep: a durable quarantine marker in any tenant's
        state dir means a kill -9 interrupted a divergence heal — the
        restarted daemon re-enters the quarantine (reads refused) and
        lets that tenant's follower stream resume the heal off the
        marker's phase."""
        from . import scrub as scrub_mod
        for name in self.tenants.names():
            t = self.tenants.get(name)
            if t.core is None or not t.core.state_dir:
                continue
            marker = scrub_mod.read_quarantine(t.core.state_dir)
            if marker is not None:
                t.core.quarantined = True
                self.config.events.append(
                    ("quarantine_resumed", name,
                     marker.get("phase", "?")))

    def _scrub_source(self, tenant: Tenant) -> tuple[str, int] | None:
        """Where a scrub may fetch a clean snapshot from: a follower's
        connected leader; a leader repairs from its own live core."""
        rep = tenant.replicator
        if rep is not None and rep.connected_to is not None:
            return rep.connected_to
        return None

    def _scrub_tenant(self, tenant: Tenant, core: ServeCore) -> dict:
        """One scrub pass over one tenant (caller holds _scrubbing).
        A quarantined core's state is suspect, so it never reseals its
        own snapshots — repairs come from the leader instead."""
        from . import scrub as scrub_mod
        counts = scrub_mod.run_scrub(
            core.state_dir,
            core=None if getattr(core, "quarantined", False) else core,
            leader=self._scrub_source(tenant), tenant=tenant.name)
        self.scrub_totals["runs"] += 1
        for k in ("checked", "failed", "quarantined", "repaired",
                  "unrepaired"):
            self.scrub_totals[k] += counts.get(k, 0)
        if counts.get("failed"):
            self.config.events.append(
                ("scrub", tenant.name, counts["failed"],
                 counts["repaired"]))
        return counts

    def _maybe_background_scrub(self) -> None:
        """Kick the paced background scrub when its interval elapses —
        one at a time daemon-wide, priced by plan_scrub so a pass that
        cannot amortize inside its horizon declines (the same GO/STAY
        discipline as the reseq job)."""
        interval = self.config.scrub_interval_s
        if interval <= 0:
            return
        now = time.monotonic()
        if now - self._last_scrub < interval:
            return
        if not self._scrubbing.acquire(blocking=False):
            return
        self._last_scrub = now

        def work():
            from . import scrub as scrub_mod
            from ..plan.model import plan_scrub
            try:
                for name in self.tenants.names():
                    t = self.tenants.get(name)
                    core = t.core
                    if core is None or not core.state_dir:
                        continue
                    paths = scrub_mod.sealed_artifacts(core.state_dir)
                    total = 0
                    for p in paths:
                        try:
                            total += os.path.getsize(p)
                        except OSError:
                            pass
                    plan = plan_scrub(len(paths), total)
                    if plan["decision"] != "go":
                        self.config.events.append(
                            ("scrub_declined", name, plan["reason"]))
                        continue
                    self._scrub_tenant(t, core)
            except Exception as exc:
                # scrubbing is maintenance; it never hurts serving
                self.config.events.append(("scrub_error", str(exc)))
            finally:
                self._scrubbing.release()

        threading.Thread(target=work, daemon=True,
                         name="serve-scrub").start()
