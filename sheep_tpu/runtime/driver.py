"""The fault-tolerant build driver: checkpointed chunk loops + retry +
the graceful-degradation ladder.

Failure model (ROADMAP north star: a production system serving heavy
traffic).  The chunked architecture already bounds each device dispatch
(ops/forest.py, parallel/chunked.py) because unbounded dispatches fault on
real hardware; this module makes the HOST loop around those dispatches
survivable:

  faulted dispatch      retry with exponential backoff, halving the
                        per-dispatch round count (runtime/retry.py) — a
                        dispatch that tripped the per-execution budget
                        asks for half the work next time.
  killed process        every chunk boundary checkpoints the complete
                        build state (runtime/snapshot.py); a new process
                        with ``resume=True`` continues from the last
                        completed chunk and produces the bit-identical
                        tree (forest = f(threshold connectivity) only).
  sick backend          the degradation ladder: mesh-chunked ->
                        single-chip-chunked -> host numpy union-find.
                        Every rung consumes the previous rung's
                        checkpoint, because all rungs reduce the same
                        link multiset over the same sequence — the
                        associativity that powers the reference's tree
                        merge (lib/jnode.cpp:174-201) is exactly what
                        makes partial state transportable across rungs.

Determinism: pst is order-free and counted once at prep; the parent array
is the unique elimination forest of the link multiset, so ANY interleaving
of chunks, retries, resumes, and rung handoffs converges to the same
output.  The resume-equivalence property test (tests/test_runtime.py)
kills a build at every chunk boundary and asserts bit-identical parent,
pst, and ECV(down) against the uninterrupted build.
"""

from __future__ import annotations

import contextlib
import os
from dataclasses import dataclass, field

import numpy as np

from .. import INVALID_JNID
from ..core.forest import Forest, build_forest_links, edges_to_positions
from ..core.sequence import degree_sequence
from ..integrity.errors import IntegrityError
from ..integrity.sidecar import resolve_policy
from ..obs import trace as obs
from ..plan.model import DEFAULT_LADDER, PROV_LEARNED, available_rungs, \
    plan_build
from ..resources.errors import MemoryBudgetExceeded, ResourceError
from ..resources.governor import (NATIVE_THREADS_ENV, ResourceGovernor,
                                  rss_bytes)
from .faults import (RetryBudgetExhausted, fault_point, is_retryable,
                     reset_counters)
from .retry import RetryPolicy, run_with_retry
from .snapshot import Checkpointer, Snapshot, input_signature


@dataclass
class RuntimeConfig:
    """One build's fault-tolerance knobs (CLI --checkpoint-dir/--resume/
    --max-retries; env SHEEP_CHECKPOINT_DIR/SHEEP_RESUME/SHEEP_MAX_RETRIES
    and friends — the env surface is what scripts/dist-partition.sh -C
    exports)."""

    checkpoint_dir: str | None = None
    resume: bool = False
    max_retries: int = 3
    backoff_base_s: float = 0.05
    backoff_cap_s: float = 2.0
    watchdog_s: float | None = None
    #: checkpoint cadence: persist every k-th boundary; 0 = auto-tune from
    #: measured snapshot cost vs chunk compute time (snapshot.Checkpointer)
    checkpoint_every: int = 1
    #: after this many consecutive healthy (retry-free) dispatches, a rung
    #: is promoted back to the fast pipelined dispatch path — no watchdog
    #: thread, no per-dispatch block_until_ready — and demoted to the FT
    #: wrapper again on the first fault (0 disables promotion)
    promote_after: int = 16
    #: integrity policy for checkpoint loads (strict/repair/trust; None =
    #: env SHEEP_INTEGRITY, default strict).  strict: a corrupt snapshot
    #: aborts the resume with a typed IntegrityError; repair: it is
    #: discarded and the build restarts fresh — never resumed into garbage.
    integrity: str | None = None
    #: degradation ladder, tried in order.  "mesh" is skipped when fewer
    #: than two devices are visible; "host" is the exact numpy/native
    #: union-find; "stream" (ISSUE 8) folds the SAME in-RAM link table
    #: through the resumable native union-find one hi-quantile window at
    #: a time — O(n + window) beyond the input, no int64 cast, no
    #: scratch file; "ext" (ISSUE 9) re-streams the ORIGINAL ``.dat``
    #: file block-wise through the external-memory build (ops/extmem) —
    #: O(n + block) with no link table resident at all, available only
    #: when ``edges_path`` names the file — so tight budgets pick it
    #: before "spill" (ISSUE 5), the memory FLOOR, where the links table
    #: lives in a memory-mapped scratch file and folds in bounded blocks.
    ladder: tuple[str, ...] = ("mesh", "single", "host", "stream", "ext",
                               "spill")
    #: the ``.dat`` file whose FULL record stream is this build's edge
    #: input (None for in-memory or partial-load builds).  This is what
    #: arms the "ext" rung: unlike every other rung, ext ignores the
    #: in-RAM link table and re-streams the original file, which is only
    #: the same build when the file IS the whole input.  The CLI sets it
    #: for whole-file ``.dat`` loads; SHEEP_EDGES_PATH for scripts.
    edges_path: str | None = None
    #: resource budgets (SHEEP_MEM_BUDGET / SHEEP_DISK_BUDGET); None =
    #: build one from the environment.  The governor routes the ladder
    #: around rungs whose estimated peak cannot fit, shrinks chunk work
    #: under measured-RSS pressure, and prices checkpoint writes.
    governor: ResourceGovernor | None = None
    #: observable trace of what the runtime did: ("retry", site, attempt,
    #: j), ("checkpoint", rung, boundary), ("degrade", rung, next, why),
    #: ("resume", rung, boundary, rounds).  Tests and the CLI -v path
    #: read this.
    events: list = field(default_factory=list)

    @classmethod
    def from_env(cls, **overrides) -> "RuntimeConfig":
        env = os.environ
        every_s = env.get("SHEEP_CHECKPOINT_EVERY", "1")
        kw: dict = dict(
            checkpoint_dir=env.get("SHEEP_CHECKPOINT_DIR") or None,
            resume=env.get("SHEEP_RESUME", "") == "1",
            max_retries=int(env.get("SHEEP_MAX_RETRIES", "3")),
            backoff_base_s=float(env.get("SHEEP_BACKOFF_BASE", "0.05")),
            checkpoint_every=0 if every_s == "auto" else int(every_s),
            promote_after=int(env.get("SHEEP_PROMOTE_AFTER", "16")),
            integrity=env.get("SHEEP_INTEGRITY") or None,
            edges_path=env.get("SHEEP_EDGES_PATH") or None,
        )
        if env.get("SHEEP_WATCHDOG_S"):
            kw["watchdog_s"] = float(env["SHEEP_WATCHDOG_S"])
        kw.update(overrides)
        return cls(**kw)

    def policy(self) -> RetryPolicy:
        return RetryPolicy(max_retries=self.max_retries,
                           backoff_base_s=self.backoff_base_s,
                           backoff_cap_s=self.backoff_cap_s,
                           watchdog_s=self.watchdog_s)


class ChunkRuntime:
    """The per-rung context the chunk drivers thread their host-sync
    boundaries through (ops/forest.reduce_links_hosted and
    parallel/chunked.reduce_links_sharded accept one as ``runtime=``).

    ``dispatch`` wraps one device dispatch in the retry/watchdog/fault-
    injection policy; ``boundary`` checkpoints the live link multiset at a
    completed chunk and is itself a fault-injection site ("boundary" —
    the kill point of the resume property test).
    """

    def __init__(self, policy: RetryPolicy, checkpointer: Checkpointer | None,
                 events: list, rung: str, n: int, seq: np.ndarray,
                 pst: np.ndarray, input_sig: str, rounds_base: int = 0,
                 promote_after: int = 0,
                 governor: ResourceGovernor | None = None,
                 edges_path: str | None = None,
                 ext_block: int | None = None):
        self.policy = policy
        self.ckpt = checkpointer
        self.events = events
        #: resource budgets: None = unbudgeted (every check is a no-op)
        self.governor = governor
        #: the whole-input .dat file, when one exists (the ext rung's
        #: source; RuntimeConfig.edges_path)
        self.edges_path = edges_path
        #: a planner-resolved ext block size (ISSUE 15) — set only when a
        #: measured prior CORRECTED the analytic fit; None lets the ext
        #: build run the governor's own arithmetic exactly as before
        self.ext_block = ext_block
        self._last_levels_cap: int | None = None
        self.rung = rung
        self.n = n
        self.seq = seq
        self.pst = pst
        self.input_sig = input_sig
        self.rounds_base = rounds_base
        #: promotion back to the fast pipelined path (ROADMAP PR-1
        #: follow-up): after ``promote_after`` consecutive retry-free
        #: dispatches the FT wrapper (watchdog thread + per-dispatch
        #: block_until_ready) is dropped, letting dispatches pipeline
        #: again; the first fault demotes back and retries under the
        #: full policy.  0 disables.
        self.promote_after = promote_after
        self._healthy = 0
        self._promoted = False
        import time
        self._clock = time.perf_counter
        self._last_boundary_t = self._clock()

    def cap_levels(self, levels: int, n: int) -> int:
        """Memory-budget cap on the lifting depth (the jump tables are
        the chunk loop's dominant O(n) allocation): under a configured
        ``SHEEP_MEM_BUDGET`` the depth shrinks so the tables fit the
        CURRENT headroom (governor.shrunk_levels).  Unbudgeted: identity.
        The chunk drivers call this at every lv decision, so the cap
        tracks pressure as the build's resident set grows and shrinks."""
        if self.governor is None:
            return levels
        lv = self.governor.shrunk_levels(levels, n)
        if lv != levels and lv != self._last_levels_cap:
            self._last_levels_cap = lv
            self.events.append(("mem-levels", self.rung, lv))
        return lv

    def dispatch(self, site: str, fn, j: int | None = None):
        """Run dispatch ``fn(j)`` under the retry policy (or, once
        promoted, the bare pipelined path).  Returns (outputs, j_used) —
        ``j_used`` may have shrunk (a retry after a fault, or the memory
        governor trimming chunk size under RSS pressure: a smaller j
        reaches the next compaction/boundary sooner, which is when the
        live set — and the resident set with it — shrinks)."""
        if self.governor is not None and j is not None and j > 1 \
                and self.governor.mem_pressure():
            j = max(1, j // 2)
            self.events.append(("mem-shrink", self.rung, site, j))

        inner = fn

        def fn(jj, _inner=inner, _site=site, _rung=self.rung):
            # flight-recorder span per dispatch attempt (obs/trace.py:
            # the no-op singleton when SHEEP_TRACE is unset)
            with obs.span("dispatch", site=_site, rung=_rung, j=jj):
                return _inner(jj)

        if self._promoted:
            try:
                fault_point(site)
                # no watchdog, no block_until_ready: the dispatch queues
                # asynchronously and overlaps the host loop.  An async
                # backend fault surfaces at the caller's next sync and is
                # handled by the degradation ladder; a synchronous one
                # demotes right here and retries under the full policy.
                return fn(j), j
            except BaseException as exc:
                if not is_retryable(exc):
                    raise
                self._promoted = False
                self._healthy = 0
                self.events.append(("demote", self.rung, site))

        retried = {"n": 0}

        def on_retry(s, attempt, jj):
            retried["n"] = attempt
            self.events.append(("retry", s, attempt, jj))

        out = run_with_retry(self.policy, site, fn, j, on_retry)
        self._healthy = 0 if retried["n"] else self._healthy + 1
        if self.promote_after and self._healthy >= self.promote_after \
                and not self._promoted:
            self._promoted = True
            self.events.append(("promote", self.rung, site))
        return out

    def boundary(self, rounds: int, links_fn) -> None:
        """One completed chunk boundary.  ``links_fn() -> (lo, hi)`` host
        int32 live links in the ORIGINAL vertex space (called only when
        this boundary is on the checkpoint cadence — it may cost a device
        fetch or an all_gather)."""
        if self.ckpt is None:
            return
        now = self._clock()
        chunk_s = now - self._last_boundary_t
        if self.ckpt.want():
            lo, hi = links_fn()
            self.ckpt.save(Snapshot(
                n=self.n, seq=self.seq, pst=self.pst,
                lo=np.asarray(lo, np.int32), hi=np.asarray(hi, np.int32),
                rounds=self.rounds_base + rounds, boundary=0,
                rung=self.rung, input_sig=self.input_sig))
            self.events.append(("checkpoint", self.rung,
                                self.ckpt.boundary - 1))
            # auto-cadence (SHEEP_CHECKPOINT_EVERY=auto): scale the
            # persistence interval from this boundary's measured snapshot
            # cost vs the compute time since the last boundary
            new = self.ckpt.observe(self._clock() - now, chunk_s)
            if new is not None:
                self.events.append(("cadence", self.rung, new))
        else:
            self.ckpt.skip()
        self._last_boundary_t = self._clock()
        # the deterministic kill point: "died between chunks"
        fault_point("boundary")


# ---------------------------------------------------------------------------
# Ladder rungs.  Contract: (lo, hi int32 live links, n, runtime,
# num_workers) -> parent array; int32 with n marking roots (device rungs)
# or uint32 with INVALID_JNID (host rung) — both normalized by the driver.
# All rungs reduce the same link multiset, so any rung may pick up any
# other rung's checkpoint.
# ---------------------------------------------------------------------------


def _rung_mesh(lo, hi, n, rt, num_workers):
    import jax

    from ..parallel.build import _fetch
    from ..parallel.chunked import (_extract_parent, reduce_links_sharded,
                                    stage_edges_2d)
    from ..parallel.mesh import make_mesh

    w = num_workers or len(jax.devices())
    mesh = make_mesh(min(w, len(jax.devices())))
    lo2d, hi2d = stage_edges_2d(lo, hi, n, mesh)
    slo, shi, _, gathered = reduce_links_sharded(
        lo2d, hi2d, n, mesh, global_f=True, fetch=_fetch, runtime=rt)
    return _fetch(_extract_parent(slo, shi, n, mesh, gathered))


def _rung_single(lo, hi, n, rt, num_workers):
    import jax.numpy as jnp

    from ..ops.forest import parent_from_links, reduce_links_hosted

    flo, fhi, _, _, _ = reduce_links_hosted(
        jnp.asarray(lo, jnp.int32), jnp.asarray(hi, jnp.int32), n,
        runtime=rt)
    return np.asarray(parent_from_links(flo, fhi, n))


def _rung_host(lo, hi, n, rt, num_workers):
    # exact numpy/native union-find, no device dispatches, cannot fault
    # — but it casts the whole links table to int64 (16 bytes/link), so
    # under a tight memory budget the spill rung below is the real floor.
    # pst is NOT recounted here — the driver already holds the order-free
    # pst from prep (these links may be chunk-rewritten, so per-link
    # counting would be wrong anyway).
    zero = np.zeros(n, dtype=np.uint32)
    forest = build_forest_links(lo.astype(np.int64), hi.astype(np.int64), n,
                                pst=zero)
    return forest.parent


def _rung_stream(lo, hi, n, rt, num_workers):
    """Streaming windowed fold between host and spill (ISSUE 8): the
    int32 link table stays in RAM, but instead of the host rung's
    16-bytes-per-link int64 cast it folds through the RESUMABLE native
    union-find one ascending hi-quantile window at a time — the exact
    fold the hybrid's streaming handoff feeds (core.forest.links_fold /
    native sheep_build_forest_links_begin/_block/_finish) — so the peak
    beyond the input is O(n + window), with no scratch file to pay for.

    Soundness: windows partition the multiset by CONTIGUOUS hi range
    (the shared equal-count quantile rule, host_hi_window_bounds), so
    feeding them in ascending order replays the exact grouped insert the
    monolithic build runs.  pst comes from the driver (these links may
    be chunk-rewritten), so the fold runs with a zero pst like the host
    rung.
    """
    from ..core.forest import host_hi_window_bounds, links_fold
    from ..resources.governor import SPILL_BLOCK

    zero = np.zeros(n, dtype=np.uint32)
    fold = links_fold(n, pst=zero)
    k = len(lo)
    if k:
        w = max(1, -(-k // SPILL_BLOCK))
        bounds = host_hi_window_bounds(hi, w, n) if w > 1 else [0, n]
        w = len(bounds) - 1
        for i in range(w):
            sel = hi >= bounds[i]
            if i + 1 < w:  # the last window keeps the whole tail
                sel &= hi < bounds[i + 1]
            fold.block(lo[sel], hi[sel])
            rt.events.append(("stream-window", i, int(sel.sum())))
    parent, _ = fold.finish()
    return parent


def _rung_ext(lo, hi, n, rt, num_workers):
    """The external-memory rung (ISSUE 9): re-stream the ORIGINAL
    ``.dat`` file block-wise through the out-of-core build (ops/extmem)
    — the one rung that does not consume the in-RAM link table at all,
    so its peak is O(n + block) regardless of the edge count.  Exact
    because the file's record stream over the driver's sequence is the
    same link multiset the other rungs reduce (the checkpoint handoff
    just re-derives progress from the file instead of the snapshot — any
    rung may rebuild from the original multiset, forest = f(threshold
    connectivity) only).  pst comes from the driver's prep like every
    rung, so the ext build's own accumulation is discarded.  Only
    reachable when RuntimeConfig.edges_path names the whole-input file
    (_ladder_rungs filters it otherwise)."""
    from ..ops.extmem import build_forest_extmem

    gov = rt.governor
    _, forest = build_forest_extmem(
        rt.edges_path, seq=rt.seq,
        block_edges=rt.ext_block,
        governor=gov if gov is not None else None,
        events=rt.events)
    return forest.parent


def _rung_spill(lo, hi, n, rt, num_workers):
    """The memory FLOOR of the ladder (ISSUE 5): the links table spills
    to a memory-mapped int32 scratch file and the exact union-find folds
    over it in bounded blocks — O(n + SPILL_BLOCK) resident, any link
    count.  Blocks arrive through the shared async prefetcher
    (io/prefetch.BlockPrefetcher, ISSUE 9) — the same "fold blocks
    arriving from elsewhere" path the ext rung streams its file through
    — so the scratch read of block k+1 overlaps the fold of block k
    instead of serializing in front of it.

    Soundness is the associative-merge property every other layer already
    leans on (core.forest.build_forest_streaming, the reference's
    jnode.cpp:174-201 merge): the forest of (carry-links ∪ next-block) is
    the forest of the union, and a converged forest re-enters the fold as
    its <= n (kid -> parent) links.  pst comes from the driver (order-free
    since prep), so the fold runs with a zero pst like the host rung.

    The scratch file lives under SHEEP_SCRATCH_DIR > the checkpoint dir >
    the system temp dir, and is removed on every exit path — scratch is
    never part of the durable/resumable state (the checkpoint still holds
    the authoritative link multiset).
    """
    import shutil
    import tempfile

    from ..core.forest import forest_links
    from ..io.prefetch import BlockPrefetcher
    from ..resources.governor import SPILL_BLOCK

    gov = rt.governor
    root = (gov.scratch_dir if gov is not None and gov.scratch_dir
            else None) or (rt.ckpt.directory if rt.ckpt is not None
                           else None) or tempfile.gettempdir()
    os.makedirs(root, exist_ok=True)
    k = len(lo)
    if k == 0:
        return np.full(n, INVALID_JNID, dtype=np.uint32)
    scratch = tempfile.mkdtemp(prefix="sheep-spill.", dir=root)
    zero = np.zeros(n, dtype=np.uint32)
    try:
        mlo = np.memmap(os.path.join(scratch, "lo.i32"), dtype=np.int32,
                        mode="w+", shape=(k,))
        mhi = np.memmap(os.path.join(scratch, "hi.i32"), dtype=np.int32,
                        mode="w+", shape=(k,))
        mlo[:] = lo
        mhi[:] = hi
        mlo.flush()
        mhi.flush()

        def scratch_blocks():
            # the memmap slice materializes IN THE PREFETCH THREAD — the
            # actual disk/page-cache read overlaps the consumer's fold
            for a in range(0, k, SPILL_BLOCK):
                b = min(a + SPILL_BLOCK, k)
                yield (np.asarray(mlo[a:b], dtype=np.int64),
                       np.asarray(mhi[a:b], dtype=np.int64))

        carry_lo = np.empty(0, dtype=np.int64)
        carry_hi = np.empty(0, dtype=np.int64)
        forest = None
        with BlockPrefetcher(scratch_blocks()) as pf:
            for i, (blo, bhi) in enumerate(pf):
                fold_lo = np.concatenate([carry_lo, blo])
                fold_hi = np.concatenate([carry_hi, bhi])
                forest = build_forest_links(fold_lo, fold_hi, n, pst=zero)
                carry_lo, carry_hi = forest_links(forest)
                rt.events.append(("spill-block", i, len(carry_lo)))
        return forest.parent
    finally:
        shutil.rmtree(scratch, ignore_errors=True)


_RUNGS = {"mesh": _rung_mesh, "single": _rung_single, "host": _rung_host,
          "stream": _rung_stream, "ext": _rung_ext, "spill": _rung_spill}


@contextlib.contextmanager
def _native_threads_env(tplan: dict):
    """Export the governor's resolved thread count as
    ``SHEEP_NATIVE_THREADS`` for the duration of one rung attempt (the
    kernels read the env per call), restoring the previous value on any
    exit — one driver call must never re-pin the whole process.  A
    pinned env (``forced``) is the operator's word and is left alone."""
    if tplan["forced"] or (tplan["threads"] <= 1
                           and NATIVE_THREADS_ENV not in os.environ):
        yield
        return
    prev = os.environ.get(NATIVE_THREADS_ENV)
    os.environ[NATIVE_THREADS_ENV] = str(tplan["threads"])
    try:
        yield
    finally:
        if prev is None:
            os.environ.pop(NATIVE_THREADS_ENV, None)
        else:
            os.environ[NATIVE_THREADS_ENV] = prev


def _ladder_rungs(config: RuntimeConfig, num_workers) -> list[str]:
    # availability routes through the planner (ISSUE 15): one filter for
    # the driver, the plan CLI, and anything else that must answer
    # "which rungs could even run here"
    import jax

    return available_rungs(config.ladder, len(jax.devices()), num_workers,
                           config.edges_path, known=_RUNGS)


def build_graph_resilient(tail, head, num_vertices=None, num_workers=None,
                          seq=None, max_vid=None,
                          config: RuntimeConfig | None = None):
    """Fault-tolerant build: (seq uint32 [m], Forest over m), same contract
    as parallel.build.build_graph_distributed.

    ``config.resume`` continues from the checkpoint in
    ``config.checkpoint_dir`` (written by a previous, killed invocation of
    this function over the same input — verified by signature); without a
    usable checkpoint it falls through to a fresh build.  The result is
    bit-identical either way (module docstring).
    """
    config = config or RuntimeConfig.from_env()
    reset_counters()
    policy = config.policy()
    events = config.events
    gov = config.governor if config.governor is not None \
        else ResourceGovernor.from_env()
    ckpt = Checkpointer(config.checkpoint_dir, config.checkpoint_every,
                        governor=gov) \
        if config.checkpoint_dir else None

    tail = np.asarray(tail)
    head = np.asarray(head)
    seq_h = np.asarray(seq, dtype=np.uint32) if seq is not None \
        else degree_sequence(tail, head)
    n = len(seq_h)
    if n == 0:
        return np.empty(0, np.uint32), Forest(
            np.empty(0, np.uint32), np.empty(0, np.uint32))
    sig = input_signature(n, seq_h, tail, head)

    # Resume REJECTS corrupt snapshots instead of resuming into garbage
    # (ISSUE 2): strict propagates the typed IntegrityError; repair logs
    # the corruption and restarts fresh — bit-identical output either way
    # a build completes.
    snap = None
    if ckpt is not None and config.resume:
        try:
            snap = ckpt.load(integrity=config.integrity)
            if snap is not None:
                snap.verify(sig)
        except IntegrityError as exc:
            if resolve_policy(config.integrity) != "repair":
                raise
            events.append(("corrupt-checkpoint", "resume", str(exc)))
            snap = None
            ckpt.boundary = 0  # fresh build: boundary indices restart
    rungs = _ladder_rungs(config, num_workers)
    if snap is not None:
        pst = snap.pst
        lo, hi = snap.lo, snap.hi
        rounds = snap.rounds
        if snap.rung in rungs:  # restart at the rung that wrote it
            rungs = rungs[rungs.index(snap.rung):]
        events.append(("resume", snap.rung, snap.boundary, rounds))
    else:
        # host prep: exact core semantics (deterministic, rung-neutral).
        # lo of every kept record is a present position < n; hi >= n marks
        # pst-only links (absent endpoint) excluded from the tree links.
        with obs.span("prep", n=n, edges=len(tail)):
            lo64, hi64 = edges_to_positions(tail, head, seq_h, max_vid)
            pst = np.bincount(lo64, minlength=n)[:n].astype(np.uint32)
            tree = hi64 < n
            lo = lo64[tree].astype(np.int32)
            hi = hi64[tree].astype(np.int32)
        rounds = 0

    # One planner to rule the rungs (ISSUE 15): rung feasibility, the
    # native thread count (SHEEP_LEG_CORES / affinity / cgroup quota,
    # budget-vetoable at 8n partial tables per extra thread — round 14),
    # and the ext block all resolve through plan_build, which folds the
    # governor's analytic prices with any measured priors
    # (SHEEP_PLAN_PRIORS).  With no prior store the plan reproduces the
    # pre-planner choices exactly; every knob is an override recorded
    # with its provenance (default | priced | learned | forced).  The
    # resolved thread count is exported as SHEEP_NATIVE_THREADS for the
    # kernels to read — restored after the build so one driver call
    # never re-pins a whole process; an operator pin is never
    # second-guessed.
    plan = plan_build(n, len(lo), rungs=rungs, governor=gov,
                      num_workers=num_workers,
                      ladder_forced=tuple(config.ladder) != DEFAULT_LADDER,
                      edges_path=config.edges_path)
    tplan = plan.native_threads
    events.append(("native-threads", tplan["threads"],
                   "pinned" if tplan["forced"] else tplan["reason"]))
    ext_block = plan.decision("ext_block")
    ext_block_planned = ext_block.value \
        if ext_block.provenance == PROV_LEARNED else None

    # Memory-budget ladder planning (ISSUE 5, via the planner): rungs
    # whose (prior-corrected) priced peak cannot fit the headroom are
    # routed around — degrading up-front beats OOM-ing mid-rung.  The
    # last rung (spill: O(n + block) resident) always survives.
    priced: list[dict] = []
    price_of: dict[str, int] = {}
    if gov.active:
        rungs = plan.rungs
        for cand in plan.candidates:
            entry = {"rung": cand["rung"],
                     "est_bytes": cand["est_bytes"],
                     "verdict": cand["verdict"]}
            if "corrected_bytes" in cand:
                entry["corrected_bytes"] = cand["corrected_bytes"]
                entry["prior"] = cand["prior"]["key"]
            priced.append(entry)
            price_of[cand["rung"]] = cand["est_bytes"]
            if cand["verdict"] == "skip":
                events.append(("mem-skip-rung", cand["rung"],
                               cand.get("corrected_bytes",
                                        cand["est_bytes"])))
    # the rung-decision record `sheep trace`/`sheep plan` explain: the
    # planned order, each rung's governor price (+ any prior correction)
    # and keep/skip verdict, the measured headroom the verdicts were
    # made against, the threaded-vs-serial pick with the constraint that
    # bound it, and every knob decision with its provenance — the
    # harvestable event the prior store learns from (n/links included)
    obs.event("ladder.plan", rungs=list(rungs), priced=priced,
              headroom_bytes=plan.headroom_bytes if gov.active else None,
              rss_bytes=rss_bytes() if gov.active else None,
              budget_bytes=gov.mem_budget if gov.active else None,
              native_threads=dict(tplan), n=n, links=len(lo),
              decisions=plan.decisions_dict())
    if snap is not None:
        obs.event("rung.resume", rung=snap.rung, boundary=snap.boundary,
                  rounds=rounds)

    parent = None
    for i, rung in enumerate(rungs):
        rt = ChunkRuntime(policy, ckpt, events, rung, n, seq_h, pst, sig,
                          rounds_base=rounds,
                          promote_after=config.promote_after,
                          governor=gov if gov.active else None,
                          edges_path=config.edges_path,
                          ext_block=ext_block_planned)
        if snap is None and i == 0:
            # boundary 0 = "prep complete": a kill during the first chunk
            # resumes without re-running the degree sort / link mapping
            rt.boundary(0, lambda: (lo, hi))
        try:
            with obs.span("rung", rung=rung, links=len(lo)), \
                    _native_threads_env(tplan):
                parent = _RUNGS[rung](lo, hi, n, rt, num_workers)
            obs.event("rung.ok", rung=rung, rss_bytes=rss_bytes(),
                      est_bytes=price_of.get(rung), n=n)
            break
        except Exception as exc:
            # Memory exhaustion degrades DOWN the ladder (the cheaper
            # rung is the recovery); disk exhaustion propagates (the
            # next rung would hit the same full disk — the run aborts
            # typed and resumable instead).
            oom = isinstance(exc, (MemoryError, MemoryBudgetExceeded))
            retryable = oom or isinstance(exc, RetryBudgetExhausted) \
                or (is_retryable(exc)
                    and not isinstance(exc, ResourceError))
            if not retryable or i + 1 >= len(rungs):
                raise
            events.append(("degrade", rung, rungs[i + 1],
                           f"{type(exc).__name__}: {exc}"))
            obs.event("rung.degrade", rung=rung, next=rungs[i + 1],
                      why=f"{type(exc).__name__}: {exc}")
            if ckpt is not None:
                # Pick up whatever progress the failed rung checkpointed —
                # but REFUSE a handoff whose checkpoint fails verification
                # (any policy): the in-memory links are known-good, a
                # corrupt snapshot is not, so the next rung just redoes
                # the failed rung's progress.
                try:
                    mid = ckpt.load(integrity=config.integrity)
                    if mid is not None:
                        mid.verify(sig)
                except IntegrityError as exc:
                    events.append(("corrupt-checkpoint", rung, str(exc)))
                    mid = None
                if mid is not None:
                    lo, hi, rounds = mid.lo, mid.hi, mid.rounds
    if parent is None:  # pragma: no cover - host rung cannot fail
        raise RuntimeError("degradation ladder exhausted without a result")

    pa = np.asarray(parent).astype(np.int64)
    out = np.full(n, INVALID_JNID, dtype=np.uint32)
    live = (pa >= 0) & (pa < n)
    out[live] = pa[live].astype(np.uint32)
    forest = Forest(out, pst.astype(np.uint32))
    # Fast-oracle gate (integrity tier 3): O(n) structural invariants on
    # the result of whatever rung finished.  A rung that "succeeded" with
    # garbage (flaky interconnect, bad chip) fails HERE, loudly, instead
    # of partitioning a wrong tree.  Links are not re-checked against pst
    # — chunk rounds rewrite the live multiset, only the structure is
    # invariant at this point.
    from ..core.validate import check_forest_fast
    problems = check_forest_fast(forest)
    if problems:
        raise IntegrityError(
            "resilient build produced an invalid forest: "
            + "; ".join(problems))
    if ckpt is not None:
        ckpt.clear()  # build complete: a later --resume starts fresh
    return seq_h, forest
