"""Deterministic fault injection + the runtime's exception taxonomy.

Every recovery path in the fault-tolerant build runtime (sheep_tpu.runtime)
must be testable on CPU, where real dispatch faults never happen.  This
module provides the hook: the chunk drivers call :func:`fault_point` at
every dispatch attempt and every checkpointed chunk boundary, and an
installed :class:`FaultPlan` (monkeypatchable via :func:`install_plan`, or
env-configured via ``SHEEP_FAULT_INJECT`` — the same spirit as the watcher's
gating tests, tests/test_watcher.py) kills exactly the k-th call at a named
site.  Sites are counted per build (:func:`reset_counters`), so "kill
dispatch 3" means the same dispatch on every run — which is what makes the
kill-at-every-chunk-boundary resume property test possible.

Fault kinds model the three real failure shapes seen on the tunneled TPU
backend (PERF_NOTES round 3):

  xla       a faulted dispatch (the per-execution budget trip) — retryable,
            surfaces as :class:`InjectedDispatchFault`, classified together
            with the backend's real ``XlaRuntimeError``.
  deadline  a hung dispatch caught by the watchdog — retryable.
  kill      SIGKILL / OOM-killer: the process dies mid-build.  Raised as
            :class:`BuildKilled`, which nothing in the runtime catches —
            recovery is a NEW process resuming from the checkpoint.
"""

from __future__ import annotations

import os
from dataclasses import dataclass


class InjectedDispatchFault(RuntimeError):
    """A deliberately faulted device dispatch (kind="xla")."""


class DeadlineExceeded(RuntimeError):
    """A dispatch exceeded the watchdog budget (real or injected)."""


class BuildKilled(RuntimeError):
    """Simulated process death (kind="kill").  Never caught by the retry
    wrapper or the degradation ladder: tests catch it at top level and
    then resume from the checkpoint, exactly like a restarted process."""


class RetryBudgetExhausted(RuntimeError):
    """A dispatch site kept faulting past the retry budget; the ladder
    degrades to the next rung on this."""


@dataclass
class FaultPlan:
    """Kill the ``at``-th (0-based) matching call — and the ``times - 1``
    following matching calls — at ``site``.

    ``site``: comma-separated site names, or "*" for every site.
    ``times``: -1 means "every matching call from ``at`` on" (used to force
    a rung to exhaust its retry budget and trigger ladder degradation).
    """

    site: str
    at: int
    kind: str = "xla"
    times: int = 1

    def matches(self, site: str, index: int) -> bool:
        if self.site != "*" and site not in self.site.split(","):
            return False
        if index < self.at:
            return False
        return self.times < 0 or index < self.at + self.times

    def raise_fault(self, site: str, index: int) -> None:
        msg = f"injected {self.kind} fault at {site}[{index}]"
        if self.kind == "kill":
            raise BuildKilled(msg)
        if self.kind == "deadline":
            raise DeadlineExceeded(msg)
        raise InjectedDispatchFault(msg)


_plan: FaultPlan | None = None
_counters: dict[str, int] = {}


def install_plan(plan: FaultPlan | None) -> None:
    """Install (or with None, clear) the active fault plan."""
    global _plan
    _plan = plan


def clear_plan() -> None:
    install_plan(None)


def active_plan() -> FaultPlan | None:
    """The installed plan, falling back to ``SHEEP_FAULT_INJECT`` —
    format ``site:at[:kind[:times]]``, e.g. ``chunk:3:xla:2`` or
    ``boundary:1:kill``."""
    if _plan is not None:
        return _plan
    spec = os.environ.get("SHEEP_FAULT_INJECT", "")
    if not spec:
        return None
    return parse_plan(spec)


def parse_plan(spec: str) -> FaultPlan:
    parts = spec.split(":")
    if len(parts) < 2:
        raise ValueError(
            f"SHEEP_FAULT_INJECT={spec!r}: want site:at[:kind[:times]]")
    site, at = parts[0], int(parts[1])
    kind = parts[2] if len(parts) > 2 else "xla"
    times = int(parts[3]) if len(parts) > 3 else 1
    if kind not in ("xla", "deadline", "kill"):
        raise ValueError(f"unknown fault kind {kind!r}")
    return FaultPlan(site=site, at=at, kind=kind, times=times)


def reset_counters() -> None:
    """Start a fresh build: site indices count from 0 again."""
    _counters.clear()


def fault_count(site: str) -> int:
    """How many times ``site`` has fired since the last reset."""
    return _counters.get(site, 0)


def fault_point(site: str) -> int:
    """Record one call at ``site`` and raise if the active plan kills it.
    Returns this call's 0-based index (useful for logging)."""
    index = _counters.get(site, 0)
    _counters[site] = index + 1
    plan = active_plan()
    if plan is not None and plan.matches(site, index):
        from ..obs import trace as obs
        obs.event("fault", site=site, index=index, kind=plan.kind)
        plan.raise_fault(site, index)
    return index


def is_retryable(exc: BaseException) -> bool:
    """Classify a dispatch failure: True = retry/degrade territory, False =
    programming error or simulated process death (propagate).

    Real backend faults arrive as ``jaxlib...XlaRuntimeError`` (also the
    base of jax's ResourceExhausted/Internal errors); matching by class
    name keeps this working across jaxlib layouts without importing
    private modules.
    """
    if isinstance(exc, BuildKilled):
        return False
    if isinstance(exc, (InjectedDispatchFault, DeadlineExceeded)):
        return True
    for klass in type(exc).__mro__:
        if klass.__name__ in ("XlaRuntimeError", "JaxRuntimeError"):
            return True
    return False
