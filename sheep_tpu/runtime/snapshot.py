"""Resumable build snapshots: the checkpoint format + atomic persistence.

What makes a mid-build checkpoint SOUND here is a structural property of
the whole architecture (ops/forest.py module docstring): every chunk
transform preserves threshold connectivity, and the elimination forest is a
function of threshold connectivity only.  So the complete build state at
any chunk boundary is just

    (sequence, pst accumulator, live link multiset, round counter)

— no schedule position, no lifting depth, no device state.  A build
resumed from ANY boundary snapshot converges to the bit-identical parent
array, because every trajectory over the same link multiset reaches the
same (unique) forest; and pst is order-free (counted once from the
original links before any reduction).  The same property is what lets the
degradation ladder hand a snapshot from the mesh rung to the single-chip
rung to the host oracle: all rungs operate on the same link multiset over
the same sequence.

On disk a snapshot is ONE uncompressed ``.npz`` written crash-safely
(io/atomic.py: temp + fsync + atomic rename), so the file under the final
name is always a complete, self-consistent checkpoint — a kill mid-write
leaves the previous checkpoint in place.  An ``input_sig`` (sha256 over
the vertex count, sequence, and edge bytes) guards against resuming
someone else's build: a mismatch is an error, not a silent wrong tree.

Integrity (ISSUE 2): every snapshot is sealed with a ``.sum`` sidecar
(integrity.sidecar) and loads through :func:`load_snapshot`, which layers
sidecar checksum -> zip member CRCs -> schema -> structural invariants
(Snapshot.validate).  A corrupt snapshot is NEVER partially salvaged —
under the repair policy the driver discards it and rebuilds fresh.
"""

from __future__ import annotations

import hashlib
import os
import zipfile
from dataclasses import dataclass

import numpy as np

from ..integrity.errors import IntegrityError, MalformedArtifact
from ..integrity.sidecar import (resolve_policy, sealed_write, sidecar_path,
                                 verify_file)
from ..resources import (ResourceGovernor, gc_orphan_temps, retention_gc,
                         snapshot_nbytes)

SNAPSHOT_NAME = "sheep-ckpt.npz"
_VERSION = 1


def input_signature(n: int, seq: np.ndarray,
                    tail: np.ndarray | None = None,
                    head: np.ndarray | None = None) -> str:
    """Stable identity of a build input.  Edge bytes are included when the
    caller still has them (one linear pass); a resume deliberately hashes
    the same fields so mismatched graphs are rejected up front."""
    h = hashlib.sha256()
    h.update(f"v{_VERSION}:n{n}:".encode())
    h.update(np.ascontiguousarray(seq, dtype=np.uint32).tobytes())
    for arr in (tail, head):
        if arr is not None:
            h.update(np.ascontiguousarray(arr, dtype=np.uint32).tobytes())
    return h.hexdigest()


@dataclass
class Snapshot:
    """One resumable build state (see module docstring for why this tuple
    is complete)."""

    n: int                 # position-space size (len(seq))
    seq: np.ndarray        # uint32 [m] — the elimination order
    pst: np.ndarray        # uint32 [n] — order-free, final from round 0
    lo: np.ndarray         # int32 [k] live links (lo < hi < n)
    hi: np.ndarray         # int32 [k]
    rounds: int            # chunk rounds completed so far
    boundary: int          # checkpointed chunk boundaries so far
    rung: str              # ladder rung that wrote it (mesh/single/host)
    input_sig: str         # sha256 identity of the build input

    def verify(self, input_sig: str | None) -> None:
        if input_sig is not None and input_sig != self.input_sig:
            raise IntegrityError(
                "checkpoint does not belong to this input graph/sequence "
                f"(snapshot sig {self.input_sig[:12]}..., "
                f"input sig {input_sig[:12]}...) — refusing to resume")

    def validate(self) -> None:
        """Structural invariants a well-formed snapshot always satisfies;
        violation means the file was corrupted (or written by a sick rung)
        and resuming from it would build a silently wrong tree."""
        problems = []
        if self.n < 0:
            problems.append(f"negative n {self.n}")
        if len(self.seq) != self.n:
            problems.append(f"len(seq)={len(self.seq)} != n={self.n}")
        if len(self.pst) != self.n:
            problems.append(f"len(pst)={len(self.pst)} != n={self.n}")
        if len(self.lo) != len(self.hi):
            problems.append(
                f"link arrays disagree: {len(self.lo)} lo vs "
                f"{len(self.hi)} hi")
        else:
            lo = np.asarray(self.lo, dtype=np.int64)
            hi = np.asarray(self.hi, dtype=np.int64)
            if len(lo) and not bool(((lo >= 0) & (lo < hi)
                                     & (hi < self.n)).all()):
                problems.append(
                    "live links violate 0 <= lo < hi < n")
        if self.rounds < 0 or self.boundary < 0:
            problems.append(
                f"negative counters (rounds={self.rounds}, "
                f"boundary={self.boundary})")
        if problems:
            raise MalformedArtifact(
                "corrupt snapshot — " + "; ".join(problems))


#: auto-cadence targets: snapshot overhead <= this fraction of compute
#: time between persisted boundaries, with the cadence capped so a crash
#: never loses more than _AUTO_MAX chunks of progress.
_AUTO_OVERHEAD = 0.1
_AUTO_MAX = 64


class Checkpointer:
    """Owns the snapshot file of one build: save at chunk boundaries,
    load at resume, clear on success.

    ``every``: persist every k-th boundary (the fetch + write costs one
    host sync; on the tunneled backend a coarser cadence may be wanted).
    Boundaries are still COUNTED every time so fault-injection indices
    stay stable regardless of cadence.

    ``every=0`` selects AUTO cadence (env ``SHEEP_CHECKPOINT_EVERY=auto``):
    start persisting every boundary, then retune from measurement — the
    driver reports each persisted snapshot's cost and the chunk compute
    time since the previous boundary (:meth:`observe`), and the cadence is
    scaled so snapshot overhead stays under ~10% of compute.  A fast
    local-disk run keeps every=1 (cheap snapshots, maximum resumability);
    a run whose checkpoints cost an all_gather over a tunneled mesh backs
    off automatically instead of making the operator guess a number.
    """

    def __init__(self, directory: str, every: int = 1,
                 governor: ResourceGovernor | None = None):
        if every < 0:
            raise ValueError(f"checkpoint every={every} must be >= 0 "
                             f"(0 = auto-tune)")
        self.directory = directory
        self.auto = every == 0
        self.every = 1 if self.auto else every
        self.boundary = 0
        self.governor = governor if governor is not None \
            else ResourceGovernor.from_env()
        os.makedirs(directory, exist_ok=True)
        # a killed/faulted predecessor's write debris: unpublished by
        # construction, reclaimed before it can crowd out OUR snapshots
        gc_orphan_temps(directory)

    def observe(self, save_s: float, chunk_s: float) -> int | None:
        """Feed one (snapshot cost, chunk compute time) measurement; in
        auto mode retunes ``every`` and returns the new cadence when it
        changed (None otherwise).  Deterministic given the measurements —
        the property tests drive it with synthetic costs."""
        if not self.auto or chunk_s <= 0 or save_s < 0:
            return None
        import math
        want = save_s / (_AUTO_OVERHEAD * chunk_s)
        new = int(min(_AUTO_MAX, max(1, math.ceil(want))))
        if new == self.every:
            return None
        self.every = new
        return new

    @property
    def path(self) -> str:
        return os.path.join(self.directory, SNAPSHOT_NAME)

    def want(self) -> bool:
        """Will the NEXT boundary be persisted?  Callers use this to skip
        an expensive link fetch/gather when the answer is no."""
        return (self.boundary % self.every) == 0

    def skip(self) -> None:
        """Count an off-cadence boundary without persisting anything."""
        self.boundary += 1

    def preflight(self, n: int, links: int) -> int:
        """Disk preflight for the NEXT snapshot (ISSUE 5): price it
        analytically, run the retention GC when the ``SHEEP_DISK_BUDGET``
        cap would trip (keep-resumable: the live snapshot + sidecar are
        protected; orphan temps and stale files go first), and refuse
        with a typed DiskExhausted when neither the budget nor the
        filesystem can hold it.  Returns the estimate."""
        est = snapshot_nbytes(n, links)
        gov = self.governor
        deficit = gov.dir_budget_deficit(self.directory, est)
        if deficit > 0:
            retention_gc(self.directory,
                         protect=(self.path, sidecar_path(self.path)),
                         keep_last=0, need=deficit)
            gov.check_dir_budget(self.directory, est, "checkpoint")
        gov.preflight_write(self.directory, est)
        return est

    def save(self, snap: Snapshot) -> None:
        """Persist ``snap`` at the current boundary and advance the
        counter (callers gate on :meth:`want` first).  Snapshot writes
        guard themselves twice over: structurally invalid state (a sick
        rung handing over garbage links) is refused BEFORE it becomes
        durable, and a disk that cannot hold the snapshot is refused
        BEFORE any bytes land (:meth:`preflight`) — in both cases the
        previous checkpoint stays in place and the run stays resumable."""
        snap.boundary = self.boundary
        self.boundary += 1
        snap.validate()
        est = self.preflight(snap.n, len(snap.lo))
        from ..obs import trace as obs
        # The npz writer seeks (zip local headers), so the sidecar sums
        # the sealed temp by read-back (sealed_write) — sidecar first,
        # artifact second, like every publish in the system.
        with obs.span("checkpoint.save", rung=snap.rung,
                      boundary=snap.boundary, rounds=snap.rounds,
                      links=len(snap.lo)), \
                sealed_write(self.path, "wb", expect_bytes=est) as f:
            np.savez(
                f,
                version=np.int64(_VERSION),
                n=np.int64(snap.n),
                seq=np.asarray(snap.seq, dtype=np.uint32),
                pst=np.asarray(snap.pst, dtype=np.uint32),
                lo=np.asarray(snap.lo, dtype=np.int32),
                hi=np.asarray(snap.hi, dtype=np.int32),
                rounds=np.int64(snap.rounds),
                boundary=np.int64(snap.boundary),
                rung=np.str_(snap.rung),
                input_sig=np.str_(snap.input_sig),
            )

    def load(self, integrity: str | None = None) -> Snapshot | None:
        """The last persisted snapshot, or None when there is none.
        Raises IntegrityError when the snapshot exists but is corrupt —
        resuming into garbage is never an option (the driver decides
        whether to fall back to a fresh build, per policy)."""
        if not os.path.exists(self.path):
            return None
        snap = load_snapshot(self.path, integrity=integrity)
        # resume continues counting boundaries where the dead build stopped
        self.boundary = snap.boundary + 1
        return snap

    def clear(self) -> None:
        """Remove the snapshot and its sidecar (the build completed; a
        later --resume must start fresh rather than replay a finished
        state)."""
        for path in (self.path, sidecar_path(self.path)):
            try:
                os.unlink(path)
            except FileNotFoundError:
                pass


def load_snapshot(path: str, integrity: str | None = None) -> Snapshot:
    """Load + fully verify one snapshot file: sidecar checksum, zip-member
    CRCs (np.load's zipfile layer), schema, and structural invariants.
    Every corruption class surfaces as a typed IntegrityError — this is
    also the ``sheep fsck`` checker for ``.npz`` artifacts."""
    mode = resolve_policy(integrity)
    # A snapshot is never partially salvageable — resuming from bytes that
    # "mostly parse" builds a wrong tree.  So the checksum check is strict
    # even under the repair policy; repair's graceful path lives in the
    # DRIVER, which catches the IntegrityError and rebuilds from scratch.
    if mode != "trust":
        verify_file(path, "strict")
    try:
        with np.load(path) as z:
            if int(z["version"]) != _VERSION:
                raise MalformedArtifact(
                    f"{path}: snapshot version {int(z['version'])} "
                    f"!= supported {_VERSION}")
            snap = Snapshot(
                n=int(z["n"]), seq=z["seq"].copy(), pst=z["pst"].copy(),
                lo=z["lo"].copy(), hi=z["hi"].copy(),
                rounds=int(z["rounds"]), boundary=int(z["boundary"]),
                rung=str(z["rung"]), input_sig=str(z["input_sig"]))
    except IntegrityError:
        raise
    except (zipfile.BadZipFile, KeyError, OSError, ValueError,
            EOFError) as exc:
        # np.load surfaces member bit-flips as BadZipFile ("Bad CRC-32"),
        # missing members as KeyError, torn files as OSError/EOFError
        raise MalformedArtifact(
            f"{path}: corrupt snapshot ({type(exc).__name__}: {exc})")
    snap.validate()
    return snap
