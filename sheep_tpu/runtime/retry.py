"""Retry-with-backoff for device dispatches, with adaptive shrinking.

The chunk drivers' unit of failure is one bounded dispatch (a
``fixpoint_chunk`` / ``chunk_sharded`` call).  On the tunneled TPU backend
a dispatch faults when its wall time outgrows the per-execution budget
(PERF_NOTES round 3) — and since wall time scales with the per-dispatch
round count, the right retry is not "same thing again" but SHRINK: halve
``jrounds`` before re-dispatching, so a dispatch that tripped the budget
asks for half the work next time.  Progress already made is never lost
(dispatches are functional — the inputs are intact after a fault), and a
1-round dispatch is the minimum quantum, so shrinking terminates.

Exponential backoff between attempts covers the transient-infrastructure
case (tunnel hiccup, preempted worker): sleeping ``base * 2^attempt``
capped at ``cap``.  A watchdog (optional) bounds how long a HUNG dispatch
can stall the build: block_until_ready runs on a helper thread and a
timeout classifies the dispatch as faulted (DeadlineExceeded, retryable —
the stuck execution is abandoned to the backend).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable

from .faults import (DeadlineExceeded, RetryBudgetExhausted, fault_point,
                     is_retryable)


@dataclass
class RetryPolicy:
    """Knobs for one build's dispatch retries (CLI: --max-retries; env:
    SHEEP_MAX_RETRIES / SHEEP_BACKOFF_BASE / SHEEP_WATCHDOG_S)."""

    max_retries: int = 3
    backoff_base_s: float = 0.05
    backoff_cap_s: float = 2.0
    shrink: bool = True
    watchdog_s: float | None = None
    # injectable for tests (no real sleeping in the suite)
    sleep: Callable[[float], None] = field(default=time.sleep)

    def backoff(self, attempt: int) -> float:
        return min(self.backoff_base_s * (2 ** attempt), self.backoff_cap_s)


def call_with_watchdog(fn, j, timeout_s: float | None):
    """``fn(j)`` + block_until_ready under a deadline (None = unbounded).

    The whole attempt runs on a helper thread: dispatch itself can block
    too (compilation, a wedged tunnel), not just the result wait.  On
    timeout the attempt is abandoned to the backend and classified as a
    retryable :class:`DeadlineExceeded`.
    """
    import jax

    if timeout_s is None:
        out = fn(j)
        jax.block_until_ready(out)
        return out
    done = threading.Event()
    result: dict = {}

    def attempt():
        try:
            out = fn(j)
            jax.block_until_ready(out)
            result["out"] = out
        except BaseException as exc:  # surfaced on the caller thread
            result["err"] = exc
        finally:
            done.set()

    t = threading.Thread(target=attempt, daemon=True)
    t.start()
    if not done.wait(timeout_s):
        raise DeadlineExceeded(
            f"dispatch still not ready after {timeout_s}s watchdog")
    if "err" in result:
        raise result["err"]
    return result["out"]


def run_with_retry(policy: RetryPolicy, site: str,
                   fn: Callable, j: int | None,
                   on_retry: Callable[[str, int, int | None], None]
                   | None = None):
    """Run ``fn(j)`` (a dispatch returning device outputs), blocking until
    ready; on a retryable failure, back off, halve ``j`` (when shrinking
    applies and ``j`` is not None), and retry up to the budget.

    Each ATTEMPT passes through :func:`faults.fault_point` under ``site``
    — that is the deterministic injection hook.  Returns
    ``(outputs, j_used)``.  Raises :class:`RetryBudgetExhausted` once the
    budget is spent (the degradation ladder's cue), and re-raises
    non-retryable exceptions (including BuildKilled) untouched.
    """
    attempt = 0
    while True:
        try:
            fault_point(site)
            return call_with_watchdog(fn, j, policy.watchdog_s), j
        except BaseException as exc:
            if not is_retryable(exc):
                raise
            if attempt >= policy.max_retries:
                raise RetryBudgetExhausted(
                    f"{site}: {attempt + 1} attempts all faulted "
                    f"(last: {type(exc).__name__}: {exc})") from exc
            policy.sleep(policy.backoff(attempt))
            if policy.shrink and j is not None:
                j = max(1, j // 2)
            attempt += 1
            if on_retry is not None:
                on_retry(site, attempt, j)
