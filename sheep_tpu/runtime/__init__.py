"""Fault-tolerant chunked-build runtime.

Four modules, layered bottom-up:

  faults.py    deterministic fault injection + the exception taxonomy
  snapshot.py  resumable checkpoint format (atomic .npz at chunk bounds)
  retry.py     retry-with-backoff + adaptive round-count shrinking
  driver.py    checkpointed build driver + the mesh -> single-chip ->
               host-numpy graceful-degradation ladder

See driver.py's docstring for the failure model and the determinism
argument (why a resumed or degraded build is bit-identical).
"""

from .driver import (ChunkRuntime, RuntimeConfig, build_graph_resilient)
from .faults import (BuildKilled, DeadlineExceeded, FaultPlan,
                     InjectedDispatchFault, RetryBudgetExhausted, clear_plan,
                     fault_point, install_plan, reset_counters)
from .retry import RetryPolicy, run_with_retry
from .snapshot import Checkpointer, Snapshot, input_signature, load_snapshot

__all__ = [
    "BuildKilled",
    "Checkpointer",
    "ChunkRuntime",
    "DeadlineExceeded",
    "FaultPlan",
    "InjectedDispatchFault",
    "RetryBudgetExhausted",
    "RetryPolicy",
    "RuntimeConfig",
    "Snapshot",
    "build_graph_resilient",
    "clear_plan",
    "fault_point",
    "input_signature",
    "install_plan",
    "load_snapshot",
    "reset_counters",
    "run_with_retry",
]
