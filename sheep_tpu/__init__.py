"""sheep_tpu: a TPU-native streaming elimination-tree graph partitioner.

A from-scratch reimplementation of the capabilities of the Sheep partitioner
(Margo & Seltzer, VLDB'15; reference C++/MPI implementation surveyed in
SURVEY.md) designed for TPU execution with JAX/XLA:

- the per-worker streaming tree-insert loop becomes a batched, fixed-shape
  "hooking" kernel over edge blocks (`sheep_tpu.ops.forest`),
- the distributed degree sort becomes a `psum` histogram + replicated argsort
  (`sheep_tpu.parallel`),
- the associative tree merge becomes a collective min-reduction over the
  device mesh instead of an MPI_Reduce custom op,
- partitioning + evaluation run on dense arrays (host C++ / numpy for the
  sequential FFD pass, device segment-ops for the evaluator).

Layout:
  io/         edge-list / sequence / tree file formats (.dat .net .seq .tre)
  integrity/  sidecar checksums, typed corruption errors, `sheep fsck`
  core/       exact sequential semantics (numpy oracle) + facts + validation
  ops/        single-device JAX kernels (sort, hooking, segment sums, eval)
  parallel/   mesh construction, sharded fused build, tournament merge
  partition/  tree partitioners (forward FFD et al.), fennel, evaluators
  serve/      the long-lived partition service: WAL, snapshots, protocol,
              admission control, incremental inserts (`sheep serve`)
  cli/        graph2tree / partition_tree / degree_sequence / merge_trees
              / fsck / supervise / serve
  utils/      phase timers (stdout grammar), misc helpers
"""

__version__ = "0.1.0"

INVALID_JNID = 0xFFFFFFFF
INVALID_VID = 0xFFFFFFFF
INVALID_PART = -1
