"""The history-learning cost-model executor (ISSUE 15).

``plan_build`` resolves one build's execution plan — rung order, native
thread count, ext/spill block size, handoff windows, distext legs —
folding the governor's analytic prices (resources/governor.py) with
measured priors learned from past traces and bench records
(plan/priors.py).  Every ``SHEEP_*`` knob is an *override* recorded in
the plan with its provenance (default | priced | learned | forced);
``sheep plan --explain`` (cli/plan.py) renders the whole story.

Jax-free on purpose: the planner must be importable from the CLI, the
supervisor parent, and the serve daemon without initializing a backend.
"""

from .model import (DEFAULT_LADDER, PROV_DEFAULT, PROV_FORCED,
                    PROV_LEARNED, PROV_PRICED, WORKER_TRANSPORT_ENV,
                    Decision, Plan, available_rungs, plan_build,
                    plan_distext_legs, plan_transport)
from .priors import (MIN_CORRECT_SAMPLES, PRIORS_ENV, PriorStore,
                     host_fingerprint, mem_ratio, prior_key, scale_bucket)

__all__ = [
    "DEFAULT_LADDER",
    "Decision",
    "MIN_CORRECT_SAMPLES",
    "PRIORS_ENV",
    "PROV_DEFAULT",
    "PROV_FORCED",
    "PROV_LEARNED",
    "PROV_PRICED",
    "Plan",
    "PriorStore",
    "available_rungs",
    "host_fingerprint",
    "mem_ratio",
    "plan_build",
    "plan_distext_legs",
    "plan_transport",
    "WORKER_TRANSPORT_ENV",
    "prior_key",
    "scale_bucket",
]
