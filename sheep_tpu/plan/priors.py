"""Measured priors for the planner: learn from what past builds did.

The governor's analytic estimates (resources/governor.py) are
first-principles arithmetic — deliberately coarse, deliberately
over-priced.  But since PR 10 every build leaves evidence of what
ACTUALLY happened: the ``ladder.plan`` event records each rung's priced
peak, ``rung.ok`` records the measured RSS beside it, ``rung`` spans
record measured seconds, and the bench records
(``BENCH_*``/``EXTBENCH_*.json``) carry whole-arm wall clocks with an
``env_capture`` naming the host.  This module closes the loop: a small
on-disk :class:`PriorStore` harvests those artifacts into per-host
per-scale statistics the cost model (plan/model.py) folds into its
prices.

What is learned, and from where:

  ``mem_ratio:<rung>``   measured_rss / priced_bytes of a finished rung
                         (``rung.ok`` events) — the correction factor
                         for the analytic peak.  >1 means the analytic
                         model under-prices on this host; the planner
                         multiplies it in before keep/skip verdicts and
                         ext-block fitting.
  ``rung_s:<rung>``      measured seconds of a ``rung`` span (and of
                         bench arms whose name matches a rung), bucketed
                         by link scale — the historical cost ``sheep
                         plan --explain`` prints beside each candidate's
                         analytic price.
  ``fold_bps:reseq``     measured bytes/s of a serve-tier re-sequence
                         fold (``reseq.fold`` spans, serve/reseq.py) —
                         replaces the analytic RESEQ_FOLD_BPS guess in
                         ``plan_reseq`` once this host has history.

Keys carry a **host fingerprint** (cpu model + effective cores) and a
**scale bucket** (log2 of n or links): a prior learned on an 8-core
bench host never corrects a plan on a 1-core container, and a prior
from a 2^14 toy never corrects a 2^26 build.

Trace harvesting reads through the ROTATED segment chain
(obs/trace.py: ``x.trace`` -> ``x.0001.trace`` ...), with the newest
segment read in repair mode — the active file of a killed daemon
legally ends in a torn line, and the whole point of learning from
history is that history includes crashes.  A mid-chain rotten segment
is skipped, never fatal: a prior store degrades to fewer samples, not
to a refusal.

The store itself is one JSON file (``SHEEP_PLAN_PRIORS`` names it),
written atomically; absent/corrupt stores read as empty — priors can
only ever ADD information to the analytic model, never break a build.
"""

from __future__ import annotations

import hashlib
import json
import os

PRIORS_ENV = "SHEEP_PLAN_PRIORS"
STORE_VERSION = 1

#: samples a prior needs before it may CORRECT a decision (a single
#: noisy run must not flip plans; --explain still shows thinner priors)
MIN_CORRECT_SAMPLES = 2


def host_fingerprint() -> str:
    """A stable id of "this kind of host" for prior keys: cpu model x
    effective cores.  Deliberately coarse — two identical containers
    should share priors; a quota change is a different host."""
    from ..utils.envinfo import effective_cores, env_capture
    cap = env_capture()
    raw = f"{cap.get('cpu_model', '?')}|{effective_cores()}"
    return hashlib.sha1(raw.encode()).hexdigest()[:12]


def scale_bucket(size: int) -> int:
    """log2 bucket of a problem size (n or links); 0 for empty."""
    return max(0, int(size).bit_length() - 1) if size > 0 else 0


def prior_key(kind: str, name: str, size: int, host: str | None = None
              ) -> str:
    host = host if host is not None else host_fingerprint()
    return f"{host}:{kind}:{name}:s{scale_bucket(size)}"


class PriorStore:
    """The on-disk prior store: {key: {"count": k, "mean": m}} plus a
    version stamp.  ``observe`` folds a sample into the running mean;
    ``lookup`` answers for one (kind, name, size) on this host."""

    def __init__(self, path: str | None = None):
        self.path = path
        self.entries: dict[str, dict] = {}
        self.meta: dict = {"v": STORE_VERSION}
        if path and os.path.exists(path):
            try:
                with open(path, encoding="utf-8") as f:
                    data = json.load(f)
                if isinstance(data, dict) \
                        and isinstance(data.get("entries"), dict):
                    self.entries = {
                        str(k): {"count": int(v.get("count", 0)),
                                 "mean": float(v.get("mean", 0.0))}
                        for k, v in data["entries"].items()
                        if isinstance(v, dict)}
            except (OSError, ValueError):
                pass  # a corrupt store reads as empty, never breaks

    @classmethod
    def from_env(cls) -> "PriorStore | None":
        path = os.environ.get(PRIORS_ENV) or None
        return cls(path) if path else None

    def __len__(self) -> int:
        return len(self.entries)

    def observe(self, kind: str, name: str, size: int, value: float,
                host: str | None = None) -> None:
        key = prior_key(kind, name, size, host)
        e = self.entries.setdefault(key, {"count": 0, "mean": 0.0})
        e["count"] += 1
        e["mean"] += (float(value) - e["mean"]) / e["count"]

    def lookup(self, kind: str, name: str, size: int,
               host: str | None = None) -> dict | None:
        """The prior for (kind, name, size-bucket) on ``host`` (default:
        this host), as {"key", "count", "mean"} — or None."""
        key = prior_key(kind, name, size, host)
        e = self.entries.get(key)
        if e is None:
            return None
        return {"key": key, "count": e["count"], "mean": e["mean"]}

    def save(self, path: str | None = None) -> str:
        path = path or self.path
        if not path:
            raise ValueError("PriorStore has no path to save to")
        from ..io.atomic import atomic_write
        payload = json.dumps({"v": STORE_VERSION, "entries": self.entries},
                             indent=1, sort_keys=True)
        with atomic_write(path, "w") as f:
            f.write(payload)
        self.path = path
        return path

    # -- harvesting --------------------------------------------------------

    def harvest_trace(self, path: str, host: str | None = None) -> int:
        """Fold one trace (or its rotated segment chain) into the store;
        returns samples observed.  Rotated segments read strict, the
        newest file in repair (a killed run's torn tail is legal
        evidence); a rotten segment is skipped with its samples lost —
        harvesting never raises over damage."""
        from ..integrity.errors import IntegrityError
        from ..obs.trace import read_trace, trace_segments
        host = host if host is not None else host_fingerprint()
        chain = trace_segments(path)
        if not chain:
            return 0
        records: list[dict] = []
        import warnings
        for i, seg in enumerate(chain):
            mode = "repair" if i == len(chain) - 1 else "strict"
            try:
                with warnings.catch_warnings():
                    warnings.simplefilter("ignore")
                    recs, _, _ = read_trace(seg, mode)
            except (IntegrityError, OSError):
                continue  # damaged segment: fewer samples, not a refusal
            records.extend(recs)
        return self._harvest_records(records, host)

    def _harvest_records(self, records: list[dict], host: str) -> int:
        seen = 0
        # the newest ladder.plan's n/links/rss contextualize later rung
        # events: the plan-time rss is the baseline the rung's measured
        # rss is charged against — raw process RSS includes the
        # interpreter+backend floor, which is not the rung's doing and
        # would swamp the ratio at small scales
        n = links = 0
        rss0 = None
        for r in records:
            k, name = r.get("k"), r.get("name")
            a = r.get("a", {})
            if k == "ev" and name == "ladder.plan":
                n = int(a.get("n") or 0)
                links = int(a.get("links") or 0)
                rss0 = a.get("rss_bytes")
            elif k == "ev" and name == "rung.ok":
                est, rss = a.get("est_bytes"), a.get("rss_bytes")
                size = int(a.get("n") or n)
                if est and rss is not None and rss0 is not None and size:
                    inc = float(rss) - float(rss0)
                    # clamp: a single run's allocator noise must not
                    # teach an unbounded correction either way
                    ratio = min(8.0, max(0.125, inc / float(est)))
                    self.observe("mem_ratio", str(a.get("rung", "?")),
                                 size, ratio, host)
                    seen += 1
            elif k == "span" and name == "rung":
                rung = a.get("rung")
                size = int(a.get("links") or links)
                dur = float(r.get("dur", 0.0))
                if rung and size and dur > 0:
                    self.observe("rung_s", str(rung), size, dur, host)
                    seen += 1
            elif k == "span" and name == "reseq.fold":
                b = int(a.get("bytes") or 0)
                dur = float(r.get("dur", 0.0))
                if b > 0 and dur > 0:
                    self.observe("fold_bps", "reseq", b, b / dur, host)
                    seen += 1
        return seen

    def harvest_bench(self, path: str, host: str | None = None) -> int:
        """Fold one bench record (``BENCH_*``/``EXTBENCH_*.json``-shaped)
        into the store: arms whose name matches a ladder rung contribute
        ``rung_s`` seconds at their record scale.  Unknown shapes
        harvest zero samples; damage never raises."""
        try:
            with open(path, encoding="utf-8") as f:
                rec = json.load(f)
        except (OSError, ValueError):
            return 0
        if not isinstance(rec, dict):
            return 0
        host = host if host is not None else host_fingerprint()
        rungs = {"mesh", "single", "host", "stream", "ext", "spill"}
        seen = 0
        arms = rec.get("arms")
        if isinstance(arms, dict):
            for name, arm in arms.items():
                if not isinstance(arm, dict):
                    continue
                rung = str(arm.get("arm", name)).split("_")[0]
                wall = arm.get("wall_s")
                size = arm.get("records") or arm.get("edges") \
                    or arm.get("links") or 0
                if rung in rungs and wall and size:
                    self.observe("rung_s", rung, int(size), float(wall),
                                 host)
                    seen += 1
        return seen


def mem_ratio(priors: "PriorStore | None", rung: str, n: int) -> dict | None:
    """The usable memory-correction prior for ``rung`` at scale ``n`` on
    this host, or None (no store / too few samples to correct)."""
    if priors is None:
        return None
    p = priors.lookup("mem_ratio", rung, n)
    if p is None or p["count"] < MIN_CORRECT_SAMPLES or p["mean"] <= 0:
        return None
    return p


def fold_bps(priors: "PriorStore | None", blob: int) -> dict | None:
    """The usable measured fold-throughput prior (bytes/s) for a
    re-sequence of ``blob`` bytes on this host, or None (no store / too
    few samples to correct)."""
    if priors is None:
        return None
    p = priors.lookup("fold_bps", "reseq", blob)
    if p is None or p["count"] < MIN_CORRECT_SAMPLES or p["mean"] <= 0:
        return None
    return p
